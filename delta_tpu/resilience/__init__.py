"""delta-resilience: unified retry/backoff, circuit breaking, and chaos.

The reference implementation survives flaky object stores by
construction — every storage round trip goes through Hadoop FS clients
that retry transients with exponential backoff, and `_delta_log`
recovery tolerates zombie writers (`Checkpoints.scala:752-767`). This
package gives the port the same shape as one shared subsystem instead
of ad-hoc loops:

- :mod:`delta_tpu.resilience.classify` — maps an exception to
  transient (worth retrying) or permanent (fail fast), consulting the
  error catalog for `DeltaError` subclasses.
- :mod:`delta_tpu.resilience.policy` — `RetryPolicy`: exponential
  backoff with decorrelated jitter, attempt caps, and a wall-clock
  deadline budget. Env-tunable via ``DELTA_TPU_RETRY_*``.
- :mod:`delta_tpu.resilience.breaker` — per-endpoint circuit breaker
  (closed → open → half-open with probe requests) so a dead endpoint
  fails fast instead of serially burning retry budgets.
- :mod:`delta_tpu.resilience.deadline` — ambient (contextvar-scoped)
  request deadlines; `RetryPolicy` honours them at every attempt
  boundary, so multi-hop work is abandoned the moment the requesting
  client's budget expires.
- :mod:`delta_tpu.resilience.chaos` — deterministic seeded
  `ChaosStore` fault-injection wrapper (superset of
  `FaultInjectingLogStore`) for soak testing.
- :mod:`delta_tpu.resilience.device_chaos` — the device-side twin:
  a seeded `ChaosEngine` that injects dispatch errors, simulated
  RESOURCE_EXHAUSTED, transfer stalls, and recompile storms at the
  `obs/device.py::device_dispatch()` funnel.
- :mod:`delta_tpu.resilience.device_faults` — the absorption half:
  HBM shed-and-retry plus classify-and-fall-back for every gated
  device route (the route breakers live in `parallel/gate.py`).

Every storage-facing layer funnels IO through :func:`io_call` so the
policy, breaker registry, and telemetry
(``storage.retry.attempts``, ``storage.breaker.state``) stay uniform.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, TypeVar

from delta_tpu.resilience.breaker import (
    CircuitBreaker,
    breaker_for,
    breaker_states,
    reset_breakers,
    route_breaker_for,
)
from delta_tpu.resilience.chaos import ChaosSchedule, ChaosStore
from delta_tpu.resilience.device_chaos import (
    ChaosEngine,
    DeviceChaosError,
    DeviceChaosSchedule,
    DeviceResourceExhaustedError,
)
from delta_tpu.resilience.classify import (
    PERMANENT,
    TRANSIENT,
    StorageRequestError,
    classify,
    is_transient,
)
from delta_tpu.resilience.deadline import (
    check_deadline,
    current_deadline,
    deadline_scope,
    deadline_scope_at,
    expired,
    remaining,
)
from delta_tpu.resilience.policy import RetryPolicy

T = TypeVar("T")

_policy_lock = threading.Lock()
_default_policy: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
    """The process-wide IO retry policy, built once from the
    ``DELTA_TPU_RETRY_*`` environment knobs."""
    global _default_policy
    p = _default_policy
    if p is None:
        with _policy_lock:
            p = _default_policy
            if p is None:
                p = RetryPolicy.from_env()
                _default_policy = p
    return p


def reset() -> None:
    """Forget the cached policy, all breaker state (route breakers
    included), and any armed device-chaos engine (tests)."""
    global _default_policy
    with _policy_lock:
        _default_policy = None
    reset_breakers()
    from delta_tpu.obs import device as _obs_device

    _obs_device.set_dispatch_chaos(None)


def endpoint_of(path: str) -> str:
    """Endpoint key for breaker bucketing: scheme plus authority
    (``gs://bucket``), or ``file`` for plain paths. The authority
    matters: breaker state must be isolated per bucket/account — one
    dead bucket opening a scheme-wide breaker would fast-fail traffic
    to every healthy bucket on that scheme."""
    i = path.find("://")
    if i <= 0:
        return "file"
    j = path.find("/", i + 3)
    return path if j < 0 else path[:j]


def io_call(endpoint: str, fn: Callable[[], T]) -> T:
    """Run one storage operation under the default retry policy and the
    endpoint's circuit breaker. This is the single funnel every
    storage-facing layer uses; keep its fault-free path cheap."""
    return default_policy().call(fn, breaker=breaker_for(endpoint))


__all__ = [
    "ChaosEngine",
    "ChaosSchedule",
    "ChaosStore",
    "CircuitBreaker",
    "DeviceChaosError",
    "DeviceChaosSchedule",
    "DeviceResourceExhaustedError",
    "PERMANENT",
    "RetryPolicy",
    "StorageRequestError",
    "TRANSIENT",
    "breaker_for",
    "breaker_states",
    "check_deadline",
    "classify",
    "current_deadline",
    "deadline_scope",
    "deadline_scope_at",
    "default_policy",
    "endpoint_of",
    "expired",
    "io_call",
    "is_transient",
    "remaining",
    "reset",
    "reset_breakers",
    "route_breaker_for",
]

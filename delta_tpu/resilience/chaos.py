"""ChaosStore: deterministic seeded fault injection for soak tests.

`FaultInjectingLogStore` arms *specific* faults for *specific* tests
("fail the next write of 00000003.json"). The chaos harness asks the
opposite question: under a sustained, seeded barrage of generic faults
— transient request errors, latency spikes, torn writes, stale
listings — does the full workload still converge to exactly the state
a fault-free run produces? That is the property serving infrastructure
actually needs, and seeding makes any failure replayable.

Fault model (each drawn independently per operation from one seeded
RNG, so a given seed yields one schedule):

- **transient errors** (`error_rate`): the operation raises
  :class:`ChaosError` *before* touching the inner store, so the fault
  is unambiguous — the op did not happen and a retry is always safe.
- **lost write acks** (`ack_loss_rate`): the *ambiguous* counterpart
  for commit ``N.json`` writes — the inner write lands, then
  :class:`ChaosError` raises as if the response was lost. The
  put-if-absent retry observes its own commit as `FileExistsError`,
  which the transaction's `CommitInfo.txnId` self-commit detection
  must recover without rebasing (no duplicate data).
- **latency spikes** (`latency_rate`): the operation sleeps a seeded
  duration first.
- **torn writes** (`torn_write_rate`): for paths matching
  ``torn_pred`` (default: checkpoint artifacts, ``.crc`` files, and
  the ``_last_checkpoint`` hint) a prefix of the payload is written,
  then :class:`ChaosError` raises — the reader-side corruption
  fallback must absorb the damage. Commit ``.json`` files are
  excluded by default: their writes are atomic-by-contract on every
  store (O_EXCL / generation preconditions), so a torn commit can
  only come from a store whose `is_partial_write_visible` is true —
  that shape is covered by the dedicated torn-commit tests.
- **stale listings** (`stale_list_rate`): `list_from` drops entries
  from the *tail* of the result — the prefix-consistent shape real
  eventually-consistent listings have. Readers see an older version;
  writers lose the put-if-absent race and rebase.
- **read corruption** (`corrupt_read_rate`): for paths matching
  ``corrupt_pred`` (default: checkpoint artifacts and ``.crc``
  files) the returned payload comes back with seeded bit flips near
  its tail — where the parquet footer / crc digest lives — so the
  read *succeeds* but the content is damaged. The reader-side
  corruption ladder (crc quarantine, checkpoint fallback to the
  commit-replay path) must absorb it; commit ``.json`` files are
  excluded because a corrupt commit is genuine data loss, which no
  reader-side ladder can recover.

All decisions honour ``path_filter`` (default: only `_delta_log`
paths) so table-data IO can be left quiet while the log is hammered.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional

from delta_tpu import obs
from delta_tpu.storage.logstore import (
    DelegatingLogStore,
    FileStatus,
    LogStore,
)

_CHAOS_FAULTS = obs.counter("chaos.faults")
_CHAOS_TORN = obs.counter("chaos.torn_writes")
_CHAOS_STALE = obs.counter("chaos.stale_listings")
_CHAOS_ACK_LOSS = obs.counter("chaos.ack_losses")
_CHAOS_CORRUPT = obs.counter("chaos.read_corruptions")


class ChaosError(IOError):
    """A seeded injected transient fault (classified retryable)."""


def _default_torn_pred(path: str) -> bool:
    name = path.rpartition("/")[2]
    return (".checkpoint" in name or name.endswith(".crc")
            or name == "_last_checkpoint")


def _default_corrupt_pred(path: str) -> bool:
    """Checkpoint artifacts and crc sidecars: the payloads whose
    corruption the reader fallback ladder is contractually able to
    absorb (commit .json damage is unrecoverable data loss)."""
    name = path.rpartition("/")[2]
    return ".checkpoint" in name or name.endswith(".crc")


def _default_ack_pred(path: str) -> bool:
    """Commit delta files (``<version>.json``): the put-if-absent path
    where a lost ack turns into a self-conflict the txn must detect."""
    name = path.rpartition("/")[2]
    return name.endswith(".json") and name[:-5].isdigit()


def _default_path_filter(path: str) -> bool:
    return "_delta_log" in path


class ChaosSchedule:
    """Seeded per-operation fault decisions. Thread-safe: draws are
    serialized so one seed produces one decision sequence."""

    def __init__(self, seed: int, error_rate: float = 0.05,
                 latency_rate: float = 0.0,
                 latency_s: tuple = (0.0002, 0.002),
                 torn_write_rate: float = 0.0,
                 stale_list_rate: float = 0.0,
                 ack_loss_rate: float = 0.0,
                 corrupt_read_rate: float = 0.0):
        self.seed = seed
        self.error_rate = error_rate
        self.latency_rate = latency_rate
        self.latency_s = latency_s
        self.torn_write_rate = torn_write_rate
        self.stale_list_rate = stale_list_rate
        self.ack_loss_rate = ack_loss_rate
        self.corrupt_read_rate = corrupt_read_rate
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def draw(self) -> float:
        with self._lock:
            return self._rng.random()

    def draw_latency(self) -> float:
        lo, hi = self.latency_s
        with self._lock:
            return self._rng.uniform(lo, hi)

    def draw_stale_drop(self, n: int) -> int:
        """How many tail entries to hide from an n-entry listing.

        Always leaves at least one entry visible: a stale listing lags
        behind the log tail, it never makes an existing table vanish
        (an empty listing is indistinguishable from "no table", which
        no amount of retrying can recover from). Returns 0 for n <= 1.
        """
        with self._lock:
            return self._rng.randint(1, max(1, min(3, n - 1))) if n > 1 else 0

    def draw_flip_offsets(self, size: int, window: int = 16,
                          n_flips: int = 3) -> List[tuple]:
        """Seeded ``(byte_offset, bit)`` pairs inside the payload's last
        ``window`` bytes — where the parquet footer magic / length and
        crc digest text live, so a flip is guaranteed to damage what
        the reader actually validates rather than some padding byte."""
        lo = max(0, size - window)
        with self._lock:
            return [(self._rng.randrange(lo, size), self._rng.randrange(8))
                    for _ in range(min(n_flips, size))]


class ChaosStore(DelegatingLogStore):
    """Seeded chaos wrapper around any `LogStore`.

    ``enabled`` can be flipped off (e.g. for final verification reads)
    without rebuilding engines; ``fault_log`` records every injection
    as ``(kind, op, path)`` for assertions and replay triage.
    """

    def __init__(self, inner: LogStore, schedule: ChaosSchedule,
                 path_filter: Optional[Callable[[str], bool]] = None,
                 torn_pred: Optional[Callable[[str], bool]] = None,
                 ack_pred: Optional[Callable[[str], bool]] = None,
                 corrupt_pred: Optional[Callable[[str], bool]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        super().__init__(inner)
        self.schedule = schedule
        self.path_filter = path_filter or _default_path_filter
        self.torn_pred = torn_pred or _default_torn_pred
        self.ack_pred = ack_pred or _default_ack_pred
        self.corrupt_pred = corrupt_pred or _default_corrupt_pred
        self.enabled = True
        self.fault_log: List[tuple] = []
        self.fault_counts: Dict[str, int] = {}
        self._sleep = sleep

    # ------------------------------------------------------------ core
    def _record(self, kind: str, op: str, path: str) -> None:
        self.fault_log.append((kind, op, path))
        self.fault_counts[kind] = self.fault_counts.get(kind, 0) + 1

    def _perturb(self, op: str, path: str) -> None:
        """Latency then maybe a transient error, before the real op."""
        if not self.enabled or not self.path_filter(path):
            return
        s = self.schedule
        if s.latency_rate and s.draw() < s.latency_rate:
            self._record("latency", op, path)
            self._sleep(s.draw_latency())
        if s.error_rate and s.draw() < s.error_rate:
            self._record("error", op, path)
            _CHAOS_FAULTS.inc()
            raise ChaosError(f"chaos[{s.seed}]: injected {op} fault: {path}")

    # ------------------------------------------------------------- ops
    def read(self, path: str) -> bytes:
        self._perturb("read", path)
        data = self.inner.read(path)
        s = self.schedule
        if (self.enabled and s.corrupt_read_rate and data
                and self.path_filter(path) and self.corrupt_pred(path)
                and s.draw() < s.corrupt_read_rate):
            # the read succeeds but the payload is damaged: seeded bit
            # flips near the tail (parquet footer / crc digest), so the
            # reader's validation — not the transport — catches it
            self._record("corrupt_read", "read", path)
            _CHAOS_CORRUPT.inc()
            buf = bytearray(data)
            for off, bit in s.draw_flip_offsets(len(buf)):
                buf[off] ^= 1 << bit
            data = bytes(buf)
        return data

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        self._perturb("write", path)
        s = self.schedule
        if (self.enabled and s.torn_write_rate and self.path_filter(path)
                and self.torn_pred(path) and s.draw() < s.torn_write_rate):
            self._record("torn_write", "write", path)
            _CHAOS_TORN.inc()
            torn = data[: len(data) // 2]
            self.inner.write(path, torn, overwrite)
            raise ChaosError(
                f"chaos[{s.seed}]: torn write ({len(torn)}/{len(data)} "
                f"bytes): {path}")
        self.inner.write(path, data, overwrite)
        if (self.enabled and s.ack_loss_rate and self.path_filter(path)
                and self.ack_pred(path) and s.draw() < s.ack_loss_rate):
            # ambiguous outcome: the write landed, the response did not
            self._record("ack_loss", "write", path)
            _CHAOS_ACK_LOSS.inc()
            raise ChaosError(
                f"chaos[{s.seed}]: write ack lost after landing: {path}")

    def write_batch(self, items, overwrite: bool = False) -> None:
        """Batched commit emit under chaos. Two fault shapes:

        - a pre-op transient error (nothing landed, retry safe);
        - a **partial-batch ack loss**: a prefix of 1..n members lands
          durably in the inner store, then the response is lost. The
          group-commit emitter must resolve every member's fate by
          read-back (txnId compare) — exactly the per-member analogue
          of the solo self-commit recovery.
        """
        items = list(items)
        if not items:
            return
        first = items[0][0]
        self._perturb("write_batch", first)
        s = self.schedule
        if (self.enabled and s.ack_loss_rate and self.path_filter(first)
                and self.ack_pred(first) and s.draw() < s.ack_loss_rate):
            # land a non-empty prefix, then lose the ack. draw() < 1.0
            # strictly, so k is always in [1, len(items)].
            k = 1 + int(s.draw() * len(items))
            self._record("batch_ack_loss", "write_batch", first)
            _CHAOS_ACK_LOSS.inc()
            self.inner.write_batch(items[:k], overwrite=overwrite)
            raise ChaosError(
                f"chaos[{s.seed}]: batch ack lost after {k}/{len(items)} "
                f"members landed: {first}")
        self.inner.write_batch(items, overwrite=overwrite)

    def list_from(self, path: str) -> Iterator[FileStatus]:
        self._perturb("list_from", path)
        entries = list(self.inner.list_from(path))
        s = self.schedule
        if (self.enabled and s.stale_list_rate and entries
                and self.path_filter(path)
                and s.draw() < s.stale_list_rate):
            drop = s.draw_stale_drop(len(entries))
            # A lagging listing hides recent tail entries; it must not
            # hide the table itself. Shrink the drop until at least one
            # commit .json stays visible (else readers conclude the
            # table does not exist — unrecoverable, not merely stale).
            def _has_commit(es):
                return any(e.path.endswith(".json") for e in es)
            while (drop and _has_commit(entries)
                   and not _has_commit(entries[:len(entries) - drop])):
                drop -= 1
            if drop:
                self._record("stale_list", "list_from", path)
                _CHAOS_STALE.inc()
                entries = entries[:-drop]
        return iter(entries)

    def list_dir(self, path: str) -> List[FileStatus]:
        self._perturb("list_dir", path)
        return self.inner.list_dir(path)

    def exists(self, path: str) -> bool:
        self._perturb("exists", path)
        return self.inner.exists(path)

    def file_status(self, path: str) -> FileStatus:
        self._perturb("file_status", path)
        return self.inner.file_status(path)

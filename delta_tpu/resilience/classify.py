"""Transient-vs-permanent exception classification.

One classifier for the whole engine so every retry site agrees on what
is worth retrying. The split mirrors the reference's treatment of
storage `CommitFailedException(retryable=...)` and the Hadoop FS
retry policies:

- **transient** — the operation may succeed if simply repeated:
  network blips (`ConnectionError`, `TimeoutError`, generic
  `OSError`), HTTP 408/429/5xx responses, DynamoDB throttling and
  5xx, and `DeltaError`s whose raise site marked them ``retryable``.
- **permanent** — repeating cannot help: protocol signals
  (`FileNotFoundError`, `FileExistsError` — put-if-absent losses must
  surface to the conflict machinery, never be swallowed by a retry
  loop), permission errors, corruption (`pyarrow` decode failures,
  `LogCorruptedError`), and every other `DeltaError`.

Classification is structural (types + attributes), with the error
catalog consulted for `DeltaError` subclasses so a class-level policy
can be kept in one place.
"""

from __future__ import annotations

TRANSIENT = "transient"
PERMANENT = "permanent"

# HTTP statuses worth retrying: request timeout, throttling, and
# server-side failures. 501 (Not Implemented) is deliberately excluded.
_RETRYABLE_HTTP = frozenset({408, 429, 500, 502, 503, 504})

# DynamoDB error types that are throttling/availability, not caller bugs.
_RETRYABLE_DDB_TYPES = frozenset({
    "ProvisionedThroughputExceededException",
    "ThrottlingException",
    "RequestLimitExceeded",
    "InternalServerError",
    "ServiceUnavailable",
    "TransactionConflictException",
    "LimitExceededException",
})

# DeltaError catalog classes that are safe to retry. Almost empty by
# design: DeltaErrors encode logical outcomes (conflicts, corruption,
# unsupported features) that retrying at the IO layer would only mask —
# retryable commit failures carry an explicit ``retryable`` attribute
# instead. The one opt-in is the serve layer's admission rejection: a
# shed request did no work at all, and backing off + retrying (per its
# ``retry_after_ms`` hint) is precisely the documented contract.
# DELTA_DEADLINE_EXCEEDED stays permanent: an expired budget cannot be
# retried into existence.
_RETRYABLE_ERROR_CLASSES = frozenset({"DELTA_SERVICE_OVERLOADED"})

# OSError subclasses that are protocol signals or caller bugs, never
# network weather.
_PERMANENT_OSERRORS = (
    FileNotFoundError,
    FileExistsError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
)


class StorageRequestError(IOError):
    """An HTTP storage request failed with a status code.

    Cloud clients raise this instead of a bare ``IOError`` so the
    classifier can discriminate 5xx/429 (transient) from 4xx
    (permanent) without parsing message text.
    """

    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = int(status)


def classify(exc: BaseException) -> str:
    """Return :data:`TRANSIENT` or :data:`PERMANENT` for ``exc``."""
    return TRANSIENT if is_transient(exc) else PERMANENT


def is_transient(exc: BaseException) -> bool:
    # Explicit override wins: anything carrying retryable=True was
    # classified at the raise site (CommitFailedError /
    # CommitFailedException both use this spelling). One carve-out:
    # a coordinator commit CONFLICT is a protocol answer — the version
    # was taken — exactly like FileExistsError on the logstore path.
    # It must surface to the conflict machinery immediately, never be
    # absorbed by an IO retry loop re-attempting the same version
    # (coordinators mark conflicts retryable=True meaning "retry at a
    # NEW version", which is the txn layer's job, not the policy's).
    retryable = getattr(exc, "retryable", None)
    if retryable is not None:
        if getattr(exc, "conflict", False):
            return False
        return bool(retryable)

    from delta_tpu.errors import DeltaError

    if isinstance(exc, DeltaError):
        return exc.error_class in _RETRYABLE_ERROR_CLASSES

    status = getattr(exc, "status", None)
    try:
        status = int(status) if status is not None else None
    except (TypeError, ValueError):
        status = None

    error_type = getattr(exc, "error_type", None)
    if error_type is not None:
        # DynamoDbError shape: .error_type + .status
        return error_type in _RETRYABLE_DDB_TYPES or (status or 0) >= 500
    if isinstance(exc, StorageRequestError):
        # status 0 means the transport itself failed (connection reset,
        # DNS) before any HTTP status arrived — retryable.
        return exc.status in _RETRYABLE_HTTP or exc.status == 0
    if status is not None and isinstance(exc, IOError):
        return status in _RETRYABLE_HTTP or status >= 500

    if isinstance(exc, _PERMANENT_OSERRORS):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    if isinstance(exc, OSError):
        # Bare OSError/IOError from sockets, HTTP stacks, and flaky
        # filesystems: retryable by default. Specific permanent shapes
        # were excluded above.
        return True
    return False

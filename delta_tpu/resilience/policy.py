"""RetryPolicy: exponential backoff with decorrelated jitter.

One policy object shared by every storage-facing layer, replacing the
ad-hoc loops that used to live at each call site. Semantics:

- attempt 1 runs immediately; only exceptions classified *transient*
  (:func:`delta_tpu.resilience.classify.is_transient`) are retried —
  permanent errors re-raise untouched on the first attempt, so
  protocol signals like `FileAlreadyExistsError` keep their exact
  meaning.
- sleep between attempts follows decorrelated jitter
  (`sleep = min(cap, uniform(base, 3 * prev_sleep))`), which avoids
  the synchronized herds plain exponential backoff produces.
- two budgets bound the loop: an attempt cap and a wall-clock
  deadline. Whichever exhausts first re-raises the last error.

Telemetry: each retry increments ``storage.retry.attempts`` and total
sleep is both counted (``storage.retry.sleep_ns``) and attributed to
the enclosing delta-trace span (``retry_sleep_ms`` attribute +
per-retry events), so a slow cold load shows *where* the time went.

Environment knobs (read by :meth:`RetryPolicy.from_env`):

========================================  =======  =========================
``DELTA_TPU_RETRY_MAX_ATTEMPTS``          5        total attempts, >= 1
``DELTA_TPU_RETRY_BASE_MS``               50       first-sleep lower bound
``DELTA_TPU_RETRY_CAP_MS``                5000     per-sleep upper bound
``DELTA_TPU_RETRY_DEADLINE_S``            60       wall-clock budget
========================================  =======  =========================
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, Optional, TypeVar

from delta_tpu import obs
from delta_tpu.errors import CircuitOpenError, DeadlineExceededError
from delta_tpu.resilience.classify import is_transient
from delta_tpu.resilience.deadline import check_deadline, remaining

T = TypeVar("T")

_RETRY_ATTEMPTS = obs.counter("storage.retry.attempts")
_RETRY_SLEEP_NS = obs.counter("storage.retry.sleep_ns")
_RETRY_EXHAUSTED = obs.counter("storage.retry.exhausted")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class RetryPolicy:
    """Immutable retry configuration plus the retry loop itself.

    ``sleep``/``clock``/``rng`` are injectable for deterministic tests;
    production call sites never pass them.
    """

    __slots__ = ("max_attempts", "base_s", "cap_s", "deadline_s",
                 "_sleep", "_clock", "_rng")

    def __init__(self, max_attempts: int = 5, base_s: float = 0.05,
                 cap_s: float = 5.0, deadline_s: float = 60.0,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        self.max_attempts = max(1, int(max_attempts))
        self.base_s = max(0.0, float(base_s))
        self.cap_s = max(self.base_s, float(cap_s))
        self.deadline_s = float(deadline_s)
        self._sleep = sleep
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()

    @classmethod
    def from_env(cls, **overrides) -> "RetryPolicy":
        kw = {
            "max_attempts": int(_env_float("DELTA_TPU_RETRY_MAX_ATTEMPTS", 5)),
            "base_s": _env_float("DELTA_TPU_RETRY_BASE_MS", 50.0) / 1000.0,
            "cap_s": _env_float("DELTA_TPU_RETRY_CAP_MS", 5000.0) / 1000.0,
            "deadline_s": _env_float("DELTA_TPU_RETRY_DEADLINE_S", 60.0),
        }
        kw.update(overrides)
        return cls(**kw)

    def with_overrides(self, **overrides) -> "RetryPolicy":
        kw = {
            "max_attempts": self.max_attempts,
            "base_s": self.base_s,
            "cap_s": self.cap_s,
            "deadline_s": self.deadline_s,
            "sleep": self._sleep,
            "clock": self._clock,
            "rng": self._rng,
        }
        kw.update(overrides)
        return RetryPolicy(**kw)

    def call(self, fn: Callable[[], T], *,
             breaker=None,
             classify: Callable[[BaseException], bool] = is_transient,
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             ) -> T:
        """Run ``fn`` under this policy.

        ``breaker`` (a :class:`CircuitBreaker`) is consulted before
        every attempt and told about each outcome; an open breaker
        raises `CircuitOpenError` without invoking ``fn``. Only
        transient failures count against the breaker — a permanent
        error like `FileNotFoundError` is an *answer* from the
        endpoint, so it reports success (crucially, that releases a
        half-open probe: a 404 probe must close the circuit, not wedge
        it). A `CircuitOpenError` surfacing from a nested call is
        neither — nobody answered — and leaves the breaker untouched.

        ``on_retry(attempt, exc)`` fires before each backoff sleep —
        call sites use it to keep bespoke counters (e.g. the GCS
        arbiter's fix-retry count) without owning the loop.

        An ambient request deadline
        (:mod:`delta_tpu.resilience.deadline`) is honoured at every
        attempt boundary: an already-expired budget raises
        `DeadlineExceededError` without invoking ``fn`` (and without
        touching the breaker — nobody answered), and the retry loop's
        wall-clock budget is clamped to it. This is what makes every
        storage hop an abandonment point for the serve layer.
        """
        check_deadline("storage call")
        if breaker is not None:
            breaker.before_call()
        try:
            result = fn()
        except BaseException as e:
            if not classify(e):
                if breaker is not None and \
                        not isinstance(e, (CircuitOpenError,
                                           DeadlineExceededError)):
                    breaker.on_success()
                raise
            if breaker is not None:
                breaker.on_failure()
            return self._retry_slow_path(fn, e, breaker, classify, on_retry)
        if breaker is not None:
            breaker.on_success()
        return result

    # Kept off the fast path: the code above is all a fault-free call
    # ever executes.
    def _retry_slow_path(self, fn, first_exc, breaker, classify, on_retry):
        start = self._clock()
        deadline = start + self.deadline_s
        # clamp to the ambient request deadline: the retry loop must
        # never sleep past the moment the client stops caring. Measured
        # as a remaining budget so injected test clocks stay coherent.
        ambient_rem = remaining()
        if ambient_rem is not None:
            deadline = min(deadline, start + max(0.0, ambient_rem))
        exc = first_exc
        prev_sleep = self.base_s
        total_sleep_ns = 0
        attempt = 1
        while True:
            if attempt >= self.max_attempts or self._clock() >= deadline:
                _RETRY_EXHAUSTED.inc()
                obs.add_event("retry.exhausted", attempts=attempt,
                              error=type(exc).__name__)
                if ambient_rem is not None and self._clock() >= \
                        start + max(0.0, ambient_rem):
                    # the *request's* budget (not the policy's) ran out:
                    # surface the typed abandonment signal, chaining the
                    # fault that was being retried
                    raise DeadlineExceededError(
                        f"request deadline exceeded after {attempt} "
                        f"attempt(s); last error: "
                        f"{type(exc).__name__}: {exc}") from exc
                raise exc
            if on_retry is not None:
                on_retry(attempt, exc)
            # Decorrelated jitter, clipped to both the per-sleep cap and
            # the remaining deadline budget.
            delay = min(self.cap_s,
                        self._rng.uniform(self.base_s, prev_sleep * 3.0))
            delay = min(delay, max(0.0, deadline - self._clock()))
            prev_sleep = max(delay, self.base_s)
            _RETRY_ATTEMPTS.inc()
            obs.add_event("retry", attempt=attempt,
                          error=type(exc).__name__, sleep_ms=delay * 1e3)
            if delay > 0:
                self._sleep(delay)
                total_sleep_ns += int(delay * 1e9)
                _RETRY_SLEEP_NS.inc(int(delay * 1e9))
            attempt += 1
            if breaker is not None:
                breaker.before_call()
            try:
                result = fn()
            except BaseException as e:
                if not classify(e):
                    # the endpoint answered (see call()); release any probe
                    if breaker is not None and \
                            not isinstance(e, CircuitOpenError):
                        breaker.on_success()
                    raise
                if breaker is not None:
                    breaker.on_failure()
                exc = e
                continue
            if breaker is not None:
                breaker.on_success()
            obs.set_attrs(retry_attempts=attempt - 1,
                          retry_sleep_ms=total_sleep_ns / 1e6)
            return result

"""Device-route failure absorption: shed-and-retry + classify-and-fall-back.

Every gated device route (`replay`/`parse`/`decode`/`skip`/`sql`) has a
host twin, so a failed device dispatch is never fatal — but the
fallback must be *disciplined*: the exception is classified through
`resilience/classify.py`, the verdict feeds the route's circuit breaker
(`parallel/gate.py::route_failed`), the route's cataloged fallback
counter is bumped, and only then does the host twin run. The
retry-discipline lint pass enforces this shape at every
`device_dispatch` call site.

The canonical consumer-site pattern::

    from delta_tpu.resilience import device_faults
    from delta_tpu.parallel import gate as gate_mod

    try:
        out = device_faults.shed_retry("replay", run_device)
        gate_mod.route_ok("replay")
    except Exception as e:
        if not device_faults.absorb_route_failure("replay", e):
            raise                      # permanent: the error is an answer
        _FALLBACKS.inc()
        obs.gate_fell_back("replay", "host",
                           reason=f"device-error:{type(e).__name__}")
        with obs.gate_observation("replay", "host"):
            out = run_host()

:func:`shed_retry` implements HBM-pressure shed-and-retry: on an
allocation failure (``RESOURCE_EXHAUSTED``) it asks the resident ledger
(`obs/hbm.py`) to evict the cheapest-to-rebuild artifacts and retries
the dispatch exactly once; a second failure — or nothing sheddable —
propagates to the absorption path and the host twin takes over.
"""

from __future__ import annotations

from typing import Callable, TypeVar

from delta_tpu import obs

T = TypeVar("T")

_SHED_RETRIES = obs.counter("hbm.shed_retries")

# Allocation-failure shapes: real XLA allocator errors carry
# RESOURCE_EXHAUSTED in their message (jaxlib raises XlaRuntimeError,
# whose *type* varies across jaxlib versions — match text, not type);
# the injected twin (device_chaos.DeviceResourceExhaustedError) uses
# the same marker on purpose.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def is_resource_exhausted(exc: BaseException) -> bool:
    """True when the exception looks like a device allocation failure."""
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def shed_retry(gate: str, fn: Callable[[], T]) -> T:
    """Run one device-route thunk with HBM-pressure shed-and-retry.

    On an allocation failure, ask the resident ledger to shed the
    cheapest-to-rebuild artifacts and retry ``fn`` once; any other
    exception — and a retry that fails again — propagates to the
    caller's absorption handler. The retry is observable: it bumps
    ``hbm.shed_retries`` and the ledger's shed counters."""
    try:
        return fn()
    except Exception as exc:
        if not is_resource_exhausted(exc):
            raise
        from delta_tpu.obs import hbm
        n, _freed = hbm.shed()
        if not n:
            raise
        _SHED_RETRIES.inc()
        obs.add_event("device.shed_retry", gate=gate, evicted=n)
        return fn()


def absorb_route_failure(gate: str, exc: BaseException) -> bool:
    """Classify one device-route failure and feed the route breaker.

    Returns True for transient verdicts — the caller bumps its fallback
    counter and runs the host twin; False for permanent ones — the
    caller re-raises (real corruption or a genuine bug must surface,
    not be silently recomputed on the host)."""
    from delta_tpu.parallel.gate import route_failed
    from delta_tpu.resilience.classify import TRANSIENT
    return route_failed(gate, exc) == TRANSIENT

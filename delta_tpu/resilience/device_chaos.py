"""Seeded device-fault injection at the dispatch funnel (chaos plane).

`ChaosStore` hammers the LogStore; this module is its device-side twin.
A :class:`ChaosEngine` arms at the ``obs/device.py::device_dispatch()``
funnel seam, so every jit/shard_map launch in ``parallel/``, ``ops/``
and ``sqlengine/device.py`` is injectable without touching a single
kernel. The fault model covers the device failure modes the paper's
architecture inherits:

- **dispatch errors** — the launch raises (:class:`DeviceChaosError`,
  classified transient via its ``retryable`` attribute);
- **allocation failures** — simulated ``RESOURCE_EXHAUSTED``
  (:class:`DeviceResourceExhaustedError`), the trigger for the resident
  ledger's shed-and-retry path (`resilience/device_faults.py`);
- **transfer stalls** — a bounded sleep before the launch, modeling a
  degraded interconnect;
- **recompile storms** — shape-key perturbation: the dispatch's compile
  key is salted so device obs sees a novel key per injection, driving
  the `device.recompile_storms` alarm without recompiling anything.

All draws come from one seeded ``random.Random`` held under a lock, so
any observed failure schedule is replayable bit-for-bit from the seed
(``fault_log`` records every injection in order). Faults raised here
propagate out of the ``with device_dispatch(...)`` statement at the
call site and are indistinguishable from a real launch failure — the
route's absorption path (classify → breaker → host twin) is what's
under test, never the kernel.

Arming::

    from delta_tpu.resilience.device_chaos import (
        ChaosEngine, DeviceChaosSchedule)

    eng = ChaosEngine(DeviceChaosSchedule(seed=7, dispatch_error_rate=0.1))
    with eng:                       # arm()/disarm() also work
        run_workload()
    assert eng.fault_log == replay_same_seed()

Env arming (captured into bench conditions): ``DELTA_TPU_DEVICE_CHAOS``
is ``off`` (default) or an integer seed; ``DELTA_TPU_DEVICE_CHAOS_RATE``
sets the per-dispatch rate for every enabled kind (default 0.05);
``DELTA_TPU_DEVICE_CHAOS_KINDS`` is a comma list drawn from
``error,oom,stall,recompile`` (default all).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Tuple

from delta_tpu import obs

KIND_ERROR = "error"
KIND_OOM = "oom"
KIND_STALL = "stall"
KIND_RECOMPILE = "recompile"
ALL_KINDS = (KIND_ERROR, KIND_OOM, KIND_STALL, KIND_RECOMPILE)

_DEVICE_FAULTS = obs.counter("chaos.device_faults")


class DeviceChaosError(RuntimeError):
    """Injected dispatch failure; transient by construction."""

    # resilience/classify.py checks this attribute first: injected
    # faults must classify transient so absorption paths fall back to
    # the host twin instead of propagating.
    retryable = True


class DeviceResourceExhaustedError(DeviceChaosError):
    """Injected allocation failure shaped like an XLA allocator error.

    The message carries ``RESOURCE_EXHAUSTED`` because that marker —
    not the type — is what `device_faults.is_resource_exhausted`
    matches, the same way real XlaRuntimeError text is matched.
    """

    def __init__(self, kernel: str):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected allocation failure while "
            f"dispatching {kernel} (simulated out-of-HBM)")


class DeviceChaosSchedule:
    """Seeded fault schedule: one RNG, drawn under a lock.

    Rates are per-dispatch probabilities evaluated independently per
    kind, in a fixed order (stall, recompile, oom, error), so the draw
    sequence — and therefore the whole fault schedule — is a pure
    function of the seed and the dispatch sequence.
    """

    def __init__(self, seed: int = 0, *,
                 dispatch_error_rate: float = 0.0,
                 oom_rate: float = 0.0,
                 stall_rate: float = 0.0,
                 stall_s: Tuple[float, float] = (0.0002, 0.002),
                 recompile_rate: float = 0.0):
        import random
        self.seed = int(seed)
        self.dispatch_error_rate = float(dispatch_error_rate)
        self.oom_rate = float(oom_rate)
        self.stall_rate = float(stall_rate)
        self.stall_s = (float(stall_s[0]), float(stall_s[1]))
        self.recompile_rate = float(recompile_rate)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()

    def draw(self) -> float:
        with self._lock:
            return self._rng.random()

    def draw_stall(self) -> float:
        lo, hi = self.stall_s
        with self._lock:
            return lo + (hi - lo) * self._rng.random()

    def draw_key_salt(self) -> int:
        with self._lock:
            return self._rng.getrandbits(32)


class ChaosEngine:
    """Device-fault injector armed at the dispatch funnel.

    ``kernel_filter`` (kernel name -> bool) scopes injection to a
    subset of kernels; ``sleep`` is swappable so tests can run stall
    schedules without wall-clock cost. ``fault_log`` records
    ``(kind, kernel, gate)`` tuples in injection order — two runs with
    the same seed and workload produce identical logs, which is the
    replayability contract the soak asserts.
    """

    def __init__(self, schedule: DeviceChaosSchedule, *,
                 kernel_filter: Optional[Callable[[str], bool]] = None,
                 sleep: Optional[Callable[[float], None]] = None):
        import time
        self.schedule = schedule
        self.kernel_filter = kernel_filter
        self.enabled = True
        self.fault_log: List[Tuple[str, str, Optional[str]]] = []
        self.fault_counts = {k: 0 for k in ALL_KINDS}
        self._sleep = sleep if sleep is not None else time.sleep
        self._log_lock = threading.Lock()

    def _record(self, kind: str, kernel: str, gate: Optional[str]) -> None:
        with self._log_lock:
            self.fault_log.append((kind, kernel, gate))
            self.fault_counts[kind] += 1
        _DEVICE_FAULTS.inc()

    @property
    def total_faults(self) -> int:
        return len(self.fault_log)

    def on_dispatch(self, name: str, *, key=None,
                    gate: Optional[str] = None, route: str = "device"):
        """Funnel hook: perturb (or fail) one dispatch; returns the
        possibly-salted compile key. Raising here surfaces at the call
        site's ``with device_dispatch(...)`` statement."""
        if not self.enabled:
            return key
        if self.kernel_filter is not None and not self.kernel_filter(name):
            return key
        s = self.schedule
        if s.stall_rate and s.draw() < s.stall_rate:
            self._record(KIND_STALL, name, gate)
            self._sleep(s.draw_stall())
        if (s.recompile_rate and key is not None
                and s.draw() < s.recompile_rate):
            self._record(KIND_RECOMPILE, name, gate)
            # a salted key is a first sighting for device obs: it counts
            # a compile and, past the alarm threshold, a recompile storm
            # — shape churn simulated without touching the jit cache
            key = (key, "chaos-recompile", s.draw_key_salt())
        if s.oom_rate and s.draw() < s.oom_rate:
            self._record(KIND_OOM, name, gate)
            raise DeviceResourceExhaustedError(name)
        if s.dispatch_error_rate and s.draw() < s.dispatch_error_rate:
            self._record(KIND_ERROR, name, gate)
            raise DeviceChaosError(
                f"injected dispatch failure: {name} (gate={gate}, "
                f"route={route})")
        return key

    def arm(self) -> None:
        from delta_tpu.obs import device as obs_device
        obs_device.set_dispatch_chaos(self)

    def disarm(self) -> None:
        from delta_tpu.obs import device as obs_device
        obs_device.set_dispatch_chaos(None)

    def __enter__(self) -> "ChaosEngine":
        self.arm()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.disarm()
        return False


def engine_from_env() -> Optional[ChaosEngine]:
    """Build an engine from ``DELTA_TPU_DEVICE_CHAOS*`` knobs, or None
    when unarmed. Call sites (bench, ad-hoc soaks) arm it explicitly —
    importing this module never injects anything."""
    raw = os.environ.get("DELTA_TPU_DEVICE_CHAOS", "off").strip().lower()
    if raw in ("", "off", "0", "false", "no"):
        return None
    try:
        seed = int(raw)
    except ValueError:
        seed = 0
    rate = float(os.environ.get("DELTA_TPU_DEVICE_CHAOS_RATE", "0.05"))
    kinds_raw = os.environ.get("DELTA_TPU_DEVICE_CHAOS_KINDS", "")
    kinds = {k.strip() for k in kinds_raw.split(",") if k.strip()} or set(
        ALL_KINDS)
    sched = DeviceChaosSchedule(
        seed,
        dispatch_error_rate=rate if KIND_ERROR in kinds else 0.0,
        oom_rate=rate if KIND_OOM in kinds else 0.0,
        stall_rate=rate if KIND_STALL in kinds else 0.0,
        recompile_rate=rate if KIND_RECOMPILE in kinds else 0.0)
    return ChaosEngine(sched)

"""Per-endpoint circuit breaker: closed → open → half-open.

When an endpoint (one storage scheme/host) fails repeatedly, retrying
every caller serially multiplies the damage — each request burns a
full backoff budget before failing. The breaker converts that into a
fast fail: after ``threshold`` consecutive transient failures the
circuit *opens* and calls raise :class:`CircuitOpenError` immediately.
After ``reset_s`` seconds one *probe* request is let through
(*half-open*); success closes the circuit, failure re-opens it and
restarts the clock.

Only transient failures count — a `FileNotFoundError` is an answer,
not an outage (see `delta_tpu/resilience/classify.py`). The
`RetryPolicy` reports permanent errors as *success*: the endpoint is
reachable and healthy, and a half-open probe that came back 404 must
close the circuit (leaving it probing would brick the endpoint). As a
backstop, a probe whose caller never reports an outcome is reclaimed
after ``reset_s``.

Telemetry: every state transition increments
``storage.breaker.state`` and emits a span event carrying the
endpoint and the new state; opens and probes have their own counters.

Env knobs: ``DELTA_TPU_BREAKER_THRESHOLD`` (default 8 consecutive
failures), ``DELTA_TPU_BREAKER_RESET_S`` (default 10.0).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict

from delta_tpu import obs
from delta_tpu.errors import CircuitOpenError

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CHANGES = obs.counter("storage.breaker.state")
_OPENS = obs.counter("storage.breaker.opens")
_PROBES = obs.counter("storage.breaker.probes")
_FAST_FAILS = obs.counter("storage.breaker.fast_fails")


class CircuitBreaker:
    """One breaker, normally one per endpoint via :func:`breaker_for`.

    The fault-free path reads ``self._state`` without taking the lock
    (attribute reads are atomic under the GIL); the lock guards only
    failure accounting and transitions.
    """

    def __init__(self, name: str, threshold: int = 8,
                 reset_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.threshold = max(1, int(threshold))
        self.reset_s = float(reset_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._probe_started = 0.0

    @property
    def state(self) -> str:
        return self._state

    def before_call(self) -> None:
        """Gate an attempt. Raises :class:`CircuitOpenError` when open,
        except for the single probe allowed once ``reset_s`` elapsed."""
        if self._state == CLOSED:
            return
        with self._lock:
            if self._state == CLOSED:
                return
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.reset_s:
                    self._transition(HALF_OPEN)
                else:
                    _FAST_FAILS.inc()
                    raise CircuitOpenError(
                        f"circuit breaker open for endpoint "
                        f"'{self.name}' after {self._failures} "
                        f"consecutive failures",
                        endpoint=self.name)
            if self._state == HALF_OPEN:
                if self._probing and \
                        self._clock() - self._probe_started < self.reset_s:
                    _FAST_FAILS.inc()
                    raise CircuitOpenError(
                        f"circuit breaker half-open for endpoint "
                        f"'{self.name}'; probe in flight",
                        endpoint=self.name)
                # no probe in flight — or the previous one went stale
                # (its caller died without reporting an outcome after a
                # full reset_s): reclaim it rather than wedging the
                # endpoint until process restart.
                self._probing = True
                self._probe_started = self._clock()
                _PROBES.inc()

    def on_success(self) -> None:
        if self._state == CLOSED and self._failures == 0:
            return
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def on_failure(self) -> None:
        """Record one transient failure."""
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN:
                self._probing = False
                self._opened_at = self._clock()
                self._transition(OPEN)
            elif self._state == CLOSED and self._failures >= self.threshold:
                self._opened_at = self._clock()
                _OPENS.inc()
                self._transition(OPEN)

    def snapshot(self) -> dict:
        """Point-in-time introspection view (health endpoints): state,
        consecutive failure count, and — when open — how long until the
        next probe is allowed."""
        with self._lock:
            out = {"state": self._state, "failures": self._failures}
            if self._state == OPEN:
                out["retry_in_s"] = round(max(
                    0.0, self.reset_s - (self._clock() - self._opened_at)), 3)
            if self._state == HALF_OPEN:
                out["probing"] = self._probing
            return out

    # call with self._lock held
    def _transition(self, state: str) -> None:
        self._state = state
        _STATE_CHANGES.inc()
        obs.add_event("breaker.transition", endpoint=self.name, state=state)


_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(endpoint: str) -> CircuitBreaker:
    """The process-wide breaker for an endpoint key
    (``scheme://authority`` from :func:`endpoint_of`, or a logical name
    like ``commit-coordinator``)."""
    b = _breakers.get(endpoint)
    if b is not None:
        return b
    with _breakers_lock:
        b = _breakers.get(endpoint)
        if b is None:
            b = CircuitBreaker(
                endpoint,
                threshold=int(float(
                    os.environ.get("DELTA_TPU_BREAKER_THRESHOLD") or 8)),
                reset_s=float(
                    os.environ.get("DELTA_TPU_BREAKER_RESET_S") or 10.0),
            )
            _breakers[endpoint] = b
    return b


def route_breaker_for(gate: str) -> CircuitBreaker:
    """The process-wide breaker for one device route (``replay``,
    ``parse``, ``decode``, ``skip``, ``sql``).

    Route breakers share the registry under a ``route:`` key prefix, so
    they surface in :func:`breaker_states` (and the serve `/health` op)
    next to storage endpoints and clear with :func:`reset_breakers`.
    They trip faster and re-arm sooner than storage breakers: a poisoned
    device route has a host twin standing by, so degrading early is
    cheap and probing early is safe. Knobs:
    ``DELTA_TPU_ROUTE_BREAKER_THRESHOLD`` (default 4 consecutive
    classified-transient failures), ``DELTA_TPU_ROUTE_BREAKER_RESET_S``
    (default 5.0 seconds to the half-open probe)."""
    key = "route:" + gate
    b = _breakers.get(key)
    if b is not None:
        return b
    with _breakers_lock:
        b = _breakers.get(key)
        if b is None:
            b = CircuitBreaker(
                key,
                threshold=int(float(os.environ.get(
                    "DELTA_TPU_ROUTE_BREAKER_THRESHOLD") or 4)),
                reset_s=float(os.environ.get(
                    "DELTA_TPU_ROUTE_BREAKER_RESET_S") or 5.0),
            )
            _breakers[key] = b
    return b


def breaker_states() -> Dict[str, dict]:
    """Introspection over every live breaker: endpoint ->
    :meth:`CircuitBreaker.snapshot`. The serve `/health` op reports
    this so operators can see which storage buckets are degraded."""
    with _breakers_lock:
        items = list(_breakers.items())
    return {name: b.snapshot() for name, b in items}


def reset_breakers() -> None:
    """Drop all breaker state (tests)."""
    with _breakers_lock:
        _breakers.clear()

"""Ambient request deadlines: contextvar-scoped wall-clock budgets.

The serve layer stamps each admitted request with the client's
``deadline_ms`` budget; everything the request touches — snapshot
load, retry loops, storage hops — must stop the moment that budget is
gone, because finishing work for a client that already timed out only
steals capacity from clients still waiting. A deadline is carried as
an *absolute* ``time.monotonic()`` instant in a :mod:`contextvars`
variable, so it flows through nested calls without threading a
parameter through every signature, and nested scopes can only tighten
it (a callee never outlives its caller's budget).

Integration points:

- :meth:`delta_tpu.resilience.policy.RetryPolicy.call` checks the
  ambient deadline before every attempt and clamps its own wall-clock
  retry budget to it, so every ``io_call`` hop is an abandonment
  point;
- the serve worker pool wraps request execution in
  :func:`deadline_scope` and converts an expired budget into a typed
  :class:`~delta_tpu.errors.DeadlineExceededError` response.

Cross-thread note: contextvars do not flow into threads implicitly.
The serve worker pool re-establishes the scope inside the worker; code
handing work to other threads must do the same (``obs.wrap`` is the
tracing analogue).
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator, Optional

from delta_tpu.errors import DeadlineExceededError

_DEADLINE: "contextvars.ContextVar[Optional[float]]" = \
    contextvars.ContextVar("delta_tpu_deadline", default=None)


@contextlib.contextmanager
def deadline_scope(seconds: Optional[float]) -> Iterator[Optional[float]]:
    """Run the body under a wall-clock budget of ``seconds`` from now.

    ``None`` leaves any enclosing deadline in force (a no-op scope).
    Nesting takes the minimum: an inner scope can shorten the budget
    but never extend past the enclosing one. Yields the absolute
    monotonic deadline in force (or ``None``)."""
    if seconds is None:
        yield _DEADLINE.get()
        return
    target = time.monotonic() + max(0.0, float(seconds))
    outer = _DEADLINE.get()
    if outer is not None:
        target = min(target, outer)
    token = _DEADLINE.set(target)
    try:
        yield target
    finally:
        _DEADLINE.reset(token)


@contextlib.contextmanager
def deadline_scope_at(at: Optional[float]) -> Iterator[Optional[float]]:
    """Like :func:`deadline_scope` but with an absolute
    ``time.monotonic()`` instant — the serve worker re-establishing a
    request's deadline in a different thread uses this."""
    if at is None:
        yield _DEADLINE.get()
        return
    outer = _DEADLINE.get()
    target = at if outer is None else min(at, outer)
    token = _DEADLINE.set(target)
    try:
        yield target
    finally:
        _DEADLINE.reset(token)


def current_deadline() -> Optional[float]:
    """The absolute monotonic deadline in force, or ``None``."""
    return _DEADLINE.get()


def remaining() -> Optional[float]:
    """Seconds left in the ambient budget (may be negative), or
    ``None`` when no deadline is in force."""
    d = _DEADLINE.get()
    return None if d is None else d - time.monotonic()


def expired() -> bool:
    d = _DEADLINE.get()
    return d is not None and time.monotonic() >= d


def check_deadline(what: str = "operation") -> None:
    """Raise :class:`DeadlineExceededError` if the ambient deadline has
    passed. The fast path (no deadline set) is one contextvar read."""
    d = _DEADLINE.get()
    if d is not None and time.monotonic() >= d:
        raise DeadlineExceededError(
            f"deadline exceeded before {what} "
            f"({(time.monotonic() - d) * 1000.0:.0f}ms past budget)")

from delta_tpu.replay.columnar import (
    CANONICAL_FILE_ACTION_SCHEMA,
    ColumnarActions,
    columnarize_log_segment,
)
from delta_tpu.replay.state import SnapshotState, reconstruct_state

__all__ = [
    "CANONICAL_FILE_ACTION_SCHEMA",
    "ColumnarActions",
    "columnarize_log_segment",
    "SnapshotState",
    "reconstruct_state",
]

"""Canonical-table assembly for the device JSON parse route.

`ops/json_parse.py` extracts per-line field lanes (spans, numerics,
flags) from a commit-window byte buffer in one batched device pass;
this module turns those lanes into exactly what the native C++ scanner
produces — the canonical file-actions Arrow table, the `(version,
order, dict)` control rows, and the `NativeReplayKeys` sidecar — so
`replay/columnar.py` and the PR 4 pipeline consume either route
interchangeably.

Fallback ladder (digest parity by construction — the device route only
answers for content it parsed exactly):

1. window ineligible (empty, not newline-terminated, >=2 GiB int32
   span overflow) -> host;
2. structural balance failed anywhere in the window (odd quote count,
   unbalanced/negative brace depth — parity is global, one bad line
   poisons every later mask) -> host, whole window;
3. any file-action line is COMPLEX (deletionVector, tags, non-empty
   partitionValues, unknown keys, duplicate keys, >int64 numerics) ->
   host, whole window;
4. a control line fails json.loads -> host, whole window (same
   contract as the native scanner's `_finish_scan`).

String spans come off the device raw; rows flagged as escaped are
unescaped host-side with a vectorized backslash-run-parity pass
(`_unescape_many`) — only `\\uXXXX` rows drop to per-row json.loads.

Counters: `parse.device_windows` / `parse.device_fallbacks`; each
window runs under a `parse.device_window` span.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa

from delta_tpu import obs
from delta_tpu.replay.native_parse import (
    NativeReplayKeys,
    _bitmap,
    _bool_array,
    _num_array,
    line_tags,
    merge_replay_keys,
)

_OBS_WINDOWS = obs.counter("parse.device_windows")
_OBS_FALLBACKS = obs.counter("parse.device_fallbacks")

# Per-window byte cap for the columnar (non-pipelined) route: bounds
# the kernel's O(bytes) scan intermediates and keeps each H2D inside
# the fast transfer bucket. Windows split at commit boundaries.
_DEFAULT_WINDOW_BYTES = 64 << 20


def window_bytes() -> int:
    env = os.environ.get("DELTA_TPU_DEVICE_PARSE_WINDOW")
    return int(env) if env else _DEFAULT_WINDOW_BYTES


# unescape value for the byte FOLLOWING an escape initiator; 0 marks
# 'u' (\\uXXXX needs real JSON decoding, handled per-row)
_ESC_LUT = np.zeros(256, np.uint8)
for _c, _v in ((34, 34), (92, 92), (47, 47), (98, 8), (102, 12),
               (110, 10), (114, 13), (116, 9)):
    _ESC_LUT[_c] = _v


def _ragged_arange(lens: np.ndarray) -> np.ndarray:
    """[0..l0), [0..l1), ... concatenated."""
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, np.int64)
    offs = np.cumsum(lens) - lens
    return np.arange(total, dtype=np.int64) - np.repeat(offs, lens)


def _unescape_many(win: np.ndarray, starts: np.ndarray,
                   lens: np.ndarray):
    """Unescape many raw JSON-string spans at once.

    Returns (arena uint8, offsets int64 [n+1], exc {row: bytes}) — the
    vectorized pass deletes escape-initiator backslashes and maps the
    following byte through `_ESC_LUT`; rows containing \\uXXXX land in
    `exc` (decoded per-row, possibly multi-byte UTF-8) and their arena
    slice is garbage the caller must override."""
    n = len(lens)
    total = int(lens.sum())
    src = np.repeat(starts, lens) + _ragged_arange(lens)
    raw = win[src]
    row_of = np.repeat(np.arange(n, dtype=np.int64), lens)
    bs = raw == 92
    prev_bs = np.zeros_like(bs)
    prev_bs[1:] = bs[:-1]
    run_start = bs & ~prev_bs
    pos = np.arange(total, dtype=np.int64)
    # a span never ends with an unpaired backslash (it would have
    # escaped its closing quote), so backslash-run parity computed over
    # the concatenation equals per-row parity
    last_rs = np.maximum.accumulate(np.where(run_start, pos, -1))
    initiator = bs & (((pos - last_rs) & 1) == 0)
    follows = np.zeros_like(initiator)
    follows[1:] = initiator[:-1]
    mapped = np.where(follows, _ESC_LUT[raw], raw)
    keep = ~initiator
    out = mapped[keep]
    out_lens = np.bincount(row_of[keep], minlength=n).astype(np.int64)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(out_lens, out=offs[1:])
    exc = {}
    if bool((follows & (raw == 117)).any()):
        for r in np.unique(row_of[follows & (raw == 117)]).tolist():
            s = int(starts[r])
            span = win[s:s + int(lens[r])].tobytes()
            exc[int(r)] = json.loads(b'"' + span + b'"').encode("utf-8")
    return out, offs, exc


def _string_column(win: np.ndarray, starts: np.ndarray,
                   ends: np.ndarray, present: np.ndarray,
                   esc: np.ndarray) -> pa.Array:
    """Assemble one string column from byte spans of `win`: raw rows
    gather in one vectorized pass, escaped rows splice in their
    unescaped bytes."""
    n = len(starts)
    starts64 = starts.astype(np.int64)
    lens = np.where(present, (ends - starts).astype(np.int64), 0)
    esc = esc & present
    er = np.flatnonzero(esc)
    exc: dict = {}
    out_lens = lens.copy()
    if len(er):
        e_arena, e_offs, exc = _unescape_many(win, starts64[er], lens[er])
        e_lens = np.diff(e_offs)
        out_lens[er] = e_lens
        for k, v in exc.items():
            out_lens[er[k]] = len(v)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(out_lens, out=offs[1:])
    arena = np.empty(int(offs[-1]), np.uint8)
    cr = np.flatnonzero(present & ~esc)
    if len(cr):
        ln = lens[cr]
        ra = _ragged_arange(ln)
        arena[np.repeat(offs[cr], ln) + ra] = win[
            np.repeat(starts64[cr], ln) + ra]
    if len(er):
        sel = np.ones(len(er), bool)
        for k in exc:
            sel[k] = False
        sr = np.flatnonzero(sel)
        if len(sr):
            ln = e_lens[sr]
            ra = _ragged_arange(ln)
            arena[np.repeat(offs[er[sr]], ln) + ra] = e_arena[
                np.repeat(e_offs[:-1][sr], ln) + ra]
        for k, v in exc.items():
            r = int(er[k])
            arena[offs[r]:offs[r] + len(v)] = np.frombuffer(v, np.uint8)
    return pa.StringArray.from_buffers(
        n, pa.py_buffer(offs.astype(np.int32)), pa.py_buffer(arena),
        _bitmap(present))


def _empty_map_column(present: np.ndarray) -> pa.Array:
    """partitionValues for simple rows: empty map when the key was
    present (`"partitionValues":{}`), null when absent — the native
    scanner's semantics."""
    n = len(present)
    map_type = pa.map_(pa.string(), pa.string())
    entries_type = map_type.field(0).type
    entries = pa.StructArray.from_arrays(
        [pa.array([], pa.string()), pa.array([], pa.string())],
        fields=[entries_type.field(0), entries_type.field(1)])
    return pa.Array.from_buffers(
        map_type, n,
        [_bitmap(present), pa.py_buffer(np.zeros(n + 1, np.int32))],
        children=[entries])


def _assemble_window(
    win: np.ndarray,
    fields: dict,
    file_starts: np.ndarray,
    file_versions: np.ndarray,
    small_only: bool,
    lazy_stats: bool,
):
    """Field lanes -> (table, others, keys, uniq, stats_thunk), or None
    when a control line fails json.loads."""
    from delta_tpu.replay.columnar import (
        CANONICAL_FILE_ACTION_SCHEMA,
        DV_STRUCT_TYPE,
        _decode_paths,
    )

    filerow = fields["is_add"] | fields["is_remove"]
    ls = fields["line_start"]
    le = fields["line_end"]
    line_versions, line_orders = line_tags(
        ls.astype(np.int64), file_starts, file_versions)

    others: List[Tuple[int, int, dict]] = []
    for ln in np.flatnonzero(~filerow & (le > ls)).tolist():
        raw = win[ls[ln]:le[ln]].tobytes()
        try:
            row = json.loads(raw)
        except ValueError:
            return None  # malformed control line: host path surfaces it
        if not isinstance(row, dict) or "add" in row or "remove" in row:
            # a file action the kernel's compact-form patterns missed
            # (e.g. whitespace between tokens): host parses the window
            return None
        others.append((int(line_versions[ln]), int(line_orders[ln]), row))

    if small_only:
        return (CANONICAL_FILE_ACTION_SCHEMA.empty_table(), others, None,
                None, None)

    rows = np.flatnonzero(filerow)
    n = len(rows)
    versions = line_versions[rows]
    orders = line_orders[rows]

    def lane(name):
        return fields[name][rows]

    path_col = _string_column(win, lane("path_start"), lane("path_end"),
                              np.ones(n, bool), lane("path_esc"))
    enc = path_col.dictionary_encode()
    decoded = _decode_paths(enc.dictionary)
    codes_ok = decoded is enc.dictionary
    path_final = pa.DictionaryArray.from_arrays(
        enc.indices, decoded).cast(pa.string())
    keys = uniq = None
    if codes_ok:
        codes = enc.indices.to_numpy(zero_copy_only=False).astype(
            np.uint32, copy=False)
        # dictionary_encode assigns codes in first-appearance order, so
        # per-code first occurrence gives the dense FA flags directly
        _, first_idx = np.unique(codes, return_index=True)
        path_new = np.zeros(n, bool)
        path_new[first_idx] = True
        keys = NativeReplayKeys(codes, path_new,
                                codes[~path_new].astype(np.uint32),
                                int(len(enc.dictionary)))
        uniq = enc.dictionary

    stats_present = lane("stats_present")
    stats_args = (win, lane("stats_start"), lane("stats_end"),
                  stats_present, lane("stats_esc"))
    stats_thunk = None
    if lazy_stats:
        stats_col = pa.nulls(n, pa.string())

        def stats_thunk(args=stats_args):
            return _string_column(*args)
    else:
        stats_col = _string_column(*stats_args)

    table = pa.table(
        {
            "path": path_final,
            "dv_id": pa.nulls(n, pa.string()),
            "partition_values": _empty_map_column(lane("pv_present")),
            "size": _num_array(
                (lane("size_val"), lane("size_present")), pa.int64()),
            "modification_time": _num_array(
                (lane("mod_time_val"), lane("mod_time_present")),
                pa.int64()),
            "data_change": _bool_array(
                (lane("data_change_val"), lane("data_change_present"))),
            "stats": stats_col,
            "tags": pa.nulls(n, pa.string()),
            "deletion_vector": pa.nulls(n, DV_STRUCT_TYPE),
            "base_row_id": pa.nulls(n, pa.int64()),
            "default_row_commit_version": pa.nulls(n, pa.int64()),
            "clustering_provider": pa.nulls(n, pa.string()),
            "deletion_timestamp": _num_array(
                (lane("del_ts_val"), lane("del_ts_present")), pa.int64()),
            "extended_file_metadata": _bool_array(
                (lane("ext_meta_val"), lane("ext_meta_present"))),
            "is_add": pa.array(lane("is_add")),
            "version": pa.array(versions, pa.int64()),
            "order": pa.array(orders, pa.int32()),
        },
        schema=CANONICAL_FILE_ACTION_SCHEMA,
    )
    return table, others, keys, uniq, stats_thunk


def _parse_one_window(buf, file_starts, file_versions, small_only,
                      lazy_stats):
    """One windowed device parse attempt; None routes to host."""
    from delta_tpu.ops.json_parse import parse_window_fields

    win = np.frombuffer(buf, np.uint8)
    nbytes = int(file_starts[-1]) if len(file_starts) else len(win)
    win = win[:nbytes]
    if nbytes == 0 or win[-1] != 10:
        _OBS_FALLBACKS.inc()
        return None
    n_lines = int(np.count_nonzero(win == 10))
    with obs.span("parse.device_window", bytes=nbytes,
                  lines=n_lines) as sp:
        fields = parse_window_fields(win, n_lines)
        if fields is None:
            _OBS_FALLBACKS.inc()
            sp.set_attrs(fallback="structural")
            return None
        filerow = fields["is_add"] | fields["is_remove"]
        if bool((fields["complex"] & filerow).any()):
            _OBS_FALLBACKS.inc()
            sp.set_attrs(fallback="complex")
            return None
        out = _assemble_window(win, fields, file_starts, file_versions,
                               small_only, lazy_stats)
        if out is None:
            _OBS_FALLBACKS.inc()
            sp.set_attrs(fallback="control-line")
            return None
        _OBS_WINDOWS.inc()
        sp.set_attrs(rows=int(filerow.sum()))
        return out


def parse_window_device(
    buf,
    file_starts: np.ndarray,
    file_versions: np.ndarray,
    lazy_stats: bool = False,
) -> Optional[tuple]:
    """Device parse of ONE pipeline window — the device twin of
    `native_parse.parse_window_native`, same return shape:
    (table, others, keys, uniq, dv_any, stats_thunk) or None."""
    out = _parse_one_window(buf, file_starts, file_versions,
                            small_only=False, lazy_stats=lazy_stats)
    if out is None:
        return None
    table, others, keys, uniq, sthunk = out
    # simple rows cannot carry deletionVector structs -> dv_any False
    return table, others, keys, uniq, False, sthunk


def parse_commits_device(
    buf,
    file_starts: np.ndarray,
    file_versions: np.ndarray,
    small_only: bool = False,
    lazy_stats: bool = True,
) -> Optional[tuple]:
    """Device parse of one concatenated commit buffer — the device twin
    of `native_parse.parse_commits_native`, same return shape: (table,
    others, keys, pending, stats_thunk) or None for the host path.

    The buffer splits at commit boundaries into <=window_bytes() device
    windows (one budgeted H2D each); any window falling back routes the
    WHOLE parse to the host so a single code path owns the result."""
    n_files = len(file_versions)
    if n_files == 0:
        return None
    total = int(file_starts[-1])
    cap = max(1, window_bytes())
    mv = memoryview(buf)
    parts = []
    lo = 0
    while lo < n_files:
        hi = lo + 1
        while (hi < n_files
               and int(file_starts[hi + 1] - file_starts[lo]) <= cap):
            hi += 1
        wbuf = mv[int(file_starts[lo]):int(file_starts[hi])]
        wstarts = file_starts[lo:hi + 1] - file_starts[lo]
        out = _parse_one_window(wbuf, wstarts, file_versions[lo:hi],
                                small_only, lazy_stats)
        if out is None:
            return None
        parts.append(out)
        lo = hi
    if len(parts) == 1:
        table, others, keys, _uniq, sthunk = parts[0]
        return table, others, keys, None, sthunk
    tables = [p[0] for p in parts]
    table = pa.concat_tables(tables)
    others = [r for p in parts for r in p[1]]
    keys = merge_replay_keys(
        [(p[2], p[3], p[0].num_rows) for p in parts])
    thunks = [p[4] for p in parts]
    sthunk = None
    if all(t is not None for t in thunks):
        def sthunk(thunks=thunks):
            return pa.concat_arrays([t() for t in thunks])
    elif any(t is not None for t in thunks):
        # mixed lazy/eager can't combine into one aligned column;
        # materialize now (cheap relative to re-parsing on the host)
        cols: List[pa.Array] = []
        for p in parts:
            cols.append(p[4]() if p[4] is not None
                        else p[0].column("stats").combine_chunks())
        table = table.set_column(
            table.schema.get_field_index("stats"), "stats",
            pa.concat_arrays(cols))
    return table, others, keys, None, sthunk

"""Pipelined snapshot load: overlap storage I/O, parse, and ingest
across chunked windows of the commit log.

The serial product path is phase-sequential — read ALL commit bytes,
then one monolithic parse, then extraction, then device replay — so a
cold load pays storage latency and parse CPU back to back. The
reference hides exactly this behind Spark's task pipeline
(`Snapshot.scala` loadActions is a distributed scan); a single-process
engine has to hide it behind an explicit producer/consumer pipeline,
the same overlap structure a training-input pipeline uses to keep an
accelerator fed.

Structure (two stage threads + the calling thread, bounded queues):

    reader thread   windows the commit list into ~64MB chunks and
                    fills one buffer per window via the shared I/O pool
                    (leaf reads only — never nested pool work)
    parser thread   native scanner (lazy stats) or Arrow read_json per
                    window; both release the GIL and are internally
                    multithreaded, so ONE parser thread saturates
    caller          consumes parsed windows in order (ordered
                    small-action resolution), then merges the
                    per-window replay-key sidecars into one dense
                    first-appearance coding and dispatches the device
                    replay BEFORE the final Arrow concat — the device
                    sorts while the host assembles

Backpressure: both queues are bounded by DELTA_TPU_PIPELINE_DEPTH
(default 2 windows), so at most depth+1 window buffers are resident per
stage boundary. Error propagation: a failing stage forwards its
exception down the queue chain; the consumer re-raises it after setting
the stop event, draining both queues, and joining both threads — no
stage ever blocks on a queue without polling the stop event, so a
mid-window failure can never hang the load or leak a thread.

Env knobs:
  DELTA_TPU_PIPELINE=on|off|force  (default on; off = serial path;
                                    on engages only where overlap can
                                    win — see `profitable`; force
                                    engages everywhere)
  DELTA_TPU_PIPELINE_WINDOW_BYTES  (default 64MB)
  DELTA_TPU_PIPELINE_DEPTH         (default 2 windows per queue)
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from delta_tpu import obs

_WINDOWS = obs.counter("pipeline.windows")
_WINDOW_FALLBACKS = obs.counter("pipeline.window_fallbacks")
_PART_BYTES_PREFETCHED = obs.counter("pipeline.part_bytes_prefetched")
_BYTES_READ = obs.counter("pipeline.bytes_read")
_READ_STALL_NS = obs.counter("pipeline.read_stall_ns")
_PARSE_STALL_NS = obs.counter("pipeline.parse_stall_ns")
_INGEST_STALL_NS = obs.counter("pipeline.ingest_stall_ns")
_READQ_DEPTH = obs.histogram("pipeline.read_queue_depth")
_PARSEQ_DEPTH = obs.histogram("pipeline.parse_queue_depth")
# same instrument as replay/device_parse.py: absorbed device-parse
# exceptions bump the cataloged parse fallback counter at this site
_PARSE_FALLBACKS = obs.counter("parse.device_fallbacks")

_DEFAULT_WINDOW_BYTES = 64 << 20
_DEFAULT_DEPTH = 2
# listing deferred the stat: assume a typical commit size for windowing
# (same nominal value the serial path uses for its compile heuristic)
_NOMINAL_COMMIT_BYTES = 8192
_POLL_S = 0.05
_JOIN_S = 30.0


def enabled() -> bool:
    return os.environ.get("DELTA_TPU_PIPELINE", "on").lower() not in (
        "off", "0", "false", "no")


def forced() -> bool:
    """`DELTA_TPU_PIPELINE=force` engages the pipeline even where the
    profitability gate would prefer the serial path (A/B runs, tests)."""
    return os.environ.get("DELTA_TPU_PIPELINE", "").lower() == "force"


def profitable(engine, commit_infos, allow_native: bool) -> bool:
    """Engage only where overlap can beat the serial path.

    The native direct reader (`scan_commit_files`) already acquires
    LOCAL commit bytes and scans them in one C++ round-trip with no
    interpreter copies — measured strictly faster than windowed
    staging on warm local storage, so the pipeline stands down there.
    It engages when byte acquisition is the bottleneck it can hide:
    any non-local path (object stores, remote mounts — per-file
    latency overlaps with parse), or no native scanner (the generic
    parse is slow enough that windows pipeline against it)."""
    if forced():
        return True
    if not allow_native:
        return True
    os_path = getattr(engine.fs, "os_path", None)
    if os_path is None:
        return True
    return any(os_path(p) is None for _, p, _ in commit_infos)


def window_bytes() -> int:
    try:
        return max(1, int(os.environ.get("DELTA_TPU_PIPELINE_WINDOW_BYTES",
                                         _DEFAULT_WINDOW_BYTES)))
    except ValueError:
        return _DEFAULT_WINDOW_BYTES


def pipeline_depth() -> int:
    try:
        return max(1, int(os.environ.get("DELTA_TPU_PIPELINE_DEPTH",
                                         _DEFAULT_DEPTH)))
    except ValueError:
        return _DEFAULT_DEPTH


def resolve_sizes(
    engine,
    commit_infos: Sequence[Tuple[int, str, int]],
) -> List[Tuple[int, str, int]]:
    """Fill in stat-deferred (-1) sizes so windows split on REAL bytes
    rather than the nominal estimate — but only when every deferred path
    is local, where a stat is microseconds. On remote stores a stat
    round-trip costs as much as the GET it precedes, so deferred sizes
    are left alone: windows split on the nominal estimate and the read
    stage fetches whole blobs without needing sizes up front. A local
    file that fails to stat keeps its -1 — the read stage surfaces the
    proper vanished-commit error (same contract as the serial path)."""
    from delta_tpu.utils.threads import parallel_map

    deferred = [p for _, p, s in commit_infos if int(s) < 0]
    if not deferred:
        return list(commit_infos)
    os_path = getattr(engine.fs, "os_path", None)
    if os_path is None or any(os_path(p) is None for p in deferred):
        return list(commit_infos)

    def stat(info):
        v, p, s = info
        if int(s) >= 0:
            return info
        try:
            return (v, p, engine.fs.file_status(p).size)
        except OSError:
            return info

    return parallel_map(stat, list(commit_infos))


def plan_windows(
    commit_infos: Sequence[Tuple[int, str, int]],
) -> List[List[Tuple[int, str, int]]]:
    """Split (version, path, size) infos into contiguous windows of
    roughly `window_bytes()` listed bytes each (a window always takes
    at least one file)."""
    target = window_bytes()
    wins: List[List[Tuple[int, str, int]]] = []
    cur: List[Tuple[int, str, int]] = []
    cur_bytes = 0
    for info in commit_infos:
        size = int(info[2])
        if size < 0:
            size = _NOMINAL_COMMIT_BYTES
        cur.append(info)
        cur_bytes += size + 1
        if cur_bytes >= target:
            wins.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        wins.append(cur)
    return wins


# ------------------------------------------------------- queue plumbing

_DONE = object()


class _Cancelled(Exception):
    """Internal: the consumer set the stop event; unwind quietly."""


class _StageError:
    """An exception crossing a queue boundary toward the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def _put(q: "queue.Queue", item, stop: threading.Event, stall) -> None:
    # delta-lint: disable=obs-span-leak (audited: stall accounting runs
    # once per queue hand-off inside stage threads — a span here would
    # add a trace node per window per stage; the counter is the right
    # aggregate and the span clock is unaffected)
    t0 = time.perf_counter_ns()
    while True:
        if stop.is_set():
            raise _Cancelled()
        try:
            q.put(item, timeout=_POLL_S)
            break
        except queue.Full:
            continue
    # delta-lint: disable=obs-span-leak (audited: see above)
    stall.inc(time.perf_counter_ns() - t0)


def _get(q: "queue.Queue", stop: threading.Event, stall):
    # delta-lint: disable=obs-span-leak (audited: see _put)
    t0 = time.perf_counter_ns()
    while True:
        if stop.is_set():
            raise _Cancelled()
        try:
            item = q.get(timeout=_POLL_S)
            break
        except queue.Empty:
            continue
    # delta-lint: disable=obs-span-leak (audited: see _put)
    stall.inc(time.perf_counter_ns() - t0)
    return item


def _drain(q: "queue.Queue") -> None:
    while True:
        try:
            q.get_nowait()
        except queue.Empty:
            return


def _offer_error(q: "queue.Queue", exc: BaseException,
                 stop: threading.Event, stall) -> None:
    try:
        _put(q, _StageError(exc), stop, stall)
    except _Cancelled:
        pass  # consumer already unwinding; it drains the queues


# ------------------------------------------------------------- stages


@dataclass
class _Window:
    """Read-stage output: one window's bytes assembled into a single
    newline-terminated buffer (every parser consumes the same layout,
    whether the bytes came from the sized buffered read or from
    per-blob fetches)."""

    index: int
    infos: List[Tuple[int, str, int]]
    buf: bytearray
    starts: np.ndarray
    versions: np.ndarray
    nbytes: int


@dataclass
class _Parsed:
    """Parse-stage output for one window, normalized across the native
    and generic parsers. `keys`/`uniq` are None on the generic path (or
    when percent-decoding collapsed path spellings); `dv_any` is
    conservatively True there too."""

    index: int
    block: pa.Table
    others: List[Tuple[int, int, dict]]
    keys: Optional[object]
    uniq: Optional[pa.Array]
    dv_any: bool
    stats_thunk: Optional[object]
    n_files: int
    nbytes: int


def _assemble_blobs(
    blobs: List[Tuple[int, bytes]],
) -> Tuple[bytearray, np.ndarray, np.ndarray]:
    """Lay per-file blobs out in the same newline-terminated buffer
    format `_read_commits_buffer` produces, so every parser path
    (native scan, Arrow, generic) consumes one layout."""
    sizes = np.fromiter((len(b) for _, b in blobs), np.int64,
                        count=len(blobs))
    starts = np.zeros(len(blobs) + 1, np.int64)
    np.cumsum(sizes + 1, out=starts[1:])
    buf = bytearray(int(starts[-1]))
    mv = memoryview(buf)
    for (_, b), off, sz in zip(blobs, starts[:-1], sizes):
        off = int(off)
        sz = int(sz)
        mv[off:off + sz] = b
        mv[off + sz] = 0x0A
    versions = np.fromiter((v for v, _ in blobs), np.int64,
                           count=len(blobs))
    return buf, starts, versions


def _read_window(engine, index: int,
                 win: List[Tuple[int, str, int]]) -> _Window:
    from delta_tpu.replay.columnar import _read_commits_buffer
    from delta_tpu.utils.threads import parallel_map

    with obs.span("pipeline.read_window", index=index, files=len(win)) as sp:
        read = None
        blob_read = not all(int(s) >= 0 for _, _, s in win)
        if not blob_read:
            read = _read_commits_buffer(engine, win)
            if read is None:
                # a listed size disagreed with the bytes read
                _WINDOW_FALLBACKS.inc()
                blob_read = True
        if read is None:
            # whole-blob fetches (ordered, shared I/O pool) — the
            # planned path for stat-deferred remote windows, the
            # fallback when a sized read mismatched
            blobs = parallel_map(
                lambda vp: (vp[0], engine.fs.read_file(vp[1])),
                [(v, p) for v, p, _ in win])
            read = _assemble_blobs(blobs)
        buf, starts, versions = read
        nbytes = int(starts[-1])
        _BYTES_READ.inc(nbytes)
        sp.set_attrs(bytes=nbytes, blob_read=blob_read)
        return _Window(index, win, buf, starts, versions, nbytes)


def _reader_main(engine, windows, out_q, stop) -> None:
    from delta_tpu.resilience import default_policy

    # Storage ops inside _read_window already retry transients through
    # io_call (shared policy + breaker); stacking the full policy here
    # again would multiply attempts (~max_attempts² per window) and
    # double-count breaker failures. The outer policy only restarts a
    # whole window ONCE, with no sleeps of its own, if the inner budget
    # exhausts mid-window; permanent errors (corruption, missing files)
    # still flow to the consumer via _offer_error for a fail-fast drain
    # + clean join.
    policy = default_policy().with_overrides(max_attempts=2, base_s=0.0,
                                             cap_s=0.0)
    try:
        for i, win in enumerate(windows):
            item = policy.call(lambda: _read_window(engine, i, win))
            _put(out_q, item, stop, _READ_STALL_NS)
        _put(out_q, _DONE, stop, _READ_STALL_NS)
    except _Cancelled:
        pass
    except BaseException as e:
        _offer_error(out_q, e, stop, _READ_STALL_NS)


def _parse_window(w: _Window, allow_native: bool,
                  lazy_stats: bool, allow_device: bool = False) -> _Parsed:
    from delta_tpu.replay import columnar as C

    with obs.span("pipeline.parse_window", index=w.index,
                  files=len(w.infos), bytes=w.nbytes) as sp:
        from delta_tpu.parallel import gate

        if gate.parse_route(w.nbytes, allow_device) == "device":
            from delta_tpu.replay.device_parse import parse_window_device
            from delta_tpu.resilience import device_faults

            fell_reason = "device-parse-unavailable"
            try:
                out = device_faults.shed_retry(
                    "parse",
                    lambda: parse_window_device(w.buf, w.starts,
                                                w.versions,
                                                lazy_stats=lazy_stats))
            except Exception as e:
                # classify (feeds the route breaker); transient -> the
                # host branches below reuse the window buffer
                if not device_faults.absorb_route_failure("parse", e):
                    raise
                _PARSE_FALLBACKS.inc()
                out = None
                fell_reason = f"device-error:{type(e).__name__}"
            if out is not None:
                gate.route_ok("parse")
                table, others, keys, uniq, dv_any, sthunk = out
                sp.set_attrs(rows=table.num_rows, device=True)
                return _Parsed(w.index, table, others, keys, uniq,
                               dv_any, sthunk, len(w.infos), w.nbytes)
            # mid-flight fallback: calibration prices the device attempt
            # PLUS the host parse below against the "device" prediction
            obs.gate_fell_back("parse", "host", reason=fell_reason)
        if allow_native:
            from delta_tpu.replay.native_parse import parse_window_native

            with obs.gate_observation("parse", "host"):
                out = parse_window_native(w.buf, w.starts, w.versions,
                                          lazy_stats=lazy_stats)
            if out is not None:
                table, others, keys, uniq, dv_any, sthunk = out
                sp.set_attrs(rows=table.num_rows, native=True)
                return _Parsed(w.index, table, others, keys, uniq,
                               dv_any, sthunk, len(w.infos), w.nbytes)
        with obs.gate_observation("parse", "host"):
            generic = C._parse_buffer_generic(w.buf, w.starts, w.versions)
            if generic is None:
                # line accounting disagreed; per-file byte extents are
                # exact (verified read or blob assembly), so slicing the
                # buffer back into per-file blobs is equivalent to the
                # serial path's re-read
                mv = memoryview(w.buf)
                blobs = [(int(v), bytes(mv[int(s):int(e) - 1]))
                         for v, s, e in zip(w.versions, w.starts[:-1],
                                            w.starts[1:])]
                generic = C.parse_commit_batch(blobs)
        tbl, versions, orders, _ = generic
        small_rows: List[Tuple[int, int, dict]] = []
        gen_blocks: List[pa.Table] = []
        if tbl is not None:
            small_rows = C._extract_small_rows(tbl, versions, orders)
            for col in ("add", "remove"):
                b = C._extract_file_actions(tbl, col, versions, orders)
                if b is not None:
                    gen_blocks.append(b)
        block = (pa.concat_tables(gen_blocks) if gen_blocks
                 else C.CANONICAL_FILE_ACTION_SCHEMA.empty_table())
        sp.set_attrs(rows=block.num_rows, native=False)
        return _Parsed(w.index, block, small_rows, None, None, True, None,
                       len(w.infos), w.nbytes)


def _parser_main(in_q, out_q, stop, allow_native, lazy_stats,
                 allow_device=False) -> None:
    try:
        while True:
            item = _get(in_q, stop, _PARSE_STALL_NS)
            if item is _DONE or isinstance(item, _StageError):
                _put(out_q, item, stop, _PARSE_STALL_NS)
                return
            parsed = _parse_window(item, allow_native, lazy_stats,
                                   allow_device)
            _put(out_q, parsed, stop, _PARSE_STALL_NS)
    except _Cancelled:
        pass
    except BaseException as e:
        _offer_error(out_q, e, stop, _PARSE_STALL_NS)


# ------------------------------------------------------------ assembly


class _MergedScan:
    """Duck-typed stand-in for a ScanResult over the merged window
    stream — exactly the attributes the early-replay launch closure
    reads (`_columnarize_log_segment`)."""

    __slots__ = ("path_code", "path_new", "refs", "n_uniq", "is_add",
                 "n_rows")

    def __init__(self, keys, is_add: np.ndarray):
        self.path_code = keys.path_code
        self.path_new = keys.path_new
        self.refs = keys.refs
        self.n_uniq = keys.n_uniq
        self.is_add = is_add
        self.n_rows = len(is_add)


def _col_numpy(blocks: List[pa.Table], name: str, dtype) -> np.ndarray:
    out = []
    for b in blocks:
        for ch in b.column(name).chunks:
            out.append(ch.to_numpy(zero_copy_only=False))
    if not out:
        return np.empty(0, dtype)
    return np.concatenate(out)


def parse_commits_pipelined(
    engine,
    windows: List[List[Tuple[int, str, int]]],
    *,
    allow_native: bool,
    lazy_stats: bool,
    launch=None,
    allow_device: bool = False,
):
    """Drive the read → parse → ingest pipeline over `windows` and
    return (ParsedSpan over ALL windows, pending replay handle or None,
    total bytes read). The span is shaped exactly like the serial
    path's fresh span (one consolidated block, merged replay-key
    sidecar, combined stats thunk), so caching and downstream
    consumption are unchanged.

    Exceptions from any stage propagate to the caller after both queues
    drain and both stage threads join."""
    from delta_tpu.replay import columnar as C
    from delta_tpu.replay.native_parse import merge_replay_keys

    depth = pipeline_depth()
    read_q: "queue.Queue" = queue.Queue(maxsize=depth)
    parsed_q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    n_files = sum(len(w) for w in windows)
    with obs.span("pipeline.load", windows=len(windows),
                  files=n_files) as sp:
        # obs.wrap: bind this span as the stage threads' parent (the
        # contextvar stack does not cross thread boundaries)
        reader = threading.Thread(
            target=obs.wrap(_reader_main),
            args=(engine, windows, read_q, stop),
            name="delta-pipeline-read", daemon=True)
        parser = threading.Thread(
            target=obs.wrap(_parser_main),
            args=(read_q, parsed_q, stop, allow_native, lazy_stats,
                  allow_device),
            name="delta-pipeline-parse", daemon=True)
        reader.start()
        parser.start()
        parts: List[_Parsed] = []
        try:
            while True:
                item = _get(parsed_q, stop, _INGEST_STALL_NS)
                _READQ_DEPTH.observe(read_q.qsize())
                _PARSEQ_DEPTH.observe(parsed_q.qsize())
                if item is _DONE:
                    break
                if isinstance(item, _StageError):
                    raise item.exc
                _WINDOWS.inc()
                parts.append(item)
        finally:
            stop.set()
            _drain(read_q)
            _drain(parsed_q)
            reader.join(timeout=_JOIN_S)
            parser.join(timeout=_JOIN_S)

        row_blocks = [p.block for p in parts if p.block.num_rows]
        others = [r for p in parts for r in p.others]
        merged = merge_replay_keys(
            [(p.keys, p.uniq, p.block.num_rows) for p in parts])
        pending = None
        if (merged is not None and launch is not None and row_blocks
                and not any(p.dv_any for p in parts)):
            versions = _col_numpy(row_blocks, "version", np.int64)
            orders = _col_numpy(row_blocks, "order", np.int32)
            is_add = _col_numpy(row_blocks, "is_add", bool)
            # dispatch BEFORE the Arrow concat: the device sorts the
            # merged key stream while the host assembles the table
            pending = launch(_MergedScan(merged, is_add), versions,
                             orders.astype(np.int32, copy=False))
        block = (pa.concat_tables(row_blocks) if row_blocks
                 else C.CANONICAL_FILE_ACTION_SCHEMA.empty_table())
        sthunk = C._combined_stats_thunk(
            [(p.block, p.stats_thunk) for p in parts if p.block.num_rows])
        span = C.ParsedSpan(
            block=block, others=others, keys=merged,
            stats_thunk=C._OnceThunk(sthunk) if sthunk is not None else None,
            n_files=n_files, nbytes=C._span_nbytes(block, others))
        nbytes = sum(p.nbytes for p in parts)
        sp.set_attrs(bytes=nbytes, rows=block.num_rows,
                     merged_keys=merged is not None)
        return span, pending, nbytes


def prefetch_file_bytes(engine, paths: Sequence[str], depth: int = 2):
    """Yield each file's raw bytes in input order with a bounded
    read-ahead on the shared I/O pool, so consuming file i overlaps
    reading file i+1. The device checkpoint page decode consumes part
    BYTES (the one-lane plan builder parses them itself), so the
    engine's parquet-table prefetcher can't serve it — this is the
    byte-level twin of `HostParquetHandler.read_parquet_files`. Reads
    are leaf pool tasks; a cancelled tail never leaks a future."""
    from collections import deque

    from delta_tpu.utils.threads import shared_pool

    paths = list(paths)
    if len(paths) <= 1:
        for p in paths:
            yield engine.fs.read_file(p)
        return
    pool = shared_pool()
    read = obs.wrap(engine.fs.read_file)
    pending: deque = deque()
    i = 0
    try:
        while pending or i < len(paths):
            while i < len(paths) and len(pending) <= depth:
                if pending:
                    _PART_BYTES_PREFETCHED.inc()
                pending.append(pool.submit(read, paths[i]))
                i += 1
            yield pending.popleft().result()
    finally:
        for fut in pending:
            fut.cancel()

"""State reconstruction driver: columnar actions → SnapshotState.

Pipeline (TPU path):
1. Columnarize the log segment (columnar.py) → canonical Arrow table.
2. Dictionary-encode the replay key `(path, dv_id)` into int32 codes
   (exact, vectorized factorization — the host-side equivalent of the
   reference's path canonicalization + hashing at `Snapshot.scala:477-483`).
3. Run the device sort + segmented last-wins reduce (ops.replay) to get
   the live/tombstone masks.
4. Filter the Arrow table by the masks; aggregate numFiles/sizeInBytes.

HostEngine path replaces step 3 with the sequential dict replay — the
faithful re-implementation of `InMemoryLogReplay` used as parity oracle
and baseline.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np
import pandas as pd
import pyarrow as pa

from delta_tpu import obs
from delta_tpu.errors import LogCorruptedError, UnsupportedTableFeatureError
from delta_tpu.models.actions import (
    AddFile,
    CommitInfo,
    DeletionVectorDescriptor,
    DomainMetadata,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
)
from delta_tpu.replay.columnar import ColumnarActions, columnarize_log_segment

# same registry instrument as parallel/resident.py: the cataloged
# fallback counter for the replay route (route-contract lint)
_ROUTE_FALLBACKS = obs.counter("replay.resident_fallbacks")


@dataclass
class SnapshotState:
    version: int
    protocol: Protocol
    metadata: Metadata
    set_transactions: Dict[str, SetTransaction]
    domain_metadata: Dict[str, DomainMetadata]
    file_actions_raw: pa.Table        # canonical schema, all actions; the
                                      # stats column may be a deferred
                                      # placeholder until first
                                      # `file_actions` access
    live_mask: np.ndarray             # bool over file_actions rows
    tombstone_mask: np.ndarray
    latest_commit_info: Optional[CommitInfo] = None
    commit_infos: Dict[int, CommitInfo] = field(default_factory=dict)
    timestamp_ms: int = 0
    # deferred stats decode from the lazy-stats native scan (columnar
    # stats_thunk); spliced exactly once below
    stats_thunk: Optional[object] = None
    # Device-resident sharded replay state (parallel/resident.py):
    # exactly one SnapshotState owns it at a time — `advance_state`
    # moves it to the advanced state (the append kernel donates the
    # device buffer, so the prior owner's reference would be stale)
    resident: Optional[object] = field(default=None, repr=False,
                                       compare=False)
    # Resident scan-planning stats index (stats/device_index.py):
    # built at most once per state under `_stats_index_lock` — a
    # dedicated lock because the build reads `add_files_table`, which
    # takes `_splice_lock` itself. `advance_state` carries it forward
    # on empty deltas and releases it otherwise; serve-cache eviction
    # releases it through `release_snapshot_resident`.
    stats_index: Optional[object] = field(default=None, repr=False,
                                          compare=False)
    # Resident SQL operand cache (sqlengine/operands.py): per-column
    # device lanes for join/group keys, built lazily per state under
    # `_operand_cache_lock`. `advance_state` carries it forward on
    # empty deltas and releases it otherwise; serve-cache eviction
    # releases it through `release_snapshot_resident`.
    operand_cache: Optional[object] = field(default=None, repr=False,
                                            compare=False)
    # Table root this state was reconstructed from — threaded into the
    # HBM resident ledger so lazily built device artifacts (stats-index
    # lanes, replay key lanes grown on advance) attribute to the right
    # table even when built outside a `hbm.table_scope` block.
    table_path: Optional[str] = None

    _add_table_cache: Optional[pa.Table] = None
    _tombstone_table_cache: Optional[pa.Table] = None
    _splice_lock: object = field(default_factory=threading.Lock,
                                 repr=False, compare=False)
    _stats_index_lock: object = field(default_factory=threading.Lock,
                                      repr=False, compare=False)
    _operand_cache_lock: object = field(default_factory=threading.Lock,
                                        repr=False, compare=False)

    @property
    def file_actions(self) -> pa.Table:
        """The complete canonical table. Splices the deferred stats
        column in on first access — stats are ~60% of commit bytes and
        pure metadata loads (num_files/size_in_bytes/replay) never pay
        for decoding them. Locked: two threads' first accesses must not
        both run the thunk."""
        from delta_tpu.replay.columnar import splice_stats

        with self._splice_lock:
            self.file_actions_raw, self.stats_thunk = splice_stats(
                self.file_actions_raw, self.stats_thunk)
            return self.file_actions_raw

    @property
    def add_files_table(self) -> pa.Table:
        """Live files as an Arrow table (canonical schema)."""
        if self._add_table_cache is None:
            self._add_table_cache = self.file_actions.filter(
                pa.array(self.live_mask)
            )
        return self._add_table_cache

    @property
    def tombstones_table(self) -> pa.Table:
        if self._tombstone_table_cache is None:
            self._tombstone_table_cache = self.file_actions.filter(
                pa.array(self.tombstone_mask)
            )
        return self._tombstone_table_cache

    @property
    def num_files(self) -> int:
        return int(self.live_mask.sum())

    @property
    def size_in_bytes(self) -> int:
        # raw access on purpose: aggregates never touch stats, so they
        # must not trigger the deferred decode
        sizes = np.asarray(
            self.file_actions_raw.column("size").fill_null(0),
            dtype=np.int64
        )
        return int(sizes[self.live_mask].sum())

    def visible_domain_metadata(self) -> Dict[str, DomainMetadata]:
        return {k: v for k, v in self.domain_metadata.items() if not v.removed}

    def add_files(self) -> list[AddFile]:
        """Materialize live files as AddFile objects (small results only —
        columnar consumers should use add_files_table)."""
        return [_row_to_add(r) for r in self.add_files_table.to_pylist()]

    def tombstones(self) -> list[RemoveFile]:
        return [_row_to_remove(r) for r in self.tombstones_table.to_pylist()]


def _row_dv(r) -> Optional[DeletionVectorDescriptor]:
    dv = r.get("deletion_vector")
    if dv is None or dv.get("storageType") is None:
        return None
    return DeletionVectorDescriptor(
        storageType=dv["storageType"],
        pathOrInlineDv=dv["pathOrInlineDv"],
        sizeInBytes=dv.get("sizeInBytes") or 0,
        cardinality=dv.get("cardinality") or 0,
        offset=dv.get("offset"),
        maxRowIndex=dv.get("maxRowIndex"),
    )


def _pv_dict(r) -> dict:
    pv = r.get("partition_values")
    if pv is None:
        return {}
    if isinstance(pv, list):  # arrow map -> list of (k, v)
        return {k: v for k, v in pv}
    return dict(pv)


def _row_to_add(r: dict) -> AddFile:
    import json as _json

    return AddFile(
        path=r["path"],
        partitionValues=_pv_dict(r),
        size=r.get("size") or 0,
        modificationTime=r.get("modification_time") or 0,
        dataChange=bool(r.get("data_change", True)),
        stats=r.get("stats"),
        tags=_json.loads(r["tags"]) if r.get("tags") else None,
        deletionVector=_row_dv(r),
        baseRowId=r.get("base_row_id"),
        defaultRowCommitVersion=r.get("default_row_commit_version"),
        clusteringProvider=r.get("clustering_provider"),
    )


def _row_to_remove(r: dict) -> RemoveFile:
    import json as _json

    return RemoveFile(
        path=r["path"],
        deletionTimestamp=r.get("deletion_timestamp"),
        dataChange=bool(r.get("data_change", True)),
        extendedFileMetadata=r.get("extended_file_metadata"),
        partitionValues=_pv_dict(r) or None,
        size=r.get("size"),
        stats=r.get("stats"),
        tags=_json.loads(r["tags"]) if r.get("tags") else None,
        deletionVector=_row_dv(r),
        baseRowId=r.get("base_row_id"),
        defaultRowCommitVersion=r.get("default_row_commit_version"),
    )


def build_replay_keys(file_actions: pa.Table) -> tuple[np.ndarray, np.ndarray]:
    """Dictionary-encode (path, dv_id) into two int32 code arrays.

    pd.factorize is exact (no collisions) and C-vectorized; null dv_id
    maps to code 0, real ids to 1+code."""
    paths = file_actions.column("path").combine_chunks()
    path_codes, _ = pd.factorize(paths.to_pandas(), sort=False)
    dv = file_actions.column("dv_id").combine_chunks()
    if dv.null_count == len(dv):
        dv_codes = np.zeros(len(dv), dtype=np.int64)
    else:
        codes, _ = pd.factorize(dv.to_pandas(), sort=False, use_na_sentinel=True)
        dv_codes = codes + 1  # NaN sentinel -1 -> 0
    return path_codes.astype(np.uint32), dv_codes.astype(np.uint32)


def _dv_codes_only(file_actions: pa.Table) -> np.ndarray:
    """dv_id lane codes (0 = no DV) without touching the path column."""
    dv = file_actions.column("dv_id").combine_chunks()
    if dv.null_count == len(dv):
        return np.zeros(len(dv), dtype=np.uint32)
    codes, _ = pd.factorize(dv.to_pandas(), sort=False, use_na_sentinel=True)
    return (codes + 1).astype(np.uint32)


# beyond this many file actions, one-shot device replay would need
# multi-GB HBM headroom for the sort; stream blocks instead
BLOCKWISE_MIN_ROWS = 32_000_000


def _replay_host_twin(columnar: ColumnarActions,
                      exc: Exception) -> tuple[np.ndarray, np.ndarray]:
    """Fallback bookkeeping + host replay after an absorbed (already
    classified transient) device failure: bump the cataloged fallback
    counter and run the host twin under the calibration join."""
    _ROUTE_FALLBACKS.inc()
    obs.gate_fell_back("replay", "host",
                       reason=f"device-error:{type(exc).__name__}")
    with obs.gate_observation("replay", "host"):
        return compute_masks_host(columnar)


def compute_masks_device(
    columnar: ColumnarActions, engine=None
) -> tuple[np.ndarray, np.ndarray]:
    from delta_tpu.ops.replay import replay_select
    from delta_tpu.parallel import gate
    from delta_tpu.resilience import device_faults

    fa = columnar.file_actions
    n = fa.num_rows
    if n == 0:
        z = np.zeros(0, bool)
        return z, z
    pending = columnar.pending_masks
    if pending is not None:
        # device replay was dispatched during columnarization (overlapped
        # with the Arrow assembly) — just collect the masks; a failed
        # overlapped dispatch degrades to the host twin like any other
        try:
            out = device_faults.shed_retry("replay", pending.finish)
        except Exception as e:
            # classify (feeds the route breaker); permanent -> re-raise
            if not device_faults.absorb_route_failure("replay", e):
                raise
            return _replay_host_twin(columnar, e)
        gate.route_ok("replay")
        return out
    keys = columnar.replay_keys
    fa_hint = None
    if keys is not None and len(keys.path_code) == n:
        # the native scanner already dictionary-coded the paths in
        # first-appearance order and emitted the delta encoding — skip
        # the factorize pass entirely
        path_codes = keys.path_code
        dv_codes = _dv_codes_only(fa)
        fa_hint = (keys.path_new, keys.refs, keys.n_uniq)
    else:
        path_codes, dv_codes = build_replay_keys(fa)
    version = np.asarray(fa.column("version"), dtype=np.int64)
    # versions fit int32 in practice (2^31 commits); assert to be safe
    assert version.max(initial=0) < 2**31, "version overflow"
    order = np.asarray(fa.column("order"), dtype=np.int32)
    is_add = np.asarray(fa.column("is_add"), dtype=bool)

    mesh = getattr(engine, "mesh", None) if engine is not None else None
    n_shards = mesh.devices.size if mesh is not None else 1
    forced = ("sharded" if n_shards > 1
              and getattr(engine, "_mesh_forced", False) else None)
    route = gate.replay_route(n, n_shards=n_shards, forced=forced)
    if route == "host":
        # RTT-dominated tiny segment: dispatching to the device costs
        # more than the host-vectorized replay (DEVICE_MERIT link model)
        with obs.gate_observation("replay", "host"):
            return compute_masks_host(columnar)
    def _run_device() -> tuple[np.ndarray, np.ndarray]:
        if route == "sharded":
            if n >= BLOCKWISE_MIN_ROWS * n_shards:
                # sharded AND >HBM: each shard streams its substream in
                # bounded blocks with a persistent bitset — the
                # `Snapshot.scala:481-511` multi-host configuration
                from delta_tpu.parallel.sharded_blockwise import (
                    replay_select_sharded_blockwise,
                )

                live, tomb, _ = replay_select_sharded_blockwise(
                    [path_codes, dv_codes], version.astype(np.int32),
                    order, is_add, mesh)
                return live, tomb
            from delta_tpu.parallel import resident as _resident
            from delta_tpu.parallel.sharded_replay import (
                sharded_replay_select,
            )

            sink = [] if _resident.enabled() else None
            live, tomb, _, _ = sharded_replay_select(
                path_codes, dv_codes, version.astype(np.int32), order,
                is_add, mesh=mesh, fa_hint=fa_hint, resident_sink=sink,
            )
            if sink:
                # keep the per-shard state on device so Snapshot.update()
                # ships only delta rows (ownership moves to SnapshotState
                # in reconstruct_state)
                columnar.resident = _resident.establish_resident(
                    sink[0], fa, path_codes)
            return live, tomb
        if n >= BLOCKWISE_MIN_ROWS:
            # >HBM scale path (SURVEY §5.7): stream fixed-size blocks
            # through the device with a persistent key bitset instead of
            # one giant sort
            from delta_tpu.ops.replay_blockwise import (
                replay_select_blockwise,
            )

            return replay_select_blockwise(
                [path_codes, dv_codes], version.astype(np.int32), order,
                is_add)
        return replay_select(
            [path_codes, dv_codes], version.astype(np.int32), order, is_add,
            fa_hint=fa_hint,
        )

    try:
        out = device_faults.shed_retry("replay", _run_device)
    except Exception as e:
        # classify (feeds the route breaker); permanent -> re-raise
        if not device_faults.absorb_route_failure("replay", e):
            raise
        return _replay_host_twin(columnar, e)
    gate.route_ok("replay")
    return out


def compute_masks_host(columnar: ColumnarActions) -> tuple[np.ndarray, np.ndarray]:
    """Sequential reference replay (`InMemoryLogReplay` semantics)."""
    fa = columnar.file_actions
    n = fa.num_rows
    live = np.zeros(n, dtype=bool)
    tomb = np.zeros(n, dtype=bool)
    if n == 0:
        return live, tomb
    paths = fa.column("path").to_pylist()
    dvs = fa.column("dv_id").to_pylist()
    version = np.asarray(fa.column("version"), dtype=np.int64)
    order = np.asarray(fa.column("order"), dtype=np.int32)
    is_add = np.asarray(fa.column("is_add"), dtype=bool)
    rows = sorted(range(n), key=lambda i: (version[i], order[i]))
    winner: dict = {}
    for i in rows:
        winner[(paths[i], dvs[i])] = i
    for i in winner.values():
        if is_add[i]:
            live[i] = True
        else:
            tomb[i] = True
    return live, tomb


SUPPORTED_READER_FEATURES = frozenset(
    {
        "deletionVectors",
        "columnMapping",
        "timestampNtz",
        "typeWidening",
        "typeWidening-preview",
        "v2Checkpoint",
        "vacuumProtocolCheck",
        "variantType",
        "variantType-preview",
        "inCommitTimestamp",
        "domainMetadata",
        "rowTracking",
        "clustering",
        "appendOnly",
        "invariants",
        "checkConstraints",
        "changeDataFeed",
        "generatedColumns",
        "identityColumns",
        "allowColumnDefaults",
        "icebergCompatV1",
        "icebergCompatV2",
        "liquid",
    }
)
MAX_READER_VERSION = 3


def check_read_supported(protocol: Protocol) -> None:
    """Protocol gate (PROTOCOL.md:844-876): reader version <= 3 and, at
    (3,7), every readerFeature must be implemented here."""
    if protocol.minReaderVersion > MAX_READER_VERSION:
        raise UnsupportedTableFeatureError(
            {f"readerVersion={protocol.minReaderVersion}"}, read=True
        )
    unsupported = protocol.reader_feature_set() - SUPPORTED_READER_FEATURES
    if unsupported:
        raise UnsupportedTableFeatureError(unsupported, read=True)


@dataclass
class SmallState:
    """Protocol/metadata/txn/domain/commitInfo resolution WITHOUT the
    file-level replay — checkpoint parquet is read with column
    projection so none of the add/remove bytes are decoded. The
    reference's P&M fast path (`Snapshot.scala:440`); serves
    metadata-only operations (schema reads, config lookups, blind-append
    transaction setup) on large tables in milliseconds."""

    version: int
    protocol: Protocol
    metadata: Metadata
    set_transactions: Dict[str, SetTransaction]
    domain_metadata: Dict[str, DomainMetadata]
    latest_commit_info: Optional[CommitInfo] = None
    commit_infos: Dict[int, CommitInfo] = field(default_factory=dict)
    timestamp_ms: int = 0


def reconstruct_small_state(engine, segment,
                            check_protocol: bool = True) -> SmallState:
    """Small-action-only reconstruction (see SmallState)."""
    columnar = columnarize_log_segment(engine, segment, small_only=True)
    if columnar.protocol is None or columnar.metadata is None:
        from delta_tpu.errors import DeltaError

        raise LogCorruptedError(
            f"log segment for version {segment.version} has no "
            f"{'protocol' if columnar.protocol is None else 'metadata'} action",
            error_class="DELTA_STATE_RECOVER_ERROR",
        )
    if check_protocol:
        check_read_supported(columnar.protocol)
    return SmallState(
        version=segment.version,
        protocol=columnar.protocol,
        metadata=columnar.metadata,
        set_transactions=columnar.set_transactions,
        domain_metadata=columnar.domain_metadata,
        latest_commit_info=columnar.latest_commit_info,
        commit_infos=columnar.commit_infos,
        timestamp_ms=segment.last_commit_timestamp,
    )


def advance_state(
    engine, prev: SnapshotState, delta: ColumnarActions, new_segment
) -> SnapshotState:
    """Replay a delta batch of commits ON TOP of a retained prior state
    — the incremental half of `update()` (SnapshotManagement log-segment
    deltas). Reuses the prior snapshot's columnar arrays: the new state's
    table is `concat(prev rows, delta rows)` (zero-copy) and only the
    delta keys' winners are recomputed; prior rows whose key is touched
    by the delta have their mask bits cleared. Produces a state
    bit-identical to a cold full replay at the same version.

    Callers must handle protocol changes BEFORE this (fallback to full
    replay) — a new protocol can change how existing actions are read.
    """
    from delta_tpu.ops.replay import delta_winner_masks

    delta_fa = delta.file_actions_complete()  # delta stats: small, eager
    m = delta_fa.num_rows
    n_prev = prev.file_actions_raw.num_rows
    resident = prev.resident

    if m == 0:
        new_raw = prev.file_actions_raw
        live = prev.live_mask
        tomb = prev.tombstone_mask
        stats_thunk = prev.stats_thunk and _chained_prev_stats(prev, None)
    elif resident is not None and (
            masks := resident.append(delta_fa, n_prev)) is not None:
        # device-resident path: only the delta rows crossed the link;
        # the device re-reconciled base+delta and the returned masks
        # already cover the concatenated table
        live, tomb = masks
        new_raw = pa.concat_tables([prev.file_actions_raw, delta_fa])
        stats_thunk = (prev.stats_thunk
                       and _chained_prev_stats(prev, delta_fa))
    else:
        if resident is not None:
            # the batch couldn't be expressed on device (DV rows,
            # capacity, ordering): residency ends here, host path takes
            # over for this and every later advancement
            resident.release()
            resident = None
            prev.resident = None
        d_paths = delta_fa.column("path").to_pylist()
        d_dv = delta_fa.column("dv_id").to_pylist()
        d_keys = list(zip(d_paths, d_dv))
        d_live, d_tomb, winner = delta_winner_masks(
            d_keys,
            np.asarray(delta_fa.column("version"), np.int64),
            np.asarray(delta_fa.column("order"), np.int32),
            np.asarray(delta_fa.column("is_add"), bool),
        )
        prev_live = prev.live_mask.copy()
        prev_tomb = prev.tombstone_mask.copy()
        if n_prev:
            # candidate prior rows: active AND path touched by the delta
            # (one vectorized hash probe over the big column; the exact
            # (path, dv_id) check runs only on the few candidates)
            touched = pa.array(sorted({p for p, _ in winner}), pa.string())
            import pyarrow.compute as pc

            hit = np.asarray(
                pc.is_in(prev.file_actions_raw.column("path"),
                         value_set=touched).combine_chunks(),
                dtype=bool)
            cand = np.nonzero(hit & (prev_live | prev_tomb))[0]
            if cand.size:
                sub = prev.file_actions_raw.take(
                    pa.array(cand, pa.int64()))
                for j, p, dv in zip(cand,
                                    sub.column("path").to_pylist(),
                                    sub.column("dv_id").to_pylist()):
                    if (p, dv) in winner:
                        prev_live[j] = False
                        prev_tomb[j] = False
        new_raw = pa.concat_tables([prev.file_actions_raw, delta_fa])
        live = np.concatenate([prev_live, d_live])
        tomb = np.concatenate([prev_tomb, d_tomb])
        stats_thunk = (prev.stats_thunk
                       and _chained_prev_stats(prev, delta_fa))

    set_txns = dict(prev.set_transactions)
    set_txns.update(delta.set_transactions)
    domains = dict(prev.domain_metadata)
    domains.update(delta.domain_metadata)
    commit_infos = dict(prev.commit_infos)
    commit_infos.update(delta.commit_infos)

    new_state = SnapshotState(
        version=new_segment.version,
        protocol=delta.protocol or prev.protocol,
        metadata=delta.metadata or prev.metadata,
        set_transactions=set_txns,
        domain_metadata=domains,
        file_actions_raw=new_raw,
        live_mask=live,
        tombstone_mask=tomb,
        latest_commit_info=delta.latest_commit_info or prev.latest_commit_info,
        commit_infos=commit_infos,
        timestamp_ms=new_segment.last_commit_timestamp,
        stats_thunk=stats_thunk,
        table_path=prev.table_path,
    )
    if resident is not None:
        # ownership moves: the append donated (mutated) the device
        # buffer, so the prior state's reference is stale by definition
        new_state.resident = resident
        prev.resident = None
    stats_index = prev.stats_index
    if stats_index is not None:
        if m == 0:
            # empty delta: the live-file table is unchanged, so the
            # index is still exact — ownership moves like `resident`
            new_state.stats_index = stats_index
            prev.stats_index = None
        else:
            # the prior version's lanes are stale; release the HBM now
            # rather than waiting for eviction (the next scan of the
            # new state rebuilds lazily)
            stats_index.release()
            prev.stats_index = None
    operand_cache = prev.operand_cache
    if operand_cache is not None:
        if m == 0:
            # empty delta: table content unchanged, the cached operand
            # lanes are still exact — ownership moves like `resident`
            new_state.operand_cache = operand_cache
            prev.operand_cache = None
        else:
            # version advance invalidates the per-(table, version,
            # column) artifacts; free the HBM now, the next device SQL
            # query over the new state re-uploads lazily
            operand_cache.release()
            prev.operand_cache = None
    return new_state


def _chained_prev_stats(prev: SnapshotState, delta_fa: Optional[pa.Table]):
    """Deferred-stats chain for an advanced state: the prior state's
    pending decode runs (exactly once, under ITS splice lock) only when
    the NEW state's stats are first touched; the delta rows' stats are
    already real."""

    def thunk():
        col = prev.file_actions.column("stats")  # splices prev on demand
        chunks = list(col.chunks)
        if delta_fa is not None:
            chunks.extend(delta_fa.column("stats").chunks)
        return pa.chunked_array(chunks, pa.string())

    return thunk


def _table_root(log_path: Optional[str]) -> Optional[str]:
    """Table root for a ``.../_delta_log`` path (ledger attribution)."""
    if not log_path:
        return None
    trimmed = log_path.rstrip("/")
    if trimmed.endswith("_delta_log"):
        trimmed = trimmed[: -len("_delta_log")].rstrip("/")
    return trimmed or log_path


def reconstruct_state(engine, segment, check_protocol: bool = True) -> SnapshotState:
    """Full state reconstruction for a log segment."""
    from delta_tpu.metrics import SnapshotMetrics

    metrics = SnapshotMetrics()
    with metrics.columnarize_timer.time():
        columnar = columnarize_log_segment(engine, segment)
    if columnar.protocol is None or columnar.metadata is None:
        from delta_tpu.errors import DeltaError

        raise LogCorruptedError(
            f"log segment for version {segment.version} has no "
            f"{'protocol' if columnar.protocol is None else 'metadata'} action"
        )
    if check_protocol:
        check_read_supported(columnar.protocol)

    use_device = getattr(engine, "use_device_replay", False)
    with metrics.replay_timer.time():
        if use_device:
            live, tomb = compute_masks_device(columnar, engine)
        else:
            live, tomb = compute_masks_host(columnar)

    metrics.num_commit_files.increment(columnar.num_commit_files)
    metrics.num_checkpoint_parts.increment(len(segment.checkpoints))
    metrics.num_actions.increment(columnar.num_actions)
    metrics.bytes_parsed.increment(columnar.bytes_parsed)
    obs.set_attrs(
        num_actions=columnar.num_actions,
        num_commit_files=columnar.num_commit_files,
        num_checkpoint_parts=len(segment.checkpoints),
        bytes_parsed=columnar.bytes_parsed,
        replay_mode="device" if use_device else "host",
    )
    if getattr(engine, "metrics_reporters", None):
        engine.report_metrics(
            metrics.report(
                segment.log_path,
                segment.version,
                extra={"replayMode": "device" if use_device else "host"},
            )
        )

    state = SnapshotState(
        version=segment.version,
        protocol=columnar.protocol,
        metadata=columnar.metadata,
        set_transactions=columnar.set_transactions,
        domain_metadata=columnar.domain_metadata,
        file_actions_raw=columnar.file_actions,
        live_mask=live,
        tombstone_mask=tomb,
        latest_commit_info=columnar.latest_commit_info,
        commit_infos=columnar.commit_infos,
        timestamp_ms=segment.last_commit_timestamp,
        stats_thunk=columnar.stats_thunk,
        table_path=_table_root(segment.log_path),
    )
    # ownership of the deferred decode moves to the snapshot state
    columnar.stats_thunk = None
    # same for the device-resident sharded replay state, when one was
    # established during compute_masks_device
    state.resident = columnar.resident
    columnar.resident = None
    return state

"""Assemble the canonical file-actions table from the native scanner.

`delta_tpu.native.scan_actions` returns flat numpy buffers (offsets +
arenas + validity) for the add/remove rows of a commit-JSON buffer; this
module zero-copies them into Arrow arrays in the canonical schema
(`CANONICAL_FILE_ACTION_SCHEMA`) and resolves per-row (version, order)
tags from line positions. Non-file actions come back as byte spans and
are json.loads'ed host-side — they are O(commits), not O(files).
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa


def _bitmap(valid: np.ndarray) -> Optional[pa.Buffer]:
    if valid.all():
        return None
    return pa.py_buffer(np.packbits(valid, bitorder="little"))


def _as_buf(x) -> pa.Buffer:
    """numpy array or (zero-copy foreign) pa.Buffer -> pa.Buffer."""
    return x if isinstance(x, pa.Buffer) else pa.py_buffer(x)


def _str_array(col: tuple) -> pa.Array:
    offsets, arena, valid = col
    return pa.StringArray.from_buffers(
        len(valid), _as_buf(offsets), _as_buf(arena), _bitmap(valid))


def _num_array(col: tuple, typ: pa.DataType) -> pa.Array:
    vals, valid = col
    return pa.Array.from_buffers(
        typ, len(valid), [_bitmap(valid), _as_buf(vals)])


def _bool_array(col: tuple) -> pa.Array:
    vals, valid = col
    return pa.Array.from_buffers(
        pa.bool_(), len(valid),
        [_bitmap(valid), pa.py_buffer(np.packbits(vals, bitorder="little"))])


def line_tags(
    line_starts: np.ndarray,
    file_starts: np.ndarray,
    file_versions: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """(version, order) per line: which file a line's byte offset falls
    in gives its version; order is the line's rank within that file."""
    file_of_line = np.searchsorted(file_starts, line_starts, side="right") - 1
    first_line = np.searchsorted(line_starts, file_starts[:-1], side="left")
    versions = file_versions[file_of_line]
    orders = (np.arange(len(line_starts), dtype=np.int64)
              - first_line[file_of_line]).astype(np.int32)
    return versions, orders


def _path_column(scan) -> tuple:
    """Per-row path strings from the scanner's dictionary encoding: the
    unique arena becomes the dictionary values (decoded/percent-unescaped
    once per UNIQUE path, not per row), then one take() materializes the
    canonical string column.

    Returns (column, codes_match_decoded): when percent-decoding changed
    any unique path, two raw spellings may decode to the SAME logical
    path, so the scanner's codes no longer key the decoded column and
    the replay-key sidecar must be dropped (caller re-factorizes)."""
    from delta_tpu.replay.columnar import _decode_paths

    uniq = scan.uniq_strings()
    decoded = _decode_paths(uniq)
    idx = pa.Array.from_buffers(
        pa.int32(), scan.n_rows, [None, pa.py_buffer(scan.path_code.view(np.int32))])
    col = pa.DictionaryArray.from_arrays(idx, decoded).cast(pa.string())
    return col, decoded is uniq


def build_canonical_table(scan, versions: np.ndarray, orders: np.ndarray,
                          path_and_ok=None):
    """ScanResult + per-row tags -> canonical Arrow table (+ dv struct
    pieces needed for dv_id derivation, done by the caller with the same
    expressions as the generic path)."""
    from delta_tpu.replay.columnar import (
        CANONICAL_FILE_ACTION_SCHEMA,
        DV_STRUCT_TYPE,
        _dv_unique_id,
    )

    n = scan.n_rows
    path, codes_ok = (path_and_ok if path_and_ok is not None
                      else _path_column(scan))
    if scan.stats is None:
        # lazy-stats scan: a placeholder rides in the table; the caller
        # splices the real column in before any consumer can see it
        stats_col = pa.nulls(n, pa.string())
    else:
        stats_col = _str_array(scan.stats)
    keys = _str_array(scan.pv_key)
    items = _str_array(scan.pv_val)
    map_type = pa.map_(pa.string(), pa.string())
    entries_type = map_type.field(0).type
    entries = pa.StructArray.from_arrays(
        [keys, items],
        fields=[entries_type.field(0), entries_type.field(1)])
    pv = pa.Array.from_buffers(
        map_type, n,
        [_bitmap(scan.pv_valid), _as_buf(scan.pv_offsets)],
        children=[entries])

    storage = _str_array(scan.dv_storage)
    pathinline = _str_array(scan.dv_pathinline)
    dv_offset = _num_array(scan.dv_offset, pa.int32())
    dv_struct = pa.StructArray.from_arrays(
        [storage, pathinline, dv_offset,
         _num_array(scan.dv_size, pa.int32()),
         _num_array(scan.dv_card, pa.int64()),
         _num_array(scan.dv_maxrow, pa.int64())],
        fields=list(DV_STRUCT_TYPE),
        mask=pa.array(~scan.dv_valid),
    )
    dv_id = _dv_unique_id(storage, pathinline, dv_offset, scan.dv_valid, n)

    tbl = pa.table(
        {
            "path": path,
            "dv_id": dv_id,
            "partition_values": pv,
            "size": _num_array(scan.size, pa.int64()),
            "modification_time": _num_array(scan.mod_time, pa.int64()),
            "data_change": _bool_array(scan.data_change),
            "stats": stats_col,
            "tags": _str_array(scan.tags),
            "deletion_vector": dv_struct,
            "base_row_id": _num_array(scan.base_row_id, pa.int64()),
            "default_row_commit_version": _num_array(scan.drcv, pa.int64()),
            "clustering_provider": _str_array(scan.clustering),
            "deletion_timestamp": _num_array(scan.del_ts, pa.int64()),
            "extended_file_metadata": _bool_array(scan.ext_meta),
            "is_add": pa.array(scan.is_add),
            "version": pa.array(versions, pa.int64()),
            "order": pa.array(orders, pa.int32()),
        },
        schema=CANONICAL_FILE_ACTION_SCHEMA,
    )
    return tbl, codes_ok


class NativeReplayKeys:
    """Replay-key sidecar from the native scan: first-appearance path
    dictionary codes plus the ready-made delta encoding the device
    kernel ships (ops/replay.py `_winner_kernel_fa`). Row-aligned with
    the canonical table built from the same scan (or, via
    `merge_replay_keys`, with the concatenation of several scans)."""

    __slots__ = ("path_code", "path_new", "refs", "n_uniq")

    def __init__(self, path_code: np.ndarray, path_new: np.ndarray,
                 refs: np.ndarray, n_uniq: int):
        self.path_code = path_code
        self.path_new = path_new
        self.refs = refs
        self.n_uniq = n_uniq

    @classmethod
    def from_scan(cls, scan) -> "NativeReplayKeys":
        return cls(scan.path_code, scan.path_new, scan.refs, scan.n_uniq)


def merge_replay_keys(
    parts: List[Tuple[Optional[NativeReplayKeys], Optional[pa.Array], int]],
) -> Optional[NativeReplayKeys]:
    """Merge per-window replay-key sidecars into one sidecar that is
    dense-first-appearance coded over the concatenated row stream.

    `parts` is [(keys, unique-path strings in window code order, n_rows)]
    in window order. Returns None when any window lacks keys (generic
    parse, or percent-decoding collapsed two spellings).

    Why this is valid: the dictionary encode assigns global codes in
    order of first appearance over the concatenated per-window unique
    arrays; each
    window's unique array is itself in window-local first-appearance
    (row) order, and windows concatenate in row order — so global code
    order == global row-stream first-appearance order, exactly the dense
    coding `ops.replay.derive_fa_flags` requires. A row is globally new
    iff it is locally new AND its path's first window is this one."""
    if not parts or any(k is None or u is None for k, u, _ in parts):
        return None
    if len(parts) == 1:
        return parts[0][0]
    uniqs = [u.combine_chunks() if isinstance(u, pa.ChunkedArray) else u
             for _, u, _ in parts]
    lens = np.fromiter((len(u) for u in uniqs), np.int64, len(uniqs))
    offs = np.zeros(len(uniqs) + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    all_uniq = pa.concat_arrays(uniqs)
    # Arrow's dictionary_encode assigns codes in order of first
    # appearance (same contract as pd.factorize) without materializing
    # the strings into the interpreter
    enc = all_uniq.dictionary_encode()
    codes = enc.indices.to_numpy(zero_copy_only=False).astype(
        np.int64, copy=False)
    n_uniq = len(enc.dictionary)
    # earliest window containing each global code (reverse iteration:
    # earlier windows overwrite later ones)
    first_win = np.empty(n_uniq, np.int32)
    for w in range(len(uniqs) - 1, -1, -1):
        first_win[codes[offs[w]:offs[w + 1]]] = w
    pc_parts: List[np.ndarray] = []
    pn_parts: List[np.ndarray] = []
    ref_parts: List[np.ndarray] = []
    for w, (k, _, _) in enumerate(parts):
        local_to_global = codes[offs[w]:offs[w + 1]]
        g = (local_to_global[k.path_code] if len(k.path_code)
             else np.empty(0, np.int64))
        flags = k.path_new & (first_win[g] == w) if len(g) else k.path_new
        pc_parts.append(g.astype(np.uint32))
        pn_parts.append(flags)
        ref_parts.append(g[~flags].astype(np.uint32))
    return NativeReplayKeys(
        np.concatenate(pc_parts), np.concatenate(pn_parts),
        np.concatenate(ref_parts), n_uniq)


def _finish_scan(
    scan,
    others_raw: List[bytes],
    file_starts: np.ndarray,
    file_versions: np.ndarray,
    small_only: bool,
    launch=None,
) -> Optional[Tuple[pa.Table, List[Tuple[int, int, dict]],
                    Optional[NativeReplayKeys], Optional[object],
                    Optional[object]]]:
    """`launch`: optional callable (scan, row_versions, row_orders) ->
    pending-replay handle, invoked BEFORE the Arrow assembly so the
    device sorts while the host builds the canonical table. Only called
    when the scanner's codes key the final column exactly (no percent
    decoding collapse, no DV lane)."""
    line_versions, line_orders = line_tags(
        scan.line_starts, file_starts, file_versions)
    keys: Optional[NativeReplayKeys] = None
    pending = None
    stats_thunk = None
    if getattr(scan, "stats_lazy", False):
        def stats_thunk(scan=scan):
            scan.materialize_stats()
            return _str_array(scan.stats)
    if small_only:
        from delta_tpu.replay.columnar import CANONICAL_FILE_ACTION_SCHEMA

        table = CANONICAL_FILE_ACTION_SCHEMA.empty_table()
    else:
        path_and_ok = _path_column(scan)
        row_versions = (line_versions[scan.line_no] if scan.n_rows
                        else np.empty(0, np.int64))
        row_orders = (line_orders[scan.line_no] if scan.n_rows
                      else np.empty(0, np.int32))
        if (launch is not None and path_and_ok[1] and scan.n_rows
                and not bool(scan.dv_valid.any())):
            pending = launch(scan, row_versions, row_orders)
        table, codes_ok = build_canonical_table(
            scan, row_versions, row_orders, path_and_ok=path_and_ok)
        if codes_ok:
            keys = NativeReplayKeys.from_scan(scan)
    # NOTE: a malformed control line below aborts AFTER a launch may have
    # been issued; the pending handle is simply dropped (harmless async
    # work) and the generic path re-parses.
    others: List[Tuple[int, int, dict]] = []
    for ln, raw in zip(scan.other_line_no.tolist(), others_raw):
        try:
            row = json.loads(raw)
        except ValueError:
            return None  # malformed control line: let the generic path err
        others.append((int(line_versions[ln]), int(line_orders[ln]), row))
    return table, others, keys, pending, stats_thunk


def parse_commits_native(
    buf,
    file_starts: np.ndarray,
    file_versions: np.ndarray,
    small_only: bool = False,
    launch=None,
) -> Optional[Tuple[pa.Table, List[Tuple[int, int, dict]],
                    Optional[NativeReplayKeys], Optional[object],
                    Optional[object]]]:
    """Native fast path over one concatenated commit buffer.

    Returns (canonical file-actions table, [(version, order, action-dict)
    for non-file actions], replay-key sidecar, pending-replay handle) or
    None when the native scanner is unavailable/fails (caller uses the
    generic Arrow parser). `small_only` skips materializing the
    file-action table (the P&M fast path throws it away)."""
    from delta_tpu import native

    scan = native.scan_actions(buf)
    if scan is None:
        return None
    mv = memoryview(buf)
    others_raw = [bytes(mv[s:e])
                  for s, e in zip(scan.other_start.tolist(),
                                  scan.other_end.tolist())]
    return _finish_scan(scan, others_raw, file_starts, file_versions,
                        small_only, launch=launch)


def parse_commit_paths_native(
    local_paths: List[str],
    file_versions: np.ndarray,
    small_only: bool = False,
    launch=None,
    lazy_stats: bool = False,
) -> Optional[Tuple[pa.Table, List[Tuple[int, int, dict]],
                    Optional[NativeReplayKeys], Optional[object],
                    Optional[object], int]]:
    """Native read+scan of local commit files in one round-trip (no
    per-file Python I/O). Returns (..., total_bytes) or None."""
    from delta_tpu import native

    out = native.scan_commit_files(local_paths, lazy_stats=lazy_stats)
    if out is None:
        return None
    scan, others_raw, starts, total = out
    fin = _finish_scan(scan, others_raw, starts, file_versions, small_only,
                       launch=launch)
    if fin is None:
        return None
    return (*fin, total)


def parse_window_native(
    buf,
    file_starts: np.ndarray,
    file_versions: np.ndarray,
    lazy_stats: bool = False,
) -> Optional[Tuple[pa.Table, List[Tuple[int, int, dict]],
                    Optional[NativeReplayKeys], Optional[pa.Array],
                    bool, Optional[object]]]:
    """Native parse of ONE pipeline window. Like `parse_commits_native`
    but never launches (the pipeline launches once over the merged
    stream) and additionally surfaces what cross-window key merging
    needs: (table, others, keys, unique path strings in code order,
    any-DV flag, stats thunk), or None for the generic fallback."""
    from delta_tpu import native

    scan = native.scan_actions(buf, lazy_stats=lazy_stats)
    if scan is None:
        return None
    mv = memoryview(buf)
    others_raw = [bytes(mv[s:e])
                  for s, e in zip(scan.other_start.tolist(),
                                  scan.other_end.tolist())]
    fin = _finish_scan(scan, others_raw, file_starts, file_versions,
                       small_only=False, launch=None)
    if fin is None:
        return None
    table, others, keys, _pending, stats_thunk = fin
    uniq = scan.uniq_strings() if keys is not None else None
    dv_any = bool(scan.dv_valid.any()) if scan.n_rows else False
    return table, others, keys, uniq, dv_any, stats_thunk

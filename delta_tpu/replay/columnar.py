"""Columnarization: log files → one canonical Arrow file-actions table.

This is the host half of state reconstruction. It turns the log segment's
JSON commits and Parquet checkpoint parts into:

- one Arrow table of *file actions* (adds + removes unified, `is_add`
  flag), each row tagged with `(version, order)` — the chronological
  coordinate the device replay sorts by; and
- the *small actions* (protocol, metaData, txn, domainMetadata,
  commitInfo) resolved host-side (they are O(commits), not O(files)).

Key performance move: all JSON commit files in a segment are concatenated
into ONE buffer and parsed by a single `pyarrow.json.read_json` call
(C++, multithreaded) — per-row version tags are derived from per-file line
counts. The reference pays this cost as a Spark JSON scan
(`Snapshot.scala:524` loadActions); the kernel as per-file Jackson parses
(`ActionsIterator.java:77`).
"""

from __future__ import annotations

import json
import threading
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.json as pa_json

from delta_tpu import obs
from delta_tpu.models.actions import (
    CommitInfo,
    DomainMetadata,
    Metadata,
    Protocol,
    SetTransaction,
)

# process-wide parse-cache effectiveness counters (obs registry names
# mirror the per-instance ParsedCommitCache fields)
_OBS_CACHE_HITS = obs.counter("parse_cache.hits")
_OBS_CACHE_PARTIAL = obs.counter("parse_cache.partial_hits")
_OBS_CACHE_MISSES = obs.counter("parse_cache.misses")
_OBS_CACHE_HIT_FILES = obs.counter("parse_cache.hit_files")
_OBS_CACHE_MISS_FILES = obs.counter("parse_cache.miss_files")
_TORN_COMMITS = obs.counter("log.torn_commits")
_OBS_DECODE_PARTS = obs.counter("decode.device_parts")
_OBS_DECODE_FALLBACKS = obs.counter("decode.device_fallbacks")
# same instrument as replay/device_parse.py: absorbed device-parse
# exceptions bump the cataloged parse fallback counter here (the
# in-module bumps cover only the None-return unsupported shapes)
_OBS_PARSE_FALLBACKS = obs.counter("parse.device_fallbacks")

DV_STRUCT_TYPE = pa.struct(
    [
        pa.field("storageType", pa.string()),
        pa.field("pathOrInlineDv", pa.string()),
        pa.field("offset", pa.int32()),
        pa.field("sizeInBytes", pa.int32()),
        pa.field("cardinality", pa.int64()),
        pa.field("maxRowIndex", pa.int64()),
    ]
)

# The unified add/remove row. `dv_id` is the computed DV unique id (null =
# no DV); replay key is (path, dv_id). Checkpoint-only columns (stats,
# tags...) are nullable.
CANONICAL_FILE_ACTION_SCHEMA = pa.schema(
    [
        pa.field("path", pa.string()),
        pa.field("dv_id", pa.string()),
        pa.field("partition_values", pa.map_(pa.string(), pa.string())),
        pa.field("size", pa.int64()),
        pa.field("modification_time", pa.int64()),
        pa.field("data_change", pa.bool_()),
        pa.field("stats", pa.string()),
        pa.field("tags", pa.string()),  # JSON-encoded map; rare
        pa.field("deletion_vector", DV_STRUCT_TYPE),
        pa.field("base_row_id", pa.int64()),
        pa.field("default_row_commit_version", pa.int64()),
        pa.field("clustering_provider", pa.string()),
        pa.field("deletion_timestamp", pa.int64()),  # removes only
        pa.field("extended_file_metadata", pa.bool_()),  # removes only
        pa.field("is_add", pa.bool_()),
        pa.field("version", pa.int64()),
        pa.field("order", pa.int32()),
    ]
)


@dataclass
class ColumnarActions:
    """Output of columnarization for one log segment."""

    file_actions: pa.Table  # CANONICAL_FILE_ACTION_SCHEMA
    protocol: Optional[Protocol] = None
    metadata: Optional[Metadata] = None
    set_transactions: Dict[str, SetTransaction] = field(default_factory=dict)
    domain_metadata: Dict[str, DomainMetadata] = field(default_factory=dict)
    latest_commit_info: Optional[CommitInfo] = None
    commit_infos: Dict[int, CommitInfo] = field(default_factory=dict)
    num_commit_files: int = 0
    bytes_parsed: int = 0
    # Replay-key sidecar from the native scanner (first-appearance path
    # codes + delta encoding), row-aligned with file_actions. Only set
    # when file_actions came from one native scan (no checkpoint blocks)
    # so the alignment is exact; replay falls back to factorize otherwise.
    replay_keys: Optional[object] = None
    # Early-launched device replay (ops.replay.ReplayPending): dispatched
    # right after the native scan so the device sorts while the host
    # assembles the Arrow table. Row-aligned with file_actions under the
    # same sole-native-block condition as replay_keys.
    pending_masks: Optional[object] = None
    # Deferred stats decode (lazy-stats native scan): () -> Arrow string
    # array replacing the placeholder stats column. Set under the same
    # sole-native-block condition. NOTE: while this is set,
    # `file_actions` carries an all-null stats PLACEHOLDER — internal
    # replay consumers read only replay-safe columns, and SnapshotState
    # splices the real column before any user-facing surface; any other
    # caller must use `file_actions_complete()`.
    stats_thunk: Optional[object] = None
    # Device-resident sharded replay state (parallel/resident.py
    # ResidentShardState), established by compute_masks_device when the
    # sharded route runs; reconstruct_state moves ownership to the
    # SnapshotState so `Snapshot.update()` can append delta rows without
    # re-shipping the base state.
    resident: Optional[object] = None
    _splice_lock: object = field(default_factory=threading.Lock,
                                 repr=False, compare=False)

    def file_actions_complete(self) -> pa.Table:
        """The canonical table with the stats column materialized (the
        safe accessor for code outside the snapshot pipeline). Locked so
        concurrent first calls run the decode thunk exactly once."""
        with self._splice_lock:
            self.file_actions, self.stats_thunk = splice_stats(
                self.file_actions, self.stats_thunk)
            return self.file_actions

    @property
    def num_actions(self) -> int:
        return self.file_actions.num_rows


def splice_stats(table: pa.Table, stats_thunk):
    """Replace the deferred-stats placeholder column with the decoded
    one (shared by ColumnarActions and SnapshotState). Returns
    (table, None); no-op when no decode is pending."""
    if stats_thunk is None:
        return table, None
    idx = table.schema.get_field_index("stats")
    return (table.set_column(idx, table.schema.field(idx), stats_thunk()),
            None)


def _field_or_null(struct_arr: pa.StructArray, name: str, typ: pa.DataType) -> pa.Array:
    n = len(struct_arr)
    t = struct_arr.type
    if t.get_field_index(name) >= 0:
        arr = pc.struct_field(struct_arr, name)
        # struct-typed actual values (e.g. JSON-inferred tags maps) are
        # normalized downstream, never cast here
        if (arr.type != typ
                and not (pa.types.is_map(typ) or pa.types.is_struct(typ))
                and not pa.types.is_struct(arr.type)):
            arr = arr.cast(typ, safe=False)
        return arr
    return pa.nulls(n, typ)


def _struct_to_map(arr: pa.Array, n: int) -> pa.Array:
    """Normalize partitionValues: JSON inference yields struct<col:string>,
    checkpoints yield map<string,string>. Returns map<string,string>.
    Every struct field becomes a map entry per row (protocol: one entry
    per partition column, value may be null)."""
    map_type = pa.map_(pa.string(), pa.string())
    if pa.types.is_map(arr.type):
        if arr.type != map_type:
            arr = arr.cast(map_type, safe=False)
        return arr
    if pa.types.is_null(arr.type):
        return pa.nulls(n, map_type)
    assert pa.types.is_struct(arr.type), arr.type
    k = arr.type.num_fields
    names = [arr.type.field(i).name for i in range(k)]
    if k == 0:
        offsets = np.zeros(n + 1, dtype=np.int32)
        return pa.MapArray.from_arrays(
            pa.array(offsets, pa.int32()), pa.array([], pa.string()), pa.array([], pa.string())
        )
    valid = np.asarray(pc.is_valid(arr), dtype=bool)
    counts = np.where(valid, k, 0).astype(np.int64)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    # keys: tile names for valid rows
    keys = np.tile(np.array(names, dtype=object), n)[np.repeat(valid, k)] if k else []
    item_cols = [pc.struct_field(arr, i) for i in range(k)]
    # interleave: row-major [row0f0, row0f1, ..., row1f0, ...]
    item_mat = np.empty((n, k), dtype=object)
    for j, col_arr in enumerate(item_cols):
        item_mat[:, j] = np.asarray(col_arr.cast(pa.string()), dtype=object)
    items = item_mat.reshape(-1)[np.repeat(valid, k)]
    return pa.MapArray.from_arrays(
        pa.array(offsets, pa.int64()).cast(pa.int32()),
        pa.array(list(keys), pa.string()),
        pa.array(list(items), pa.string()),
    )


def _map_or_json_to_string(arr: pa.Array, n: int) -> pa.Array:
    """tags → JSON string column (host-only metadata, rarely set)."""
    if pa.types.is_string(arr.type):
        return arr
    if pa.types.is_null(arr.type):
        return pa.nulls(n, pa.string())
    pylist = arr.to_pylist()
    out = [
        json.dumps(dict(v) if not isinstance(v, dict) else v, sort_keys=True)
        if v is not None
        else None
        for v in pylist
    ]
    return pa.array(out, pa.string())


def _dv_unique_id(storage, path_or_inline, offset, valid_mask, n) -> pa.Array:
    """unique id = storageType + pathOrInlineDv [+ "@" + offset]
    (DeletionVectorDescriptor.uniqueId semantics)."""
    # no DVs anywhere (the overwhelmingly common case): skip the string
    # kernels entirely — they cost ~0.2s per 3M rows
    if isinstance(valid_mask, np.ndarray):
        any_dv = bool(valid_mask.any())
    else:
        any_dv = bool(pc.any(valid_mask).as_py())
    if not any_dv:
        return pa.nulls(n, pa.string())
    base = pc.binary_join_element_wise(
        pc.fill_null(storage, ""), pc.fill_null(path_or_inline, ""), ""
    )
    with_offset = pc.binary_join_element_wise(
        base, pc.cast(offset, pa.string()), "@"
    )
    dv_id = pc.if_else(pc.is_valid(offset), with_offset, base)
    return pc.if_else(valid_mask, dv_id, pa.nulls(n, pa.string()))


def _normalize_dv(arr: pa.Array, n: int) -> tuple[pa.Array, pa.Array]:
    """Returns (dv struct column, dv_id string column)."""
    if pa.types.is_null(arr.type) or not pa.types.is_struct(arr.type):
        return pa.nulls(n, DV_STRUCT_TYPE), pa.nulls(n, pa.string())
    storage = _field_or_null(arr, "storageType", pa.string())
    path_or_inline = _field_or_null(arr, "pathOrInlineDv", pa.string())
    offset = _field_or_null(arr, "offset", pa.int32())
    size = _field_or_null(arr, "sizeInBytes", pa.int32())
    card = _field_or_null(arr, "cardinality", pa.int64())
    max_row = _field_or_null(arr, "maxRowIndex", pa.int64())
    valid_mask = pc.is_valid(arr)
    dv_struct = pa.StructArray.from_arrays(
        [storage, path_or_inline, offset, size, card, max_row],
        fields=list(DV_STRUCT_TYPE),
        mask=pc.invert(valid_mask),
    )
    return dv_struct, _dv_unique_id(storage, path_or_inline, offset, valid_mask, n)


_URI_ESCAPE = pc.match_substring  # detection helper (see _decode_paths)


def _decode_paths(arr: pa.Array) -> pa.Array:
    """Percent-decode RFC 2396 path URIs. Fast path: untouched when no '%'
    appears (the common case for writer-generated UUID file names)."""
    has_escape = pc.any(pc.match_substring(pc.fill_null(arr, ""), "%")).as_py()
    if not has_escape:
        return arr
    from urllib.parse import unquote

    py = arr.to_pylist()
    return pa.array([unquote(p) if p is not None and "%" in p else p for p in py], pa.string())


def _extract_file_actions(
    table: pa.Table,
    col: str,
    versions: np.ndarray,
    orders: np.ndarray,
) -> Optional[pa.Table]:
    """Extract add/remove rows from one parsed chunk into the canonical
    schema. `versions`/`orders` are per-row tags for the whole chunk."""
    if col not in table.column_names:
        return None
    struct_chunks = table.column(col)
    if struct_chunks.null_count == len(struct_chunks):
        return None
    struct_arr = struct_chunks.combine_chunks()
    if pa.types.is_null(struct_arr.type):
        return None
    valid = pc.is_valid(struct_arr)
    mask = np.asarray(valid, dtype=bool)
    sel = np.nonzero(mask)[0]
    if sel.size == 0:
        return None
    # filter, not take: selection-by-mask over a wide struct (stats
    # strings, partitionValues maps) is ~2x faster than row gather
    sub = struct_arr.filter(valid)
    n = len(sub)
    is_add = col == "add"

    path = _decode_paths(_field_or_null(sub, "path", pa.string()))
    pv = _struct_to_map(_field_or_null(sub, "partitionValues", pa.map_(pa.string(), pa.string())), n)
    size = _field_or_null(sub, "size", pa.int64())
    mod_time = _field_or_null(sub, "modificationTime", pa.int64())
    data_change = _field_or_null(sub, "dataChange", pa.bool_())
    stats = _field_or_null(sub, "stats", pa.string())
    if is_add and stats.null_count == n:
        # writeStatsAsJson=false checkpoints carry stats only in the
        # stats_parsed struct — re-serialize so skipping keeps working
        stats = _stats_from_parsed(sub, n) or stats
    tags = _map_or_json_to_string(_field_or_null(sub, "tags", pa.string()), n)
    dv_struct, dv_id = _normalize_dv(
        _field_or_null(sub, "deletionVector", DV_STRUCT_TYPE), n
    )
    base_row_id = _field_or_null(sub, "baseRowId", pa.int64())
    drcv = _field_or_null(sub, "defaultRowCommitVersion", pa.int64())
    clustering = _field_or_null(sub, "clusteringProvider", pa.string())
    del_ts = _field_or_null(sub, "deletionTimestamp", pa.int64())
    ext_meta = _field_or_null(sub, "extendedFileMetadata", pa.bool_())

    return pa.table(
        {
            "path": path,
            "dv_id": dv_id,
            "partition_values": pv,
            "size": size,
            "modification_time": mod_time,
            "data_change": data_change,
            "stats": stats,
            "tags": tags,
            "deletion_vector": dv_struct,
            "base_row_id": base_row_id,
            "default_row_commit_version": drcv,
            "clustering_provider": clustering,
            "deletion_timestamp": del_ts,
            "extended_file_metadata": ext_meta,
            "is_add": pa.array(np.full(n, is_add, dtype=bool)),
            "version": pa.array(versions[sel], pa.int64()),
            "order": pa.array(orders[sel], pa.int32()),
        },
        schema=CANONICAL_FILE_ACTION_SCHEMA,
    )


def _stats_from_parsed(sub: pa.StructArray, n: int) -> Optional[pa.Array]:
    """Re-serialize `stats_parsed` structs to stats JSON strings (only
    taken when the checkpoint was written with writeStatsAsJson=false,
    so the struct is the sole stats form).

    Deliberately a per-row Python pass: JSON string escaping rules out a
    compositional Arrow-kernel rebuild, and this path only runs for the
    opt-in struct-only checkpoint configuration, once per snapshot load
    (the result is cached with the snapshot state)."""
    names = [f.name for f in sub.type]
    if "stats_parsed" not in names:
        return None
    sp = sub.field("stats_parsed")
    if pa.types.is_null(sp.type) or sp.null_count == len(sp):
        return None
    import json as _json

    from delta_tpu.stats.collection import _json_value

    out = []
    for r in sp.to_pylist():
        if not r:
            out.append(None)
        else:
            out.append(_json.dumps(_prune_nones(r), separators=(",", ":"),
                                   default=_json_value))
    return pa.array(out, pa.string())


def _prune_nones(d):
    if isinstance(d, dict):
        return {k: _prune_nones(v) for k, v in d.items() if v is not None}
    if isinstance(d, list):
        return [_prune_nones(v) for v in d]
    return d


@dataclass
class _SmallActionTracker:
    """Latest-seen-wins resolution for O(commits) actions."""

    protocol: tuple = (-1, -1, None)
    metadata: tuple = (-1, -1, None)
    txns: Dict[str, tuple] = field(default_factory=dict)
    domains: Dict[str, tuple] = field(default_factory=dict)
    commit_infos: Dict[int, CommitInfo] = field(default_factory=dict)

    def scan_chunk(self, table: pa.Table, versions: np.ndarray, orders: np.ndarray):
        for col, handler in (
            ("protocol", self._on_protocol),
            ("metaData", self._on_metadata),
            ("txn", self._on_txn),
            ("domainMetadata", self._on_domain),
            ("commitInfo", self._on_commit_info),
        ):
            if col not in table.column_names:
                continue
            arr = table.column(col).combine_chunks()
            if pa.types.is_null(arr.type):
                continue
            mask = np.asarray(pc.is_valid(arr), dtype=bool)
            sel = np.nonzero(mask)[0]
            if sel.size == 0:
                continue
            rows = arr.take(pa.array(sel, pa.int64())).to_pylist()
            for i, row in zip(sel, rows):
                handler(int(versions[i]), int(orders[i]), _prune_nones(row))

    def _on_protocol(self, v, o, row):
        if (v, o) > self.protocol[:2]:
            self.protocol = (v, o, Protocol.from_dict(row))

    def _on_metadata(self, v, o, row):
        if (v, o) > self.metadata[:2]:
            self.metadata = (v, o, Metadata.from_dict(row))

    def _on_txn(self, v, o, row):
        txn = SetTransaction.from_dict(row)
        cur = self.txns.get(txn.appId)
        if cur is None or (v, o) > cur[:2]:
            self.txns[txn.appId] = (v, o, txn)

    def _on_domain(self, v, o, row):
        dm = DomainMetadata.from_dict(row)
        cur = self.domains.get(dm.domain)
        if cur is None or (v, o) > cur[:2]:
            self.domains[dm.domain] = (v, o, dm)

    def _on_commit_info(self, v, o, row):
        self.commit_infos[v] = CommitInfo.from_dict(row)

    def scan_pylist(self, rows: Sequence[Tuple[int, int, dict]]):
        """Consume (version, order, {action-key: body}) rows — the
        native scanner's non-file-action lines."""
        handlers = {
            "protocol": self._on_protocol,
            "metaData": self._on_metadata,
            "txn": self._on_txn,
            "domainMetadata": self._on_domain,
            "commitInfo": self._on_commit_info,
        }
        for v, o, row in rows:
            for key, body in row.items():
                h = handlers.get(key)
                if h is not None and body is not None:
                    h(v, o, _prune_nones(body))


def _read_commits_buffer(
    engine,
    commit_infos: Sequence[Tuple[int, str, int]],
    max_workers: int = 16,
) -> Optional[tuple[bytearray, np.ndarray, np.ndarray]]:
    """Parallel-read commit files into ONE preallocated buffer.

    commit_infos: (version, path, size-from-listing). Each file gets a
    region of `size + 1` bytes, the last byte forced to "\\n" (blank
    lines between files are ignored by the parsers). Returns
    (buffer, per-file byte starts[n+1], per-file versions), or None when
    a listed size disagrees with the bytes read (caller re-reads)."""
    n = len(commit_infos)
    if any(int(s) < 0 for _, _, s in commit_infos):
        # fast listing deferred the stats: resolve sizes now (this path
        # runs only when the native one-round-trip reader is unavailable)
        from delta_tpu.utils.threads import parallel_map

        def stat(info):
            v, p, s = info
            if int(s) >= 0:
                return info
            return (v, p, engine.fs.file_status(p).size)

        try:
            commit_infos = parallel_map(stat, list(commit_infos))
        except FileNotFoundError as e:
            from delta_tpu.log.segment import CorruptLogError

            # a listed commit vanished before reading: concurrent log
            # cleanup — the same contract as a listing gap
            raise CorruptLogError(
                f"commit file vanished after listing (concurrent log "
                f"cleanup?): {e}",
                error_class="DELTA_COMMIT_FILE_VANISHED") from e
    sizes = np.array([max(0, int(s)) for _, _, s in commit_infos], dtype=np.int64)
    starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(sizes + 1, out=starts[1:])
    total = int(starts[-1])
    buf = bytearray(total)
    mv = memoryview(buf)
    mismatch: List[int] = []

    def fill(i: int):
        _, path, _ = commit_infos[i]
        off = starts[i]
        local = engine.fs.os_path(path)
        if local is not None:
            # local file: read straight into the shared buffer (no
            # intermediate bytes object, no second copy)
            try:
                with open(local, "rb") as f:
                    got = f.readinto(mv[off:off + sizes[i]])
                    if got != sizes[i] or f.read(1):
                        mismatch.append(i)
                        return
            except OSError:
                mismatch.append(i)
                return
        else:
            data = engine.fs.read_file(path)
            if len(data) != sizes[i]:
                mismatch.append(i)
                return
            mv[off:off + sizes[i]] = data
        mv[off + sizes[i]] = 0x0A

    from delta_tpu.utils.threads import default_io_threads, shared_pool

    workers = min(max_workers, default_io_threads())
    with obs.span("storage.read_commits", files=n, bytes=total,
                  workers=workers if n > 4 else 0):
        if n > 4:
            # obs.wrap: contextvars don't cross the pool boundary, so
            # bind this span as the workers' parent explicitly. The
            # shared pool is safe here because fill() is a leaf read —
            # it never submits pool work of its own.
            shared_pool().map(obs.wrap(fill), range(n))
        else:
            for i in range(n):
                fill(i)
    if mismatch:
        return None
    version_arr = np.array([v for v, _, _ in commit_infos], dtype=np.int64)
    return buf, starts, version_arr


def _parse_buffer_generic(
    buf, starts: np.ndarray, version_arr: np.ndarray
) -> Optional[tuple[pa.Table, np.ndarray, np.ndarray, int]]:
    """Generic path over one concatenated buffer: one Arrow read_json
    call. Row→version mapping comes from one vectorized pass: a row ends
    at every newline not preceded by a newline; per-file counts by
    searchsorted over region boundaries. None when the parsed row count
    disagrees with the line accounting (caller re-reads per file)."""
    total = int(starts[-1])
    arr = np.frombuffer(buf, np.uint8)
    nl = arr == 0x0A
    prev = np.empty_like(nl)
    prev[0] = True
    prev[1:] = nl[:-1]
    row_ends = np.nonzero(nl & ~prev)[0]
    counts = np.diff(np.searchsorted(row_ends, starts))
    versions = np.repeat(version_arr, counts)
    orders = (
        np.arange(versions.shape[0], dtype=np.int64)
        - np.repeat(np.concatenate([[0], np.cumsum(counts)[:-1]]), counts)
    ).astype(np.int32)

    try:
        table = pa_json.read_json(
            pa.BufferReader(pa.py_buffer(buf)),
            read_options=pa_json.ReadOptions(block_size=1 << 24),
        )
    except pa.ArrowInvalid:
        # malformed JSON somewhere in the concatenated buffer; the
        # per-file fallback path diagnoses which commit (and whether it
        # is a torn trailing line) precisely
        return None
    if table.num_rows != versions.shape[0]:
        return None
    return table, versions, orders, total


def parse_commit_files(
    engine,
    commit_infos: Sequence[Tuple[int, str, int]],
    max_workers: int = 16,
) -> tuple[Optional[pa.Table], np.ndarray, np.ndarray, int]:
    """One buffer, one Arrow read_json call; per-file re-read fallback
    when listed sizes or line accounting disagree."""
    if not commit_infos:
        return None, np.empty(0, np.int64), np.empty(0, np.int32), 0
    read = _read_commits_buffer(engine, commit_infos, max_workers)
    out = _parse_buffer_generic(*read) if read is not None else None
    if out is None:
        from delta_tpu.utils.threads import parallel_map

        blobs = parallel_map(
            lambda vp: (vp[0], engine.fs.read_file(vp[1])),
            [(v, p) for v, p, _ in commit_infos])
        return parse_commit_batch(blobs)
    return out


def parse_commit_batch(
    commit_blobs: Sequence[Tuple[int, bytes]],
) -> tuple[Optional[pa.Table], np.ndarray, np.ndarray, int]:
    """Concatenate (version, raw bytes) commit files and parse once.

    Returns (parsed table, per-row versions, per-row orders, total bytes).
    """
    if not commit_blobs:
        return None, np.empty(0, np.int64), np.empty(0, np.int32), 0
    versions_parts: List[np.ndarray] = []
    orders_parts: List[np.ndarray] = []
    bufs: List[bytes] = []
    total = 0
    for version, blob in commit_blobs:
        total += len(blob)
        if not blob.endswith(b"\n"):
            blob = blob + b"\n"
        # vectorized line count; writers never emit blank lines, but fall
        # back to an exact scan if one shows up
        if b"\n\n" in blob or blob.startswith(b"\n"):
            nlines = sum(1 for ln in blob.split(b"\n") if ln.strip())
        else:
            nlines = int((np.frombuffer(blob, np.uint8) == 10).sum())
        bufs.append(blob)
        versions_parts.append(np.full(nlines, version, np.int64))
        orders_parts.append(np.arange(nlines, dtype=np.int32))
    data = b"".join(bufs)
    versions = np.concatenate(versions_parts) if versions_parts else np.empty(0, np.int64)
    orders = np.concatenate(orders_parts) if orders_parts else np.empty(0, np.int32)
    try:
        table = pa_json.read_json(
            pa.BufferReader(data),
            read_options=pa_json.ReadOptions(block_size=1 << 24),
        )
    except pa.ArrowInvalid as e:
        _raise_commit_parse_error(commit_blobs, str(e), cause=e)
    if table.num_rows != versions.shape[0]:
        _raise_commit_parse_error(
            commit_blobs,
            f"JSON parse row count {table.num_rows} != line count "
            f"{versions.shape[0]}",
        )
    return table, versions, orders, total


def _raise_commit_parse_error(
    commit_blobs: Sequence[Tuple[int, bytes]], detail: str, cause=None
):
    """Diagnose a commit-batch parse failure before raising.

    A crashed writer on a non-atomic store leaves the *newest* commit
    with a truncated final line; everything before it is intact. That
    shape is recoverable (drop the tip, read at version - 1), so it gets
    a dedicated `TornCommitError` carrying the torn version. Corruption
    anywhere else means the log itself is damaged and stays a plain
    `LogCorruptedError`.
    """
    from delta_tpu.errors import LogCorruptedError, TornCommitError

    tip_version, tip_blob = max(commit_blobs, key=lambda vb: vb[0])
    lines = [ln for ln in tip_blob.split(b"\n") if ln.strip()]
    torn = False
    if lines:
        try:
            json.loads(lines[-1])
        except ValueError:
            torn = all(_json_line_ok(ln) for ln in lines[:-1])
    if torn:
        _TORN_COMMITS.inc()
        raise TornCommitError(
            f"commit {tip_version} ends with a torn JSON line "
            f"(interrupted write); earlier lines are intact",
            version=tip_version,
        ) from cause
    raise LogCorruptedError(detail, version=tip_version) from cause


def _json_line_ok(line: bytes) -> bool:
    try:
        json.loads(line)
        return True
    except ValueError:
        return False


SMALL_ACTION_COLUMNS = ("protocol", "metaData", "txn", "domainMetadata")


def _extract_small_rows(
    table: pa.Table, versions: np.ndarray, orders: np.ndarray
) -> List[Tuple[int, int, dict]]:
    """Small-action rows of a parsed chunk in the native scanner's
    `others` format: (version, order, {action-key: body}). Lets a cached
    generic parse feed `_SmallActionTracker.scan_pylist` on later loads
    without re-touching the Arrow chunk."""
    rows: List[Tuple[int, int, dict]] = []
    for col in (*SMALL_ACTION_COLUMNS, "commitInfo"):
        if col not in table.column_names:
            continue
        arr = table.column(col).combine_chunks()
        if pa.types.is_null(arr.type):
            continue
        mask = np.asarray(pc.is_valid(arr), dtype=bool)
        sel = np.nonzero(mask)[0]
        if sel.size == 0:
            continue
        vals = arr.take(pa.array(sel, pa.int64())).to_pylist()
        for i, row in zip(sel, vals):
            rows.append((int(versions[i]), int(orders[i]), {col: row}))
    return rows


class _OnceThunk:
    """Memoize a one-shot decode thunk (the native scan's stats thunk
    consumes its scan object on first call) so a cached parse can serve
    the decoded column to any number of later snapshots."""

    __slots__ = ("_thunk", "_value", "_lock")

    def __init__(self, thunk):
        self._thunk = thunk
        self._value = None
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            if self._thunk is not None:
                self._value = self._thunk()
                self._thunk = None
            return self._value


def _combined_stats_thunk(parts):
    """Deferred stats decode spanning several blocks: `parts` is a list
    of (block, thunk-or-None); blocks without a thunk contribute their
    already-real stats column. Returns None when nothing is deferred."""
    if all(th is None for _, th in parts):
        return None

    def thunk():
        chunks: List[pa.Array] = []
        for block, th in parts:
            col = th() if th is not None else block.column("stats")
            if isinstance(col, pa.ChunkedArray):
                chunks.extend(col.chunks)
            else:
                chunks.append(col)
        return pa.chunked_array(chunks, pa.string())

    return thunk


@dataclass
class ParsedSpan:
    """One cached parse result covering a contiguous run of commit
    files. `keys` (native replay-key sidecar) is row-aligned with
    `block` and only usable when the span is the snapshot's sole
    file-action source."""

    block: pa.Table
    others: List[Tuple[int, int, dict]]
    keys: Optional[object]
    stats_thunk: Optional[_OnceThunk]
    n_files: int
    nbytes: int


def _span_nbytes(block: pa.Table, others: list) -> int:
    try:
        b = block.get_total_buffer_size()
    except (AttributeError, NotImplementedError):
        b = block.nbytes  # older pyarrow without the buffer-level API
    return int(b) + 256 * len(others)


class ParsedCommitCache:
    """Process-wide LRU of parsed commit spans, keyed by the tuple of
    `(path, size, mtime)` of the files each span covers (commit files
    are written put-if-absent, so the triple identifies the content;
    stat-deferred listings key on `(path, -1, 0)` consistently).

    Shared between full and incremental loads: a full load caches one
    span for the whole commit run; each `update()` caches one small span
    for its tail — so a later full reload is assembled entirely from
    cached spans and re-parses nothing. Coverage is greedy from the
    front of the request; only the uncovered tail is parsed."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        from collections import OrderedDict

        self._spans: "OrderedDict[tuple, ParsedSpan]" = OrderedDict()
        self._by_first: Dict[tuple, List[tuple]] = {}
        self._bytes = 0
        self.hits = 0          # lookups fully served from cache
        self.partial_hits = 0  # a prefix was served, tail parsed
        self.misses = 0
        self.hit_files = 0
        self.miss_files = 0

    def get_covering(self, file_keys: tuple) -> List[ParsedSpan]:
        """Longest greedy prefix cover of `file_keys` by cached spans
        (possibly empty). Covered spans are LRU-refreshed."""
        out: List[ParsedSpan] = []
        n = len(file_keys)
        with self._lock:
            i = 0
            while i < n:
                best = None
                for k in self._by_first.get(file_keys[i], ()):
                    if (len(k) <= n - i
                            and (best is None or len(k) > len(best))
                            and file_keys[i:i + len(k)] == k):
                        best = k
                if best is None:
                    break
                self._spans.move_to_end(best)
                out.append(self._spans[best])
                i += len(best)
            self.hit_files += i
            self.miss_files += n - i
            _OBS_CACHE_HIT_FILES.inc(i)
            _OBS_CACHE_MISS_FILES.inc(n - i)
            if i == n:
                self.hits += 1
                _OBS_CACHE_HITS.inc()
            elif out:
                self.partial_hits += 1
                _OBS_CACHE_PARTIAL.inc()
            else:
                self.misses += 1
                _OBS_CACHE_MISSES.inc()
        return out

    def put(self, file_keys: tuple, span: ParsedSpan) -> None:
        if not file_keys or span.nbytes > self.max_bytes:
            return
        with self._lock:
            if file_keys in self._spans:
                return
            self._spans[file_keys] = span
            self._by_first.setdefault(file_keys[0], []).append(file_keys)
            self._bytes += span.nbytes
            while self._bytes > self.max_bytes and len(self._spans) > 1:
                old_key, old = self._spans.popitem(last=False)
                self._bytes -= old.nbytes
                sibs = self._by_first.get(old_key[0], [])
                if old_key in sibs:
                    sibs.remove(old_key)
                    if not sibs:
                        del self._by_first[old_key[0]]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._by_first.clear()
            self._bytes = 0

    @property
    def cached_bytes(self) -> int:
        return self._bytes


_PARSE_CACHE: Optional[ParsedCommitCache] = None
_PARSE_CACHE_LOCK = threading.Lock()
_PARSE_CACHE_DEFAULT_BYTES = 6 << 30


def parse_cache() -> Optional[ParsedCommitCache]:
    """The process-wide parsed-commit cache, or None when disabled via
    DELTA_TPU_PARSE_CACHE_BYTES=0."""
    global _PARSE_CACHE
    if _PARSE_CACHE is None:
        with _PARSE_CACHE_LOCK:
            if _PARSE_CACHE is None:
                budget = int(os.environ.get(
                    "DELTA_TPU_PARSE_CACHE_BYTES",
                    _PARSE_CACHE_DEFAULT_BYTES))
                _PARSE_CACHE = (ParsedCommitCache(budget) if budget > 0
                                else False)
    return _PARSE_CACHE or None


def clear_parse_cache() -> None:
    """Drop all cached parses AND re-read the budget env var (tests and
    the bench cold-comparator use this)."""
    global _PARSE_CACHE
    with _PARSE_CACHE_LOCK:
        _PARSE_CACHE = None


def columnarize_log_segment(
    engine,
    segment,
    table_root: Optional[str] = None,
    small_only: bool = False,
    early_replay: bool = True,
) -> ColumnarActions:
    """Read every file in the segment and produce a ColumnarActions.

    Chunk order: checkpoint parts first (tagged with the checkpoint
    version), then compacted deltas, then commits ascending — but order
    only matters through the (version, order) tags; the device sort makes
    global order irrelevant.

    `small_only`: resolve only the small actions (protocol / metaData /
    txn / domainMetadata / commitInfo) — checkpoint parquet is read with
    column projection (the add/remove columns, i.e. ~all of a large
    checkpoint's bytes, are never decoded), sidecars are skipped (file
    actions only), and no file-action blocks are built. This is the
    reference's P&M fast path (`Snapshot.scala:440`,
    `LogReplay.loadTableProtocolAndMetadata`).
    """
    with obs.span("log.columnarize", version=segment.version,
                  small_only=small_only) as osp:
        out = _columnarize_log_segment(engine, segment, table_root,
                                       small_only, early_replay)
        osp.set_attrs(bytes_parsed=out.bytes_parsed,
                      num_commit_files=out.num_commit_files,
                      num_actions=out.num_actions)
        return out


def _columnarize_log_segment(
    engine,
    segment,
    table_root: Optional[str],
    small_only: bool,
    early_replay: bool,
) -> ColumnarActions:
    tracker = _SmallActionTracker()
    blocks: List[pa.Table] = []
    bytes_parsed = 0

    # Device-resident replay handoff: when the checkpoint is the sole
    # file-action source, every part's replay-key code lane (decoded on
    # device, never materialized on host) can feed the replay kernel
    # directly. Any contributor the decoder didn't key (sidecar, Arrow
    # fallback, JSON part) or any count/dv mismatch disables it — the
    # host replay path is then authoritative.
    want_handoff = (early_replay and not small_only
                    and bool(segment.checkpoints)
                    and not segment.compacted_deltas
                    and not segment.deltas)
    handoff = {"ok": want_handoff, "parts": []}

    def _dv_all_null(block) -> bool:
        return (block is None
                or block.column("dv_id").null_count == block.num_rows)

    def _abandon_handoff(part_keys=None) -> None:
        # a dead handoff abandons every accumulated device code lane;
        # deregister them so the resident ledger never counts lanes no
        # launch will ever consume
        from delta_tpu.ops.page_decode import release_part_keys

        dead = list(handoff["parts"])
        if part_keys is not None:
            dead.append(part_keys)
        handoff["parts"] = []
        release_part_keys(dead)

    def _track_handoff(part_keys, add_block, rem_block) -> None:
        if not handoff["ok"]:
            if part_keys is not None:
                _abandon_handoff(part_keys)
            return
        n_add = add_block.num_rows if add_block is not None else 0
        n_rem = rem_block.num_rows if rem_block is not None else 0
        if part_keys is None:
            # keyless contributors break row alignment unless they
            # contribute no file-action rows at all
            handoff["ok"] = not (n_add or n_rem)
            if not handoff["ok"]:
                _abandon_handoff()
            return
        # the device key lane must agree row-for-row with the Arrow
        # blocks: same present counts, no null paths inside present
        # structs, and no deletion vectors (the key lane is path-only)
        if (part_keys.n_bad or part_keys.n_add != n_add
                or part_keys.n_rem != n_rem
                or not _dv_all_null(add_block)
                or not _dv_all_null(rem_block)):
            handoff["ok"] = False
            _abandon_handoff(part_keys)
        else:
            handoff["parts"].append(part_keys)

    def _consume_checkpoint_table(tbl: pa.Table, part_keys=None):
        nonlocal blocks
        n = tbl.num_rows
        versions = np.full(n, cp_version, np.int64)
        # checkpoint rows precede all commit rows at the same version;
        # order is irrelevant within a checkpoint (keys are unique)
        orders = np.arange(n, dtype=np.int32)
        tracker.scan_chunk(tbl, versions, orders)
        if small_only:
            return  # sidecars carry only file actions — nothing to do
        part_blocks = {}
        for col in ("add", "remove"):
            block = _extract_file_actions(tbl, col, versions, orders)
            part_blocks[col] = block
            if block is not None:
                blocks.append(block)
        _track_handoff(part_keys, part_blocks["add"],
                       part_blocks["remove"])
        # V2 checkpoints: resolve sidecar pointers to _sidecars/ parquet
        if "sidecar" in tbl.column_names:
            sc = tbl.column("sidecar").combine_chunks()
            if not pa.types.is_null(sc.type):
                paths = pc.struct_field(sc, "path").to_pylist()
                sidecar_paths = [
                    p if "/" in p else f"{segment.log_path}/_sidecars/{p}"
                    for p in paths
                    if p is not None
                ]
                for sub in engine.parquet.read_parquet_files(sidecar_paths):
                    _consume_checkpoint_table(sub)

    def _read_checkpoint_part(path: str):
        if not small_only:
            yield from engine.parquet.read_parquet_files([path])
            return
        try:
            yield from engine.parquet.read_parquet_files(
                [path], columns=list(SMALL_ACTION_COLUMNS))
        except (pa.ArrowException, KeyError, ValueError):
            # part lacks some small column (e.g. a multipart tail part
            # written by another engine): fall back to a full read
            yield from engine.parquet.read_parquet_files([path])

    # --- checkpoint parts (columnar already) ---
    cp_version = segment.checkpoint_version

    def _consume_parts_device(parts):
        """Device page-decode route: prefetched part BYTES feed the
        one-lane plan builder (one dispatch per part); an unsupported
        shape decodes the SAME bytes through Arrow — never re-fetched."""
        nonlocal bytes_parsed
        import pyarrow.parquet as pq

        from delta_tpu.log.page_decode import read_checkpoint_part_device
        from delta_tpu.parallel import gate as gate_mod
        from delta_tpu.replay.pipeline import prefetch_file_bytes
        from delta_tpu.resilience import device_faults

        byte_iter = prefetch_file_bytes(
            engine, [f.path for f in parts
                     if not f.path.endswith(".json")])
        for fstat in parts:
            try:
                if fstat.path.endswith(".json"):
                    tbl = pa_json.read_json(pa.BufferReader(
                        engine.fs.read_file(fstat.path)))
                    _consume_checkpoint_table(tbl)
                else:
                    data = next(byte_iter)
                    host_reason = "unsupported-shape"
                    try:
                        out = device_faults.shed_retry(
                            "decode",
                            lambda data=data: read_checkpoint_part_device(
                                data, want_keys=want_handoff))
                    except Exception as e:
                        # classify (feeds the route breaker); permanent
                        # errors — a missing part file is one — re-raise
                        # into the handler below
                        if not device_faults.absorb_route_failure(
                                "decode", e):
                            raise
                        out = None
                        host_reason = f"device-error:{type(e).__name__}"
                    if out is not None:
                        _OBS_DECODE_PARTS.inc()
                        gate_mod.route_ok("decode")
                        _consume_checkpoint_table(out[0], out[1])
                    else:
                        _OBS_DECODE_FALLBACKS.inc()
                        obs.gate_fell_back("decode", "host",
                                           reason=host_reason)
                        with obs.gate_observation("decode", "host"):
                            tbl = pq.read_table(pa.BufferReader(data))
                        _consume_checkpoint_table(tbl)
            except FileNotFoundError:
                from delta_tpu.errors import LogCorruptedError

                raise LogCorruptedError(
                    f"couldn't find all part files of the checkpoint at "
                    f"version {cp_version}: {fstat.path} is missing",
                    error_class="DELTA_MISSING_PART_FILES")
            bytes_parsed += fstat.size

    def _consume_checkpoint_parts():
        nonlocal bytes_parsed
        parts = list(segment.checkpoints)
        # One routing decision per checkpoint read (the dispatch funnel
        # accumulates every part's cost onto it): raw part bytes over
        # the link vs the host Arrow decode rate.
        if not small_only and any(not f.path.endswith(".json")
                                  for f in parts):
            from delta_tpu.parallel import gate as _gate

            nbytes = sum(max(0, int(f.size)) for f in parts
                         if not f.path.endswith(".json"))
            if _gate.decode_route(
                    nbytes, getattr(engine, "use_device_decode",
                                    False)) == "device":
                _consume_parts_device(parts)
                return
        # Multipart/V2 parquet checkpoints: ONE batched handler call so
        # its byte-prefetch overlaps part i's decode with part i+1's
        # read. Consumption order is unchanged; the small_only
        # projection fallback keeps the per-part loop below.
        if (len(parts) > 1 and not small_only
                and all(not f.path.endswith(".json") for f in parts)):
            tables = engine.parquet.read_parquet_files(
                [f.path for f in parts])
            for fstat in parts:
                try:
                    # sidecar reads nest inside the consume call; a
                    # vanished sidecar maps like a vanished part
                    _consume_checkpoint_table(next(tables))
                except FileNotFoundError:
                    from delta_tpu.errors import LogCorruptedError

                    raise LogCorruptedError(
                        f"couldn't find all part files of the checkpoint "
                        f"at version {cp_version}: {fstat.path} is missing",
                        error_class="DELTA_MISSING_PART_FILES")
                bytes_parsed += fstat.size
            return
        for fstat in parts:
            try:
                if fstat.path.endswith(".json"):
                    # V2 top-level checkpoint in JSON form
                    tbl = pa_json.read_json(pa.BufferReader(engine.fs.read_file(fstat.path)))
                    _consume_checkpoint_table(tbl)
                else:
                    for tbl in _read_checkpoint_part(fstat.path):
                        _consume_checkpoint_table(tbl)
            except FileNotFoundError:
                # selected as a complete checkpoint at LIST time, gone at
                # read time (`DeltaErrors.missingPartFilesException`)
                from delta_tpu.errors import LogCorruptedError

                raise LogCorruptedError(
                    f"couldn't find all part files of the checkpoint at "
                    f"version {cp_version}: {fstat.path} is missing",
                    error_class="DELTA_MISSING_PART_FILES")
            bytes_parsed += fstat.size

    native_keys = None
    native_pending = None
    native_stats_thunk = None

    if segment.checkpoints:
        try:
            with obs.span("log.read_checkpoint", version=cp_version,
                          parts=len(segment.checkpoints)):
                _consume_checkpoint_parts()
        except BaseException:
            # a torn/corrupt checkpoint aborts the load mid-accumulation
            # (the caller falls back to an older segment) — the decoded
            # code lanes must leave the resident ledger with it
            _abandon_handoff()
            raise
        if handoff["ok"] and handoff["parts"]:
            # checkpoint-only load with every part keyed on device:
            # launch the replay straight from the device-resident code
            # lanes — the device sorts while the host assembles Arrow
            from delta_tpu.ops.page_decode import (
                launch_checkpoint_handoff,
            )

            mesh = getattr(engine, "mesh", None)
            n_shards = mesh.devices.size if mesh is not None else 1
            forced = ("sharded" if n_shards > 1 and getattr(
                engine, "_mesh_forced", False) else None)
            native_pending = launch_checkpoint_handoff(
                handoff["parts"], n_shards=n_shards, forced=forced)

    # --- compacted deltas + commits: parallel read, one JSON parse ---
    from delta_tpu.utils import filenames as fn

    commit_infos: List[Tuple[int, str, int]] = []
    commit_stats: List[object] = []  # FileStatus aligned with commit_infos
    for fstat in segment.compacted_deltas:
        _, hi = fn.compacted_delta_versions(fstat.path)
        commit_infos.append((hi, fstat.path, fstat.size))
        commit_stats.append(fstat)
    for fstat in segment.deltas:
        commit_infos.append((fn.delta_version(fstat.path), fstat.path, fstat.size))
        commit_stats.append(fstat)

    checkpoint_blocks = list(blocks)
    if commit_infos:
        cache = parse_cache()
        file_keys = tuple(
            (f.path, f.size, f.modification_time) for f in commit_stats)
        span_parts: List[ParsedSpan] = (
            cache.get_covering(file_keys) if cache is not None else [])
        n_covered = sum(s.n_files for s in span_parts)
        remaining = commit_infos[n_covered:]
        fresh_pending = None
        if remaining:
            version_arr = np.array([v for v, _, _ in remaining],
                                   dtype=np.int64)
            from delta_tpu import native as _native

            total_listed = sum(max(0, int(s)) for _, _, s in remaining)
            if any(int(s) < 0 for _, _, s in remaining):
                # stat-deferred listing: estimate with a typical commit size
                total_listed = max(total_listed, 8192 * len(remaining))
            allow_compile = total_listed >= _native.MIN_BYTES_FOR_COLD_BUILD
            parsed_native = generic = read = None
            native_rejected = False

            # Early device dispatch: when the native block will be the sole
            # block (no checkpoint rows, no cached spans) on a
            # single-device engine, kick the replay kernel off as soon as
            # the scan's key lanes exist — the device sorts while the host
            # assembles the Arrow table.
            launch = None
            mesh = getattr(engine, "mesh", None)
            sole_fresh = not blocks and not span_parts
            if early_replay and sole_fresh and not small_only:
                def launch(scan, row_versions, row_orders):
                    from delta_tpu.ops.replay import replay_select_launch
                    from delta_tpu.parallel import gate
                    from delta_tpu.replay.state import BLOCKWISE_MIN_ROWS
                    from delta_tpu.resilience import device_faults

                    # Same routing decision compute_masks_device will
                    # make: an early launch may only claim the replay
                    # when the single-chip kernel is the chosen route
                    # (host/sharded routes dispatch there instead).
                    n_shards = mesh.devices.size if mesh is not None else 1
                    forced = ("sharded" if n_shards > 1 and getattr(
                        engine, "_mesh_forced", False) else None)
                    if gate.replay_route(scan.n_rows, n_shards=n_shards,
                                         forced=forced) != "single":
                        return None
                    if scan.n_rows >= BLOCKWISE_MIN_ROWS:
                        return None  # >HBM: compute_masks_device streams blocks
                    if row_versions.max(initial=0) >= 2**31:
                        return None
                    try:
                        return device_faults.shed_retry(
                            "replay", lambda: replay_select_launch(
                                [scan.path_code,
                                 np.zeros(scan.n_rows, np.uint32)],
                                row_versions.astype(np.int32), row_orders,
                                scan.is_add.astype(bool),
                                fa_hint=(scan.path_new, scan.refs,
                                         scan.n_uniq),
                            ))
                    except Exception as e:
                        # The early launch is an overlap optimization:
                        # a transient device failure here just forfeits
                        # the head start — compute_masks_device makes
                        # its own (absorbed) attempt later, so no
                        # fallback counter and no host twin yet.
                        if not device_faults.absorb_route_failure(
                                "replay", e):
                            raise
                        return None
            # Pipelined load: when the tail is big enough to window,
            # overlap storage reads with parsing (and with the device
            # replay dispatch) instead of the phase-serial flow below.
            fresh = None
            if not small_only:
                from delta_tpu.replay import pipeline as _pipeline

                if _pipeline.enabled() and _pipeline.profitable(
                        engine, remaining,
                        _native.available(allow_compile)):
                    windows = _pipeline.plan_windows(
                        _pipeline.resolve_sizes(engine, remaining))
                    if len(windows) >= 2:
                        fresh, fresh_pending, pipe_nbytes = (
                            _pipeline.parse_commits_pipelined(
                                engine, windows,
                                allow_native=_native.available(
                                    allow_compile),
                                lazy_stats=not os.environ.get(
                                    "DELTA_TPU_EAGER_STATS"),
                                launch=launch,
                                allow_device=getattr(
                                    engine, "use_device_parse", False)))
                        bytes_parsed += pipe_nbytes
            if fresh is None:
                # Device JSON parse: gated by the engine's accelerator
                # opt-in + link economics (or DELTA_TPU_DEVICE_PARSE).
                # On fallback the buffer it read is REUSED by the host
                # branches below — never fetched twice.
                from delta_tpu.parallel import gate as _gate

                if _gate.parse_route(
                        total_listed,
                        getattr(engine, "use_device_parse",
                                False)) == "device":
                    from delta_tpu.replay import device_parse as _dp
                    from delta_tpu.resilience import device_faults

                    fell_reason = None
                    read = _read_commits_buffer(engine, remaining)
                    if read is not None:
                        buf, starts, version_arr = read
                        try:
                            parsed_native = device_faults.shed_retry(
                                "parse",
                                lambda: _dp.parse_commits_device(
                                    buf, starts, version_arr,
                                    small_only=small_only,
                                    lazy_stats=(not small_only
                                                and not os.environ.get(
                                                    "DELTA_TPU_EAGER_STATS"
                                                ))))
                        except Exception as e:
                            # classify (feeds the route breaker);
                            # transient -> host twin reuses the buffer
                            if not device_faults.absorb_route_failure(
                                    "parse", e):
                                raise
                            _OBS_PARSE_FALLBACKS.inc()
                            fell_reason = (
                                f"device-error:{type(e).__name__}")
                        if parsed_native is not None:
                            _gate.route_ok("parse")
                            bytes_parsed += int(starts[-1])
                    if parsed_native is None:
                        # buffer (if read) is reused by the host
                        # branches; price them against the "device"
                        # prediction for gate calibration
                        obs.gate_fell_back(
                            "parse", "host",
                            reason=(fell_reason if fell_reason is not None
                                    else "read-failed" if read is None
                                    else "device-parse-unavailable"))
            if (fresh is None and parsed_native is None and read is None
                    and _native.available(allow_compile)):
                # local files: one native read+scan round-trip (no per-file
                # interpreter I/O, no buffer copy into Python)
                local = [engine.fs.os_path(p) for _, p, _ in remaining]
                if all(p is not None for p in local):
                    from delta_tpu.replay.native_parse import (
                        parse_commit_paths_native,
                    )

                    out = parse_commit_paths_native(
                        local, version_arr, small_only=small_only,
                        launch=launch,
                        # stats decode defers only when a deferred column
                        # can later be assembled: the combined stats thunk
                        # spans blocks, so any non-small parse may defer
                        lazy_stats=(not small_only
                                    and not os.environ.get(
                                        "DELTA_TPU_EAGER_STATS")))
                    if out is not None:
                        block, others, keys, pending, sthunk, total = out
                        parsed_native = (block, others, keys, pending, sthunk)
                        bytes_parsed += total
                    else:
                        # the scanner saw (and rejected) this exact content —
                        # don't scan the same bytes natively a second time
                        native_rejected = True
            if fresh is None and parsed_native is None:
                # one parallel read into one buffer; the native C++ scanner
                # and the generic Arrow parser are alternative consumers of
                # the SAME bytes — a native-side rejection never re-fetches
                # (and a device-route fallback above already supplied them)
                if read is None:
                    read = _read_commits_buffer(engine, remaining)
                if read is not None:
                    buf, starts, version_arr = read
                    if not native_rejected and _native.available(allow_compile):
                        from delta_tpu.replay.native_parse import (
                            parse_commits_native,
                        )

                        parsed_native = parse_commits_native(
                            buf, starts, version_arr, small_only=small_only,
                            launch=launch)
                        if parsed_native is not None:
                            bytes_parsed += int(starts[-1])
                    if parsed_native is None:
                        generic = _parse_buffer_generic(buf, starts, version_arr)
            if parsed_native is not None:
                block, others, keys, pending, sthunk = parsed_native
                fresh_pending = pending
                fresh = ParsedSpan(
                    block=block, others=others, keys=keys,
                    stats_thunk=_OnceThunk(sthunk) if sthunk is not None
                    else None,
                    n_files=len(remaining),
                    nbytes=_span_nbytes(block, others))
            elif fresh is None:
                if generic is None:  # size mismatch or accounting failure
                    from delta_tpu.utils.threads import parallel_map

                    blobs = parallel_map(
                        lambda vp: (vp[0], engine.fs.read_file(vp[1])),
                        [(v, p) for v, p, _ in remaining])
                    generic = parse_commit_batch(blobs)
                tbl, versions, orders, nbytes = generic
                bytes_parsed += nbytes
                gen_blocks: List[pa.Table] = []
                small_rows: List[Tuple[int, int, dict]] = []
                if tbl is not None:
                    if small_only:
                        tracker.scan_chunk(tbl, versions, orders)
                    else:
                        small_rows = _extract_small_rows(tbl, versions,
                                                         orders)
                        for col in ("add", "remove"):
                            b = _extract_file_actions(tbl, col, versions,
                                                      orders)
                            if b is not None:
                                gen_blocks.append(b)
                fresh = None
                if not small_only:
                    gb = (pa.concat_tables(gen_blocks) if gen_blocks
                          else CANONICAL_FILE_ACTION_SCHEMA.empty_table())
                    fresh = ParsedSpan(
                        block=gb, others=small_rows, keys=None,
                        stats_thunk=None, n_files=len(remaining),
                        nbytes=_span_nbytes(gb, small_rows))
            if fresh is not None:
                span_parts.append(fresh)
                # never cache a small_only parse — its span has no file
                # actions and would poison later full loads
                if cache is not None and not small_only:
                    cache.put(file_keys[n_covered:], fresh)
        for part in span_parts:
            tracker.scan_pylist(part.others)
            if not small_only and part.block.num_rows:
                blocks.append(part.block)
        if not small_only:
            if not checkpoint_blocks and len(span_parts) == 1:
                # sole file-action source: the span's replay-key sidecar
                # (and any in-flight device dispatch) are row-aligned
                # with the final table
                native_keys = span_parts[0].keys
                native_pending = fresh_pending
            native_stats_thunk = _combined_stats_thunk(
                [(b, None) for b in checkpoint_blocks]
                + [(p.block, p.stats_thunk) for p in span_parts
                   if p.block.num_rows])

    if blocks:
        file_actions = pa.concat_tables(blocks)
    else:
        file_actions = CANONICAL_FILE_ACTION_SCHEMA.empty_table()

    latest_ci = None
    if tracker.commit_infos:
        latest_ci = tracker.commit_infos[max(tracker.commit_infos)]

    return ColumnarActions(
        file_actions=file_actions,
        protocol=tracker.protocol[2],
        metadata=tracker.metadata[2],
        set_transactions={k: t[2] for k, t in tracker.txns.items()},
        domain_metadata={k: t[2] for k, t in tracker.domains.items()},
        latest_commit_info=latest_ci,
        commit_infos=tracker.commit_infos,
        num_commit_files=len(commit_infos),
        pending_masks=native_pending,
        stats_thunk=native_stats_thunk,
        bytes_parsed=bytes_parsed,
        replay_keys=native_keys,
    )


def columnarize_commit_blobs(
    commit_blobs: Sequence[Tuple[int, bytes]],
) -> ColumnarActions:
    """In-memory commits → ColumnarActions, no filesystem access. The
    post-commit fast path feeds the bytes a transaction just wrote
    straight into snapshot advancement — the commit it authored is never
    re-listed or re-read (`SnapshotManagement.updateAfterCommit`)."""
    tracker = _SmallActionTracker()
    tbl, versions, orders, nbytes = parse_commit_batch(commit_blobs)
    blocks: List[pa.Table] = []
    if tbl is not None:
        tracker.scan_chunk(tbl, versions, orders)
        for col in ("add", "remove"):
            b = _extract_file_actions(tbl, col, versions, orders)
            if b is not None:
                blocks.append(b)
    fa = (pa.concat_tables(blocks) if blocks
          else CANONICAL_FILE_ACTION_SCHEMA.empty_table())
    latest_ci = None
    if tracker.commit_infos:
        latest_ci = tracker.commit_infos[max(tracker.commit_infos)]
    return ColumnarActions(
        file_actions=fa,
        protocol=tracker.protocol[2],
        metadata=tracker.metadata[2],
        set_transactions={k: t[2] for k, t in tracker.txns.items()},
        domain_metadata={k: t[2] for k, t in tracker.domains.items()},
        latest_commit_info=latest_ci,
        commit_infos=tracker.commit_infos,
        num_commit_files=len(commit_blobs),
        bytes_parsed=nbytes,
    )

"""Typed metric reports + timers.

Reference kernel `internal/metrics/` (Timer/Counter,
SnapshotMetrics/ScanMetrics/TransactionMetrics) pushed as
SnapshotReport / ScanReport / TransactionReport to engine-registered
MetricsReporters (`engine/Engine.java:61`), and spark's
`recordDeltaOperation` timing scopes (`DeltaLogging.scala:118`).

Reports are plain dicts with a `type` tag so reporters stay trivial;
`delta_tpu.engine.host.LoggingMetricsReporter` collects them in-memory.
"""

from __future__ import annotations

import time
import uuid
from contextlib import contextmanager
from typing import Dict, Optional


class Timer:
    def __init__(self):
        self.count = 0
        self.total_ns = 0

    @contextmanager
    def time(self):
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.record(time.perf_counter_ns() - t0)

    def record(self, duration_ns: int) -> None:
        self.count += 1
        self.total_ns += duration_ns

    @property
    def total_ms(self) -> float:
        return self.total_ns / 1e6


class Counter:
    def __init__(self):
        self.value = 0

    def increment(self, n: int = 1) -> None:
        self.value += n


class SnapshotMetrics:
    def __init__(self):
        self.load_init_state_timer = Timer()      # listing + segment build
        self.columnarize_timer = Timer()          # log parse → arrow
        self.replay_timer = Timer()               # dedup kernel
        self.num_commit_files = Counter()
        self.num_checkpoint_parts = Counter()
        self.num_actions = Counter()
        self.bytes_parsed = Counter()

    def report(self, table_path: str, version: int, extra: Optional[Dict] = None) -> Dict:
        r = {
            "type": "SnapshotReport",
            "reportUUID": str(uuid.uuid4()),
            "tablePath": table_path,
            "version": version,
            "loadInitStateMs": self.load_init_state_timer.total_ms,
            "columnarizeMs": self.columnarize_timer.total_ms,
            "replayMs": self.replay_timer.total_ms,
            "numCommitFiles": self.num_commit_files.value,
            "numCheckpointParts": self.num_checkpoint_parts.value,
            "numActions": self.num_actions.value,
            "bytesParsed": self.bytes_parsed.value,
        }
        if extra:
            r.update(extra)
        return r


def transaction_report(
    table_path: str,
    operation: str,
    read_version: int,
    committed_version: Optional[int],
    attempts: int,
    total_ms: float,
    num_adds: int,
    num_removes: int,
    success: bool,
) -> Dict:
    return {
        "type": "TransactionReport",
        "reportUUID": str(uuid.uuid4()),
        "tablePath": table_path,
        "operation": operation,
        "readVersion": read_version,
        "committedVersion": committed_version,
        "numCommitAttempts": attempts,
        "totalCommitMs": total_ms,
        "numAddFiles": num_adds,
        "numRemoveFiles": num_removes,
        "success": success,
    }

"""Typed metric reports + timers, derived from obs spans.

Reference kernel `internal/metrics/` (Timer/Counter,
SnapshotMetrics/ScanMetrics/TransactionMetrics) pushed as
SnapshotReport / ScanReport / TransactionReport to engine-registered
MetricsReporters (`engine/Engine.java:61`), and spark's
`recordDeltaOperation` timing scopes (`DeltaLogging.scala:118`).

Reports are plain dicts with a `type` tag so reporters stay trivial;
`delta_tpu.engine.host.LoggingMetricsReporter` collects them in-memory.

Since the obs subsystem landed, every `Timer` is a span bridge: give it
a `span_name` and each `time()` scope both records into the report (the
always-on path reporters depend on) and opens a `delta_tpu.obs` span
(the `DELTA_TPU_TRACE`-gated path traces are built from). Report timings
and trace timings therefore come from the same scopes — a report is the
flat projection of the spans of one operation.
"""
# delta-lint: file-disable=shared-state-race — audited:
# Timer/Counter here are per-operation metric bags (one
# SnapshotMetrics per snapshot load, owned by the operation's
# thread); the cross-thread instruments live in obs.registry, which
# locks where it must.

from __future__ import annotations

import time
import uuid
from contextlib import contextmanager
from typing import Dict, Optional

from delta_tpu.obs import span as _span


class Timer:
    """Count/total-ns accumulator; `span_name` makes each timed scope
    also an obs span (no-op when tracing is off)."""

    def __init__(self, span_name: Optional[str] = None):
        self.count = 0
        self.total_ns = 0
        self.span_name = span_name

    @contextmanager
    def time(self):
        if self.span_name:
            with _span(self.span_name):
                yield from self._measure()
        else:
            yield from self._measure()

    def _measure(self):
        # the report path must stay alive with tracing off, so this is
        # the one sanctioned raw-clock site the obs spans are bridged to
        # delta-lint: disable=obs-span-leak
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            # delta-lint: disable=obs-span-leak
            self.record(time.perf_counter_ns() - t0)

    def record(self, duration_ns: int) -> None:
        self.count += 1
        self.total_ns += duration_ns

    @property
    def total_ms(self) -> float:
        return self.total_ns / 1e6


class Counter:
    def __init__(self):
        self.value = 0

    def increment(self, n: int = 1) -> None:
        self.value += n


class SnapshotMetrics:
    def __init__(self):
        # span names mirror the phase names in docs/observability.md
        self.load_init_state_timer = Timer("snapshot.load_init_state")
        self.columnarize_timer = Timer("snapshot.columnarize")
        self.replay_timer = Timer("snapshot.replay")
        self.num_commit_files = Counter()
        self.num_checkpoint_parts = Counter()
        self.num_actions = Counter()
        self.bytes_parsed = Counter()

    def report(self, table_path: str, version: int, extra: Optional[Dict] = None) -> Dict:
        r = {
            "type": "SnapshotReport",
            "reportUUID": str(uuid.uuid4()),
            "tablePath": table_path,
            "version": version,
            "loadInitStateMs": self.load_init_state_timer.total_ms,
            "columnarizeMs": self.columnarize_timer.total_ms,
            "replayMs": self.replay_timer.total_ms,
            "numCommitFiles": self.num_commit_files.value,
            "numCheckpointParts": self.num_checkpoint_parts.value,
            "numActions": self.num_actions.value,
            "bytesParsed": self.bytes_parsed.value,
        }
        if extra:
            r.update(extra)
        return r


def transaction_report(
    table_path: str,
    operation: str,
    read_version: int,
    committed_version: Optional[int],
    attempts: int,
    total_ms: float,
    num_adds: int,
    num_removes: int,
    success: bool,
) -> Dict:
    return {
        "type": "TransactionReport",
        "reportUUID": str(uuid.uuid4()),
        "tablePath": table_path,
        "operation": operation,
        "readVersion": read_version,
        "committedVersion": committed_version,
        "numCommitAttempts": attempts,
        "totalCommitMs": total_ms,
        "numAddFiles": num_adds,
        "numRemoveFiles": num_removes,
        "success": success,
    }

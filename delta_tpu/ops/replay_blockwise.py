"""Blockwise (>HBM) replay: bounded-memory snapshot reconstruction.

SURVEY §5.7's scale path: a state too large for one device sort streams
through the kernel in blocks. The trick that keeps the merge bounded is
running the blocks in REVERSE chronological order with a persistent
device bitset of already-seen keys — the kernel-descending formulation
of replay (reference `ActiveAddFilesIterator.java:146`: first
occurrence wins when walking newest-to-oldest):

    for block in blocks[newest..oldest]:
        local_last = last occurrence of each key within the block
        winner     = local_last & ~seen[key]
        seen      |= block's keys

Device residency per step: one block's key lane + add bits + the seen
bitset (n_uniq / 8 bytes — 100M logical files = 12.5MB), regardless of
total row count. The bitset is donated between steps so XLA updates it
in place; winner masks come home bit-packed per block.

The output equals `replay_select` exactly (same winner-per-key
semantics, padding handling, live/tombstone split on the host).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from delta_tpu import obs
from delta_tpu.ops.replay import (
    _PAD_KEY,
    _unpack_bits,
    chrono_ok,
    combine_key_lanes,
    pad_bucket,
)

DEFAULT_BLOCK_ROWS = 1 << 22  # 4M rows/block: ~24MB device footprint


def _block_kernel_impl(seen_words, keys, n_real, m: int):
    """One reverse-order block step.

    seen_words u32[W]: bitset over key space (donated, updated in place).
    keys u32[m]: block's combined key lane (pad = sentinel); n_real i32.
    Returns (winner_words u32[m/32], updated seen_words) — the winner
    bits split into live/tombstone on the host, where is_add lives."""
    iota = jnp.arange(m, dtype=jnp.uint32)
    # sort by (key, pos): within a key run positions ascend, so the run's
    # LAST element is the block-locally-newest action for that key
    s_key, s_pos = lax.sort((keys, iota), num_keys=2)
    is_last = jnp.concatenate(
        [s_key[:-1] != s_key[1:], jnp.ones((1,), bool)])
    local_last = jnp.zeros((m,), bool).at[s_pos].set(is_last)

    valid = iota < jnp.uint32(n_real)
    key_clip = jnp.where(valid, keys, 0)
    seen_bit = (seen_words[key_clip >> 5] >> (key_clip & 31)) & jnp.uint32(1)
    winner = local_last & valid & (seen_bit == 0)

    # OR this block's keys into the bitset. Bits sharing a word must
    # combine, so: one bit per FIRST occurrence of each key (distinct
    # powers of two within a word), segment-sum by word (= exact OR for
    # distinct powers), scatter the per-word OR. Sorted keys make both
    # groupings contiguous. Sentinel pads contribute zero bits and
    # scatter a no-op value into word 0.
    is_first = jnp.concatenate(
        [jnp.ones((1,), bool), s_key[1:] != s_key[:-1]])
    real = s_key != jnp.uint32(0xFFFFFFFF)
    uniq_bit = jnp.where(is_first & real,
                         jnp.uint32(1) << (s_key & 31), jnp.uint32(0))
    # pads and unused segment slots scatter to an out-of-bounds sentinel
    # and DROP — a default of word 0 would race a real word-0 segment's
    # update with stale values (duplicate-index scatter is undefined)
    oob = jnp.uint32(seen_words.shape[0])
    word = jnp.where(real, s_key >> 5, oob)
    word_boundary = jnp.concatenate(
        [jnp.ones((1,), bool), word[1:] != word[:-1]])
    seg = jnp.cumsum(word_boundary.astype(jnp.int32)) - 1
    or_per_seg = jax.ops.segment_sum(uniq_bit, seg, num_segments=m)
    seg_word = jnp.full((m,), oob).at[seg].set(word)
    gathered = seen_words.at[seg_word].get(mode="clip")
    seen_words = seen_words.at[seg_word].set(
        gathered | or_per_seg.astype(jnp.uint32), mode="drop")

    bit_pos = jnp.arange(32, dtype=jnp.uint32)
    weights = jnp.uint32(1) << bit_pos
    winner_words = (winner.reshape(-1, 32).astype(jnp.uint32)
                    * weights).sum(axis=1, dtype=jnp.uint32)
    return winner_words, seen_words


_block_kernel = functools.partial(jax.jit, static_argnames=("m",),
                                  donate_argnums=(0,))(_block_kernel_impl)


def replay_select_blockwise(
    key_lanes,
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    device=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Bounded-memory replay over arbitrarily many rows; returns
    (live_mask, tombstone_mask) identical to `replay_select`."""
    n = int(version.shape[0])
    if n == 0:
        z = np.zeros((0,), dtype=bool)
        return z, z

    perm = None
    if not chrono_ok(np.asarray(version), np.asarray(order)):
        perm = np.lexsort((order, version))
        key_lanes = [np.asarray(k)[perm] for k in key_lanes]
        is_add = np.asarray(is_add)[perm]

    key = combine_key_lanes([np.asarray(k) for k in key_lanes])
    if key is None:
        wide = (np.asarray(key_lanes[0]).astype(np.uint64) << np.uint64(32)
                | np.asarray(key_lanes[1]).astype(np.uint64))
        _, key = np.unique(wide, return_inverse=True)
        key = key.astype(np.uint32)
    is_add = np.asarray(is_add, dtype=bool)

    n_uniq = int(key.max()) + 1 if n else 0
    m = pad_bucket(min(block_rows, n))
    n_words = pad_bucket(-(-max(n_uniq, 1) // 32), min_bucket=1024)
    seen = jnp.zeros((n_words,), jnp.uint32)
    if device is not None:
        # one-time seed upload of the persistent bitset (donated and
        # updated in place by every block step after)
        with obs.device_dispatch("replay.blockwise_seed",
                                 key=(n_words,)) as dd:
            seen = dd.h2d("seen", jax.device_put(seen, device))

    winner = np.zeros(n, dtype=bool)
    starts = list(range(0, n, m))
    for s in reversed(starts):
        e = min(s + m, n)
        blk = np.full(m, _PAD_KEY, np.uint32)
        blk[:e - s] = key[s:e]
        ops = (blk, np.int32(e - s))
        with obs.device_dispatch("replay.blockwise", key=(m, n_words),
                                 gate="replay", route="single") as dd:
            dd.h2d("block", int(blk.nbytes))
            if device is not None:
                ops = tuple(jax.device_put(o, device) for o in ops)
            winner_words, seen = _block_kernel(seen, *ops, m=m)
            winner[s:e] = _unpack_bits(
                dd.d2h("winner_words", np.asarray(winner_words)), m)[:e - s]

    live = winner & is_add
    tomb = winner & ~is_add
    if perm is not None:
        inv_live = np.zeros(n, dtype=bool)
        inv_tomb = np.zeros(n, dtype=bool)
        inv_live[perm] = live
        inv_tomb[perm] = tomb
        live, tomb = inv_live, inv_tomb
    return live, tomb

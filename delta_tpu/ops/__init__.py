"""Device (JAX/XLA/Pallas) columnar kernels.

- `replay`: snapshot state reconstruction as sort + segmented last-wins
  reduce — the TPU-native formulation of the reference's per-row hash-map
  replay (spark `InMemoryLogReplay.scala:52-100`, kernel
  `ActiveAddFilesIterator.java:146-219`).
- `hashing`: vectorized multi-lane 32-bit polynomial string hashing over
  padded byte matrices (key derivation that needs no host dictionary —
  the shard-routable path for multi-host replay).
- `zorder`: bit-interleave / Hilbert curve keys for OPTIMIZE clustering.
- `stats`: masked min/max/nullCount segment reductions for stats
  collection and checkpoint summaries.
"""

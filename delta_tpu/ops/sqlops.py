"""Device SQL operators: the execution spine for the SQL engine.

The reference delegates query execution to Spark's distributed columnar
engine (injected at
`spark/src/main/scala/io/delta/sql/DeltaSparkSessionExtension.scala:84-173`;
scans planned via
`spark/src/main/scala/org/apache/spark/sql/delta/stats/PrepareDeltaScan.scala:308`).
This module is the TPU-native replacement for the three relational
operators that dominate that substrate's work on TPC-DS: equi-join,
GROUP BY aggregation, and (window) sort. The division of labor follows
the replay kernel's proven shape (`ops/replay.py`):

- host: dictionary-encode string/float keys to dense uint32 codes
  (pandas factorize — same as `ops/join.py::equi_join_device`) and do
  O(output) gathers/expansions;
- device: the O(n log n) sorts (`jax.lax.sort`, stable, multi-lane) and
  O(n) segment reductions/scans (`jax.ops.segment_*`,
  `jax.lax.associative_scan`) on bucket-padded static shapes so jit
  caches a bounded number of programs across table sizes.

Aggregation dtype policy: integer columns accumulate in int64 (exact),
floats in float64 — x64 is enabled lazily on first use. The repo's other
kernels are dtype-explicit throughout, so flipping the global flag is
safe for them (verified by the full suite).

Null semantics match pandas GROUP BY (`dropna=False` on keys; null
values excluded from aggregates; all-null group sum/min/max = NULL) so
HostEngine's pandas path stays the bit-exact parity oracle.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from delta_tpu import obs
from delta_tpu.ops.replay import pad_bucket

_PAD_CODE = np.uint32(0xFFFFFFFF)
_x64_enabled = False

# Build sides at or above this many rows fan the segment-reduce
# aggregation out over the engine mesh (`shard_map` over REPLAY_AXIS),
# host-parity-gated like `ops/replay.py::compute_masks_device`. Below
# it the single-chip kernel wins: the shard routing pass costs more
# than the per-shard reduction saves.
DEFAULT_SHARDED_AGG_MIN_ROWS = 2_000_000


def sharded_agg_min_rows() -> int:
    env = os.environ.get("DELTA_TPU_SQL_SHARD_MIN_ROWS")
    if env:
        return int(env)
    return DEFAULT_SHARDED_AGG_MIN_ROWS


def _ensure_x64() -> None:
    """int64/float64 device math for exact aggregation. Lazy so
    processes that never touch the SQL spine keep the default."""
    global _x64_enabled
    if not _x64_enabled:
        jax.config.update("jax_enable_x64", True)
        _x64_enabled = True


# ------------------------------------------------------------- sort ----

@functools.partial(jax.jit, static_argnames=("num_keys",))
def _sort_kernel(operands, num_keys: int):
    out = jax.lax.sort(operands, num_keys=num_keys, is_stable=True)
    return out[-1]


def sort_permutation(lanes: Sequence[np.ndarray],
                     device=None) -> np.ndarray:
    """Stable multi-key ascending sort; returns the permutation (int64
    row indices). Lanes are NaN-free numerics, primary first; callers
    encode direction (negate for DESC) and null ordering (a 0/1 null
    lane per key) before calling — the device only ever sorts
    ascending."""
    _ensure_x64()
    n = int(len(lanes[0]))
    if n == 0:
        return np.empty(0, np.int64)
    npad = pad_bucket(n)
    with obs.device_dispatch("sqlops.sort", key=(len(lanes), npad),
                             budget="sql-sort-lanes", units=npad,
                             gate="sql") as dd:
        padded = []
        for lane in lanes:
            lane = np.asarray(lane)
            if lane.dtype == np.float32:
                lane = lane.astype(np.float64)
            elif lane.dtype == bool:  # 0/1 null-ordering lanes
                lane = lane.astype(np.uint8)
            if lane.dtype.kind == "f":
                fill = np.inf
            else:
                fill = np.iinfo(lane.dtype).max
            # "key" lanes mix dtypes (i64/f64 values, u8 null lanes), so
            # the manifest prices them at runtime via the recorded bytes
            # (entry is non-exhaustive); only iota is statically pinned
            key = np.full(npad, fill, dtype=lane.dtype)
            key[:n] = lane
            dd.h2d("key", key)
            padded.append(jax.device_put(key, device))
        iota = np.arange(npad, dtype=np.int64)
        dd.h2d("iota", iota)
        perm = np.asarray(_sort_kernel(
            tuple(padded) + (jax.device_put(iota, device),),
            num_keys=len(padded)))
    return perm[perm < n]


# --------------------------------------------------- group-by reduce ----

@functools.partial(jax.jit, static_argnames=("op", "n_seg"))
def _segagg_kernel(codes, v, valid, op: str, n_seg: int):
    """One aggregate over dense group codes. Returns (agg[n_seg],
    valid_count[n_seg])."""
    cnt = jax.ops.segment_sum(valid.astype(jnp.int64), codes,
                              num_segments=n_seg)
    if op == "count":
        return cnt, cnt
    if op == "sum":
        zero = jnp.zeros((), v.dtype)
        s = jax.ops.segment_sum(jnp.where(valid, v, zero), codes,
                                num_segments=n_seg)
        return s, cnt
    if v.dtype.kind == "f":
        big = jnp.array(np.inf, v.dtype)
    else:
        big = jnp.array(np.iinfo(np.int64).max, v.dtype)
    if op == "min":
        s = jax.ops.segment_min(jnp.where(valid, v, big), codes,
                                num_segments=n_seg)
    elif op == "max":
        s = jax.ops.segment_max(jnp.where(valid, v, -big), codes,
                                num_segments=n_seg)
    else:
        raise ValueError(op)
    return s, cnt


def _agg_mesh(n: int, mesh=None):
    """Resolve the mesh for the sharded segment-reduce fan-out; None
    keeps the single-chip kernel (input below the row threshold, a
    1-device mesh, or no usable mesh at all)."""
    if n < sharded_agg_min_rows():
        return None
    if mesh is None:
        try:
            from delta_tpu.parallel.mesh import make_mesh

            mesh = make_mesh()
        except (ImportError, RuntimeError, ValueError):
            return None
    if mesh is None or mesh.devices.size <= 1:
        return None
    return mesh


@functools.lru_cache(maxsize=16)
def _sharded_segagg_fn(mesh, op: str, n_seg: int):
    """Mesh-sharded segment reduce: each shard reduces its row block
    into a full [n_seg] partial, combined with one cross-shard
    psum/pmin/pmax. Per-segment results are identical to the
    single-chip kernel for int64 accumulation (the parity gate in
    tests/test_sql_operand_cache.py pins this); float64 sums may
    differ in the last ulp from the reassociated addition order."""
    from jax.sharding import PartitionSpec as P

    from delta_tpu.parallel.mesh import REPLAY_AXIS
    from delta_tpu.parallel.sharded_replay import shard_map

    def kernel(codes, v, valid):
        cnt = jax.ops.segment_sum(valid.astype(jnp.int64), codes,
                                  num_segments=n_seg)
        cnt = jax.lax.psum(cnt, REPLAY_AXIS)
        if op == "count":
            return cnt, cnt
        if op == "sum":
            zero = jnp.zeros((), v.dtype)
            s = jax.ops.segment_sum(jnp.where(valid, v, zero), codes,
                                    num_segments=n_seg)
            return jax.lax.psum(s, REPLAY_AXIS), cnt
        if v.dtype.kind == "f":
            big = jnp.array(np.inf, v.dtype)
        else:
            big = jnp.array(np.iinfo(np.int64).max, v.dtype)
        if op == "min":
            s = jax.ops.segment_min(jnp.where(valid, v, big), codes,
                                    num_segments=n_seg)
            s = jax.lax.pmin(s, REPLAY_AXIS)
        elif op == "max":
            s = jax.ops.segment_max(jnp.where(valid, v, -big), codes,
                                    num_segments=n_seg)
            s = jax.lax.pmax(s, REPLAY_AXIS)
        else:
            raise ValueError(op)
        return s, cnt

    spec = P(REPLAY_AXIS)
    fn = shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=(P(), P()))
    return jax.jit(fn)


@functools.partial(jax.jit, static_argnames=("n_seg",))
def _group_sizes_kernel(codes, real, n_seg: int):
    return jax.ops.segment_sum(real.astype(jnp.int64), codes,
                               num_segments=n_seg)


@functools.partial(jax.jit, static_argnames=("n_seg",))
def _centered_sumsq_kernel(codes, v, valid, means, n_seg: int):
    """Second pass for variance: sum((v - mean[g])^2) over valid rows."""
    d = v - means[codes]
    zero = jnp.zeros((), d.dtype)
    return jax.ops.segment_sum(jnp.where(valid, d * d, zero), codes,
                               num_segments=n_seg)


class GroupAggregator:
    """Padded, device-resident group codes plus per-spec reductions.

    Usage: construct with the row->group code array, then call
    `reduce(values, valid, op)` per aggregate. Ints accumulate in i64,
    floats in f64; `var(values, valid)` runs the exact two-pass
    variance. Results are sliced to `n_groups`.
    """

    def __init__(self, codes: np.ndarray, n_groups: int, device=None,
                 mesh=None):
        _ensure_x64()
        self.n = int(len(codes))
        self.n_groups = int(n_groups)
        self.n_seg = pad_bucket(self.n_groups + 1, min_bucket=256)
        self.npad = pad_bucket(max(self.n, 1))
        self._codes_np = np.asarray(codes)  # host copy for reuse
        codes_p = np.full(self.npad, self.n_seg - 1, np.int32)
        codes_p[:self.n] = codes
        self.device = device
        real = np.zeros(self.npad, bool)
        real[:self.n] = True
        with obs.device_dispatch("sqlops.group_codes", key=(self.npad,),
                                 budget="sql-agg-lanes", units=self.npad,
                                 gate="sql") as dd:
            dd.h2d("codes_p", codes_p)
            dd.h2d("real", real)
            self.codes = jax.device_put(codes_p, device)
            self._real = jax.device_put(real, device)
        mesh = _agg_mesh(self.n, mesh)
        if mesh is not None and self.npad % mesh.devices.size:
            mesh = None  # row blocks must split evenly over the mesh
        self._mesh = mesh

    def sizes(self) -> np.ndarray:
        """COUNT(*) per group."""
        out = _group_sizes_kernel(self.codes, self._real,
                                  n_seg=self.n_seg)
        return np.asarray(out)[:self.n_groups]

    def _pad(self, values: np.ndarray, valid: np.ndarray):
        v = np.asarray(values)
        if v.dtype.kind in "ui" or v.dtype == bool:
            v = v.astype(np.int64)
        else:
            v = v.astype(np.float64)
        # both arms are 8 B/unit, so the static budget holds either way
        vp = np.zeros(self.npad, np.int64) if v.dtype.kind != "f" \
            else np.zeros(self.npad, np.float64)
        vp[:self.n] = v
        mp = np.zeros(self.npad, bool)
        mp[:self.n] = valid
        with obs.device_dispatch("sqlops.agg_values", key=(self.npad,),
                                 budget="sql-agg-values", units=self.npad,
                                 gate="sql") as dd:
            dd.h2d("vp", vp)
            dd.h2d("mp", mp)
            return (jax.device_put(vp, self.device),
                    jax.device_put(mp, self.device))

    def reduce(self, values, valid, op: str):
        """Returns (agg[n_groups], valid_count[n_groups]) numpy arrays.
        Callers NULL-out groups where count==0 (min_count=1 sum
        semantics) and restore original dtypes."""
        vp, mp = self._pad(values, valid)
        if self._mesh is not None:
            fn = _sharded_segagg_fn(self._mesh, op, self.n_seg)
            agg, cnt = fn(self.codes, vp, mp)
        else:
            agg, cnt = _segagg_kernel(self.codes, vp, mp, op=op,
                                      n_seg=self.n_seg)
        return (np.asarray(agg)[:self.n_groups],
                np.asarray(cnt)[:self.n_groups])

    def var(self, values, valid):
        """Two-pass sample variance (exact centering — a single-pass
        sumsq in f64 loses catastrophically on money columns). Returns
        (var[n_groups], count[n_groups]); var is NaN where count < 2."""
        vp, mp = self._pad(values, valid)
        if vp.dtype != np.float64:
            vp = vp.astype(jnp.float64)
        s, cnt = _segagg_kernel(self.codes, vp, mp, op="sum",
                                n_seg=self.n_seg)
        means = s / jnp.maximum(cnt, 1)
        ss = _centered_sumsq_kernel(self.codes, vp, mp, means,
                                    n_seg=self.n_seg)
        cnt_np = np.asarray(cnt)[:self.n_groups]
        ss_np = np.asarray(ss)[:self.n_groups]
        with np.errstate(invalid="ignore", divide="ignore"):
            var = np.where(cnt_np >= 2, ss_np / np.maximum(cnt_np - 1, 1),
                           np.nan)
        return var, cnt_np

    def count_distinct(self, value_codes: np.ndarray,
                       valid: np.ndarray) -> np.ndarray:
        """COUNT(DISTINCT x) per group: device-sort (group, value)
        pairs, count run boundaries per group."""
        vc = np.asarray(value_codes, np.int64)
        g = self._codes_np.astype(np.int64)  # no D2H round-trip
        keep = np.asarray(valid, bool)
        g, vc = g[keep], vc[keep]
        m = len(g)
        if m == 0:
            return np.zeros(self.n_groups, np.int64)
        mpad = pad_bucket(m)
        gp = np.full(mpad, self.n_seg - 1, np.int64)
        gp[:m] = g
        vp = np.full(mpad, np.iinfo(np.int64).max, np.int64)
        vp[:m] = vc
        with obs.device_dispatch("sqlops.count_distinct", key=(mpad,),
                                 budget="sql-agg-distinct", units=mpad,
                                 gate="sql") as dd:
            dd.h2d("gp", gp)
            dd.h2d("vp", vp)
            out = _count_distinct_kernel(
                jax.device_put(gp, self.device),
                jax.device_put(vp, self.device), n_seg=self.n_seg)
        return np.asarray(out)[:self.n_groups]


@functools.partial(jax.jit, static_argnames=("n_seg",))
def _count_distinct_kernel(g, v, n_seg: int):
    sg, sv = jax.lax.sort((g, v), num_keys=2)
    first = jnp.concatenate([
        jnp.ones((1,), bool),
        (sg[1:] != sg[:-1]) | (sv[1:] != sv[:-1])])
    # pad group's runs land in segment n_seg-1, sliced off by caller
    return jax.ops.segment_sum(first.astype(jnp.int64), sg,
                               num_segments=n_seg)


# ----------------------------------------------------------- join ----

@jax.jit
def _join_sort_kernel(codes, side, iota):
    return jax.lax.sort((codes, side, iota), num_keys=2,
                        is_stable=True)


@jax.jit
def _join_lanes_kernel(l_vals, r_vals, n_l, n_r):
    """Sort (pad_flag, value, side) over the concatenated padded int64
    key lanes; side and iota are generated ON DEVICE (they never cross
    the link), and pads are identified positionally so any fill value
    in the padding is safe."""
    nl_pad = l_vals.shape[0]
    vals = jnp.concatenate([l_vals, r_vals])
    iota = jnp.arange(vals.shape[0], dtype=jnp.int64)
    side = (iota >= nl_pad).astype(jnp.uint8)
    local = jnp.where(side == 1, iota - nl_pad, iota)
    limit = jnp.where(side == 1, n_r, n_l)
    pad = (local >= limit).astype(jnp.uint8)
    return jax.lax.sort((pad, vals, side, iota), num_keys=3,
                        is_stable=True)


def _expand_pairs(
    s_key: np.ndarray,
    s_side: np.ndarray,
    s_pos: np.ndarray,
    r_offset: int,
    how: str,
) -> tuple[np.ndarray, np.ndarray]:
    """O(output) host pair expansion over key-sorted (key, side,
    position) triples: one run per distinct key, all left x right
    combinations per run; `how`-preserved unmatched rows get the other
    side's index = -1. Right positions are `r_offset`-rebased into
    right-frame indices. The output is variable-size, so this stays
    host-side under XLA's static-shape model."""
    empty = np.empty(0, np.int64)
    m = len(s_key)
    if m == 0:
        return empty, empty

    starts = np.flatnonzero(
        np.concatenate([[True], s_key[1:] != s_key[:-1]]))
    run_len = np.diff(np.concatenate([starts, [m]]))
    n_r = np.add.reduceat(s_side, starts).astype(np.int64)
    n_l = run_len - n_r

    pairs = n_l * n_r
    total = int(pairs.sum())
    run_of = np.repeat(np.arange(len(starts)), pairs)
    off = np.concatenate([[0], np.cumsum(pairs)[:-1]])
    within = np.arange(total, dtype=np.int64) - off[run_of]
    nr_run = n_r[run_of]
    li = within // nr_run
    ri = within - li * nr_run
    l_idx = s_pos[starts[run_of] + li]
    r_idx = s_pos[starts[run_of] + n_l[run_of] + ri] - r_offset

    extras_l = extras_r = None
    if how != "inner":
        run_of_sorted = np.repeat(np.arange(len(starts)), run_len)
    if how in ("left", "outer"):
        sel = (n_r[run_of_sorted] == 0) & (s_side == 0)
        extras_l = s_pos[sel]
    if how in ("right", "outer"):
        sel = (n_l[run_of_sorted] == 0) & (s_side == 1)
        extras_r = s_pos[sel] - r_offset
    if extras_l is not None and len(extras_l):
        l_idx = np.concatenate([l_idx, extras_l])
        r_idx = np.concatenate([r_idx, np.full(len(extras_l), -1,
                                               np.int64)])
    if extras_r is not None and len(extras_r):
        l_idx = np.concatenate([l_idx, np.full(len(extras_r), -1,
                                               np.int64)])
        r_idx = np.concatenate([r_idx, extras_r])
    return l_idx.astype(np.int64), r_idx.astype(np.int64)


def join_pairs(
    l_codes: np.ndarray,
    r_codes: np.ndarray,
    how: str = "inner",
    device=None,
) -> tuple[np.ndarray, np.ndarray]:
    """General many-to-many equi-join on pre-densified uint32 codes
    (< 0xFFFFFFFF). Returns (l_idx, r_idx) int64 pair indices;
    unmatched rows preserved by `how` appear with the other side's
    index = -1. Device does the combined O(n log n) sort; the host does
    the O(output) pair expansion with vectorized numpy.

    Unlike `ops/join.py::equi_join_codes` (MERGE's cardinality-
    restricted 1-match variant) the output here is variable-size — the
    expansion must live host-side under XLA's static-shape model.
    """
    _ensure_x64()
    nl, nr = int(len(l_codes)), int(len(r_codes))
    n = nl + nr
    empty = np.empty(0, np.int64)
    if n == 0:
        return empty, empty
    npad = pad_bucket(n)
    codes = np.full(npad, _PAD_CODE, np.uint32)
    codes[:nl] = l_codes
    codes[nl:n] = r_codes
    side = np.zeros(npad, np.uint32)
    side[nl:] = 1
    iota = np.arange(npad, dtype=np.int64)
    with obs.device_dispatch("sqlops.join_codes", key=(npad,),
                             budget="sql-join-lanes", units=npad,
                             gate="sql") as dd:
        dd.h2d("codes", codes)
        dd.h2d("side", side)
        dd.h2d("iota", iota)
        s_code, s_side, s_pos = (
            np.asarray(a) for a in _join_sort_kernel(
                jax.device_put(codes, device),
                jax.device_put(side, device),
                jax.device_put(iota, device)))
    real = s_code != _PAD_CODE
    return _expand_pairs(s_code[real], s_side[real], s_pos[real],
                         nl, how)


def join_pairs_lanes(
    l_vals: np.ndarray,
    r_vals: Optional[np.ndarray] = None,
    r_resident: Optional[Tuple[object, int]] = None,
    how: str = "inner",
    device=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Single-key many-to-many equi-join directly on int64 value lanes
    — no host factorize, and the side/iota lanes are generated on
    device, so only the key values ever cross the link (8 B/row vs the
    16 B/row `join_pairs` ships for codes + side + iota).

    `r_resident` is `(device_lane, n_rows)` from the operand cache
    (`sqlengine/operands.py`): the build side then costs ZERO H2D
    bytes. Exactly one of `r_vals` / `r_resident` must be given.
    Output contract matches `join_pairs` (pair order is value-sorted
    rather than first-appearance-sorted; both are valid many-to-many
    expansions of the same multiset)."""
    _ensure_x64()
    nl = int(len(l_vals))
    if r_resident is not None:
        r_dev, nr = r_resident
        nr = int(nr)
        nr_pad = int(r_dev.shape[0])
    else:
        nr = int(len(r_vals))
        nr_pad = pad_bucket(max(nr, 1))
    empty = np.empty(0, np.int64)
    if nl + nr == 0:
        return empty, empty
    nl_pad = pad_bucket(max(nl, 1))
    lp = np.zeros(nl_pad, np.int64)
    lp[:nl] = np.asarray(l_vals, np.int64)
    with obs.device_dispatch("sqlops.join_lanes",
                             key=(nl_pad, nr_pad),
                             budget="sql-join-values", units=nl_pad,
                             gate="sql") as dd:
        dd.h2d("lp", lp)
        l_dev = jax.device_put(lp, device)
        if r_resident is None:
            rp = np.zeros(nr_pad, np.int64)
            rp[:nr] = np.asarray(r_vals, np.int64)
            dd.h2d("rp", rp, units=nr_pad)
            r_dev = jax.device_put(rp, device)
        s_pad, s_val, s_side, s_pos = (
            np.asarray(a) for a in _join_lanes_kernel(
                l_dev, r_dev, jnp.int64(nl), jnp.int64(nr)))
    real = s_pad == 0
    return _expand_pairs(s_val[real], s_side[real], s_pos[real],
                         nl_pad, how)


# --------------------------------------------------------- windows ----

_NEG = np.int64(-(1 << 62))


@jax.jit
def _ranks_kernel(pb, kb):
    """Sorted-order rank family. pb[i]: row i starts a partition;
    kb[i]: row i starts an order-key run (kb includes pb positions).
    Returns (row_number, rank, dense_rank), all 1-based int64."""
    n = pb.shape[0]
    iota = jnp.arange(n, dtype=jnp.int64)
    neg = jnp.int64(_NEG)
    start = jax.lax.cummax(jnp.where(pb, iota, neg))
    row_number = iota - start + 1
    kstart = jax.lax.cummax(jnp.where(kb, iota, neg))
    rank = kstart - start + 1
    kcum = jnp.cumsum(kb.astype(jnp.int64))
    kcum_at_start = jax.lax.cummax(jnp.where(pb, kcum, neg))
    dense = kcum - kcum_at_start + 1
    return row_number, rank, dense


def window_ranks(pb: np.ndarray, kb: np.ndarray, device=None):
    """Host wrapper: bucket-pads the boundary lanes (pads start their
    own partitions so they can't bleed backwards) and slices."""
    _ensure_x64()
    n = len(pb)
    if n == 0:
        z = np.empty(0, np.int64)
        return z, z, z
    npad = pad_bucket(n)
    pbp = np.ones(npad, bool)
    kbp = np.ones(npad, bool)
    pbp[:n] = pb
    kbp[:n] = kb | pb
    with obs.device_dispatch("sqlops.window_ranks", key=(npad,),
                             budget="sql-window-ranks", units=npad,
                             gate="sql") as dd:
        dd.h2d("pbp", pbp)
        dd.h2d("kbp", kbp)
        rn, rk, dr = _ranks_kernel(jax.device_put(pbp, device),
                                   jax.device_put(kbp, device))
    return (np.asarray(rn)[:n], np.asarray(rk)[:n],
            np.asarray(dr)[:n])


@functools.partial(jax.jit, static_argnames=("op",))
def _segscan_kernel(v, valid, pb, op: str):
    """Segmented running aggregate in sorted order. Partitions are
    contiguous; pb marks starts. Returns (running[n], run_count[n])."""
    n = v.shape[0]
    iota = jnp.arange(n, dtype=jnp.int64)
    neg = jnp.int64(_NEG)
    start = jax.lax.cummax(jnp.where(pb, iota, neg))
    cnt_cum = jnp.cumsum(valid.astype(jnp.int64))
    cnt_base = jnp.where(start > 0,
                         cnt_cum[jnp.maximum(start - 1, 0)], 0)
    rcount = cnt_cum - cnt_base
    if op in ("sum", "mean"):
        zero = jnp.zeros((), v.dtype)
        c = jnp.cumsum(jnp.where(valid, v, zero))
        base = jnp.where(start > 0, c[jnp.maximum(start - 1, 0)],
                         zero)
        rsum = c - base
        if op == "mean":
            return rsum / jnp.maximum(rcount, 1), rcount
        return rsum, rcount
    if op == "count":
        return rcount.astype(jnp.float64), rcount
    # min/max: segmented scan via associative combine with reset flag
    if op == "min":
        fill = jnp.array(np.inf, v.dtype)
        red = jnp.minimum
    else:
        fill = jnp.array(-np.inf, v.dtype)
        red = jnp.maximum

    def comb(a, b):
        va, ba = a
        vb, bb = b
        return jnp.where(bb, vb, red(va, vb)), ba | bb

    vf = jnp.where(valid, v, fill)
    out, _ = jax.lax.associative_scan(comb, (vf, pb))
    return out, rcount


def window_running(v: np.ndarray, valid: np.ndarray, pb: np.ndarray,
                   op: str, device=None):
    """Running sum/mean/min/max/count within contiguous partitions (the
    SQL default RANGE UNBOUNDED PRECEDING..CURRENT ROW before peer
    sharing). Returns (values f64[n], counts i64[n]); rows where
    count==0 are NULL (callers mask)."""
    _ensure_x64()
    n = len(v)
    if n == 0:
        return np.empty(0, np.float64), np.empty(0, np.int64)
    npad = pad_bucket(n)
    vp = np.zeros(npad, np.float64)
    vp[:n] = np.asarray(v, np.float64)
    mp = np.zeros(npad, bool)
    mp[:n] = valid
    pbp = np.ones(npad, bool)
    pbp[:n] = pb
    with obs.device_dispatch("sqlops.window_running", key=(npad,),
                             budget="sql-window-running", units=npad,
                             gate="sql") as dd:
        dd.h2d("vp", vp)
        dd.h2d("mp", mp)
        dd.h2d("pbp", pbp)
        out, cnt = _segscan_kernel(jax.device_put(vp, device),
                                   jax.device_put(mp, device),
                                   jax.device_put(pbp, device), op=op)
    return np.asarray(out)[:n], np.asarray(cnt)[:n]


@jax.jit
def _peer_last_kernel(vals, counts, kb):
    """RANGE-frame peer sharing: every row takes the running value at
    the LAST row of its order-key run."""
    n = vals.shape[0]
    krun = jnp.cumsum(kb.astype(jnp.int64)) - 1
    iota = jnp.arange(n, dtype=jnp.int64)
    last = jax.ops.segment_max(iota, krun, num_segments=n)
    take = last[krun]
    return vals[take], counts[take]


def window_peer_last(vals: np.ndarray, counts: np.ndarray,
                     kb: np.ndarray, pb: Optional[np.ndarray] = None,
                     device=None):
    """`kb` marks order-key run starts; peers never span partitions,
    so pass `pb` (or pre-OR it in) — and row 0 always starts a run
    (forced here so a raw diff-based lane can't wrap the first run
    into the padding segment)."""
    _ensure_x64()
    n = len(vals)
    if n == 0:
        return vals, counts
    npad = pad_bucket(n)
    vp = np.zeros(npad, np.float64)
    vp[:n] = vals
    cp = np.zeros(npad, np.int64)
    cp[:n] = counts
    kbp = np.ones(npad, bool)
    kbp[:n] = kb if pb is None else (np.asarray(kb) | np.asarray(pb))
    kbp[0] = True
    with obs.device_dispatch("sqlops.window_peer_last", key=(npad,),
                             budget="sql-window-peers", units=npad,
                             gate="sql") as dd:
        dd.h2d("vp", vp)
        dd.h2d("cp", cp)
        dd.h2d("kbp", kbp)
        v_out, c_out = _peer_last_kernel(jax.device_put(vp, device),
                                         jax.device_put(cp, device),
                                         jax.device_put(kbp, device))
    return np.asarray(v_out)[:n], np.asarray(c_out)[:n]

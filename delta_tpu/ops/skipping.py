"""Batched data-skipping kernel: one dispatch over the whole conjunct
list x file-stats table (reference `stats/DataSkippingReader.scala`
constructDataFilters, here compiled instead of interpreted).

`stats/device_index.py` columnarizes the snapshot's parsed file stats
into an int64 lane matrix (3 rows per skipping-eligible column: min /
max / nullCount, plus one trailing numRecords row) with a validity
bitplane, resident on device across scans of one snapshot version. A
scan's conjunct list is compiled into flat *atom* arrays — one atom per
`col op lit` comparison, grouped so that OR-alternatives share a group
id — and this module evaluates every atom against every file in ONE
jitted call: gather the three stat rows per atom, apply the per-op
"known false" predicate, segment-fold atoms into per-group skip
verdicts, and AND the groups into a single keep mask (one bool D2H).

Kleene semantics match the host Arrow path by construction: an atom is
*known false* for a file only when the deciding stat is present and
proves no row can match; anything unknown keeps the file. A group
(OR of atoms) skips only when every atom is known false; the final
mask is the AND over groups. All lane math is int64 (floats are
pre-encoded into order-preserving int64 by the index builder), so the
numpy twin below is bit-identical to the jit kernel and routing is a
pure performance decision (`parallel/gate.py::skip_route`).

Atom op codes:
  0 '<'   1 '<='   2 '>'   3 '>='   4 '='   5 '!='
  6 IS NULL        7 IS NOT NULL
Ops 0-5 additionally treat an all-null column (nullCount == numRecords)
as known false, mirroring the host path's not-all-null augmentation.

This module performs no `jax.device_put`: the resident lanes are
uploaded by the budgeted site in `stats/device_index.py`, and the
per-scan atom arrays (~13 B per atom) ride along as jit arguments.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

from delta_tpu import obs
from delta_tpu.ops.stats import _x64


class AtomBlock(NamedTuple):
    """Compiled conjunct list: flat atom arrays over the lane matrix.

    `rows_mn/rows_mx/rows_nc` index lane-matrix rows (the index builder
    lays column c out as rows 3c/3c+1/3c+2, numRecords last); `grp`
    assigns each atom to an OR-group; groups are ANDed into the mask.
    """

    rows_mn: np.ndarray  # int32 [A] min-lane row per atom
    rows_mx: np.ndarray  # int32 [A] max-lane row per atom
    rows_nc: np.ndarray  # int32 [A] nullCount-lane row per atom
    ops: np.ndarray      # int32 [A] op code (see module docstring)
    lits: np.ndarray     # int64 [A] encoded literal (0 for ops 6/7)
    grp: np.ndarray      # int32 [A] OR-group id, dense in [0, n_groups)
    n_atoms: int
    n_groups: int


def _known_false(xp, mn, mx, nc, nr, vmn, vmx, vnc, vnr, ops, lits):
    """Per-atom x per-file "stats prove no row matches" matrix.

    Shared by the jit kernel and the numpy twin: `xp` is jax.numpy or
    numpy, every input already broadcast to [A, F] (or [1, F] for
    nr/vnr) and every value int64/bool, so both backends produce
    bit-identical results.
    """
    op = ops[:, None]
    lit = lits[:, None]
    all_null = vnc & vnr & (nc == nr)
    kf = xp.where(op == 0, vmn & (mn >= lit),
         xp.where(op == 1, vmn & (mn > lit),
         xp.where(op == 2, vmx & (mx <= lit),
         xp.where(op == 3, vmx & (mx < lit),
         xp.where(op == 4, (vmn & (mn > lit)) | (vmx & (mx < lit)),
         xp.where(op == 5, vmn & vmx & (mn == lit) & (mx == lit),
         xp.where(op == 6, vnc & (nc == 0),
                  vnc & vnr & (nc == nr))))))))
    return kf | ((op <= 5) & all_null)


@functools.lru_cache(maxsize=32)
def _skip_fn_cached(a_pad: int, g_segs: int):
    """jit'd keep-mask kernel for `a_pad` atom slots folding into
    `g_segs` segments (last segment is the pad-atom sink)."""
    import jax
    import jax.numpy as jnp

    def kernel(vals, valid, rows_mn, rows_mx, rows_nc, ops, lits, grp,
               n_atoms):
        mn, mx, nc = vals[rows_mn], vals[rows_mx], vals[rows_nc]
        vmn, vmx, vnc = valid[rows_mn], valid[rows_mx], valid[rows_nc]
        nr, vnr = vals[-1][None, :], valid[-1][None, :]
        kf = _known_false(jnp, mn, mx, nc, nr, vmn, vmx, vnc, vnr,
                          ops, lits)
        pad = (jnp.arange(a_pad, dtype=jnp.int32) >= n_atoms)[:, None]
        # pad atoms are routed to the sink segment with kf=True so they
        # can never unskip a real group nor skip anything themselves
        kf = jnp.where(pad, True, kf)
        g_min = jax.ops.segment_min(kf.astype(jnp.int32), grp,
                                    num_segments=g_segs)
        counts = jax.ops.segment_sum(
            jnp.where(pad[:, 0], 0, 1), grp, num_segments=g_segs)
        skip_g = (g_min == 1) & (counts > 0)[:, None]
        return ~jnp.any(skip_g[: g_segs - 1], axis=0)

    return jax.jit(kernel)


def skip_mask_block(dev_vals, dev_valid, block: AtomBlock,
                    n_files: int) -> np.ndarray:
    """Evaluate a compiled conjunct list against resident device lanes;
    one dispatch, one bool-mask D2H. `dev_vals`/`dev_valid` are the
    index's device arrays [R, F_pad]."""
    import jax.numpy as jnp

    from delta_tpu.ops.replay import pad_bucket

    a_pad = pad_bucket(max(block.n_atoms, 1), min_bucket=16)
    g_pad = pad_bucket(max(block.n_groups, 1), min_bucket=16)
    g_segs = g_pad + 1

    def _pad(a, fill, dtype):
        out = np.full(a_pad, fill, dtype=dtype)
        out[: block.n_atoms] = a
        return out

    rows_mn = _pad(block.rows_mn, 0, np.int32)
    rows_mx = _pad(block.rows_mx, 0, np.int32)
    rows_nc = _pad(block.rows_nc, 0, np.int32)
    ops = _pad(block.ops, 0, np.int32)
    lits = _pad(block.lits, 0, np.int64)
    grp = _pad(block.grp, g_segs - 1, np.int32)
    # the index lanes are HBM-resident (budgeted at upload in
    # stats/device_index.py); the per-scan atom arrays ride as jit
    # arguments, so this dispatch carries no budgeted device_put lane
    with obs.device_dispatch("skipping.mask_block", key=(a_pad, g_segs),
                             gate="skip") as dd, _x64():
        keep = _skip_fn_cached(a_pad, g_segs)(
            dev_vals, dev_valid, rows_mn, rows_mx, rows_nc, ops,
            jnp.asarray(lits), grp, np.int32(block.n_atoms))
        return dd.d2h("keep", np.asarray(keep))[:n_files]


def host_skip_mask(vals: np.ndarray, valid: np.ndarray, block: AtomBlock,
                   n_files: int) -> np.ndarray:
    """numpy twin of the device kernel: identical formulas over the
    identical int64 lanes, so masks are bit-identical across routes."""
    vals = vals[:, :n_files]
    valid = valid[:, :n_files]
    mn, mx, nc = vals[block.rows_mn], vals[block.rows_mx], vals[block.rows_nc]
    vmn, vmx, vnc = (valid[block.rows_mn], valid[block.rows_mx],
                     valid[block.rows_nc])
    nr, vnr = vals[-1][None, :], valid[-1][None, :]
    kf = _known_false(np, mn, mx, nc, nr, vmn, vmx, vnc, vnr,
                      block.ops, block.lits)
    keep = np.ones(n_files, dtype=bool)
    for g in range(block.n_groups):
        members = block.grp == g
        if members.any():
            keep &= ~kf[members].all(axis=0)
    return keep

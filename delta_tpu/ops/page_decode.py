"""Batched one-lane checkpoint page decode (the paper's 4th kernel).

`log/page_decode.py` owns the host side: it walks a checkpoint part's
projected column chunks, decompresses pages, parses the tiny varint run
headers of every RLE/bit-packed hybrid stream, and packs ALL page
payloads (data pages + dictionary pages + def-level streams + synthetic
path-dictionary remap tables) into ONE padded uint8 byte lane plus two
int32 plan lanes. This module owns the device side: a single cached-jit
dispatch per part decodes every hybrid position, expands def-levels to
a validity mask, gathers dictionary/PLAIN values, and — when the part's
path columns are cleanly dictionary-coded — compacts the replay-key
code lanes device-side so they NEVER round-trip through the host.

Plan layout (all int32):

run_plan[R, 6]   per hybrid run: global hybrid start, value count,
                 absolute lane bit offset, bit width, is_rle, rle value
                 (u32 bit pattern).
page_plan[P, 11] per data page: global output row start, row count,
                 max def level, def-stream hybrid start, kind
                 (PLAIN/BOOL/DICT), value byte offset, item size,
                 aux hybrid start (dict-index or bool bit stream),
                 dictionary byte offset, dictionary size, key column
                 flag (0 none / 1 add.path / 2 remove.path).

Everything is host-precomputed and static-shaped (pad_bucket), so the
whole decode is ONE dispatch per part: hybrid extract (Pallas tile on
TPU via `shift_extract`, fused jnp elsewhere) -> per-row def-level
lookup + present-rank cumsum -> byte gathers. int64/double values leave
as two u32 lanes combined host-side, which keeps the kernel x32-clean
for Mosaic.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from delta_tpu import obs
from delta_tpu.obs import hbm

# run_plan columns
R_H, R_N, R_BIT, R_W, R_RLE, R_VAL = range(6)
RUN_F = 6
# page_plan columns
(PG_OUT, PG_N, PG_MAXDEF, PG_DEFH, PG_KIND, PG_VALB, PG_ITEM, PG_AUXH,
 PG_DICTB, PG_DICTN, PG_KEY) = range(11)
PAGE_F = 11

KIND_PLAIN = 0
KIND_BOOL = 1
KIND_DICT = 2

KEY_NONE = 0
KEY_ADD = 1
KEY_REMOVE = 2

# searchsorted sentinel for plan padding rows: larger than any real
# hybrid/row index, far below int32 overflow
_FAR = 0x3FFFFFFF

# byte-lane cap so every absolute bit offset fits int32 (8*B < 2^31);
# a part beyond this falls back to Arrow whole-part
MAX_LANE_BYTES = 192 << 20

_OBS_HANDOFFS = obs.counter("decode.handoff_launches")


@dataclass
class PartPlan:
    """Host-built decode plan for one checkpoint part (see module doc
    for the lane layouts). Array shapes are already bucket-padded."""

    lane: np.ndarray       # uint8[B_pad]
    runs: np.ndarray       # int32[R_pad, RUN_F]
    pages: np.ndarray      # int32[P_pad, PAGE_F]
    h_total: int           # real hybrid positions (pre-pad)
    n_rows: int            # real output rows across all planned columns
    has_keys: bool         # any KEY_ADD/KEY_REMOVE pages present


@dataclass
class PartKeys:
    """Device-resident replay-key handoff for one part: part-local path
    codes compacted into (add rows, remove rows, pad) order. `codes`
    stays a device array — the handoff launcher remaps and consumes it
    without a host round trip."""

    codes: object          # jax u32[K_pad] device array (None if empty)
    n_add: int
    n_rem: int
    n_bad: int             # struct-present rows with a null path
    uniq: List[bytes]      # part-local dictionary, code order, raw bytes
    n_rows: int
    # resident-ledger handle for the device code lane; released by
    # `release_part_keys` when the handoff consumes or abandons it
    hbm: object = None


def _decode_stage_hybrid(lane, runs, h_pad: int, use_pallas: bool):
    import jax.numpy as jnp

    from delta_tpu.ops.pallas_kernels import shift_extract

    h = jnp.arange(h_pad, dtype=jnp.int32)
    run_h = runs[:, R_H]
    rid = jnp.clip(jnp.searchsorted(run_h, h, side="right") - 1,
                   0, runs.shape[0] - 1).astype(jnp.int32)
    row = runs[rid]
    j = jnp.clip(h - row[:, R_H], 0, row[:, R_N])
    w = row[:, R_W]
    bit = row[:, R_BIT] + j * w
    byte0 = bit >> 3
    b_max = lane.shape[0] - 1
    gb = [lane[jnp.clip(byte0 + k, 0, b_max)].astype(jnp.uint32)
          for k in range(5)]
    lo = gb[0] | (gb[1] << 8) | (gb[2] << 16) | (gb[3] << 24)
    val = shift_extract(lo, gb[4], (bit & 7).astype(jnp.uint32),
                        w.astype(jnp.uint32), use_pallas)
    return jnp.where(row[:, R_RLE] == 1, row[:, R_VAL].astype(jnp.uint32),
                     val)


@functools.lru_cache(maxsize=32)
def _decode_fn(b_pad: int, r_pad: int, p_pad: int, h_pad: int,
               n_pad: int, k_pad: int, has_keys: bool, use_pallas: bool):
    import jax
    import jax.numpy as jnp

    def fn(lane, runs, pages):
        hyb = _decode_stage_hybrid(lane, runs, h_pad, use_pallas)

        i = jnp.arange(n_pad, dtype=jnp.int32)
        pid = jnp.clip(jnp.searchsorted(pages[:, PG_OUT], i, side="right")
                       - 1, 0, p_pad - 1).astype(jnp.int32)
        pg = pages[pid]
        j = i - pg[:, PG_OUT]
        in_page = (j >= 0) & (j < pg[:, PG_N])
        maxdef = pg[:, PG_MAXDEF]
        h_max = h_pad - 1
        jc = jnp.clip(j, 0, _FAR)
        lvl = jnp.where(
            maxdef > 0,
            hyb[jnp.clip(pg[:, PG_DEFH] + jc, 0, h_max)].astype(jnp.int32),
            maxdef)
        defined = in_page & (lvl == maxdef)

        cdef = jnp.cumsum(defined.astype(jnp.int32))
        out0 = pg[:, PG_OUT]
        base = jnp.where(out0 > 0, cdef[jnp.clip(out0 - 1, 0, n_pad - 1)],
                         0)
        p = jnp.clip(cdef - 1 - base, 0, _FAR)

        kind = pg[:, PG_KIND]
        aux = hyb[jnp.clip(pg[:, PG_AUXH] + p, 0, h_max)]
        item = pg[:, PG_ITEM]
        idx = jnp.clip(aux.astype(jnp.int32), 0,
                       jnp.maximum(pg[:, PG_DICTN] - 1, 0))
        src = jnp.where(kind == KIND_DICT,
                        pg[:, PG_DICTB] + idx * item,
                        pg[:, PG_VALB] + p * item)
        b_max = b_pad - 1
        vb = [lane[jnp.clip(src + k, 0, b_max)].astype(jnp.uint32)
              for k in range(8)]
        lo = vb[0] | (vb[1] << 8) | (vb[2] << 16) | (vb[3] << 24)
        hi = vb[4] | (vb[5] << 8) | (vb[6] << 16) | (vb[7] << 24)
        lo = jnp.where(kind == KIND_BOOL, aux, lo)
        hi = jnp.where((kind != KIND_BOOL) & (item == 8), hi,
                       jnp.uint32(0))
        zero = jnp.uint32(0)
        out_lo = jnp.where(defined, lo, zero)
        out_hi = jnp.where(defined, hi, zero)
        if not has_keys:
            return out_lo, out_hi, defined

        key_col = pg[:, PG_KEY]
        struct_ok = lvl >= maxdef - 1
        pres_a = in_page & (key_col == KEY_ADD) & struct_ok
        pres_r = in_page & (key_col == KEY_REMOVE) & struct_ok
        bad = (pres_a | pres_r) & (lvl < maxdef)
        n_add = jnp.sum(pres_a.astype(jnp.int32))
        n_rem = jnp.sum(pres_r.astype(jnp.int32))
        n_bad = jnp.sum(bad.astype(jnp.int32))
        rank_a = jnp.cumsum(pres_a.astype(jnp.int32)) - 1
        rank_r = jnp.cumsum(pres_r.astype(jnp.int32)) - 1
        pos = jnp.where(pres_a, rank_a,
                        jnp.where(pres_r, n_add + rank_r, k_pad))
        codes = jnp.full((k_pad,), 0xFFFFFFFF,
                         jnp.uint32).at[pos].set(lo, mode="drop")
        return (out_lo, out_hi, defined, codes,
                jnp.stack([n_add, n_rem, n_bad]))

    return jax.jit(fn)


def decode_part(plan: PartPlan, device=None
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                           Optional[PartKeys]]:
    """Run the one-dispatch decode for `plan`. Returns (lo, hi, defined)
    numpy lanes over the plan's global row space plus the device-
    resident PartKeys (None when the plan carries no key pages).

    One H2D per lane of the `ckpt-page-decode` budget entry; values and
    validity return as dense D2H blocks, key codes STAY on device (only
    the three count scalars come back)."""
    import jax

    from delta_tpu.ops.pallas_kernels import _TILE, _use_interpret, _x32

    lane_bytes = np.asarray(plan.lane, np.uint8)
    run_plan = np.asarray(plan.runs, np.int32)
    page_plan = np.asarray(plan.pages, np.int32)
    b_pad = lane_bytes.shape[0]
    r_pad, p_pad = run_plan.shape[0], page_plan.shape[0]
    from delta_tpu.ops.replay import pad_bucket

    h_pad = pad_bucket(plan.h_total)
    n_pad = pad_bucket(plan.n_rows)
    k_pad = pad_bucket(plan.n_rows)
    use_pallas = not _use_interpret() and h_pad % _TILE == 0
    fn = _decode_fn(b_pad, r_pad, p_pad, h_pad, n_pad, k_pad,
                    plan.has_keys, use_pallas)
    with obs.device_dispatch(
            "page_decode.part",
            key=(b_pad, r_pad, p_pad, h_pad, n_pad, plan.has_keys),
            budget="ckpt-page-decode", units=b_pad,
            gate="decode") as dd, _x32():
        dd.h2d("lane_bytes", lane_bytes)
        dd.h2d("run_plan", run_plan, units=run_plan.size)
        dd.h2d("page_plan", page_plan, units=page_plan.size)
        outs = fn(jax.device_put(lane_bytes, device),
                  jax.device_put(run_plan, device),
                  jax.device_put(page_plan, device))
        lo = np.asarray(dd.d2h("out_lo", outs[0]))
        hi = np.asarray(dd.d2h("out_hi", outs[1]))
        defined = np.asarray(dd.d2h("defined", outs[2]))
        keys = None
        if plan.has_keys:
            counts = np.asarray(dd.d2h("key_counts", outs[4]))
            keys = PartKeys(codes=outs[3], n_add=int(counts[0]),
                            n_rem=int(counts[1]), n_bad=int(counts[2]),
                            uniq=[], n_rows=plan.n_rows)
            keys.hbm = hbm.register(
                keys, kind=hbm.KIND_CKPT_HANDOFF, arrays=(outs[3],),
                rebuild_cost_class="cheap",  # re-decode of one part
            )
    return lo, hi, defined, keys


# ---------------------------------------------------------------- handoff --


def release_part_keys(parts: Sequence[PartKeys]) -> None:
    """Deregister the device code lanes of `parts` — they were either
    consumed by a launched handoff or abandoned (handoff disqualified,
    route not chosen); either way the artifact's residency ends here."""
    for p in parts:
        if p.hbm is not None:
            p.hbm.release()
            p.hbm = None


def _decoded_paths(raw: Sequence[bytes]) -> Optional[List[str]]:
    """Decode raw path bytes with the same RFC 2396 percent-decoding the
    columnarizer applies (`replay/columnar.py::_decode_paths`); None on
    non-utf8 bytes (caller disqualifies the handoff)."""
    try:
        out = [b.decode("utf-8") for b in raw]
    except UnicodeDecodeError:
        return None
    if any("%" in s for s in out):
        from urllib.parse import unquote

        out = [unquote(s) if "%" in s else s for s in out]
    return out


@functools.lru_cache(maxsize=16)
def _handoff_fn(m: int, k_pads: tuple):
    import jax
    import jax.numpy as jnp

    from delta_tpu.ops.replay import _sort_winner_pack

    def fn(remap, meta, n_real, *code_lanes):
        out = jnp.full((m,), 0xFFFFFFFF, jnp.uint32)
        for i, codes in enumerate(code_lanes):
            local = jnp.clip(codes.astype(jnp.int32), 0,
                             jnp.maximum(meta[i, 1] - 1, 0))
            g = remap[jnp.clip(meta[i, 0] + local, 0,
                               remap.shape[0] - 1)]
            kidx = jnp.arange(codes.shape[0], dtype=jnp.int32)
            pos = jnp.where(kidx < meta[i, 3], meta[i, 2] + kidx, m)
            out = out.at[pos].set(g, mode="drop")
        return _sort_winner_pack((out,), n_real)

    return jax.jit(fn)


def launch_checkpoint_handoff(parts: Sequence[PartKeys], n_shards: int = 1,
                              forced: Optional[str] = None, device=None):
    """Launch the checkpoint-only replay straight from device-resident
    part key lanes. Returns an `ops.replay.ReplayPending` (the device
    sorts while the host assembles the Arrow table) or None when the
    single-chip route isn't chosen / the parts disqualify.

    Host work is O(unique paths): per-part dictionaries unify into one
    global code space and only the tiny uint32 remap tables cross the
    link — the O(rows) key lanes never leave the device. Row order is
    (part order) x (add block, remove block), exactly how the
    columnarizer concatenates checkpoint blocks, and a checkpoint holds
    at most one action per (path, dvId), so the synthetic chronological
    rank can never change a winner."""
    import jax

    from delta_tpu.ops.pallas_kernels import _x32
    from delta_tpu.ops.replay import ReplayPending, _pack_bits, pad_bucket
    from delta_tpu.parallel import gate
    from delta_tpu.replay.state import BLOCKWISE_MIN_ROWS

    # the launch consumes (or abandons) every part's code lane
    # on every return path below — residency ends with this call
    try:
        live = [p for p in parts if p.n_add + p.n_rem > 0]
        n = sum(p.n_add + p.n_rem for p in live)
        if not live or n == 0:
            return None
        if any(p.n_bad > 0 or p.codes is None for p in live):
            return None
        if n >= BLOCKWISE_MIN_ROWS:
            return None
        if gate.replay_route(n, n_shards=n_shards, forced=forced) != "single":
            return None

        # global path-code unification over RAW dictionary bytes, with the
        # percent-decoded collision check (two raw spellings of one decoded
        # path must share a replay code — rare, so just disqualify)
        global_codes: dict = {}
        remaps: List[np.ndarray] = []
        offs: List[int] = []
        off = 0
        for p in live:
            decoded = _decoded_paths(p.uniq)
            if decoded is None:
                return None
            remap = np.empty(max(len(decoded), 1), np.uint32)
            for j, s in enumerate(decoded):
                remap[j] = global_codes.setdefault(s, len(global_codes))
            offs.append(off)
            remaps.append(remap)
            off += remap.shape[0]
        if len(global_codes) >= 0xFFFFFFFF:
            return None

        m = pad_bucket(n)
        r_pad = pad_bucket(off, min_bucket=128)
        remap_lane = np.zeros(r_pad, np.uint32)
        remap_lane[:off] = np.concatenate(remaps)
        part_meta = np.zeros((len(live), 4), np.int32)
        is_add = np.zeros(m, np.bool_)
        row = 0
        for i, p in enumerate(live):
            part_meta[i] = (offs[i], remaps[i].shape[0], row,
                            p.n_add + p.n_rem)
            is_add[row:row + p.n_add] = True
            row += p.n_add + p.n_rem
        add_words = _pack_bits(is_add)

        k_pads = tuple(int(p.codes.shape[0]) for p in live)
        fn = _handoff_fn(m, k_pads)
        with obs.device_dispatch("page_decode.handoff", key=(m, k_pads),
                                 budget="ckpt-decode-handoff", units=r_pad,
                                 gate="replay", route="single") as dd, _x32():
            dd.h2d("remap_lane", remap_lane)
            dd.h2d("part_meta", part_meta, units=part_meta.size)
            winner = fn(jax.device_put(remap_lane, device),
                        jax.device_put(part_meta, device),
                        np.int32(n), *[p.codes for p in live])
        _OBS_HANDOFFS.inc()
        return ReplayPending(winner, add_words, n, None)
    finally:
        release_part_keys(parts)

"""Device kernels for the checkpoint WRITE path: per-column min/max/
null-count/sum segment aggregation, partition-code distinct counts, and
deletion-vector bitmap-container packing.

The checkpoint writer's aggregation stage summarizes the snapshot's
live-file columnar state per checkpoint part (rows, logical bytes,
modification-time bounds, null counts, distinct partition values) for
the part manifest and the `checkpoint.write` span tree. On an
accelerator the whole stage is ONE batched dispatch over the numeric
lanes — the state is already columnar, and the per-part segment
reductions are exactly the shape the replay kernels use — with the
results shipped back as one dense D2H block. Both stat modes (host
numpy / device) produce bit-identical aggregates: every lane is int64
and every reduction (min/max/sum/count) is order-independent over
integers, so checkpoints are byte-identical regardless of where the
aggregation ran (asserted by the write->read parity matrix in
tests/test_checkpoint_write.py).

H2D lanes are pinned by `resources/transfer_budget.json`
(`ckpt-stats-block`, `ckpt-dv-pack`): lane matrix int64, validity as a
packed bitplane, part ids int32; DV packing ships one int64 flat bit
index per set bit.

Env:
  DELTA_TPU_DEVICE_CKPT_STATS=1|0  force the aggregation stage on/off
                                   (unset: the engine flag decides —
                                   TpuEngine autodetects a non-CPU
                                   backend, HostEngine stays host)
  DELTA_TPU_DEVICE_DV_PACK=1      route multi-container roaring bitmap
                                   packing through the device kernel
  DELTA_TPU_DEVICE_DV_DECODE=1    route DV blob -> row-mask expansion
                                   through the decode kernel (the
                                   pack kernel's inverse)
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional, Sequence

import numpy as np

from delta_tpu import obs

# identity elements for empty segments — shared by both modes so the
# host fallback is bit-identical to jax.ops.segment_min/max
IDENT_MIN = np.iinfo(np.int64).max
IDENT_MAX = np.iinfo(np.int64).min

_BITMAP_WORDS = 2048  # 8192-byte roaring bitmap container, as uint32

def _x64():
    """Scoped 64-bit context for the dispatch: exact (order-independent)
    int64 device math without flipping the process-global
    `jax_enable_x64`, which would silently change default dtypes for
    every other kernel sharing the process."""
    from jax.experimental import enable_x64

    return enable_x64()


def device_stats_enabled(engine=None) -> bool:
    """Should the checkpoint aggregation stage run on device? Env
    override first (tests force either mode on any engine), then the
    engine's construction-time flag."""
    env = os.environ.get("DELTA_TPU_DEVICE_CKPT_STATS")
    if env is not None:
        return env not in ("0", "off", "false", "no")
    return bool(getattr(engine, "use_device_ckpt_stats", False))


def device_dv_pack_enabled() -> bool:
    return os.environ.get("DELTA_TPU_DEVICE_DV_PACK") == "1"


def device_dv_decode_enabled() -> bool:
    return os.environ.get("DELTA_TPU_DEVICE_DV_DECODE") == "1"


def accel_backend_default() -> bool:
    """Construction-time autodetect for TpuEngine: aggregate on device
    when a real accelerator backend is present."""
    try:
        import jax

        return jax.default_backend() != "cpu"
    # delta-lint: disable=except-swallow (audited: backend discovery can
    # fail on misconfigured hosts; engine construction must survive and
    # the stats stage falls back to the host path)
    except Exception:
        return False


# ---------------------------------------------------------- aggregation


@functools.lru_cache(maxsize=16)
def _agg_fn_cached(n_lanes: int, n_pad: int, p_pad: int):
    """jit'd segmented min/max/sum/null-count over an int64 lane matrix
    plus a distinct-count of the (part, code) pairs in the LAST lane.
    Padded rows carry part id `p_pad` and are dropped by the segment
    ops. One dense output block -> one D2H transfer."""
    import jax
    import jax.numpy as jnp

    def kernel(vals, valid_words, parts, code_mult):
        valid = jnp.unpackbits(valid_words, axis=1, count=n_pad,
                               bitorder="little").astype(bool)
        seg = parts
        vmin = jnp.where(valid, vals, jnp.int64(IDENT_MIN))
        vmax = jnp.where(valid, vals, jnp.int64(IDENT_MAX))
        vsum = jnp.where(valid, vals, jnp.int64(0))
        nulls = (~valid).astype(jnp.int64)
        mins = jax.vmap(
            lambda v: jax.ops.segment_min(v, seg, num_segments=p_pad))(vmin)
        maxs = jax.vmap(
            lambda v: jax.ops.segment_max(v, seg, num_segments=p_pad))(vmax)
        sums = jax.vmap(
            lambda v: jax.ops.segment_sum(v, seg, num_segments=p_pad))(vsum)
        nullc = jax.vmap(
            lambda v: jax.ops.segment_sum(v, seg, num_segments=p_pad))(nulls)
        # distinct (part, partition-code) pairs via one sorted pass over
        # the last lane: sort the combined key, count fresh values per
        # part segment (sentinel = padded/invalid rows, sorts last)
        codes = vals[-1]
        okrow = valid[-1] & (seg < p_pad)
        sentinel = jnp.int64(IDENT_MIN)
        key = jnp.where(okrow, seg.astype(jnp.int64) * code_mult + codes,
                        sentinel)
        skey = jnp.sort(key)
        fresh = jnp.concatenate(
            [skey[:1] != sentinel,
             (skey[1:] != skey[:-1]) & (skey[1:] != sentinel)])
        part_of = jnp.where(skey == sentinel, jnp.int64(p_pad),
                            skey // code_mult).astype(jnp.int32)
        distinct = jax.ops.segment_sum(fresh.astype(jnp.int64), part_of,
                                       num_segments=p_pad)
        return jnp.concatenate(
            [mins, maxs, sums, nullc, distinct[None, :]], axis=0)

    return jax.jit(kernel)


def checkpoint_stats_block(
    lanes: Sequence[np.ndarray],
    valids: Sequence[np.ndarray],
    part_of_row: np.ndarray,
    n_parts: int,
    n_codes: int,
    device=None,
) -> np.ndarray:
    """Per-part aggregates of `lanes` on device, one dispatch, one dense
    D2H block of shape [4*L + 1, n_parts]: rows 0..L-1 min, L..2L-1 max,
    2L..3L-1 sum, 3L..4L-1 null count, last row = distinct partition
    codes (the last lane holds the partition-value dictionary codes).

    `device` colocates the lane upload with e.g. the resident replay
    state's device. All lanes int64, validity a packed bitplane, part
    ids int32 — the transfer plane committed in transfer_budget.json.
    """
    import jax

    from delta_tpu.ops.replay import pad_bucket

    n_l = len(lanes)
    n = int(lanes[0].shape[0]) if n_l else 0
    n_pad = pad_bucket(max(n, 1))
    p_pad = pad_bucket(max(n_parts, 1), min_bucket=8)
    lane_vals = np.zeros((n_l, n_pad), np.int64)
    vb = np.zeros((n_l, n_pad), bool)
    for i, (lane, valid) in enumerate(zip(lanes, valids)):
        lane_vals[i, :n] = np.asarray(lane, np.int64)
        vb[i, :n] = np.asarray(valid, bool)
    valid_words = np.packbits(vb, axis=1, bitorder="little")
    part_ids = np.full(n_pad, p_pad, np.int32)
    part_ids[:n] = np.asarray(part_of_row, np.int32)
    # a code multiplier > any code keeps (part, code) pairs distinct
    code_mult = np.int64(max(int(n_codes), 1) + 1)
    fn = _agg_fn_cached(n_l, n_pad, p_pad)
    # lane matrices are [n_l, n_pad]: each lane prices at its own unit
    # count (the manifest unit is one padded file row per stat lane)
    with obs.device_dispatch("stats.ckpt_block", key=(n_l, n_pad, p_pad),
                             budget="ckpt-stats-block",
                             units=n_pad) as dd, _x64():
        dd.h2d("lane_vals", lane_vals, units=n_l * n_pad)
        dd.h2d("valid_words", valid_words, units=n_l * n_pad)
        dd.h2d("part_ids", part_ids)
        block = fn(jax.device_put(lane_vals, device),
                   jax.device_put(valid_words, device),
                   jax.device_put(part_ids, device),
                   code_mult)
        return dd.d2h("block", np.asarray(block))[:, :n_parts]


def host_stats_block(
    lanes: Sequence[np.ndarray],
    valids: Sequence[np.ndarray],
    part_of_row: np.ndarray,
    n_parts: int,
    n_codes: int,
) -> np.ndarray:
    """Host-mode twin of `checkpoint_stats_block` — bit-identical
    output (same identities, same int64 arithmetic)."""
    n_l = len(lanes)
    out = np.zeros((4 * n_l + 1, n_parts), np.int64)
    out[0:n_l, :] = IDENT_MIN
    out[n_l:2 * n_l, :] = IDENT_MAX
    pid = np.asarray(part_of_row, np.int64)
    for p in range(n_parts):
        m = pid == p
        for i in range(n_l):
            v = np.asarray(lanes[i], np.int64)[m]
            ok = np.asarray(valids[i], bool)[m]
            if ok.any():
                out[i, p] = v[ok].min()
                out[n_l + i, p] = v[ok].max()
            out[2 * n_l + i, p] = int(v[ok].sum()) if ok.any() else 0
            out[3 * n_l + i, p] = int((~ok).sum())
        if n_l:
            codes = np.asarray(lanes[-1], np.int64)[m]
            okc = np.asarray(valids[-1], bool)[m]
            out[4 * n_l, p] = len(np.unique(codes[okc]))
    return out


# ------------------------------------------------------- DV bit packing


@functools.lru_cache(maxsize=16)
def _pack_fn_cached(n_pad: int, n_words: int):
    """jit'd scatter of flat bit indexes into a stack of roaring bitmap
    containers. Each set bit appears exactly once, so the per-word
    contributions are distinct powers of two and `add` == bitwise-or.
    The sentinel index (word == n_words) drops."""
    import jax
    import jax.numpy as jnp

    def kernel(idx):
        word = (idx >> 5).astype(jnp.int32)
        bit = jnp.left_shift(jnp.uint32(1), (idx & 31).astype(jnp.uint32))
        return jnp.zeros(n_words, jnp.uint32).at[word].add(bit, mode="drop")

    return jax.jit(kernel)


def pack_bitmap_words(flat_bits: np.ndarray, n_containers: int,
                      device=None) -> np.ndarray:
    """Pack flat container-relative bit indexes (container * 65536 +
    low16) into `n_containers` 8192-byte roaring bitmap containers in
    one batched dispatch; returns a [n_containers, 8192] uint8 block
    (one dense D2H) laid out exactly like the host packer
    (little-endian bit order)."""
    import jax

    from delta_tpu.ops.replay import pad_bucket

    n = int(len(flat_bits))
    n_pad = pad_bucket(max(n, 1))
    n_words = int(n_containers) * _BITMAP_WORDS
    flat_idx = np.full(n_pad, n_words * 32, np.int64)
    flat_idx[:n] = np.asarray(flat_bits, np.int64)
    with obs.device_dispatch("stats.dv_pack", key=(n_pad, n_words),
                             budget="ckpt-dv-pack", units=n_pad) as dd, \
            _x64():
        dd.h2d("flat_idx", flat_idx)
        words = _pack_fn_cached(n_pad, n_words)(
            jax.device_put(flat_idx, device))
        out = dd.d2h("words", np.ascontiguousarray(np.asarray(words)))
    if out.dtype.byteorder == ">":  # pragma: no cover - LE hosts only
        out = out.astype("<u4")
    return out.view(np.uint8).reshape(n_containers, 8192)


# ------------------------------------------------------- DV bit decode


@functools.lru_cache(maxsize=16)
def _decode_fn_cached(i_pad: int, w_pad: int, n_words: int):
    """jit'd inverse of `_pack_fn_cached`: scatter array-container bit
    indexes AND whole bitmap-container words into one flat uint32 word
    stream. The two lane families are disjoint by construction — a
    roaring container is either array-coded (contributes single bits)
    or bitmap-coded (contributes whole words) — and set bits are
    unique, so `add` == bitwise-or throughout. Sentinels (bit index ==
    n_words*32, word position == n_words) drop."""
    import jax
    import jax.numpy as jnp

    def kernel(bit_idx, bm_words, bm_pos):
        word = (bit_idx >> 5).astype(jnp.int32)
        bit = jnp.left_shift(jnp.uint32(1),
                             (bit_idx & 31).astype(jnp.uint32))
        out = jnp.zeros(n_words, jnp.uint32).at[word].add(bit, mode="drop")
        return out.at[bm_pos].add(bm_words, mode="drop")

    return jax.jit(kernel)


def decode_mask_words(bit_idx: np.ndarray, bm_words: np.ndarray,
                      bm_pos: np.ndarray, n_words: int,
                      device=None) -> np.ndarray:
    """Expand a deletion vector's containers to a flat little-endian
    uint32 word stream on device, one batched dispatch: `bit_idx` are
    absolute row indexes from array/run containers (int64), `bm_words`
    are raw bitmap-container words placed at word positions `bm_pos`.
    Returns [n_words] uint32 (one dense D2H) — the exact inverse of
    `pack_bitmap_words`."""
    import jax

    from delta_tpu.ops.replay import pad_bucket

    ni = int(len(bit_idx))
    nw = int(len(bm_words))
    i_pad = pad_bucket(max(ni, 1))
    w_pad = pad_bucket(max(nw, 1))
    lane_bit_idx = np.full(i_pad, int(n_words) * 32, np.int64)
    lane_bit_idx[:ni] = np.asarray(bit_idx, np.int64)
    lane_bm_words = np.zeros(w_pad, np.uint32)
    lane_bm_words[:nw] = np.asarray(bm_words, np.uint32)
    lane_bm_pos = np.full(w_pad, int(n_words), np.int32)
    lane_bm_pos[:nw] = np.asarray(bm_pos, np.int32)
    with obs.device_dispatch("stats.dv_decode",
                             key=(i_pad, w_pad, int(n_words)),
                             budget="dv-decode-lanes") as dd, _x64():
        dd.h2d("lane_bit_idx", lane_bit_idx, units=i_pad)
        dd.h2d("lane_bm_words", lane_bm_words, units=w_pad)
        dd.h2d("lane_bm_pos", lane_bm_pos, units=w_pad)
        words = _decode_fn_cached(i_pad, w_pad, int(n_words))(
            jax.device_put(lane_bit_idx, device),
            jax.device_put(lane_bm_words, device),
            jax.device_put(lane_bm_pos, device))
        out = dd.d2h("words", np.ascontiguousarray(np.asarray(words)))
    if out.dtype.byteorder == ">":  # pragma: no cover - LE hosts only
        out = out.astype("<u4")
    return out

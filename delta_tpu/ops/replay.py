"""Log replay as a device sort + segmented last-wins reduce.

The reconciliation contract (PROTOCOL.md:823-843): for each logical file
key `(path, dv_unique_id)`, the newest action wins — a surviving `add` is
a live file, a surviving `remove` is a tombstone (kept for VACUUM), and
the live/tombstone key sets are disjoint.

The reference implements this as sequential hash-map upserts per action
(ascending, spark `InMemoryLogReplay.scala:52`) or hash-set probes
(descending, kernel `ActiveAddFilesIterator.java:146`). Neither
vectorizes. The TPU-native formulation used here:

1. Encode each file action as fixed-width columns:
   `key...` (one or more int32 lanes identifying `(path, dv)`),
   `version` (int32), `order` (int32, position within its commit), and
   `is_add`.
2. `lax.sort` all rows lexicographically by (key..., version, order).
   After the sort every logical file's history is a contiguous run in
   chronological order.
3. The run boundary mask (`key[i] != key[i+1]`) marks each run's last
   element — exactly the newest action per key. No loops, no hash table;
   XLA lowers the whole thing to its TPU sort + fused elementwise ops.
4. Scatter the winner mask back to input order.

Padding rows (key lanes = 0xFFFFFFFF, valid=False) sort to the end and are
masked out, so batch sizes are bucketed to limit recompilation.

Complexity O(n log n) versus the hash maps' O(n) — but at 200+ GB/s of
sorted bandwidth on one chip versus pointer-chasing JVM maps, and it
shards cleanly: route rows by key hash to devices, sort/reduce locally,
no cross-device dedup needed (delta_tpu.parallel).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_PAD_KEY = np.uint32(0xFFFFFFFF)
_MIN_BUCKET = 1024


def pad_bucket(n: int) -> int:
    """Round up to the next power of two (min 1024) so jit caches a small
    number of shapes across snapshot sizes."""
    if n <= _MIN_BUCKET:
        return _MIN_BUCKET
    return 1 << (int(n - 1).bit_length())


class ReplayResult(NamedTuple):
    live: jax.Array        # bool[n]: action survives as a live add
    tombstone: jax.Array   # bool[n]: action survives as a remove tombstone


@functools.partial(jax.jit, static_argnames=("num_key_lanes",))
def _replay_select(keys_and_meta, num_key_lanes: int) -> ReplayResult:
    """keys_and_meta = (*key_lanes[uint32], version[i32], order[i32],
    is_add[bool], valid[bool], idx[i32]). All length-n, padded."""
    *key_lanes, version, order, is_add, valid, idx = keys_and_meta
    n = version.shape[0]
    operands = tuple(key_lanes) + (version, order, is_add, valid, idx)
    num_keys = num_key_lanes + 2  # sort by key lanes, then version, then order
    sorted_ops = lax.sort(operands, num_keys=num_keys, is_stable=False)
    s_keys = sorted_ops[:num_key_lanes]
    s_is_add = sorted_ops[num_key_lanes + 2]
    s_valid = sorted_ops[num_key_lanes + 3]
    s_idx = sorted_ops[num_key_lanes + 4]

    same_as_next = jnp.ones((n - 1,), dtype=bool)
    for k in s_keys:
        same_as_next = same_as_next & (k[:-1] == k[1:])
    is_last = jnp.concatenate([~same_as_next, jnp.ones((1,), dtype=bool)])

    winner = is_last & s_valid
    live_sorted = winner & s_is_add
    tomb_sorted = winner & ~s_is_add

    live = jnp.zeros((n,), dtype=bool).at[s_idx].set(live_sorted)
    tomb = jnp.zeros((n,), dtype=bool).at[s_idx].set(tomb_sorted)
    return ReplayResult(live, tomb)


def replay_select(
    key_lanes: Sequence[np.ndarray],
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    device=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-facing wrapper: pads, ships to device, runs the kernel, and
    returns (live_mask, tombstone_mask) as numpy bool arrays of the
    original length.

    key_lanes: one or more uint32/int32 arrays jointly identifying the
    logical file (dictionary codes or hash lanes). version/order: int32.
    """
    n = int(version.shape[0])
    if n == 0:
        z = np.zeros((0,), dtype=bool)
        return z, z
    m = pad_bucket(n)
    pad = m - n

    def pad_with(arr, value, dtype):
        arr = np.asarray(arr, dtype=dtype)
        if pad == 0:
            return arr
        return np.concatenate([arr, np.full((pad,), value, dtype=dtype)])

    lanes = tuple(pad_with(k, _PAD_KEY, np.uint32) for k in key_lanes)
    operands = lanes + (
        pad_with(version, -1, np.int32),
        pad_with(order, -1, np.int32),
        pad_with(is_add, False, np.bool_),
        np.concatenate([np.ones((n,), bool), np.zeros((pad,), bool)]) if pad else
        np.ones((n,), bool),
        np.arange(m, dtype=np.int32),
    )
    if device is not None:
        operands = tuple(jax.device_put(o, device) for o in operands)
    result = _replay_select(operands, num_key_lanes=len(lanes))
    live = np.asarray(result.live)[:n]
    tomb = np.asarray(result.tombstone)[:n]
    return live, tomb


def python_replay_reference(
    keys: Sequence[tuple],
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """Sequential hash-map replay — the reference semantics
    (`InMemoryLogReplay.scala:52-100`) — used for parity tests and as the
    honest CPU baseline in benchmarks."""
    n = len(keys)
    rows = sorted(range(n), key=lambda i: (int(version[i]), int(order[i])))
    winner: dict = {}
    for i in rows:
        winner[keys[i]] = i
    live = np.zeros(n, dtype=bool)
    tomb = np.zeros(n, dtype=bool)
    for key, i in winner.items():
        if is_add[i]:
            live[i] = True
        else:
            tomb[i] = True
    return live, tomb

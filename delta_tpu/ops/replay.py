"""Log replay as a device sort + segmented last-wins reduce.

The reconciliation contract (PROTOCOL.md:823-843): for each logical file
key `(path, dv_unique_id)`, the newest action wins — a surviving `add` is
a live file, a surviving `remove` is a tombstone (kept for VACUUM), and
the live/tombstone key sets are disjoint.

The reference implements this as sequential hash-map upserts per action
(ascending, spark `InMemoryLogReplay.scala:52`) or hash-set probes
(descending, kernel `ActiveAddFilesIterator.java:146`). Neither
vectorizes. The TPU-native formulation used here:

1. The columnarizer emits actions in chronological order (checkpoint
   rows, then commits ascending, line order within a commit), so the row
   index *is* the chronological rank — no (version, order) columns need
   to ship to the device; a device-side iota is the sort tiebreaker.
   (If a caller passes rows out of order, the host permutes them into
   chronological order first and un-permutes the masks after — the
   kernel itself never sees a rank lane.)
2. Key lanes are dense dictionary codes assigned by the columnarizer in
   FIRST-APPEARANCE order (`pd.factorize`, replay/state.py). In a real
   Delta log every `add` carries a fresh UUID file name, so most rows
   introduce a brand-new code — which, under first-appearance coding, is
   always `prev_max + 1`. The transfer exploits that: one `is_new` flag
   bit per row, explicit byte-packed codes only for the minority of rows
   that reference an existing file (removes, DV re-adds), and a sparse
   (row, value) list for the rare non-zero DV lane. The device rebuilds
   the exact code array with a cumsum + gather. Typical cost: ~1–2
   bits/row over the host↔device link instead of 4 bytes. Streams that
   aren't first-appearance-coded (verified host-side with two cheap
   vector passes) fall back to shipping the combined code lane as the
   minimum number of little-endian byte planes that hold its range.
3. One `lax.sort` by (key, chrono_rank) — two operands total, both
   sort keys, and the rank is a device-side iota. After the sort every
   logical file's history is a contiguous run in chronological order;
   the run-boundary mask `key[i] != key[i+1]` marks the newest action
   per key. No loops, no hash table. The add/remove bit never ships:
   the iota is already unique, so the bit cannot change any winner, and
   the host keeps its own packed copy for the live/tombstone split.
4. One scatter puts the per-run winner mask back in input order; the
   winner bits ship home packed (32× smaller D2H) and the host — which
   already holds `is_add` — splits winners into live (`winner & add`)
   and tombstone (`winner & ~add`) with two packed-word ops. The device
   never materializes the live/tomb masks separately.

Padding rows (key = all-ones sentinel) sort to the end; a run that mixes
real and padding rows is won by its last *valid* row via the
`is_last | ~next_valid` mask, so no `valid` lane ships.

Complexity O(n log n) versus the hash maps' O(n) — but as one fused XLA
sort at HBM bandwidth versus pointer-chasing JVM maps, and it shards
cleanly: route rows by key to devices, sort/reduce locally, no
cross-device dedup needed (delta_tpu.parallel).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from delta_tpu import obs

_PAD_KEY = np.uint32(0xFFFFFFFF)
_MIN_BUCKET = 1024

# Bytes of replay operands shipped host->device. The residency tests
# and the bench artifact read this to prove incremental updates ship
# only delta rows (never the 10M-row base state).
_H2D_BYTES = obs.counter("replay.h2d_bytes")


_FINE_PAD_START = 1 << 20  # above this, pad linearly instead of to pow2
_FINE_PAD_STEP = 1 << 19


def pad_bucket(n: int, min_bucket: int = _MIN_BUCKET) -> int:
    """Round up to a shape bucket so jit caches a bounded number of
    shapes across snapshot sizes: next power of two up to 1M rows, then
    the next multiple of 512k. Pure pow2 padding wastes up to ~2× in
    transfer bytes and sort rows exactly at the multi-million-row scale
    where each step costs hundreds of ms; the linear tail keeps waste
    under 5% there while still bounding distinct compiled shapes."""
    if n <= min_bucket:
        return min_bucket
    if n <= _FINE_PAD_START:
        return 1 << (int(n - 1).bit_length())
    return -(-n // _FINE_PAD_STEP) * _FINE_PAD_STEP


def chrono_ok(version: np.ndarray, order: np.ndarray) -> bool:
    """True if rows are already in chronological (version, order) order,
    in which case the row index is the chronological rank.

    Uses elementwise comparisons rather than diffs so any integer dtype
    (signed or unsigned, any width) is handled without overflow-prone
    casts or copies."""
    if version.shape[0] <= 1:
        return True
    v0, v1 = version[:-1], version[1:]
    if (v1 < v0).any():
        return False
    same = v1 == v0
    if not same.any():
        return True
    return not bool((same & (order[1:] < order[:-1])).any())


def combine_key_lanes(key_lanes: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """Mixed-radix combine of dense key-code lanes into one uint32 lane
    (reserving 0xFFFFFFFF for padding). None if the ranges don't fit.

    All arithmetic stays in uint32: every mixed-radix partial value is
    bounded by the final radix product, which is checked (in Python ints)
    to fit below the sentinel before any array math runs."""
    lanes = [np.asarray(k) for k in key_lanes]
    maxes = [int(lane.max(initial=0)) for lane in lanes]
    radix = 1
    for mx in maxes:
        radix *= mx + 1
        if radix > 0xFFFFFFFF:  # need the sentinel free: values < 0xFFFFFFFF
            return None
    if len(lanes) == 1:
        return lanes[0].astype(np.uint32, copy=False)
    combined = lanes[0].astype(np.uint32, copy=True)
    for lane, mx in zip(lanes[1:], maxes[1:]):
        combined *= np.uint32(mx + 1)
        combined += lane.astype(np.uint32, copy=False)
    return combined


def key_byte_width(max_key: int) -> int:
    """Bytes/row needed to ship keys so that the all-ones sentinel of that
    width stays reserved for padding."""
    for width in (1, 2, 3):
        if max_key < (1 << (8 * width)) - 1:
            return width
    return 4


def _pack_key_planes(key: np.ndarray, width: int, pad: int,
                     pad_byte: int = 0xFF) -> tuple[np.ndarray, ...]:
    """uint32[n] -> `width` separate contiguous uint8 planes (little-endian
    byte j of each value), padded. Planar layout: interleaved (n, width)
    u8 would force stride-`width` byte access on device, which TPUs hate."""
    b = np.ascontiguousarray(key).view(np.uint8).reshape(-1, 4)
    planes = []
    for j in range(width):
        plane = np.ascontiguousarray(b[:, j])
        if pad:
            plane = np.concatenate([plane, np.full(pad, pad_byte, np.uint8)])
        planes.append(plane)
    return tuple(planes)


def _pack_bits(mask: np.ndarray) -> np.ndarray:
    """bool[n] -> uint32[n/32] little-endian bit words (n % 32 == 0)."""
    return np.packbits(mask, bitorder="little").view(np.uint32)


def _unpack_bits(words: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(words.view(np.uint8), bitorder="little")[:n].astype(bool)


def _unpack_bits_device(words: jax.Array) -> jax.Array:
    """uint32[m/32] -> uint32[m] of 0/1 bits (little-endian bit order)."""
    bit_pos = jnp.arange(32, dtype=jnp.uint32)
    return ((words[:, None] >> bit_pos[None, :]) & jnp.uint32(1)).reshape(-1)


def _decode_planes(planes) -> jax.Array:
    """Little-endian uint8 planes -> uint32 values."""
    key = planes[0].astype(jnp.uint32)
    for j in range(1, len(planes)):
        key = key | (planes[j].astype(jnp.uint32) << jnp.uint32(8 * j))
    return key


def _sort_winner_pack(lanes, n_real) -> jax.Array:
    """Shared tail of both kernels: sort by (key..., iota) where the
    iota is the chronological rank (callers permute first if their rows
    aren't already chronological). Marks per-run winners in sorted
    order, scatters the single winner mask back to input order, and
    bit-packs it. The iota is unique, so no extra tiebreaker lane can
    ever change a winner — in particular the add/remove bit stays home
    (the r05 regression shipped it per-row and widened the payload for
    nothing). Padding rows (idx >= n_real) sort after the real rows of
    any run they share a key with (their iota is larger), so the winner
    of a run is its last *valid* row — a real row whose key happens to
    equal the all-ones pad sentinel is never swallowed by padding."""
    m = lanes[0].shape[0]
    payload = jnp.arange(m, dtype=jnp.uint32)
    sorted_ = lax.sort((*lanes, payload), num_keys=len(lanes) + 1,
                       is_stable=False)
    s_lanes, s_payload = sorted_[:-1], sorted_[-1]
    s_idx = s_payload.astype(jnp.int32)
    s_valid = s_idx < n_real

    same_as_next = jnp.ones((m - 1,), dtype=bool)
    for k in s_lanes:
        same_as_next = same_as_next & (k[:-1] == k[1:])
    next_valid = jnp.concatenate([s_valid[1:], jnp.zeros((1,), dtype=bool)])
    is_last = jnp.concatenate([~same_as_next, jnp.ones((1,), dtype=bool)])
    winner = s_valid & (is_last | ~next_valid)

    winner_orig = jnp.zeros((m,), dtype=bool).at[s_idx].set(winner)
    bit_pos = jnp.arange(32, dtype=jnp.uint32)
    weights = jnp.uint32(1) << bit_pos
    return (winner_orig.reshape(-1, 32).astype(jnp.uint32) * weights).sum(
        axis=1, dtype=jnp.uint32)


@functools.partial(jax.jit, static_argnames=("width",))
def _winner_kernel(operands, width: int) -> jax.Array:
    """Full-key path. operands = (*key_planes[u8, m] | *key_lanes[u32, m],
    n_real[i32]) -> winner_words[u32, m/32]."""
    *key_ops, n_real = operands
    lanes = (_decode_planes(key_ops),) if width else tuple(key_ops)
    return _sort_winner_pack(lanes, n_real)


def _bitcast_u32(b: jax.Array) -> jax.Array:
    """u8[4k] -> u32[k] (little-endian)."""
    return jax.lax.bitcast_convert_type(b.reshape(-1, 4), jnp.uint32)


@functools.partial(jax.jit, static_argnames=("layout",))
def _winner_kernel_fa_packed(buf, layout) -> jax.Array:
    """Single-transfer variant of `_winner_kernel_fa`: every operand —
    n_real, sub_radix, flag words, ref planes, the sparse DV lane —
    rides in ONE uint8 buffer and is sliced out on device. Over a
    high-latency host<->device link (the tunnel pays ~120ms per
    transfer), one H2D beats six.

    layout = (m, ref_width, r_pad, d_pad) — all bucket-padded statics."""
    m, ref_width, r_pad, d_pad = layout
    off = 0

    def take(nbytes):
        # delta-lint: disable=jit-impure (audited: `off` is trace-time
        # python-int bookkeeping — each take() slices at a static offset
        # baked into the jaxpr, not runtime mutation)
        nonlocal off
        s = jax.lax.slice(buf, (off,), (off + nbytes,))
        off += nbytes
        return s

    n_real = _bitcast_u32(take(4))[0].astype(jnp.int32)
    sub_radix = _bitcast_u32(take(4))[0]
    flag_words = _bitcast_u32(take(m // 32 * 4))
    ref_planes = tuple(take(r_pad) for _ in range(ref_width))
    has_sub = d_pad > 0
    if has_sub:
        sub_idx = _bitcast_u32(take(d_pad * 4))
        sub_val = _bitcast_u32(take(d_pad * 4))

    is_new = _unpack_bits_device(flag_words)
    new_rank = jnp.cumsum(is_new.astype(jnp.int32))
    ref_rank = jnp.arange(1, m + 1, dtype=jnp.int32) - new_rank
    refs = _decode_planes(ref_planes)
    ref_gather = refs[jnp.clip(ref_rank - 1, 0, refs.shape[0] - 1)]
    key = jnp.where(is_new == 1, (new_rank - 1).astype(jnp.uint32),
                    ref_gather)
    if has_sub:
        sub = jnp.zeros((m,), jnp.uint32).at[sub_idx].set(
            sub_val, mode="drop")
        key = key * sub_radix + sub
    iota = jnp.arange(m, dtype=jnp.int32)
    key = jnp.where(iota < n_real, key, jnp.uint32(0xFFFFFFFF))
    return _sort_winner_pack((key,), n_real)


def _pack_fa_operands(fa: "_FAEncoding", n: int) -> tuple[np.ndarray, tuple]:
    """Concatenate the FA operands into one uint8 buffer + its static
    layout key."""
    m = fa.flag_words.shape[0] * 32
    r_pad = fa.ref_planes[0].shape[0] if fa.ref_planes else 0
    d_pad = fa.sub_idx.shape[0]
    parts = [
        np.asarray([n], np.uint32).view(np.uint8),
        np.asarray([fa.sub_radix], np.uint32).view(np.uint8),
        fa.flag_words.view(np.uint8),
        *fa.ref_planes,
    ]
    if d_pad:
        parts += [fa.sub_idx.view(np.uint8), fa.sub_val.view(np.uint8)]
    return parts, (m, len(fa.ref_planes), r_pad, d_pad)


@functools.lru_cache(maxsize=16)
def _concat_chunks_jit(k: int):
    return jax.jit(lambda *chunks: jnp.concatenate(chunks))


def _put_chunked(buf: np.ndarray, device):
    """device_put that rides the fast H2D bandwidth bucket: the link
    model (parallel/gate.py) says large transfers collapse to ~29 MB/s
    while <=8 MB chunks sustain ~1 GB/s, so a buffer bigger than the
    fast-bucket size ships as fixed-size chunks and is reassembled by a
    jit'd concatenate. The trailing zero-pad past `buf.nbytes` is never
    read — the packed kernel slices at static offsets that end at the
    real layout length. Disabled (plain device_put) when the model has
    no bandwidth cliff (CPU backends) or the buffer already fits one
    chunk."""
    from delta_tpu.parallel import gate

    chunk = gate.link_model().chunk_bytes()
    if not chunk or buf.nbytes <= chunk:
        return jax.device_put(buf, device)
    k = -(-buf.nbytes // chunk)
    padded = np.zeros(k * chunk, np.uint8)
    padded[:buf.nbytes] = buf
    pieces = [jax.device_put(padded[i * chunk:(i + 1) * chunk], device)
              for i in range(k)]
    return _concat_chunks_jit(k)(*pieces)


class _FAEncoding(NamedTuple):
    """Host-side first-appearance delta encoding of the key lanes."""
    flag_words: np.ndarray     # u32[m/32] is_new bits
    ref_planes: tuple          # u8 planes of explicit codes, bucket-padded
    sub_idx: np.ndarray        # u32[D] rows with non-zero sub lane
    sub_val: np.ndarray        # u32[D]
    sub_radix: int
    nbytes: int


def derive_fa_flags(primary: np.ndarray):
    """is_new flags if `primary` is a dense first-appearance coding
    (every new value == prev_max + 1, new values are 0,1,2,...), else
    None. The single source of truth for FA validity — the single-chip
    encoder and the sharded route both use it."""
    p64 = np.asarray(primary).astype(np.int64, copy=False)
    if len(p64) == 0:
        return np.zeros(0, dtype=bool)
    run_max = np.maximum.accumulate(p64)
    prev_max = np.empty_like(run_max)
    prev_max[0] = -1
    prev_max[1:] = run_max[:-1]
    is_new = p64 == prev_max + 1
    n_new = int(is_new.sum())
    # dense first-appearance check: the j-th new row must carry code j
    if not np.array_equal(p64[is_new], np.arange(n_new, dtype=np.int64)):
        return None
    return is_new


_NATIVE_FA_MIN_ROWS = 200_000    # below this numpy encodes in ~ms anyway
_NATIVE_FA_COMPILE_ROWS = 1_000_000  # worth a one-off g++ build


def _try_fa_encode(lanes: Sequence[np.ndarray], n: int, m: int) -> Optional[_FAEncoding]:
    """Delta-encode lane 0 against first-appearance coding; lanes[1:]
    (tiny ranges, mostly zero — the DV id lane) go sparse. None when the
    stream isn't first-appearance-coded or ranges don't fit.

    Large inputs go through the multithreaded C++ encoder
    (native/src/fa_encode.cpp, same output layout); this numpy
    implementation is the toolchain-less fallback and parity oracle."""
    primary = np.asarray(lanes[0])
    sl = _sub_lane(lanes)
    if sl is None:
        return None
    sub, sub_radix = sl

    if n >= _NATIVE_FA_MIN_ROWS:
        from delta_tpu import native

        enc = native.fa_encode(
            primary, sub, n, m,
            allow_compile=n >= _NATIVE_FA_COMPILE_ROWS)
        if enc is native.NOT_FA:
            return None  # definitive: ship byte planes instead
        if enc is not None:
            full_width = key_byte_width(
                (enc.primary_max + 1) * enc.sub_radix - 1)
            if enc.nbytes >= m * full_width:
                return None  # byte planes ship fewer bytes
            return _FAEncoding(enc.flag_words, enc.ref_planes, enc.sub_idx,
                               enc.sub_val, enc.sub_radix, enc.nbytes)
        # fall through to numpy: toolchain/library unavailable
    is_new = derive_fa_flags(primary)
    if is_new is None:
        return None
    primary_max = int(primary.max()) if n else 0
    refs = primary[~is_new].astype(np.uint32, copy=False)
    return _fa_pack(is_new, refs, primary_max, sub, sub_radix, n, m)


def _sub_lane(lanes: Sequence[np.ndarray]):
    """Combine lanes[1:] into one sub lane. Returns (sub-or-None,
    sub_radix) or None when the ranges don't fit uint32."""
    if len(lanes) <= 1:
        return None, 1
    sub = combine_key_lanes(lanes[1:])
    if sub is None:
        return None
    sub_radix = int(sub.max(initial=0)) + 1
    return (sub if sub_radix > 1 else None), sub_radix


def _fa_pack(
    flags: np.ndarray,
    refs: np.ndarray,
    primary_max: int,
    sub: Optional[np.ndarray],
    sub_radix: int,
    n: int,
    m: int,
) -> Optional[_FAEncoding]:
    """Shared wire-format tail of every first-appearance encoding path:
    pack the is_new flags into bit words, the explicit refs into byte
    planes, the sub lane into sparse (row, value) pairs, and apply the
    economics check (None when plain byte planes would ship fewer
    bytes — remove-heavy streams)."""
    if (primary_max + 1) * sub_radix >= 0xFFFFFFFF:
        return None
    refs = np.ascontiguousarray(refs, dtype=np.uint32)
    r_pad = pad_bucket(len(refs), min_bucket=128)
    ref_width = key_byte_width(int(refs.max(initial=0)))
    ref_planes = _pack_key_planes(refs, ref_width, r_pad - len(refs),
                                  pad_byte=0)
    if sub is not None:
        nz = np.nonzero(sub)[0]
        d_pad = pad_bucket(len(nz), min_bucket=128)
        sub_idx = np.concatenate(
            [nz.astype(np.uint32),
             np.full(d_pad - len(nz), 0xFFFFFFFF, np.uint32)])
        sub_val = np.concatenate(
            [sub[nz].astype(np.uint32), np.zeros(d_pad - len(nz), np.uint32)])
    else:
        sub_idx = np.empty(0, np.uint32)
        sub_val = np.empty(0, np.uint32)

    pad = m - n
    flags = np.asarray(flags, dtype=np.bool_)
    flag_words = _pack_bits(
        np.concatenate([flags, np.zeros(pad, np.bool_)]) if pad else flags)
    nbytes = (flag_words.nbytes + sum(p.nbytes for p in ref_planes)
              + sub_idx.nbytes + sub_val.nbytes)
    full_width = key_byte_width((primary_max + 1) * sub_radix - 1)
    if nbytes >= m * full_width:
        return None
    return _FAEncoding(flag_words, ref_planes, sub_idx, sub_val,
                       sub_radix, nbytes)


def _fa_from_hint(
    flags: np.ndarray,
    refs: np.ndarray,
    n_uniq: int,
    lanes: Sequence[np.ndarray],
    n: int,
    m: int,
) -> Optional[_FAEncoding]:
    """Build the device encoding from a scanner-provided first-appearance
    coding (flags = is_new per row, refs = explicit codes of non-new rows
    in row order) — the host never re-derives what the dictionary pass
    already knew."""
    sl = _sub_lane(lanes)
    if sl is None:
        return None
    sub, sub_radix = sl
    return _fa_pack(flags, refs, n_uniq - 1 if n_uniq else 0,
                    sub, sub_radix, n, m)


class ReplayPending:
    """A launched (asynchronously dispatched) replay: the device owns the
    sort while the host keeps working — call `finish()` to block on the
    winner words and split them into (live, tombstone) masks."""

    __slots__ = ("_winner", "_add_words", "_n", "_perm")

    def __init__(self, winner, add_words: np.ndarray, n: int, perm):
        self._winner = winner
        self._add_words = add_words
        self._n = n
        self._perm = perm

    def finish(self) -> tuple[np.ndarray, np.ndarray]:
        n = self._n
        if n == 0:
            z = np.zeros((0,), dtype=bool)
            return z, z
        winner_words = np.asarray(self._winner)
        live_words = winner_words & self._add_words
        tomb_words = winner_words & ~self._add_words
        live = _unpack_bits(live_words, n)
        tomb = _unpack_bits(tomb_words, n)
        if self._perm is not None:
            inv_live = np.zeros(n, dtype=bool)
            inv_tomb = np.zeros(n, dtype=bool)
            inv_live[self._perm] = live
            inv_tomb[self._perm] = tomb
            live, tomb = inv_live, inv_tomb
        return live, tomb


def replay_select(
    key_lanes: Sequence[np.ndarray],
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    device=None,
    fa_hint: Optional[tuple] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-facing wrapper: permutes to chronological order if needed,
    delta- or byte-packs the key lanes (whichever ships fewer bytes),
    runs the winner kernel on device, and splits winners into
    (live_mask, tombstone_mask) numpy bool arrays of the original length
    using the host-resident add bits.

    key_lanes: one or more uint32/int32 arrays jointly identifying the
    logical file (dictionary codes or hash lanes). version/order: the
    chronological coordinate of each row; when rows are already in
    chronological order (the columnarizer's contract) they never leave
    the host.
    """
    return replay_select_launch(
        key_lanes, version, order, is_add, device=device,
        fa_hint=fa_hint).finish()


def replay_select_launch(
    key_lanes: Sequence[np.ndarray],
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    device=None,
    fa_hint: Optional[tuple] = None,
) -> ReplayPending:
    """Asynchronous half of `replay_select`: packs + ships the operands
    and dispatches the device kernel, returning immediately (jax calls
    are async). The caller overlaps host work (e.g. Arrow table
    assembly) with the device sort and calls `.finish()` when it needs
    the masks."""
    n = int(version.shape[0])
    if n == 0:
        return ReplayPending(None, np.empty(0, np.uint32), 0, None)

    perm = None
    if not chrono_ok(np.asarray(version), np.asarray(order)):
        perm = np.lexsort((order, version))
        key_lanes = [np.asarray(k)[perm] for k in key_lanes]
        is_add = np.asarray(is_add)[perm]
        fa_hint = None  # hint flags are in original row order

    m = pad_bucket(n)
    pad = m - n
    is_add = np.asarray(is_add, dtype=np.bool_)
    add_words_np = _pack_bits(
        np.concatenate([is_add, np.zeros(pad, np.bool_)]) if pad else is_add)

    lanes = [np.asarray(k) for k in key_lanes]
    fa = None
    if fa_hint is not None:
        flags, refs, n_uniq = fa_hint
        fa = _fa_from_hint(flags, refs, int(n_uniq), lanes, n, m)
    if fa is None:
        fa = _try_fa_encode(lanes, n, m)

    n_op = np.asarray(n, dtype=np.int32)
    # these data-dependent lanes are accounted at runtime through
    # replay.h2d_bytes (no static per-unit budget entry — the FA buffer
    # mixes bitplanes and byte-packed refs); the funnel still records
    # per-lane bytes and the compile/steady-state split per shape bucket
    if fa is not None:
        parts, layout = _pack_fa_operands(fa, n)
        buf = np.concatenate(parts)
        with obs.device_dispatch("replay.single_fa", key=(m, layout),
                                 gate="replay", route="single") as dd:
            dd.h2d("fa_buf", buf)
            _H2D_BYTES.inc(buf.nbytes)
            buf = _put_chunked(buf, device)
            winner_words = _winner_kernel_fa_packed(buf, layout)
    else:
        combined = combine_key_lanes(lanes)
        if combined is not None:
            width = key_byte_width(int(combined.max(initial=0)))
            key_ops = _pack_key_planes(combined, width, pad)
        else:
            width = 0
            key_ops = tuple(
                np.ascontiguousarray(np.concatenate(
                    [np.asarray(k, np.uint32),
                     np.full(pad, _PAD_KEY, np.uint32)])
                    if pad else np.asarray(k, np.uint32))
                for k in lanes)
        operands = (*key_ops, n_op)
        with obs.device_dispatch("replay.single_raw",
                                 key=(m, width, len(key_ops)),
                                 gate="replay", route="single") as dd:
            for i, o in enumerate(key_ops):
                dd.h2d(f"key_plane_{i}", o)
            _H2D_BYTES.inc(sum(int(o.nbytes) for o in key_ops))
            if device is not None:
                operands = tuple(jax.device_put(o, device) for o in operands)
            winner_words = _winner_kernel(operands, width=width)

    return ReplayPending(winner_words, add_words_np, n, perm)


def python_replay_reference(
    keys: Sequence[tuple],
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """Sequential hash-map replay — the reference semantics
    (`InMemoryLogReplay.scala:52-100`) — used for parity tests and as the
    honest CPU baseline in benchmarks."""
    n = len(keys)
    rows = sorted(range(n), key=lambda i: (int(version[i]), int(order[i])))
    winner: dict = {}
    for i in rows:
        winner[keys[i]] = i
    live = np.zeros(n, dtype=bool)
    tomb = np.zeros(n, dtype=bool)
    for key, i in winner.items():
        if is_add[i]:
            live[i] = True
        else:
            tomb[i] = True
    return live, tomb


def delta_winner_masks(
    keys: Sequence[tuple],
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, dict]:
    """Last-wins masks over a DELTA batch of actions (the commits an
    incremental `update()` appends on top of a retained snapshot).

    Same contract as python_replay_reference, plus the winner map
    `{key: row}` — the caller uses its key set to clear superseded rows
    in the prior state's masks. Delta batches are O(new commits), so the
    sequential formulation is the right tool here; the device kernels
    above exist for the O(full history) replay.
    """
    n = len(keys)
    rows = sorted(range(n), key=lambda i: (int(version[i]), int(order[i])))
    winner: dict = {}
    for i in rows:
        winner[keys[i]] = i
    live = np.zeros(n, dtype=bool)
    tomb = np.zeros(n, dtype=bool)
    for i in winner.values():
        if is_add[i]:
            live[i] = True
        else:
            tomb[i] = True
    return live, tomb, winner

"""Log replay as a device sort + segmented last-wins reduce.

The reconciliation contract (PROTOCOL.md:823-843): for each logical file
key `(path, dv_unique_id)`, the newest action wins — a surviving `add` is
a live file, a surviving `remove` is a tombstone (kept for VACUUM), and
the live/tombstone key sets are disjoint.

The reference implements this as sequential hash-map upserts per action
(ascending, spark `InMemoryLogReplay.scala:52`) or hash-set probes
(descending, kernel `ActiveAddFilesIterator.java:146`). Neither
vectorizes. The TPU-native formulation used here:

1. The columnarizer emits actions in chronological order (checkpoint
   rows, then commits ascending, line order within a commit), so the row
   index *is* the chronological rank — no (version, order) columns need
   to ship to the device; a device-side iota is the sort tiebreaker.
   (If a caller passes rows out of order, a single host `np.lexsort`
   ranks them first.)
2. Key lanes are dense dictionary codes; when their ranges fit, they are
   combined host-side into ONE uint32 lane (`k0 * |k1| + k1`), and
   `is_add` ships as packed bits — ~4.1 bytes/row over PCIe/ICI instead
   of 17.
3. `lax.sort` by (key, chrono) — 2 sort keys, 3 operands. After the sort
   every logical file's history is a contiguous run in chronological
   order; the run-boundary mask `key[i] != key[i+1]` marks the newest
   action per key. No loops, no hash table.
4. Scatter the winner mask back to input order, bit-pack the two output
   masks on device (32× smaller D2H), unpack on host.

Padding rows (key = 0xFFFFFFFF) sort to the end; at most one padding row
wins its run and its output position >= n is sliced off host-side, so no
`valid` lane is needed at all.

Complexity O(n log n) versus the hash maps' O(n) — but as one fused XLA
sort at HBM bandwidth versus pointer-chasing JVM maps, and it shards
cleanly: route rows by key to devices, sort/reduce locally, no
cross-device dedup needed (delta_tpu.parallel).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_PAD_KEY = np.uint32(0xFFFFFFFF)
_MIN_BUCKET = 1024


def pad_bucket(n: int) -> int:
    """Round up to the next power of two (min 1024) so jit caches a small
    number of shapes across snapshot sizes."""
    if n <= _MIN_BUCKET:
        return _MIN_BUCKET
    return 1 << (int(n - 1).bit_length())


class ReplayResult(NamedTuple):
    live: jax.Array        # packed uint32 words: bit i of word w = row 32w+i
    tombstone: jax.Array


def chrono_ok(version: np.ndarray, order: np.ndarray) -> bool:
    """True if rows are already in chronological (version, order) order,
    in which case the row index is the chronological rank."""
    if version.shape[0] <= 1:
        return True
    # int64 first: unsigned inputs would wrap negative diffs to huge
    # positives and misclassify a descending history as chronological
    version = np.asarray(version, dtype=np.int64)
    order = np.asarray(order, dtype=np.int64)
    dv = np.diff(version)
    if (dv < 0).any():
        return False
    same = dv == 0
    if not same.any():
        return True
    do = np.diff(order)
    return not bool((same & (do < 0)).any())


def combine_key_lanes(key_lanes: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """Mixed-radix combine of dense key-code lanes into one uint32 lane
    (reserving 0xFFFFFFFF for padding). None if the ranges don't fit."""
    lanes = [np.asarray(k, dtype=np.uint64) for k in key_lanes]
    if len(lanes) == 1:
        mx = int(lanes[0].max(initial=0))
        return lanes[0].astype(np.uint32) if mx < 0xFFFFFFFF else None
    radix = 1
    combined = np.zeros_like(lanes[0])
    for lane in lanes:
        mx = int(lane.max(initial=0))
        radix *= mx + 1
        if radix >= 0xFFFFFFFF:
            return None
        combined = combined * np.uint64(mx + 1) + lane
    return combined.astype(np.uint32)


def _pack_bits(mask: np.ndarray) -> np.ndarray:
    """bool[n] -> uint32[n/32] little-endian bit words (n % 32 == 0)."""
    return np.packbits(mask, bitorder="little").view(np.uint32)


def _unpack_bits(words: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(words.view(np.uint8), bitorder="little")[:n].astype(bool)


@functools.partial(jax.jit, static_argnames=("n_lanes", "has_rank"))
def _replay_packed(operands, n_lanes: int, has_rank: bool) -> ReplayResult:
    """operands = (*key_lanes[uint32, n], rank[i32, n]?, n_real[i32],
    add_words[u32, n/32]).

    Sorts by (key..., chrono) where chrono is the explicit rank lane or a
    device iota; marks per-run winners; scatters back; bit-packs masks.
    Padding rows (idx >= n_real) sort after the real rows of any run they
    share a key with (their rank/iota is larger), so the winner of a run
    is its last *valid* row — this keeps a real row whose key happens to
    equal the 0xFFFFFFFF pad sentinel from being swallowed by padding.
    """
    *front, n_real, add_words = operands
    lanes = front[:n_lanes]
    rank_ops = (front[n_lanes],) if has_rank else ()
    n = lanes[0].shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    bit_pos = jnp.arange(32, dtype=jnp.uint32)
    is_add = ((add_words[:, None] >> bit_pos[None, :]) & jnp.uint32(1)).reshape(-1).astype(bool)

    sorted_ = lax.sort((*lanes, *rank_ops, idx, is_add), num_keys=n_lanes + 1,
                       is_stable=False)
    s_lanes, s_idx, s_add = sorted_[:n_lanes], sorted_[-2], sorted_[-1]
    s_valid = s_idx < n_real

    same_as_next = jnp.ones((n - 1,), dtype=bool)
    for k in s_lanes:
        same_as_next = same_as_next & (k[:-1] == k[1:])
    next_valid = jnp.concatenate([s_valid[1:], jnp.zeros((1,), dtype=bool)])
    is_last = jnp.concatenate([~same_as_next, jnp.ones((1,), dtype=bool)])
    winner = s_valid & (is_last | ~next_valid)

    live = jnp.zeros((n,), dtype=bool).at[s_idx].set(winner & s_add)
    tomb = jnp.zeros((n,), dtype=bool).at[s_idx].set(winner & ~s_add)
    weights = jnp.uint32(1) << bit_pos
    live_w = (live.reshape(-1, 32).astype(jnp.uint32) * weights).sum(
        axis=1, dtype=jnp.uint32)
    tomb_w = (tomb.reshape(-1, 32).astype(jnp.uint32) * weights).sum(
        axis=1, dtype=jnp.uint32)
    return ReplayResult(live_w, tomb_w)


def replay_select(
    key_lanes: Sequence[np.ndarray],
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    device=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-facing wrapper: ranks (if needed), combines key lanes, packs,
    ships to device, runs the kernel, and returns (live_mask,
    tombstone_mask) as numpy bool arrays of the original length.

    key_lanes: one or more uint32/int32 arrays jointly identifying the
    logical file (dictionary codes or hash lanes). version/order: the
    chronological coordinate of each row; when rows are already in
    chronological order (the columnarizer's contract) they never leave
    the host.
    """
    n = int(version.shape[0])
    if n == 0:
        z = np.zeros((0,), dtype=bool)
        return z, z
    m = pad_bucket(n)
    pad = m - n

    def pad_with(arr, value, dtype):
        arr = np.asarray(arr, dtype=dtype)
        if pad == 0:
            return arr
        return np.concatenate([arr, np.full((pad,), value, dtype=dtype)])

    combined = combine_key_lanes(key_lanes)
    if combined is not None:
        lanes = (pad_with(combined, _PAD_KEY, np.uint32),)
    else:
        lanes = tuple(pad_with(k, _PAD_KEY, np.uint32) for k in key_lanes)

    rank_ops: tuple = ()
    if not chrono_ok(np.asarray(version), np.asarray(order)):
        perm = np.lexsort((order, version))
        rank = np.empty(n, dtype=np.int32)
        rank[perm] = np.arange(n, dtype=np.int32)
        rank_ops = (pad_with(rank, np.int32(0x7FFFFFFF), np.int32),)

    add_words = _pack_bits(pad_with(is_add, False, np.bool_))
    operands = (*lanes, *rank_ops, np.asarray(n, dtype=np.int32), add_words)
    if device is not None:
        operands = tuple(jax.device_put(o, device) for o in operands)
    result = _replay_packed(operands, n_lanes=len(lanes), has_rank=bool(rank_ops))
    live = _unpack_bits(np.asarray(result.live), n)
    tomb = _unpack_bits(np.asarray(result.tombstone), n)
    return live, tomb


def python_replay_reference(
    keys: Sequence[tuple],
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """Sequential hash-map replay — the reference semantics
    (`InMemoryLogReplay.scala:52-100`) — used for parity tests and as the
    honest CPU baseline in benchmarks."""
    n = len(keys)
    rows = sorted(range(n), key=lambda i: (int(version[i]), int(order[i])))
    winner: dict = {}
    for i in rows:
        winner[keys[i]] = i
    live = np.zeros(n, dtype=bool)
    tomb = np.zeros(n, dtype=bool)
    for key, i in winner.items():
        if is_add[i]:
            live[i] = True
        else:
            tomb[i] = True
    return live, tomb

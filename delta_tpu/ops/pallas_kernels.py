"""Pallas TPU kernels for the hottest per-row ops.

Two kernels with identical jnp fallbacks (used automatically off-TPU or
via `interpret=True` on CPU):

- `interleave_bits_tiled`: the OPTIMIZE ZORDER curve-key op. One VMEM
  pass per [8, 128] tile computes all output words — the 32·k-step bit
  loop stays in registers instead of materializing 32·k intermediate
  arrays for XLA to fuse.
- `segmented_minmax`: per-file min/max/count over a [files, rows] batch
  with a validity mask — the stats-collection reduction when many data
  files are written in one call (stats for the skipping index,
  `StatisticsCollection.scala:257` role).

Layout notes: rows are padded to 128 lanes; tiles are (8, 128) float32 /
int32 per the TPU tiling table.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    HAVE_PALLAS = False

_LANES = 128
_SUBLANES = 8
_TILE = _SUBLANES * _LANES


def _x32():
    """Scoped x32 context: `jax.enable_x64(False)` was removed from the
    jax namespace; `jax.experimental.enable_x64` is the supported
    scoped switch and takes the desired state as an argument."""
    from jax.experimental import enable_x64

    return enable_x64(False)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# interleave bits
# ---------------------------------------------------------------------------


def _interleave_kernel(n_cols: int, n_bits: int, n_words: int, in_ref, out_ref):
    """in_ref: [k, 8, 128] uint32; out_ref: [w, 8, 128] uint32."""
    cols = [in_ref[c] for c in range(n_cols)]
    words = [jnp.zeros((_SUBLANES, _LANES), jnp.uint32) for _ in range(n_words)]
    for g in range(n_cols * n_bits):
        c = g % n_cols
        s = n_bits - 1 - g // n_cols
        w, wb = divmod(g, 32)
        bit = (cols[c] >> jnp.uint32(s)) & jnp.uint32(1)
        words[w] = words[w] | (bit << jnp.uint32(31 - wb))
    for w in range(n_words):
        out_ref[w] = words[w]


@functools.partial(jax.jit, static_argnames=("n_bits",))
def interleave_bits_tiled(cols: jnp.ndarray, n_bits: int = 32) -> jnp.ndarray:
    """cols: [k, n] uint32 (n a multiple of 1024) -> [w, n] uint32."""
    k, n = cols.shape
    n_words = max(1, -(-(k * n_bits) // 32))
    assert n % _TILE == 0, n
    tiles = n // _TILE
    shaped = cols.reshape(k, tiles * _SUBLANES, _LANES)
    kernel = functools.partial(_interleave_kernel, k, n_bits, n_words)
    out = pl.pallas_call(
        kernel,
        grid=(tiles,),
        in_specs=[pl.BlockSpec((k, _SUBLANES, _LANES), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((n_words, _SUBLANES, _LANES), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_words, tiles * _SUBLANES, _LANES), jnp.uint32),
        interpret=_use_interpret(),
    )(shaped)
    return out.reshape(n_words, n)


def interleave_bits_auto(cols, n_bits: int = 32):
    """Pallas when available/beneficial, jnp fallback otherwise.
    x32 pinned: Mosaic grid indexing is i32 and all dtypes here are
    explicit, so a global x64 flip (the SQL spine's) must not leak in."""
    from delta_tpu.ops.zorder import interleave_bits

    with _x32():
        stacked = jnp.stack(list(cols))
        k, n = stacked.shape
        if not HAVE_PALLAS or n % _TILE != 0:
            return interleave_bits(list(cols), n_bits=n_bits)
        return interleave_bits_tiled(stacked, n_bits=n_bits)


# ---------------------------------------------------------------------------
# segmented min/max/count (stats collection)
# ---------------------------------------------------------------------------


def _minmax_kernel(in_ref, mask_ref, min_ref, max_ref, cnt_ref):
    """in/mask: [8, R]; outputs: [8, 128] (stats broadcast into lane 0)."""
    x = in_ref[:]
    valid = mask_ref[:]
    big = jnp.float32(jnp.inf)
    mn = jnp.min(jnp.where(valid, x, big), axis=1, keepdims=True)
    mx = jnp.max(jnp.where(valid, x, -big), axis=1, keepdims=True)
    cnt = jnp.sum(valid.astype(jnp.float32), axis=1, keepdims=True)
    min_ref[:] = jnp.broadcast_to(mn, (_SUBLANES, _LANES))
    max_ref[:] = jnp.broadcast_to(mx, (_SUBLANES, _LANES))
    cnt_ref[:] = jnp.broadcast_to(cnt, (_SUBLANES, _LANES))


@jax.jit
def segmented_minmax(values: jnp.ndarray, valid: jnp.ndarray):
    """values/valid: [F, R] float32/bool, F a multiple of 8, R of 128.
    Returns (min[F], max[F], valid_count[F]) — min/max over valid entries
    (±inf when a file has no valid rows)."""
    f, r = values.shape
    assert f % _SUBLANES == 0 and r % _LANES == 0, (f, r)
    grid = (f // _SUBLANES,)
    spec_in = pl.BlockSpec((_SUBLANES, r), lambda i: (i, 0))
    spec_out = pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0))
    mn, mx, cnt = pl.pallas_call(
        _minmax_kernel,
        grid=grid,
        in_specs=[spec_in, spec_in],
        out_specs=(spec_out, spec_out, spec_out),
        out_shape=(
            jax.ShapeDtypeStruct((f, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((f, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((f, _LANES), jnp.float32),
        ),
        interpret=_use_interpret(),
    )(values.astype(jnp.float32), valid)
    return mn[:, 0], mx[:, 0], cnt[:, 0].astype(jnp.int32)


def batched_file_stats(values: np.ndarray, valid: np.ndarray):
    """Host wrapper: pad [F, R] to tile multiples, run the kernel, return
    numpy (min, max, null_count, num_records) per file. x32 pinned for
    the same Mosaic reason as interleave_bits_auto."""
    with _x32():
        return _batched_file_stats_impl(values, valid)


def _batched_file_stats_impl(values: np.ndarray, valid: np.ndarray):
    f, r = values.shape
    fpad = (-f) % _SUBLANES
    rpad = (-r) % _LANES
    v = np.pad(values.astype(np.float32), ((0, fpad), (0, rpad)))
    m = np.pad(valid.astype(bool), ((0, fpad), (0, rpad)))
    mn, mx, cnt = segmented_minmax(jnp.asarray(v), jnp.asarray(m))
    mn = np.asarray(mn)[:f]
    mx = np.asarray(mx)[:f]
    cnt = np.asarray(cnt)[:f]
    num_records = np.full(f, r, dtype=np.int64)
    null_count = num_records - cnt
    return mn, mx, null_count, num_records


# ---------------------------------------------------------------------------
# JSON structural byte classes (device action parse)
# ---------------------------------------------------------------------------

# uint8 min tile is (32, 128) per the TPU tiling table
_BYTE_SUBLANES = 32
_BYTE_TILE = _BYTE_SUBLANES * _LANES

# class bit per structural byte; ops/json_parse.py tests these bits
BYTE_CLASS_BITS = {
    "newline": 1, "quote": 2, "backslash": 4,
    "colon": 8, "lbrace": 16, "rbrace": 32,
}
_BYTE_CLASS_VALUES = ((10, 1), (34, 2), (92, 4), (58, 8), (123, 16),
                      (125, 32))


def _byte_class_kernel(in_ref, out_ref):
    """in/out: [32, 128] uint8. One VMEM pass ORs the six structural
    class bits per byte — the first stage of the device JSON parse
    (quote/escape/colon masks feed the parity scans in
    ops/json_parse.py)."""
    b = in_ref[:]
    cls = jnp.zeros_like(b)
    for byte, bit in _BYTE_CLASS_VALUES:
        cls = cls | jnp.where(b == jnp.uint8(byte), jnp.uint8(bit),
                              jnp.uint8(0))
    out_ref[:] = cls


@jax.jit
def byte_class_tiled(b: jnp.ndarray) -> jnp.ndarray:
    """b: [n] uint8 (n a multiple of 4096) -> [n] uint8 class bitmask."""
    (n,) = b.shape
    assert n % _BYTE_TILE == 0, n
    tiles = n // _BYTE_TILE
    shaped = b.reshape(tiles * _BYTE_SUBLANES, _LANES)
    out = pl.pallas_call(
        _byte_class_kernel,
        grid=(tiles,),
        in_specs=[pl.BlockSpec((_BYTE_SUBLANES, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_BYTE_SUBLANES, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((tiles * _BYTE_SUBLANES, _LANES),
                                       jnp.uint8),
        interpret=_use_interpret(),
    )(shaped)
    return out.reshape(n)


# ---------------------------------------------------------------------------
# parquet bit-packed group decode (checkpoint page decoder)
# ---------------------------------------------------------------------------


def _check_unpack_width(w: int, allow_zero: bool = False) -> None:
    """Typed guard for the bit-unpack primitive. A corrupt page header
    can carry any width byte; before this guard a w>32 silently wrapped
    the value mask (`1 << w` mod 2^32) and decoded garbage."""
    lo = 0 if allow_zero else 1
    if not isinstance(w, (int, np.integer)) or not lo <= int(w) <= 32:
        from delta_tpu.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"bit-packed width must be in [{lo}, 32], got {w!r}")


def _unpack_kernel(w: int, in_ref, out_ref):
    """in_ref: [w, 8, 128] uint32 (word-index-major, like the
    interleave kernel's layout); out_ref: [32, 8, 128] uint32 values.

    One Parquet bit-packed GROUP is 32 values x w bits = w u32 words;
    value j of a group lives at bit j*w, so its word index j*w//32 and
    shift j*w%32 are STATIC per j — the 32-step loop unrolls into pure
    vector shifts/ors over the [8, 128] group tile (the exact inverse
    of `_interleave_kernel`)."""
    mask = jnp.uint32((1 << w) - 1) if w < 32 else jnp.uint32(0xFFFFFFFF)
    for j in range(32):
        bitpos = j * w
        lo, sh = divmod(bitpos, 32)
        v = in_ref[lo] >> jnp.uint32(sh)
        if sh + w > 32:
            v = v | (in_ref[lo + 1] << jnp.uint32(32 - sh))
        out_ref[j] = v & mask


@functools.partial(jax.jit, static_argnames=("w",))
def unpack_bitpacked_tiled(packed: jnp.ndarray, w: int) -> jnp.ndarray:
    """packed: [w, G] uint32 (word-major: packed[k, g] = word k of
    group g; G a multiple of 1024) -> [G * 32] uint32 values, group-
    major (value j of group g at g*32 + j)."""
    _check_unpack_width(w)
    g = packed.shape[1]
    assert g % _TILE == 0, g
    tiles = g // _TILE
    shaped = packed.reshape(w, tiles * _SUBLANES, _LANES)
    out = pl.pallas_call(
        functools.partial(_unpack_kernel, w),
        grid=(tiles,),
        in_specs=[pl.BlockSpec((w, _SUBLANES, _LANES), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((32, _SUBLANES, _LANES), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, tiles * _SUBLANES, _LANES),
                                       jnp.uint32),
        interpret=_use_interpret(),
    )(shaped)
    # [32, G] -> group-major [G, 32] -> flat
    return out.reshape(32, -1).T.reshape(-1)


def unpack_bitpacked(packed_words: np.ndarray, w: int,
                     n_groups: int, device=None) -> jnp.ndarray:
    """Decode `n_groups` Parquet bit-packed groups (32 values x w bits
    each) from a flat little-endian u32 word stream. Pallas when
    available, jnp fallback with identical semantics. Returns a device
    array of n_groups*32 uint32 values. w must be in [0, 32]; w == 0 is
    the valid all-zero run, anything outside raises
    InvalidArgumentError instead of wrapping the value mask."""
    _check_unpack_width(w, allow_zero=True)
    if w == 0:
        return jnp.zeros(n_groups * 32, jnp.uint32)
    need = n_groups * w
    padded_groups = -(-max(n_groups, 1) // _TILE) * _TILE
    buf = np.zeros(padded_groups * w, np.uint32)
    buf[:need] = packed_words[:need]
    # [G, w] group-major words -> [w, G] word-major for the kernel
    shaped = np.ascontiguousarray(buf.reshape(padded_groups, w).T)
    # Mosaic lowers this kernel with i32 grid indexing; a process that
    # enabled global x64 (the SQL spine does) would otherwise feed it
    # i64 index maps and fail to legalize — dtypes here are explicit,
    # so pin x32 semantics for the call
    with _x32():
        arr = jax.device_put(shaped, device)
        if not HAVE_PALLAS:
            return _unpack_jnp(arr, w)[:n_groups * 32]
        return unpack_bitpacked_tiled(arr, w)[:n_groups * 32]


@functools.partial(jax.jit, static_argnames=("w",))
def _unpack_jnp(packed: jnp.ndarray, w: int) -> jnp.ndarray:
    """packed: [w, G] word-major; same output layout as the kernel."""
    _check_unpack_width(w)
    g = packed.shape[1]
    mask = jnp.uint32((1 << w) - 1) if w < 32 else jnp.uint32(0xFFFFFFFF)
    outs = []
    for j in range(32):
        lo, sh = divmod(j * w, 32)
        v = packed[lo] >> jnp.uint32(sh)
        if sh + w > 32:
            v = v | (packed[lo + 1] << jnp.uint32(32 - sh))
        outs.append(v & mask)
    return jnp.stack(outs, axis=-1).reshape(g * 32)


# ---------------------------------------------------------------------------
# variable-shift bit-field extract (batched checkpoint page decode)
# ---------------------------------------------------------------------------
#
# The one-lane page decoder (ops/page_decode.py) turns every RLE/
# bit-packed hybrid position of a checkpoint part into four u32 lanes:
# the 32-bit little-endian window at the value's byte offset (`lo`),
# the spill byte above it (`hi`), the in-byte shift (`sh`, 0..7) and
# the run's bit width (`w`, 0..32). Unlike `unpack_bitpacked` the shift
# is DATA-dependent (each element belongs to a different run), so the
# extract is elementwise rather than a static unrolled group loop.


def _shift_extract_body(lo, hi, sh, w):
    """value = ((lo >> sh) | (hi << (32 - sh))) & mask(w), elementwise
    u32. `(32 - sh) & 31` + the sh>0 select keeps the sh==0 lane off
    the undefined 32-bit shift."""
    spill = jnp.where(sh > jnp.uint32(0),
                      hi << ((jnp.uint32(32) - sh) & jnp.uint32(31)),
                      jnp.uint32(0))
    mask = jnp.where(
        w >= jnp.uint32(32), jnp.uint32(0xFFFFFFFF),
        (jnp.uint32(1) << (w & jnp.uint32(31))) - jnp.uint32(1))
    return ((lo >> sh) | spill) & mask


def _shift_extract_kernel(lo_ref, hi_ref, sh_ref, w_ref, out_ref):
    """All refs: [8, 128] uint32 tiles; one VMEM pass per tile."""
    out_ref[:] = _shift_extract_body(lo_ref[:], hi_ref[:], sh_ref[:],
                                     w_ref[:])


@jax.jit
def shift_extract_tiled(lo: jnp.ndarray, hi: jnp.ndarray,
                        sh: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """lo/hi/sh/w: [n] uint32 (n a multiple of 1024) -> [n] uint32."""
    (n,) = lo.shape
    assert n % _TILE == 0, n
    tiles = n // _TILE
    spec = pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0))
    shaped = [a.reshape(tiles * _SUBLANES, _LANES)
              for a in (lo, hi, sh, w)]
    out = pl.pallas_call(
        _shift_extract_kernel,
        grid=(tiles,),
        in_specs=[spec, spec, spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((tiles * _SUBLANES, _LANES),
                                       jnp.uint32),
        interpret=_use_interpret(),
    )(*shaped)
    return out.reshape(n)


def shift_extract(lo: jnp.ndarray, hi: jnp.ndarray, sh: jnp.ndarray,
                  w: jnp.ndarray, use_pallas: bool) -> jnp.ndarray:
    """Trace-time dispatcher used INSIDE the page-decode jit: the Pallas
    tile on TPU, the identical fused-jnp body elsewhere (interpret-mode
    Pallas inside a large jit would serialize the whole dispatch)."""
    if use_pallas and HAVE_PALLAS and lo.shape[0] % _TILE == 0:
        return shift_extract_tiled(lo, hi, sh, w)
    return _shift_extract_body(lo, hi, sh, w)

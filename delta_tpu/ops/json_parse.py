"""Device JSON field extraction for the commit-replay hot path.

PAPER.md names JSON action parsing as one of the components that "must
become XLA/Pallas device kernels — not Python loops", and BASELINE.md
r05 pinned the warm path's floor at the ~270 MB/s per-byte C++
field-extraction scan. This module is the device half of that lever:
one contiguous newline-terminated commit-window byte buffer ships to
device as a single uint8 lane (the `json-parse-window` plane in
`resources/transfer_budget.json`), and a batched data-parallel pass
extracts the replay-critical fields of every *simple* add/remove line
at once:

- structural scan: quote/escape/colon/brace masks, backslash-run
  parity for escape initiators, in-string parity from unescaped
  quotes, brace depth (the byte-class stage runs as a Pallas kernel on
  TPU — `ops/pallas_kernels.py::byte_class_tiled` — with an identical
  jnp fallback);
- key-fingerprint match: shifted byte compares locate the known
  depth-2 keys (`"path"`, `"size"`, `"modificationTime"`,
  `"dataChange"`, `"deletionTimestamp"`, `"extendedFileMetadata"`,
  `"stats"`, empty `"partitionValues"`) and the `{"add":`/`{"remove":`
  line tags;
- vectorized span extraction and int parse: string spans resolve
  their closing quote through a quote-rank scatter, numerics parse
  with an unrolled Horner loop in scoped-x64 int64.

A line is SIMPLE when its depth-2 colon census is fully explained by
matched known keys, it has no depth>=3 colons (nested deletionVector /
tags / non-empty partitionValues objects), and every matched numeric/
boolean value validates. Anything else — and any window whose lines
fail the structural balance checks (odd quote count, unbalanced or
negative brace depth) — routes the WHOLE window back to the host
scanner, preserving digest parity by construction: the device route
only ever answers for content it parsed exactly.

Per-line result lanes come back as three dense blocks (int64 values,
int32 spans, packed flags), so the D2H cost is O(lines), not O(bytes).
Escaped string spans (backslashes in paths or stats) are flagged and
unescaped host-side by the caller (`replay/device_parse.py`).

Windows at or beyond 2 GiB are rejected up front (`window_eligible`):
every span lane is int32, and a >=2^31 byte offset would wrap.
"""

from __future__ import annotations

import functools

import numpy as np

from delta_tpu import obs

# Window spans are int32: a window must keep every byte offset below
# 2^31. Callers split larger buffers (replay/device_parse.py windows at
# DELTA_TPU_DEVICE_PARSE_WINDOW, default 64 MiB) long before this trips.
MAX_WINDOW_BYTES = (1 << 31) - 1

_PAT_ADD = b'{"add":{'
_PAT_REMOVE = b'{"remove":{'

# Known depth-2 keys of simple add/remove actions. Order is the lane
# order of the kernel outputs. kind: str -> quoted span; int -> int64
# numeric; bool -> true/false literal; empty -> literal '{}' value.
KEY_PATTERNS = (
    ("path", b'"path":"', "str"),
    ("stats", b'"stats":"', "str"),
    ("size", b'"size":', "int"),
    ("mod_time", b'"modificationTime":', "int"),
    ("del_ts", b'"deletionTimestamp":', "int"),
    ("data_change", b'"dataChange":', "bool"),
    ("ext_meta", b'"extendedFileMetadata":', "bool"),
    ("pv_empty", b'"partitionValues":{}', "empty"),
)
_STR_KEYS = tuple(i for i, p in enumerate(KEY_PATTERNS) if p[2] == "str")
_INT_KEYS = tuple(i for i, p in enumerate(KEY_PATTERNS) if p[2] == "int")
_BOOL_KEYS = tuple(i for i, p in enumerate(KEY_PATTERNS) if p[2] == "bool")

_TAIL_PAD = 32  # > longest pattern; keeps shifted compares off the edge
_MAX_INT_DIGITS = 18  # int64-safe; 19+ digit values fall back to host

# flag-lane order in the packed bool block
FLAG_NAMES = (
    "is_add", "is_remove", "complex",
    "path_esc", "stats_esc", "stats_present",
    "size_present", "mod_time_present", "del_ts_present",
    "data_change_present", "data_change_val",
    "ext_meta_present", "ext_meta_val",
    "pv_present",
)
# int32 span-lane order
SPAN_NAMES = ("line_start", "line_end",
              "path_start", "path_end", "stats_start", "stats_end")
# int64 value-lane order
VAL_NAMES = ("size_val", "mod_time_val", "del_ts_val")


def window_eligible(nbytes: int) -> bool:
    """int32-span guard: offsets in a window must fit in int32."""
    return 0 < nbytes < MAX_WINDOW_BYTES


def _use_device_classes() -> bool:
    """Run the byte-class stage as a real Pallas kernel only on TPU;
    interpret-mode Pallas on CPU costs more than the fused jnp
    compares it replaces."""
    import jax

    return jax.default_backend() == "tpu"


@functools.lru_cache(maxsize=32)
def _parse_fn_cached(n_pad: int, l_pad: int, pallas_classes: bool):
    """jit'd whole-window field extraction.

    Input: `bx` [n_pad + _TAIL_PAD] uint8 (real bytes then 0x20
    padding), `n_lines` int32 scalar (real line count). Output:
    (vals [3, l_pad] int64, spans [6, l_pad] int32,
     flags [len(FLAG_NAMES), l_pad] bool, window_ok scalar bool).
    """
    import jax
    import jax.numpy as jnp

    n = n_pad
    big = jnp.int32(n)

    def shift_in(m):
        """Previous-byte view of a mask (False shifted in at pos 0)."""
        return jnp.concatenate([jnp.zeros(1, m.dtype), m[:-1]])

    def kernel(bx, n_lines):
        b = bx[:n]
        pos = jnp.arange(n, dtype=jnp.int32)
        if pallas_classes:
            from delta_tpu.ops.pallas_kernels import byte_class_tiled

            cls = byte_class_tiled(b)
            nl = (cls & 1) != 0
            quote = (cls & 2) != 0
            bs = (cls & 4) != 0
            colon = (cls & 8) != 0
            lb = (cls & 16) != 0
            rb = (cls & 32) != 0
        else:
            nl = b == 10
            quote = b == 34
            bs = b == 92
            colon = b == 58
            lb = b == 123
            rb = b == 125

        nli = nl.astype(jnp.int32)
        nl_rank = jnp.cumsum(nli)        # inclusive newline rank
        line_id = nl_rank - nli          # line containing each byte
        drop = jnp.int32(l_pad)          # OOB segment sentinel
        line_start = (jnp.zeros(l_pad, jnp.int32)
                      .at[jnp.where(nl, nl_rank, drop)]
                      .set(pos + 1, mode="drop"))
        line_end = (jnp.full(l_pad, n, jnp.int32)
                    .at[jnp.where(nl, nl_rank - 1, drop)]
                    .set(pos, mode="drop"))

        # escape initiators: a backslash at even offset within its run
        run_start = bs & ~shift_in(bs)
        last_rs = jax.lax.associative_scan(
            jnp.maximum, jnp.where(run_start, pos, jnp.int32(-1)))
        initiator = bs & (((pos - last_rs) & 1) == 0)
        uq = quote & ~shift_in(initiator)  # structurally active quote
        uqi = uq.astype(jnp.int32)
        q_cum = jnp.cumsum(uqi)
        outside = ((q_cum - uqi) & 1) == 0  # even quote parity before

        s_colon = colon & outside
        depth = jnp.cumsum((lb & outside).astype(jnp.int32)
                           - (rb & outside).astype(jnp.int32))
        c1 = s_colon & (depth == 1)
        c2 = s_colon & (depth == 2)
        c3 = s_colon & (depth >= 3)

        def seg_sum(m):
            return jax.ops.segment_sum(m.astype(jnp.int32), line_id,
                                       num_segments=l_pad)

        n_c1, n_c2, n_c3 = seg_sum(c1), seg_sum(c2), seg_sum(c3)
        n_quotes = jax.ops.segment_sum(uqi, line_id, num_segments=l_pad)
        depth_end = (jnp.zeros(l_pad, jnp.int32)
                     .at[jnp.where(nl, line_id, drop)]
                     .set(depth, mode="drop"))
        depth_min = jax.ops.segment_min(depth, line_id,
                                        num_segments=l_pad)

        # rank -> position of each active quote (closing-quote lookup)
        pos_by_rank = (jnp.full(n + 1, n, jnp.int32)
                       .at[jnp.where(uq, q_cum - 1, big)]
                       .set(pos, mode="drop"))
        bs_cum = jnp.cumsum(bs.astype(jnp.int32))

        at_ls = shift_in(nl).at[0].set(True)

        def match(pat):
            acc = jnp.ones(n, bool)
            for k, ch in enumerate(pat):
                acc = acc & (bx[k:k + n] == np.uint8(ch))
            return acc

        m_add = match(_PAT_ADD) & at_ls
        m_rem = match(_PAT_REMOVE) & at_ls
        is_add = seg_sum(m_add) > 0
        is_rem = seg_sum(m_rem) > 0
        filerow = is_add | is_rem

        counts, mpos = [], []
        for _name, pat, _kind in KEY_PATTERNS:
            m = match(pat) & uq & outside & (depth == 2)
            counts.append(seg_sum(m))
            mpos.append(jax.ops.segment_min(
                jnp.where(m, pos, big), line_id, num_segments=l_pad))

        def gather8(idx):
            return bx[jnp.clip(idx, 0, n + _TAIL_PAD - 1)]

        def gather32(arr, idx, limit):
            return arr[jnp.clip(idx, 0, limit)]

        # string spans: [open_quote + 1, closing quote)
        span_start, span_end, span_esc, span_bad = {}, {}, {}, {}
        for i in _STR_KEYS:
            name, pat, _ = KEY_PATTERNS[i]
            present = counts[i] == 1
            o = mpos[i] + np.int32(len(pat) - 1)   # value's opening quote
            rank = gather32(q_cum, o, n - 1)
            close = gather32(pos_by_rank, rank, n)
            start = o + 1
            nbs = (gather32(bs_cum, close - 1, n - 1)
                   - gather32(bs_cum, start - 1, n - 1))
            span_start[name] = jnp.where(present, start, 0)
            span_end[name] = jnp.where(present, close, 0)
            span_esc[name] = present & (nbs > 0)
            span_bad[name] = present & ((close >= line_end)
                                        | (close <= o))

        # numerics: unrolled Horner over at most _MAX_INT_DIGITS digits
        num_val, num_present, num_bad = {}, {}, {}
        for i in _INT_KEYS:
            name, pat, _ = KEY_PATTERNS[i]
            present = counts[i] == 1
            vs = mpos[i] + np.int32(len(pat))
            negm = gather8(vs) == np.uint8(45)
            base = vs + negm.astype(jnp.int32)
            val = jnp.zeros(l_pad, jnp.int64)
            active = jnp.ones(l_pad, bool)
            term_ok = jnp.zeros(l_pad, bool)
            ndig = jnp.zeros(l_pad, jnp.int32)
            for j in range(_MAX_INT_DIGITS + 1):
                ch = gather8(base + np.int32(j))
                is_d = (ch >= np.uint8(48)) & (ch <= np.uint8(57))
                take = active & is_d
                val = jnp.where(take,
                                val * 10 + (ch - np.uint8(48))
                                .astype(jnp.int64), val)
                ndig = ndig + take.astype(jnp.int32)
                stop = active & ~is_d
                term_ok = jnp.where(
                    stop, (ch == np.uint8(44)) | (ch == np.uint8(125)),
                    term_ok)
                active = active & is_d
            num_val[name] = jnp.where(negm, -val, val)
            num_present[name] = present
            # still-active after the unroll = too many digits for int64
            num_bad[name] = present & (active | (ndig < 1) | ~term_ok)

        bool_val, bool_present, bool_bad = {}, {}, {}
        for i in _BOOL_KEYS:
            name, pat, _ = KEY_PATTERNS[i]
            present = counts[i] == 1
            ch = gather8(mpos[i] + np.int32(len(pat)))
            bool_val[name] = ch == np.uint8(116)   # 't'
            bool_present[name] = present
            bool_bad[name] = present & (ch != np.uint8(116)) \
                & (ch != np.uint8(102))            # nor 'f'

        matched = counts[0]
        for c in counts[1:]:
            matched = matched + c
        dup = jnp.zeros(l_pad, bool)
        for c in counts:
            dup = dup | (c > 1)
        tail_ch = gather8(line_end - 1)
        any_bad = (span_bad["path"] | span_bad["stats"]
                   | num_bad["size"] | num_bad["mod_time"]
                   | num_bad["del_ts"]
                   | bool_bad["data_change"] | bool_bad["ext_meta"])
        complex_line = filerow & (
            (n_c1 != 1) | (n_c2 != matched) | (n_c3 > 0) | dup
            | (counts[0] != 1)                 # path is mandatory
            | (tail_ch != np.uint8(125))       # line must close with '}'
            | any_bad)

        valid_line = jnp.arange(l_pad, dtype=jnp.int32) < n_lines
        bal_bad = valid_line & (((n_quotes & 1) != 0)
                                | (depth_end != 0) | (depth_min < 0))
        window_ok = ~jnp.any(bal_bad)

        vals = jnp.stack([num_val["size"], num_val["mod_time"],
                          num_val["del_ts"]])
        spans = jnp.stack([line_start, line_end,
                           span_start["path"], span_end["path"],
                           span_start["stats"], span_end["stats"]])
        flags = jnp.stack([
            is_add, is_rem, complex_line,
            span_esc["path"], span_esc["stats"],
            counts[1] == 1,
            num_present["size"], num_present["mod_time"],
            num_present["del_ts"],
            bool_present["data_change"], bool_val["data_change"],
            bool_present["ext_meta"], bool_val["ext_meta"],
            counts[7] == 1,
        ])
        return vals, spans, flags, window_ok

    return jax.jit(kernel)


def parse_window_fields(window: np.ndarray, n_lines: int, device=None):
    """Run the field-extraction kernel over one newline-terminated
    uint8 window. Returns a dict of per-line numpy lanes (keys:
    VAL_NAMES + SPAN_NAMES + FLAG_NAMES, each length `n_lines`) or
    None when the window failed the structural balance checks.

    One H2D copy: the padded uint8 lane (`json-parse-window` budget
    entry). D2H is three dense per-line blocks.
    """
    import jax

    from delta_tpu.ops.replay import pad_bucket
    from delta_tpu.ops.stats import _x64

    n = int(window.shape[0])
    if not window_eligible(n):
        return None
    n_pad = pad_bucket(n)
    l_pad = pad_bucket(n_lines + 1)
    # 0x20 padding: joins the (discarded) tail line, matches no pattern
    lane_bytes = np.full(n_pad + _TAIL_PAD, 0x20, np.uint8)
    lane_bytes[:n] = window
    from delta_tpu.ops.pallas_kernels import _BYTE_TILE

    pallas_ok = _use_device_classes() and n_pad % _BYTE_TILE == 0
    fn = _parse_fn_cached(n_pad, l_pad, pallas_ok)
    with obs.device_dispatch("json_parse.window",
                             key=(n_pad, l_pad, pallas_ok),
                             budget="json-parse-window",
                             units=lane_bytes.shape[0],
                             gate="parse") as dd, _x64():
        dd.h2d("lane_bytes", lane_bytes)
        vals, spans, flags, window_ok = fn(
            jax.device_put(lane_bytes, device), np.int32(n_lines))
        if not bool(window_ok):
            dd.set(window_ok=False)
            return None
        vals = dd.d2h("vals", np.asarray(vals))[:, :n_lines]
        spans = dd.d2h("spans", np.asarray(spans))[:, :n_lines]
        flags = dd.d2h("flags", np.asarray(flags))[:, :n_lines]
    out = {}
    for i, name in enumerate(VAL_NAMES):
        out[name] = vals[i]
    for i, name in enumerate(SPAN_NAMES):
        out[name] = spans[i]
    for i, name in enumerate(FLAG_NAMES):
        out[name] = flags[i]
    return out

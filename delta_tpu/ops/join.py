"""Device equi-join for MERGE matching.

The reference's MERGE finds matches with a Spark shuffle join
(`commands/merge/ClassicMergeExecutor.scala`). The TPU-native
formulation reuses the replay kernel's shape: dictionary-encode the join
keys host-side, then ONE fixed-shape device pass — sort (code, side) and
segment-reduce — produces everything MERGE's planner needs:

- per-target-row: the matching source row (or -1);
- per-source-row: whether any target row matched it (insert detection),
  shipped home as packed bits;
- one scalar: how many target rows have MULTIPLE matching source rows
  (the cardinality rule needs only the count — shipping a full per-row
  count lane home would triple the D2H bytes).

MERGE's cardinality rule makes the fixed shapes possible: a target row
matched by more than one source row is an ERROR when update/delete
clauses exist, so the successful output is exactly one source index per
target row — no variable-length pair materialization.

Operands are laid out as [target block | source block] with separately
bucket-padded static sizes, so outputs slice exactly on device and jit
programs are reused across growing tables.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from delta_tpu import obs
from delta_tpu.ops.replay import _unpack_bits, pad_bucket

_PAD_CODE = np.uint32(0xFFFFFFFF)


@functools.partial(jax.jit, static_argnames=("nt_pad", "ns_pad"))
def _join_kernel(codes, nt_pad: int, ns_pad: int):
    """codes u32[nt_pad + ns_pad]: target codes then source codes, pads =
    all-ones sentinel. Returns (match_src i32[nt_pad] source-local row or
    -1, src_matched_words u32[ns_pad/32], n_multi i32[] count of target
    rows whose key has > 1 source row)."""
    n = nt_pad + ns_pad
    iota = jnp.arange(n, dtype=jnp.uint32)
    side = (iota >= nt_pad).astype(jnp.uint32)  # 0 target, 1 source
    # pads carry the sentinel code; their side bit doesn't matter — the
    # sentinel run never matches a real run's code
    s_code, s_side, s_pos = jax.lax.sort((codes, side, iota), num_keys=2)

    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), s_code[1:] != s_code[:-1]])
    run_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1

    pad_run = s_code == jnp.uint32(0xFFFFFFFF)
    is_src = (s_side == 1) & ~pad_run
    is_tgt = (s_side == 0) & ~pad_run
    n_src_per_run = jax.ops.segment_sum(
        is_src.astype(jnp.int32), run_id, num_segments=n)
    n_tgt_per_run = jax.ops.segment_sum(
        is_tgt.astype(jnp.int32), run_id, num_segments=n)
    src_pos_or_inf = jnp.where(is_src, s_pos, jnp.uint32(n))
    first_src_sorted = jax.ops.segment_min(
        src_pos_or_inf, run_id, num_segments=n)

    # scatter run aggregates back to input positions
    n_src_in = jnp.zeros((n,), jnp.int32).at[s_pos].set(n_src_per_run[run_id])
    n_tgt_in = jnp.zeros((n,), jnp.int32).at[s_pos].set(n_tgt_per_run[run_id])
    first_src_in = jnp.full((n,), jnp.uint32(n)).at[s_pos].set(
        first_src_sorted[run_id])

    match_src = jnp.where(
        n_src_in[:nt_pad] > 0,
        first_src_in[:nt_pad].astype(jnp.int32) - jnp.int32(nt_pad),
        jnp.int32(-1))
    n_multi = jnp.sum((n_src_in[:nt_pad] > 1).astype(jnp.int32))

    src_matched = (n_tgt_in[nt_pad:] > 0)
    bit_pos = jnp.arange(32, dtype=jnp.uint32)
    weights = jnp.uint32(1) << bit_pos
    src_words = (src_matched.reshape(-1, 32).astype(jnp.uint32)
                 * weights).sum(axis=1, dtype=jnp.uint32)
    return match_src, src_words, n_multi


def equi_join_codes(
    t_codes: np.ndarray, s_codes: np.ndarray, device=None,
) -> tuple[np.ndarray, int, np.ndarray]:
    """Join by pre-encoded key codes (< 0xFFFFFFFF). Returns
    (match_src int32[nt] source row index or -1, n_multi int,
    source_matched bool[ns])."""
    nt, ns = len(t_codes), len(s_codes)
    nt_pad = pad_bucket(max(nt, 1))
    ns_pad = pad_bucket(max(ns, 1))
    codes = np.full(nt_pad + ns_pad, _PAD_CODE, np.uint32)
    codes[:nt] = t_codes
    codes[nt_pad:nt_pad + ns] = s_codes
    with obs.device_dispatch("join.merge_match",
                             key=(nt_pad, ns_pad),
                             budget="merge-join-codes",
                             units=nt_pad + ns_pad) as dd:
        dd.h2d("codes", codes)
        codes_dev = jax.device_put(codes, device) \
            if device is not None else codes
        match_src, src_words, n_multi = _join_kernel(
            codes_dev, nt_pad=nt_pad, ns_pad=ns_pad)
    match_src = np.asarray(match_src)[:nt]
    src_matched = _unpack_bits(np.asarray(src_words), ns_pad)[:ns]
    return match_src, int(n_multi), src_matched


def equi_join_device(
    target_keys, source_keys, device=None,
) -> tuple[np.ndarray, int, np.ndarray]:
    """Join on one or more key columns (numpy arrays, null-free —
    callers drop SQL-null keys first). Dictionary-encodes
    (target ++ source) jointly with pandas factorize, then runs the
    device kernel. Returns (match_src, n_multi, source_matched) as in
    `equi_join_codes`."""
    import pandas as pd

    t_cols = [np.asarray(c) for c in target_keys]
    s_cols = [np.asarray(c) for c in source_keys]
    nt = len(t_cols[0]) if t_cols else 0
    codes = None
    for tc, sc in zip(t_cols, s_cols):
        both = np.concatenate([tc, sc])
        # use_na_sentinel=False: float NaN gets a REAL code (all NaNs the
        # same one), so NaN = NaN matches — Spark's equi-join semantics.
        # (Genuinely-NULL keys were dropped by the caller; the sentinel
        # -1 would wrap to 2**64-1 under uint64 and poison the radix.)
        c, _ = pd.factorize(both, sort=False, use_na_sentinel=False)
        c = c.astype(np.uint64)
        if codes is None:
            codes = c
        else:
            codes = codes * np.uint64(int(c.max(initial=0)) + 1) + c
        if int(codes.max(initial=0)) >= 1 << 32:
            # keep the running radix far from uint64 wrap (3+ wide keys)
            _, codes = np.unique(codes, return_inverse=True)
            codes = codes.astype(np.uint64)
    if codes is None:
        raise ValueError("equi_join_device requires at least one key")
    if int(codes.max(initial=0)) >= 0xFFFFFFFF - 1:
        # joint radix overflows u32: re-densify
        _, codes = np.unique(codes, return_inverse=True)
    codes = codes.astype(np.uint32)
    return equi_join_codes(codes[:nt], codes[nt:], device=device)

"""Space-filling-curve clustering keys (OPTIMIZE ZORDER BY / Hilbert).

The reference computes Z-order keys with a per-row JVM bit-interleave UDF
(`expressions/InterleaveBits.scala:40`) and Hilbert indexes via a
state-machine table (`HilbertIndex.java` / `HilbertStates.java`). Here
both are branch-free vectorized bit manipulation over whole columns —
XLA fuses the (static) bit loops into a handful of VPU passes, and rows
never leave the device between ranking, curve-key computation, and the
range-partition sort.

Pipeline (`MultiDimClustering.scala:41-69` semantics):
1. `range_rank` — each clustering column → dense uint32 rank (the exact
   equivalent of RangePartitionId's sampled ranges).
2. `interleave_bits` (Z-order) or `hilbert_key` (Hilbert, Skilling's
   public-domain transform) — [k] rank columns → [k] uint32 key words,
   most-significant word first.
3. `curve_order` — lexicographic argsort of the key words; OPTIMIZE
   writes files by slicing that order into target-size ranges.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from delta_tpu import obs


def range_rank(values: jnp.ndarray) -> jnp.ndarray:
    """Dense rank in [0, n) as uint32 (ties broken arbitrarily but
    consistently — fine for clustering)."""
    n = values.shape[0]
    order = jnp.argsort(values)
    ranks = jnp.zeros((n,), dtype=jnp.uint32).at[order].set(
        jnp.arange(n, dtype=jnp.uint32)
    )
    return ranks


def _scale_ranks(ranks: jnp.ndarray, n: int, n_bits: int) -> jnp.ndarray:
    """Spread ranks over the full n_bits key space so interleaving uses
    high bits first."""
    shift = max(0, n_bits - max(1, (n - 1).bit_length()))
    return (ranks << np.uint32(shift)).astype(jnp.uint32)


@functools.partial(jax.jit, static_argnames=("n_bits",))
def interleave_bits(cols: Sequence[jnp.ndarray], n_bits: int = 32) -> jnp.ndarray:
    """Round-robin bit interleave of k uint32 columns.

    Returns [k, n] uint32 words, word 0 most significant — sorting rows by
    (word0, word1, ...) sorts by the Z-order curve. Matches the reference's
    MSB-first round-robin layout (`InterleaveBits.scala:40`).
    """
    k = len(cols)
    n = cols[0].shape[0]
    total_bits = k * n_bits
    n_words = max(1, -(-total_bits // 32))
    words = [jnp.zeros((n,), dtype=jnp.uint32) for _ in range(n_words)]
    for g in range(total_bits):
        c = g % k              # source column (round-robin)
        s = n_bits - 1 - g // k  # source bit, MSB first
        w, wb = divmod(g, 32)
        bit = (cols[c] >> jnp.uint32(s)) & jnp.uint32(1)
        words[w] = words[w] | (bit << jnp.uint32(31 - wb))
    return jnp.stack(words)


@functools.partial(jax.jit, static_argnames=("n_bits",))
def hilbert_transpose(cols: Sequence[jnp.ndarray], n_bits: int = 16) -> list:
    """Skilling's inverse transform: coordinates → 'transposed' Hilbert
    form (public-domain algorithm, Skilling 2004). All ops are elementwise
    selects over the columns; the bit loop is static."""
    d = len(cols)
    X = [c.astype(jnp.uint32) for c in cols]
    M = jnp.uint32(1 << (n_bits - 1))

    # Inverse undo excess work
    Q = 1 << (n_bits - 1)
    while Q > 1:
        Qc = jnp.uint32(Q)
        P = jnp.uint32(Q - 1)
        for i in range(d):
            has = (X[i] & Qc) != 0
            # if bit set: invert low bits of X[0]; else swap low bits X[0]<->X[i]
            t = (X[0] ^ X[i]) & P
            X0_if = X[0] ^ P
            X0_else = X[0] ^ t
            Xi_else = X[i] ^ t
            X[0] = jnp.where(has, X0_if, X0_else)
            if i != 0:
                X[i] = jnp.where(has, X[i], Xi_else)
        Q >>= 1

    # Gray encode
    for i in range(1, d):
        X[i] = X[i] ^ X[i - 1]
    t = jnp.zeros_like(X[0])
    Q = 1 << (n_bits - 1)
    while Q > 1:
        Qc = jnp.uint32(Q)
        t = jnp.where((X[d - 1] & Qc) != 0, t ^ jnp.uint32(Q - 1), t)
        Q >>= 1
    for i in range(d):
        X[i] = X[i] ^ t
    return X


def hilbert_key(cols: Sequence[jnp.ndarray], n_bits: int = 16) -> jnp.ndarray:
    """Coordinates → sortable Hilbert key words [ceil(k*n_bits/32), n].

    The Hilbert integer is the bit-interleave of the transposed form
    (axis 0 contributes the most significant bit of each group)."""
    X = hilbert_transpose(cols, n_bits=n_bits)
    return interleave_bits(X, n_bits=n_bits)


def curve_order(key_words: jnp.ndarray) -> jnp.ndarray:
    """Row order along the curve: lexicographic argsort of the key words.
    Returns int32 permutation."""
    k, n = key_words.shape
    idx = jnp.arange(n, dtype=jnp.int32)
    operands = tuple(key_words[i] for i in range(k)) + (idx,)
    out = lax.sort(operands, num_keys=k)
    return out[-1]


@functools.partial(jax.jit, static_argnames=("curve",))
def _curve_perm(stacked: jnp.ndarray, curve: str) -> jnp.ndarray:
    """One fused device program: rank -> scale -> curve key -> argsort.
    `stacked` is the [n_cols, m] uint32 key matrix — all clustering
    columns ride ONE transfer and one dispatch; the column count and
    the (bucket-padded) row count are static shapes. Padding rows carry
    the all-ones sentinel, rank at the top, and sort to the end of the
    curve (the host drops them from the permutation)."""
    m = stacked.shape[1]
    cols = tuple(stacked[i] for i in range(stacked.shape[0]))
    ranks = [range_rank(c) for c in cols]
    if curve == "hilbert":
        n_bits = 16
        scaled = [
            _scale_ranks(r, m, 32) >> jnp.uint32(32 - n_bits) for r in ranks
        ]
        keys = hilbert_key(scaled, n_bits=n_bits)
    else:
        from delta_tpu.ops.pallas_kernels import interleave_bits_auto

        scaled = [_scale_ranks(r, m, 32) for r in ranks]
        # m is always a tile multiple (pad_bucket), so this is the
        # Pallas VMEM-tile kernel on TPU (jnp fallback elsewhere)
        keys = interleave_bits_auto(scaled, n_bits=32)
    return curve_order(keys)


def zorder_sort_indices(cols: Sequence[np.ndarray], curve: str = "zorder") -> np.ndarray:
    """Host entry: rank columns, build curve keys, return the row
    permutation that clusters rows along the curve.

    Rows are padded to a shape bucket (`ops.replay.pad_bucket`) so
    OPTIMIZE over many different bin sizes compiles a handful of
    programs instead of one per size, and the whole pipeline runs as a
    single jit (one dispatch, fully fused) rather than eager per-op
    round-trips. The per-column u32 keys are stacked into one host
    matrix first, so ALL clustering columns cross the link in a single
    transfer instead of one round trip per column."""
    n = len(cols[0])
    if n == 0:
        return np.empty(0, dtype=np.int32)
    from delta_tpu.ops.replay import pad_bucket

    m = pad_bucket(n, min_bucket=1024)
    # all-ones padding ranks above (or tied with) every real value, so
    # padding rows sort to the end of the curve
    stacked = np.full((len(cols), m), 0xFFFFFFFF, np.uint32)
    for i, c in enumerate(cols):
        stacked[i, :n] = _to_sortable_u32(c)
    # stacked rides as a jit argument (no device_put lane to budget)
    with obs.device_dispatch("zorder.curve_perm",
                             key=(len(cols), m, curve)) as dd:
        dd.h2d("stacked", stacked)
        perm = dd.d2h("perm",
                      np.asarray(_curve_perm(jnp.asarray(stacked), curve)))
    if m > n:
        perm = perm[perm < n]
    return perm


def _to_sortable_u32(col: np.ndarray) -> np.ndarray:
    """Map a numpy column to uint32 preserving order (for ranking)."""
    c = np.asarray(col)
    if c.dtype.kind == "f":
        # IEEE-754 total order trick
        bits = c.astype(np.float32).view(np.uint32)
        mask = np.where(bits >> 31 == 1, np.uint32(0xFFFFFFFF), np.uint32(0x80000000))
        return bits ^ mask
    if c.dtype.kind in ("i",):
        c64 = c.astype(np.int64)
        lo, hi = int(c64.min()), int(c64.max())
        if hi - lo < 2**32:
            return (c64 - lo).astype(np.uint32)
        # wide int64 range: dense host rank preserves order exactly
        order = np.argsort(c64, kind="stable")
        ranks = np.empty(len(c64), dtype=np.uint32)
        ranks[order] = np.arange(len(c64), dtype=np.uint32)
        return ranks
    if c.dtype.kind in ("u", "b"):
        return c.astype(np.uint32)
    if c.dtype.kind in ("U", "S", "O"):
        # strings: rank via numpy argsort on the host (exact order)
        order = np.argsort(c, kind="stable")
        ranks = np.empty(len(c), dtype=np.uint32)
        ranks[order] = np.arange(len(c), dtype=np.uint32)
        return ranks
    if np.issubdtype(c.dtype, np.datetime64):
        return _to_sortable_u32(c.astype("datetime64[us]").astype(np.int64) // 1000)
    raise ValueError(f"cannot build curve key from dtype {c.dtype}")

"""Durable cross-process commit arbitration (the DynamoDB role).

The external-arbiter protocol in `cloud.py` is only as strong as its
arbiter: `InMemoryCommitArbiter` is process-local, so two *processes*
racing commits on the same table get no arbitration at all. This module
supplies the durable arbiter the reference gets from DynamoDB
(`storage/src/main/java/io/delta/storage/S3DynamoDBLogStore.java:72`,
conditional put at `BaseExternalLogStore.java:321`):

- `SqliteCommitArbiter` — a strongly-consistent conditional-put table
  backed by sqlite in WAL mode. sqlite serializes writers across
  processes with file locks, and a UNIQUE primary key turns the insert
  into a true conditional put: exactly one of N racing
  `put_entry(overwrite=False)` calls for a version succeeds, the rest
  get `FileAlreadyExistsError` — the same contract as DynamoDB's
  `attribute_not_exists` condition expression.
- `RacyLocalStore` — a local-FS store with *S3 semantics*: blind PUT
  (no O_EXCL), non-atomic exists-check. Used by the multi-process fuzz
  to prove the arbiter provides the mutual exclusion the object store
  cannot.

Recovery (`fix_delta_log`, `cloud.py`) is arbiter-driven, so with a
durable arbiter any *other process* can complete a SIGKILLed writer's
half commit — the property `tools/arbiter_fuzz.py` kill-tests.
"""

from __future__ import annotations

import os
import sqlite3
import uuid
from contextlib import closing
from typing import Optional

from delta_tpu.storage.cloud import (
    CommitArbiter,
    ExternalArbiterLogStore,
    ExternalCommitEntry,
)
from delta_tpu.storage.logstore import (
    FileAlreadyExistsError,
    LocalLogStore,
    LogStore,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS commit_entries (
    table_path  TEXT NOT NULL,
    file_name   TEXT NOT NULL,
    temp_path   TEXT NOT NULL,
    complete    INTEGER NOT NULL,
    expire_time INTEGER,
    PRIMARY KEY (table_path, file_name)
)
"""


class SqliteCommitArbiter(CommitArbiter):
    """Conditional-put arbiter table usable from independent processes.

    One sqlite file == one DynamoDB table; rows are keyed by
    (table_path, file_name) exactly like the reference's
    `ExternalCommitEntry.java`. Every operation opens its own
    connection: connections are cheap at commit rates, and it keeps the
    arbiter safe to use after fork/spawn (sqlite connections must not
    cross process boundaries)."""

    def __init__(self, db_path: str, timeout_s: float = 30.0):
        self.db_path = db_path
        self.timeout_s = timeout_s
        parent = os.path.dirname(db_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with closing(self._connect()) as conn, conn:
            conn.execute(_SCHEMA)

    def _connect(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.db_path, timeout=self.timeout_s)
        # WAL survives SIGKILL mid-transaction (auto-rollback on next
        # open) and lets readers proceed under a writer. FULL (not
        # NORMAL): an acknowledged conditional put is the commit
        # arbiter's durability promise — it must survive power loss,
        # not just process death, to match DynamoDB semantics
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=FULL")
        return conn

    def put_entry(self, entry: ExternalCommitEntry,
                  overwrite: bool) -> None:
        row = (entry.table_path, entry.file_name, entry.temp_path,
               int(entry.complete), entry.expire_time)
        with closing(self._connect()) as conn, conn:
            if overwrite:
                conn.execute(
                    "INSERT OR REPLACE INTO commit_entries VALUES "
                    "(?, ?, ?, ?, ?)", row)
                return
            try:
                conn.execute(
                    "INSERT INTO commit_entries VALUES (?, ?, ?, ?, ?)",
                    row)
            except sqlite3.IntegrityError:
                raise FileAlreadyExistsError(entry.file_name)

    def put_entries(self, entries, overwrite: bool = False) -> int:
        """All-or-nothing conditional multi-put: one transaction, so a
        batch either claims every version or none (TransactWriteItems
        semantics). Returns len(entries) on success, 0 on any key
        collision — never a partial count."""
        entries = list(entries)
        if not entries:
            return 0
        rows = [(e.table_path, e.file_name, e.temp_path,
                 int(e.complete), e.expire_time) for e in entries]
        sql = ("INSERT OR REPLACE INTO commit_entries VALUES (?, ?, ?, ?, ?)"
               if overwrite else
               "INSERT INTO commit_entries VALUES (?, ?, ?, ?, ?)")
        # IntegrityError is caught OUTSIDE the `with conn` block: the
        # context manager must see the exception so it rolls back the
        # already-inserted prefix of the executemany.
        try:
            with closing(self._connect()) as conn, conn:
                conn.executemany(sql, rows)
        except sqlite3.IntegrityError:
            return 0
        return len(rows)

    def get_entry(self, table_path: str,
                  file_name: str) -> Optional[ExternalCommitEntry]:
        with closing(self._connect()) as conn, conn:
            cur = conn.execute(
                "SELECT table_path, file_name, temp_path, complete, "
                "expire_time FROM commit_entries WHERE table_path = ? "
                "AND file_name = ?", (table_path, file_name))
            row = cur.fetchone()
        return self._row_to_entry(row)

    def get_latest_entry(
            self, table_path: str) -> Optional[ExternalCommitEntry]:
        with closing(self._connect()) as conn, conn:
            cur = conn.execute(
                "SELECT table_path, file_name, temp_path, complete, "
                "expire_time FROM commit_entries WHERE table_path = ? "
                "ORDER BY file_name DESC LIMIT 1", (table_path,))
            row = cur.fetchone()
        return self._row_to_entry(row)

    def get_incomplete_entries(self, table_path: str):
        with closing(self._connect()) as conn, conn:
            cur = conn.execute(
                "SELECT table_path, file_name, temp_path, complete, "
                "expire_time FROM commit_entries WHERE table_path = ? "
                "AND complete = 0 ORDER BY file_name ASC", (table_path,))
            rows = cur.fetchall()
        return [self._row_to_entry(r) for r in rows]

    @staticmethod
    def _row_to_entry(row) -> Optional[ExternalCommitEntry]:
        if row is None:
            return None
        return ExternalCommitEntry(
            table_path=row[0], file_name=row[1], temp_path=row[2],
            complete=bool(row[3]), expire_time=row[4])


class RacyLocalStore(LocalLogStore):
    """Local FS with S3 PUT semantics: `write(overwrite=False)` is a
    non-atomic exists-check followed by a blind put — the TOCTOU window
    the external arbiter exists to close. Only for arbitration tests
    and fuzzes; real tables on local disk use `LocalLogStore`."""

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        if not overwrite and os.path.exists(path):
            raise FileAlreadyExistsError(path)
        super().write(path, data, overwrite=True)


def external_arbiter_store(db_path: str,
                           inner: Optional[LogStore] = None,
                           ) -> ExternalArbiterLogStore:
    """The multi-process-safe store: S3-semantics inner + sqlite
    arbiter. Independent processes pointing at the same `db_path` get
    real commit arbitration (the `S3DynamoDBLogStore` deployment
    shape)."""
    return ExternalArbiterLogStore(
        inner if inner is not None else RacyLocalStore(),
        SqliteCommitArbiter(db_path))

"""Azure (ADLS Gen2) rename-based LogStore.

The reference's Azure commit path is the rename family: write the
commit to a hidden temp file, then atomically rename it onto the final
name, failing if the destination exists
(`storage/src/main/java/io/delta/storage/AzureLogStore.java:1`,
`HadoopFileSystemLogStore.java` `writeWithRename`). ADLS Gen2 exposes
exactly that primitive over REST: `PUT <dest> x-ms-rename-source=...`
with `If-None-Match: *`.

Shape mirrors `storage/cloud.py`'s GCS pair: a thin REST client with
an injectable transport (tests run a real HTTP server), and a
`LogStore` whose atomicity contract comes from the service's rename
precondition. `is_partial_write_visible` is False — a reader can never
observe a half-written commit under its final name, only under the
dot-prefixed temp name, which the delta-log listing ignores.
"""

from __future__ import annotations

import datetime
import json
import urllib.parse
import uuid
from typing import Dict, Iterator, List, Optional

from delta_tpu.resilience.classify import StorageRequestError
from delta_tpu.storage.cloud import HttpTransport, Transport
from delta_tpu.storage.logstore import (
    FileAlreadyExistsError,
    FileStatus,
    LogStore,
)


class AdlsGen2Client:
    """Minimal ADLS Gen2 (DFS endpoint) client: create/append/flush,
    read, rename-if-absent, list, stat, delete."""

    def __init__(self, account: str, filesystem: str,
                 transport: Optional[Transport] = None,
                 base_url: Optional[str] = None,
                 bearer_token: Optional[str] = None):
        self.account = account
        self.filesystem = filesystem
        self.transport = transport or HttpTransport()
        self.base = (base_url
                     or f"https://{account}.dfs.core.windows.net")
        self.token = bearer_token

    def _headers(self, extra: Optional[Dict[str, str]] = None
                 ) -> Dict[str, str]:
        h = {"x-ms-version": "2023-11-03"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        if extra:
            h.update(extra)
        return h

    def _url(self, name: str, query: str = "") -> str:
        path = urllib.parse.quote(f"/{self.filesystem}/{name}")
        return f"{self.base}{path}" + (f"?{query}" if query else "")

    def put_file(self, name: str, data: bytes) -> None:
        """create + append + flush (the Gen2 three-step upload)."""
        status, _, body = self.transport(
            "PUT", self._url(name, "resource=file"), self._headers(),
            b"")
        if status not in (200, 201):
            raise StorageRequestError(
                f"adls create {name}: {status} {body[:200]!r}", status)
        if data:
            status, _, body = self.transport(
                "PATCH", self._url(name, "action=append&position=0"),
                self._headers(), data)
            if status not in (200, 202):
                raise StorageRequestError(
                    f"adls append {name}: {status} {body[:200]!r}", status)
        status, _, body = self.transport(
            "PATCH",
            self._url(name, f"action=flush&position={len(data)}"),
            self._headers(), b"")
        if status not in (200, 202):
            raise StorageRequestError(
                f"adls flush {name}: {status} {body[:200]!r}", status)

    def rename_if_absent(self, src: str, dst: str) -> bool:
        """Atomic rename failing if `dst` exists. True on success,
        False on destination-exists."""
        headers = self._headers({
            "x-ms-rename-source": urllib.parse.quote(
                f"/{self.filesystem}/{src}"),
            "If-None-Match": "*",
        })
        status, _, body = self.transport("PUT", self._url(dst),
                                         headers, b"")
        if status in (200, 201):
            return True
        if status in (409, 412):  # exists / precondition failed
            return False
        raise StorageRequestError(
            f"adls rename {src}->{dst}: {status} {body[:200]!r}", status)

    def rename_overwrite(self, src: str, dst: str) -> None:
        """Atomic rename replacing `dst` if it exists (no
        precondition — the service swaps the destination in one op)."""
        headers = self._headers({
            "x-ms-rename-source": urllib.parse.quote(
                f"/{self.filesystem}/{src}"),
        })
        status, _, body = self.transport("PUT", self._url(dst),
                                         headers, b"")
        if status not in (200, 201):
            raise StorageRequestError(
                f"adls rename {src}->{dst}: {status} {body[:200]!r}", status)

    def get(self, name: str) -> bytes:
        status, _, body = self.transport("GET", self._url(name),
                                         self._headers(), None)
        if status == 404:
            raise FileNotFoundError(name)
        if status != 200:
            raise StorageRequestError(f"adls get {name}: {status}", status)
        return body

    def stat(self, name: str) -> dict:
        status, headers, _ = self.transport("HEAD", self._url(name),
                                            self._headers(), None)
        if status == 404:
            raise FileNotFoundError(name)
        if status != 200:
            raise StorageRequestError(f"adls head {name}: {status}", status)
        return {k.lower(): v for k, v in headers.items()}

    def list_dir(self, directory: str) -> List[dict]:
        # ADLS Gen2 paginates listings (default 5000 entries/page);
        # follow x-ms-continuation until absent or a page comes back
        # empty-with-the-same-token (defensive stop).
        base_q = ("resource=filesystem&recursive=false&directory="
                  + urllib.parse.quote(directory))
        out: List[dict] = []
        continuation: Optional[str] = None
        while True:
            q = base_q
            if continuation:
                q += "&continuation=" + urllib.parse.quote(
                    continuation, safe="")
            url = f"{self.base}/{self.filesystem}?{q}"
            status, headers, body = self.transport(
                "GET", url, self._headers(), None)
            if status == 404:
                if continuation is not None:
                    # the directory vanished mid-pagination: a partial
                    # listing must not masquerade as a complete one
                    raise IOError(
                        f"adls list {directory}: 404 on continuation "
                        "page (listing changed underneath)")
                return out
            if status != 200:
                raise StorageRequestError(f"adls list {directory}: {status}",
                                          status)
            out.extend(json.loads(body.decode()).get("paths", []))
            nxt = {k.lower(): v for k, v in headers.items()}.get(
                "x-ms-continuation")
            if not nxt or nxt == continuation:
                return out
            continuation = nxt

    def delete(self, name: str) -> None:
        status, _, _ = self.transport("DELETE", self._url(name),
                                      self._headers(), None)
        if status not in (200, 202, 404):
            raise StorageRequestError(f"adls delete {name}: {status}", status)


def _mtime_ms(item: dict) -> int:
    raw = item.get("lastModified") or item.get("last-modified") or ""
    if not raw:
        return 0
    try:
        dt = datetime.datetime.strptime(
            raw, "%a, %d %b %Y %H:%M:%S %Z")
        return int(dt.replace(
            tzinfo=datetime.timezone.utc).timestamp() * 1000)
    except ValueError:
        return 0


class AzureRenameLogStore(LogStore):
    """Rename-based atomic commits (`AzureLogStore.java:1` role):
    write `<dir>/.<name>.<uuid>.tmp`, then rename-if-absent onto the
    final name. A crash before the rename leaves only a dot-temp the
    log listing ignores; the rename itself is service-atomic."""

    def __init__(self, client: AdlsGen2Client,
                 scheme_prefix: str = "abfss"):
        self.client = client
        self.prefix = f"{scheme_prefix}://{client.filesystem}@" \
                      f"{client.account}"

    def _name(self, path: str) -> str:
        if "://" in path:
            rest = path.split("://", 1)[1]
            # abfss://<fs>@<account>/<obj> or flat <host>/<obj>
            rest = rest.split("/", 1)[1] if "/" in rest else ""
            return rest
        return path.lstrip("/")

    def read(self, path: str) -> bytes:
        return self.client.get(self._name(path))

    def write(self, path: str, data: bytes,
              overwrite: bool = False) -> None:
        name = self._name(path)
        parent, _, base = name.rpartition("/")
        tmp = (f"{parent}/" if parent else "") + \
            f".{base}.{uuid.uuid4().hex}.tmp"
        if overwrite:
            # temp + unconditional rename keeps the destination
            # all-or-nothing, so is_partial_write_visible stays False
            # for every write path (create+append+flush directly onto
            # the final name would expose an empty/partial file).
            self.client.put_file(tmp, data)
            try:
                self.client.rename_overwrite(tmp, name)
            except Exception:
                self._cleanup_tmp(tmp)
                raise
            return
        self.client.put_file(tmp, data)
        # a successful rename removes the source atomically; only the
        # destination-exists and transport-error paths leave a temp to
        # clean (an orphan temp is invisible to the log listing anyway)
        try:
            renamed = self.client.rename_if_absent(tmp, name)
        except Exception:
            self._cleanup_tmp(tmp)
            raise
        if not renamed:
            self._cleanup_tmp(tmp)
            raise FileAlreadyExistsError(path)

    def _cleanup_tmp(self, tmp: str) -> None:
        try:
            self.client.delete(tmp)
        except IOError:
            pass

    def _status(self, item: dict, directory: str) -> FileStatus:
        name = item["name"]
        return FileStatus(
            path=f"{self.prefix}/{name}",
            size=int(item.get("contentLength", 0)),
            modification_time=_mtime_ms(item),
        )

    def list_from(self, path: str) -> Iterator[FileStatus]:
        name = self._name(path)
        directory, _, start = name.rpartition("/")
        items = self.client.list_dir(directory)
        out = []
        for it in items:
            base = it["name"].rpartition("/")[2]
            if base >= start and not it.get("isDirectory"):
                out.append(self._status(it, directory))
        return iter(sorted(out, key=lambda s: s.path))

    def list_from_fast(self, path: str, skip_stat) -> Iterator[FileStatus]:
        return self.list_from(path)

    def list_dir(self, path: str) -> List[FileStatus]:
        name = self._name(path)
        return sorted(
            (self._status(it, name)
             for it in self.client.list_dir(name)
             if not it.get("isDirectory")),
            key=lambda s: s.path)

    def walk(self, path: str) -> Iterator[FileStatus]:
        name = self._name(path)
        stack = [name]
        while stack:
            d = stack.pop()
            for it in self.client.list_dir(d):
                if it.get("isDirectory"):
                    stack.append(it["name"])
                else:
                    yield self._status(it, d)

    def exists(self, path: str) -> bool:
        try:
            self.client.stat(self._name(path))
            return True
        except FileNotFoundError:
            return False

    def delete(self, path: str) -> None:
        self.client.delete(self._name(path))

    def mkdirs(self, path: str) -> None:
        pass  # Gen2 directories materialize with their files

    def file_status(self, path: str) -> FileStatus:
        h = self.client.stat(self._name(path))
        return FileStatus(
            path=path, size=int(h.get("content-length", 0)),
            modification_time=_mtime_ms(h))

    def is_partial_write_visible(self, path: str) -> bool:
        return False  # rename is atomic; temps hide under dot-names


def register_azure_schemes() -> None:
    """Register abfs/abfss factories resolving connection details from
    DELTA_TPU_AZURE_ACCOUNT / _FILESYSTEM / _TOKEN / _ENDPOINT."""
    import os

    from delta_tpu.storage.logstore import register_logstore_scheme

    def factory() -> AzureRenameLogStore:
        account = os.environ.get("DELTA_TPU_AZURE_ACCOUNT")
        fs = os.environ.get("DELTA_TPU_AZURE_FILESYSTEM")
        if not account or not fs:
            raise ValueError(
                "set DELTA_TPU_AZURE_ACCOUNT and "
                "DELTA_TPU_AZURE_FILESYSTEM to use abfs:// paths")
        return AzureRenameLogStore(AdlsGen2Client(
            account, fs,
            base_url=os.environ.get("DELTA_TPU_AZURE_ENDPOINT"),
            bearer_token=os.environ.get("DELTA_TPU_AZURE_TOKEN")))

    for scheme in ("abfs", "abfss", "wasb", "wasbs"):
        register_logstore_scheme(scheme, factory)

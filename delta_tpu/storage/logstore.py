"""Log-storage abstraction: the atomic put-if-absent commit primitive.

The entire ACID story of the log protocol reduces to two storage
guarantees (reference `storage/.../LogStore.java:57-140`):

1. `write(path, data, overwrite=False)` must fail with
   `FileAlreadyExistsError` if the path exists — mutual exclusion for
   commit files.
2. `list_from(path)` must return files in lexicographic order and reflect
   all completed writes (listing consistency).

Implementations here:
- `LocalLogStore` — POSIX: `O_CREAT|O_EXCL` open gives atomic
  put-if-absent; write-to-temp + `os.rename` gives atomic overwrite. On a
  GCS/S3 deployment the equivalent is `x-goog-if-generation-match: 0`
  preconditions / DynamoDB conditional put; the scheme registry below is
  the plug-in point (reference `DelegatingLogStore.scala:37`).
- `InMemoryLogStore` — lock-protected dict; used by tests and by the
  in-memory commit coordinator to simulate multi-writer races
  deterministically.
- `FaultInjectingLogStore` — wrapper that fails/blocks according to a
  schedule; the rebuild's analogue of `BlockWritesLocalFileSystem.scala`.
"""

from __future__ import annotations

import os
import threading
import uuid
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional

from delta_tpu import obs

# local-store counters: fsync count tracks commit durability cost, the
# conflict counter counts put-if-absent races lost (each one is a txn
# retry upstream)
_LOCAL_FSYNCS = obs.counter("storage.local.fsyncs")
_LOCAL_CONFLICTS = obs.counter("storage.local.conflicts")


@dataclass(frozen=True)
class FileStatus:
    """A listed file: path + size + modification time (ms since epoch)."""

    path: str
    size: int
    modification_time: int


class LogStore:
    """SPI. Paths are plain strings; `/`-separated. All methods raise
    FileNotFoundError / FileAlreadyExistsError with standard semantics."""

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        """Atomically create `path` with `data`. Without `overwrite`, raise
        FileAlreadyExistsError if it exists; the failure must be atomic
        (no partial file visible)."""
        raise NotImplementedError

    def write_batch(self, items, overwrite: bool = False) -> None:
        """Write several `(path, data)` pairs in order. Default: one
        `write` per item, stopping at the first failure — the already-
        written prefix stays durable, so a caller that sees an error
        must resolve each member's fate individually (read-back) rather
        than resubmitting the batch. Batch-aware stores (the external
        arbiter) override this with a one-round-trip protocol carrying
        the same prefix-durability contract."""
        for path, data in items:
            self.write(path, data, overwrite=overwrite)

    def list_from(self, path: str) -> Iterator[FileStatus]:
        """List files in the parent of `path` whose name is
        lexicographically >= `path`'s name, in sorted order."""
        raise NotImplementedError

    def list_from_fast(self, path: str, skip_stat) -> Iterator[FileStatus]:
        """Like list_from, but entries whose NAME satisfies `skip_stat`
        MAY come back with size=-1 / mtime=0 instead of paying a stat —
        callers needing a skipped entry's size/mtime stat it directly.
        Default: no stats are skippable; delegate to list_from."""
        return self.list_from(path)

    def list_dir(self, path: str) -> List[FileStatus]:
        raise NotImplementedError

    def walk(self, path: str) -> Iterator[FileStatus]:
        """Recursively yield every file under `path`."""
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def file_status(self, path: str) -> FileStatus:
        raise NotImplementedError

    def is_partial_write_visible(self, path: str) -> bool:
        """Whether a reader may observe a half-written file (true for
        rename-less stores). Drives whether commit files must be written
        via temp+rename."""
        return False


class LocalLogStore(LogStore):
    """POSIX-filesystem store with O_EXCL atomicity."""

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        if overwrite:
            tmp = os.path.join(parent, f".{os.path.basename(path)}.{uuid.uuid4().hex}.tmp")
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            _LOCAL_FSYNCS.inc()
            os.replace(tmp, path)
            return
        # Atomic put-if-absent. Write to a temp file first so a crash
        # mid-write never leaves a partial commit visible under the final
        # name; link() is atomic and fails if the target exists.
        tmp = os.path.join(parent, f".{os.path.basename(path)}.{uuid.uuid4().hex}.tmp")
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        _LOCAL_FSYNCS.inc()
        try:
            os.link(tmp, path)
        except FileExistsError:
            # the exact moment a commit race is lost — pin it to the
            # enclosing txn-attempt span before the retry machinery runs
            _LOCAL_CONFLICTS.inc()
            obs.add_event("commit_conflict", path=path)
            raise FileAlreadyExistsError(path)
        finally:
            os.unlink(tmp)

    def list_from(self, path: str) -> Iterator[FileStatus]:
        return self.list_from_fast(path, lambda _name: False)

    def list_from_fast(self, path: str, skip_stat) -> Iterator[FileStatus]:
        """Like list_from, but entries whose NAME satisfies `skip_stat`
        come back with size=-1 / mtime=0 instead of paying a stat
        syscall — at 100k-commit logs the per-file stats cost over a
        second while the commit reader discovers real sizes itself.
        Callers needing a specific entry's size/mtime stat it directly."""
        parent = os.path.dirname(path)
        name = os.path.basename(path)
        if not os.path.isdir(parent):
            raise FileNotFoundError(parent)
        try:
            with os.scandir(parent) as it:
                entries = sorted(
                    (e for e in it if e.name >= name), key=lambda e: e.name)
        except FileNotFoundError:
            raise FileNotFoundError(parent)
        sep = "" if parent.endswith("/") else "/"
        for e in entries:
            full = f"{parent}{sep}{e.name}"
            if skip_stat(e.name):
                yield FileStatus(full, -1, 0)
                continue
            try:
                st = e.stat()
            except FileNotFoundError:
                continue
            yield FileStatus(full, st.st_size, int(st.st_mtime * 1000))

    def list_dir(self, path: str) -> List[FileStatus]:
        out = []
        for e in sorted(os.listdir(path)):
            full = os.path.join(path, e)
            st = os.stat(full)
            out.append(FileStatus(full, st.st_size, int(st.st_mtime * 1000)))
        return out

    def walk(self, path: str) -> Iterator[FileStatus]:
        for root, _dirs, files in os.walk(path):
            for name in sorted(files):
                full = os.path.join(root, name)
                try:
                    st = os.stat(full)
                except FileNotFoundError:
                    continue
                yield FileStatus(full, st.st_size, int(st.st_mtime * 1000))

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def delete(self, path: str) -> None:
        os.unlink(path)

    def mkdirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def file_status(self, path: str) -> FileStatus:
        st = os.stat(path)
        return FileStatus(path, st.st_size, int(st.st_mtime * 1000))


class FileAlreadyExistsError(FileExistsError):
    error_class = "DELTA_FILE_ALREADY_EXISTS"


class InMemoryLogStore(LogStore):
    """Deterministic in-memory store for unit tests and race simulation."""

    def __init__(self):
        self._lock = threading.Lock()
        self._files: Dict[str, tuple[bytes, int]] = {}
        self._clock = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def read(self, path: str) -> bytes:
        with self._lock:
            if path not in self._files:
                raise FileNotFoundError(path)
            return self._files[path][0]

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        with self._lock:
            if not overwrite and path in self._files:
                raise FileAlreadyExistsError(path)
            self._files[path] = (data, self._tick())

    def list_from(self, path: str) -> Iterator[FileStatus]:
        parent, _, name = path.rpartition("/")
        with self._lock:
            found_parent = False
            matches = []
            for p, (data, mtime) in self._files.items():
                pp, _, pn = p.rpartition("/")
                if pp == parent:
                    found_parent = True
                    if pn >= name:
                        matches.append(FileStatus(p, len(data), mtime))
            if not found_parent:
                raise FileNotFoundError(parent)
        return iter(sorted(matches, key=lambda fs: fs.path))

    def list_dir(self, path: str) -> List[FileStatus]:
        path = path.rstrip("/")
        with self._lock:
            out = [
                FileStatus(p, len(d), m)
                for p, (d, m) in self._files.items()
                if p.rpartition("/")[0] == path
            ]
        return sorted(out, key=lambda fs: fs.path)

    def walk(self, path: str) -> Iterator[FileStatus]:
        prefix = path.rstrip("/") + "/"
        with self._lock:
            out = [
                FileStatus(p, len(d), m)
                for p, (d, m) in self._files.items()
                if p.startswith(prefix)
            ]
        return iter(sorted(out, key=lambda fs: fs.path))

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._files

    def delete(self, path: str) -> None:
        with self._lock:
            if path not in self._files:
                raise FileNotFoundError(path)
            del self._files[path]

    def mkdirs(self, path: str) -> None:
        pass

    def file_status(self, path: str) -> FileStatus:
        with self._lock:
            if path not in self._files:
                raise FileNotFoundError(path)
            data, mtime = self._files[path]
            return FileStatus(path, len(data), mtime)


class DelegatingLogStore(LogStore):
    """Explicit method-by-method delegation base for wrapper stores.
    (A `__getattr__` fallback alone is NOT enough: `LogStore` defines
    every method as raising NotImplementedError, so normal attribute
    lookup finds those and never falls through to the wrapped store.)"""

    def __init__(self, inner: LogStore):
        self.inner = inner

    def read(self, path: str) -> bytes:
        return self.inner.read(path)

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        self.inner.write(path, data, overwrite)

    def list_from(self, path: str) -> Iterator[FileStatus]:
        return self.inner.list_from(path)

    def list_from_fast(self, path: str, skip_stat) -> Iterator[FileStatus]:
        # NOT inner.list_from_fast: wrapper subclasses override list_from
        # with extra semantics (e.g. the external arbiter's half-commit
        # recovery) that a stat-skipping bypass must never skip
        return self.list_from(path)

    def list_dir(self, path: str) -> List[FileStatus]:
        return self.inner.list_dir(path)

    def walk(self, path: str) -> Iterator[FileStatus]:
        return self.inner.walk(path)

    def exists(self, path: str) -> bool:
        return self.inner.exists(path)

    def delete(self, path: str) -> None:
        self.inner.delete(path)

    def mkdirs(self, path: str) -> None:
        self.inner.mkdirs(path)

    def file_status(self, path: str) -> FileStatus:
        return self.inner.file_status(path)

    def is_partial_write_visible(self, path: str) -> bool:
        return self.inner.is_partial_write_visible(path)


class FaultInjectingLogStore(DelegatingLogStore):
    """Wraps a store; `fail_on(path_predicate)` arms one-shot or persistent
    failures, `block_on` installs a barrier the test releases. Used by
    concurrency tests to force specific interleavings."""

    def __init__(self, inner: LogStore):
        super().__init__(inner)
        self._write_faults: List[tuple[Callable[[str], bool], Exception, bool]] = []
        self._write_barriers: List[tuple[Callable[[str], bool], threading.Event]] = []
        self.write_log: List[str] = []

    def fail_writes(self, pred: Callable[[str], bool], exc: Optional[Exception] = None,
                    once: bool = True) -> None:
        self._write_faults.append((pred, exc or IOError("injected fault"), once))

    def block_writes(self, pred: Callable[[str], bool]) -> threading.Event:
        ev = threading.Event()
        self._write_barriers.append((pred, ev))
        return ev

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        self.write_log.append(path)
        for pred, ev in list(self._write_barriers):
            if pred(path):
                ev.wait()
        for i, (pred, exc, once) in enumerate(list(self._write_faults)):
            if pred(path):
                if once:
                    self._write_faults.pop(i)
                raise exc
        self.inner.write(path, data, overwrite)


_SCHEME_REGISTRY: Dict[str, Callable[[], LogStore]] = {}


def register_logstore_scheme(scheme: str, factory: Callable[[], LogStore]) -> None:
    """Register a LogStore factory for a URI scheme (e.g. 'gs', 's3a') —
    the rebuild's `DelegatingLogStore` extension point."""
    _SCHEME_REGISTRY[scheme] = factory


_local = LocalLogStore()
_memory_stores: Dict[str, InMemoryLogStore] = {}


def logstore_for_path(path: str) -> LogStore:
    """Resolve the store owning `path` by scheme; plain paths and file://
    map to the local POSIX store, memory:// to a process-wide namespace."""
    if "://" not in path:
        return _local
    scheme = path.split("://", 1)[0]
    if scheme == "file":
        return _local
    if scheme == "memory":
        ns = path.split("://", 1)[1].split("/", 1)[0]
        if ns not in _memory_stores:
            _memory_stores[ns] = InMemoryLogStore()
        return _memory_stores[ns]
    if scheme in _SCHEME_REGISTRY:
        return _SCHEME_REGISTRY[scheme]()
    raise ValueError(f"no LogStore registered for scheme {scheme!r}")

from delta_tpu.storage.logstore import (
    FileStatus,
    LogStore,
    LocalLogStore,
    InMemoryLogStore,
    FaultInjectingLogStore,
    logstore_for_path,
    register_logstore_scheme,
)

__all__ = [
    "FileStatus",
    "LogStore",
    "LocalLogStore",
    "InMemoryLogStore",
    "FaultInjectingLogStore",
    "logstore_for_path",
    "register_logstore_scheme",
]

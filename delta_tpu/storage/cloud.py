"""Cloud object-store LogStores: conditional-put (GCS), single-driver
(S3), and external-arbiter (S3+DynamoDB pattern) commit semantics.

The local O_EXCL trick (`logstore.py`) doesn't exist on object stores;
each cloud needs its own mutual-exclusion story (reference
`storage/src/main/java/io/delta/storage/`):

- **GCS** (`GCSLogStore.java:100-106`): generation preconditions — a PUT
  with `ifGenerationMatch=0` succeeds only if the object does not exist;
  HTTP 412 maps to FileAlreadyExistsError. Atomic put-if-absent comes
  from the server, so no temp+rename dance is needed.
- **S3 single-driver** (`S3SingleDriverLogStore.java`): plain S3 PUT
  cannot be conditional (pre-2024 semantics the reference targets), so
  mutual exclusion holds only WITHIN one process: a per-path lock plus
  an existence check. Multi-writer safety requires the arbiter below.
- **S3 + external arbiter** (`BaseExternalLogStore.java:154-270`): a
  strongly-consistent side store (DynamoDB) arbitrates commits via
  conditional put. Write N.json = prepare (temp file T(N) + entry
  E(N, T(N), complete=false)) -> copy T(N) to N.json -> acknowledge
  (E.complete=true). A crash between prepare and acknowledge leaves a
  half commit that ANY subsequent reader or writer repairs
  (`fixDeltaLog`, `BaseExternalLogStore.java:369-373`): copy T(N) into
  place if missing, then mark complete. The arbiter entry, not the
  object store, is the source of truth for who won version N.

Transports are injectable: `GCSObjectClient` takes any callable with
the (method, url, headers, body) -> (status, headers, body) shape.
`HttpTransport` is the real urllib implementation — tests exercise it
against a local in-process HTTP server that faithfully implements the
generation-precondition subset of the GCS JSON API.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse
import urllib.request
import uuid
from dataclasses import dataclass, replace
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from delta_tpu import obs
from delta_tpu.resilience import default_policy
from delta_tpu.resilience.classify import StorageRequestError
from delta_tpu.storage.logstore import (
    DelegatingLogStore,
    FileAlreadyExistsError,
    FileStatus,
    LogStore,
)

_log = logging.getLogger(__name__)

# cloud I/O counters: request counts and byte volumes per direction, plus
# how often arbiter recovery had to retry — the signals a flaky-network
# incident shows up in first
_GCS_REQUESTS = obs.counter("storage.gcs.requests")
_GCS_GET_BYTES = obs.counter("storage.gcs.get_bytes")
_GCS_PUT_BYTES = obs.counter("storage.gcs.put_bytes")
_ARBITER_FIXES = obs.counter("storage.arbiter.fixes")
_ARBITER_FIX_RETRIES = obs.counter("storage.arbiter.fix_retries")

Transport = Callable[[str, str, Dict[str, str], Optional[bytes]],
                     Tuple[int, Dict[str, str], bytes]]


class PreconditionFailedError(Exception):
    """HTTP 412: the generation precondition did not hold."""


class HttpTransport:
    """urllib-backed transport. `base_url` lets tests point the real
    HTTP code path at a local mock server."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def __call__(self, method: str, url: str, headers: Dict[str, str],
                 body: Optional[bytes]):
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, dict(resp.headers), resp.read()
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers or {}), e.read()


class GCSObjectClient:
    """Minimal GCS JSON-API client: conditional upload, media download,
    prefix listing, delete. Only what a LogStore needs."""

    def __init__(self, bucket: str, transport: Optional[Transport] = None,
                 base_url: str = "https://storage.googleapis.com",
                 token_provider: Optional[Callable[[], str]] = None):
        self.bucket = bucket
        self.transport = transport or HttpTransport()
        self.base = base_url.rstrip("/")
        self.token_provider = token_provider

    def _headers(self) -> Dict[str, str]:
        h = {}
        if self.token_provider is not None:
            h["Authorization"] = f"Bearer {self.token_provider()}"
        return h

    def put(self, name: str, data: bytes,
            if_generation_match: Optional[int] = None) -> None:
        q = {"uploadType": "media", "name": name}
        if if_generation_match is not None:
            q["ifGenerationMatch"] = str(if_generation_match)
        url = (f"{self.base}/upload/storage/v1/b/{self.bucket}/o?"
               + urllib.parse.urlencode(q))
        headers = self._headers()
        headers["Content-Type"] = "application/octet-stream"
        _GCS_REQUESTS.inc()
        _GCS_PUT_BYTES.inc(len(data))
        with obs.span("storage.gcs.put", object=name, bytes=len(data),
                      conditional=if_generation_match is not None) as sp:
            status, _, body = self.transport("POST", url, headers, data)
            sp.set_attr("http_status", status)
            if status == 412:
                raise PreconditionFailedError(name)
            if status >= 300:
                raise StorageRequestError(
                    f"GCS put {name}: HTTP {status} {body[:200]!r}", status)

    def get(self, name: str) -> bytes:
        url = (f"{self.base}/storage/v1/b/{self.bucket}/o/"
               f"{urllib.parse.quote(name, safe='')}?alt=media")
        _GCS_REQUESTS.inc()
        with obs.span("storage.gcs.get", _verbose=True, object=name) as sp:
            status, _, body = self.transport("GET", url, self._headers(),
                                             None)
            sp.set_attr("http_status", status)
            if status == 404:
                raise FileNotFoundError(name)
            if status >= 300:
                raise StorageRequestError(
                    f"GCS get {name}: HTTP {status}", status)
            sp.set_attr("bytes", len(body))
        _GCS_GET_BYTES.inc(len(body))
        return body

    def list_prefix(self, prefix: str) -> List[dict]:
        with obs.span("storage.gcs.list", prefix=prefix) as sp:
            items: List[dict] = []
            page: Optional[str] = None
            pages = 0
            while True:
                q = {"prefix": prefix}
                if page:
                    q["pageToken"] = page
                url = (f"{self.base}/storage/v1/b/{self.bucket}/o?"
                       + urllib.parse.urlencode(q))
                _GCS_REQUESTS.inc()
                status, _, body = self.transport("GET", url, self._headers(),
                                                 None)
                pages += 1
                if status >= 300:
                    raise StorageRequestError(
                        f"GCS list {prefix}: HTTP {status}", status)
                doc = json.loads(body)
                items.extend(doc.get("items", []))
                page = doc.get("nextPageToken")
                if not page:
                    sp.set_attrs(pages=pages, objects=len(items))
                    return items

    def stat(self, name: str) -> dict:
        """Object metadata (size/updated/generation) without the body —
        one tiny response instead of a full media download."""
        url = (f"{self.base}/storage/v1/b/{self.bucket}/o/"
               f"{urllib.parse.quote(name, safe='')}")
        status, _, body = self.transport("GET", url, self._headers(), None)
        if status == 404:
            raise FileNotFoundError(name)
        if status >= 300:
            raise StorageRequestError(f"GCS stat {name}: HTTP {status}",
                                      status)
        return json.loads(body)

    def delete(self, name: str) -> None:
        url = (f"{self.base}/storage/v1/b/{self.bucket}/o/"
               f"{urllib.parse.quote(name, safe='')}")
        status, _, _ = self.transport("DELETE", url, self._headers(), None)
        if status == 404:
            raise FileNotFoundError(name)
        if status >= 300:
            raise StorageRequestError(f"GCS delete {name}: HTTP {status}",
                                      status)


def _split_object_path(path: str) -> str:
    """'gs://bucket/a/b' or 'a/b' -> object name 'a/b'."""
    if "://" in path:
        return path.split("://", 1)[1].split("/", 1)[1]
    return path.lstrip("/")


def _mtime_ms(item: dict) -> int:
    upd = item.get("updated")
    if not upd:
        return 0
    # RFC3339 'YYYY-MM-DDTHH:MM:SS(.fff)Z'
    from datetime import datetime, timezone

    try:
        dt = datetime.fromisoformat(upd.replace("Z", "+00:00"))
        return int(dt.astimezone(timezone.utc).timestamp() * 1000)
    except ValueError:
        return 0


class GCSLogStore(LogStore):
    """Put-if-absent via GCS generation preconditions — the server is
    the arbiter, so this is multi-writer safe with zero extra
    infrastructure (reference `GCSLogStore.java`)."""

    def __init__(self, client: GCSObjectClient, scheme_prefix: str = "gs"):
        self.client = client
        self._prefix = f"{scheme_prefix}://{client.bucket}/"

    def _name(self, path: str) -> str:
        return _split_object_path(path)

    def read(self, path: str) -> bytes:
        return self.client.get(self._name(path))

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        name = self._name(path)
        if overwrite:
            self.client.put(name, data)
            return
        try:
            self.client.put(name, data, if_generation_match=0)
        except PreconditionFailedError:
            raise FileAlreadyExistsError(path)

    def list_from(self, path: str) -> Iterator[FileStatus]:
        name = self._name(path)
        parent, _, base = name.rpartition("/")
        prefix = parent + "/" if parent else ""
        out = []
        for item in self.client.list_prefix(prefix):
            obj = item["name"]
            rest = obj[len(prefix):]
            if "/" in rest:  # only direct children
                continue
            if rest >= base:
                out.append(FileStatus(self._prefix + obj,
                                      int(item.get("size", 0)),
                                      _mtime_ms(item)))
        return iter(sorted(out, key=lambda fs: fs.path))

    def list_dir(self, path: str) -> List[FileStatus]:
        name = self._name(path).rstrip("/")
        prefix = name + "/" if name else ""
        out = []
        for item in self.client.list_prefix(prefix):
            rest = item["name"][len(prefix):]
            if "/" in rest:
                continue
            out.append(FileStatus(self._prefix + item["name"],
                                  int(item.get("size", 0)), _mtime_ms(item)))
        return sorted(out, key=lambda fs: fs.path)

    def walk(self, path: str) -> Iterator[FileStatus]:
        name = self._name(path).rstrip("/")
        prefix = name + "/" if name else ""
        out = [FileStatus(self._prefix + item["name"],
                          int(item.get("size", 0)), _mtime_ms(item))
               for item in self.client.list_prefix(prefix)]
        return iter(sorted(out, key=lambda fs: fs.path))

    def exists(self, path: str) -> bool:
        try:
            self.client.stat(self._name(path))
            return True
        except FileNotFoundError:
            return False

    def delete(self, path: str) -> None:
        self.client.delete(self._name(path))

    def mkdirs(self, path: str) -> None:
        pass  # object stores have no directories

    def file_status(self, path: str) -> FileStatus:
        meta = self.client.stat(self._name(path))
        return FileStatus(path, int(meta.get("size", 0)), _mtime_ms(meta))

    def is_partial_write_visible(self, path: str) -> bool:
        return False  # uploads are atomic per object


class _HeldPathLock:
    __slots__ = ("_locks", "_path")

    def __init__(self, locks: "_PathLocks", path: str):
        self._locks = locks
        self._path = path

    def release(self) -> None:
        self._locks._release(self._path)


class _PathLocks:
    """Per-path in-process locks (reference `PathLock.java` role).
    Entries are refcounted and dropped when the last holder/waiter
    releases — commit paths are unique per version, so an unbounded map
    would leak one Lock per commit for the life of the process."""

    def __init__(self):
        self._guard = threading.Lock()
        self._locks: Dict[str, list] = {}  # path -> [Lock, refcount]

    def acquire(self, path: str) -> _HeldPathLock:
        with self._guard:
            entry = self._locks.setdefault(path, [threading.Lock(), 0])
            entry[1] += 1
        entry[0].acquire()
        return _HeldPathLock(self, path)

    def _release(self, path: str) -> None:
        with self._guard:
            entry = self._locks[path]
            entry[0].release()
            entry[1] -= 1
            if entry[1] == 0:
                del self._locks[path]


class S3SingleDriverLogStore(DelegatingLogStore):
    """Single-process mutual exclusion over a store whose put is NOT
    conditional: per-path lock + existence check. Faithful to the
    reference's caveat (`S3SingleDriverLogStore.java`): concurrent
    writers from DIFFERENT processes are unsafe — use the external
    arbiter for that."""

    _locks = _PathLocks()

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        if overwrite:
            self.inner.write(path, data, overwrite=True)
            return
        lk = self._locks.acquire(path)
        try:
            if self.inner.exists(path):
                raise FileAlreadyExistsError(path)
            self.inner.write(path, data, overwrite=True)
        finally:
            lk.release()

    def is_partial_write_visible(self, path: str) -> bool:
        return False


# ------------------------------------------------------ external arbiter


@dataclass(frozen=True)
class ExternalCommitEntry:
    """One row of the arbiter table (reference
    `ExternalCommitEntry.java`)."""

    table_path: str
    file_name: str       # e.g. 00000000000000000010.json
    temp_path: str       # relative: _delta_log/.tmp/<file>.<uuid>
    complete: bool
    expire_time: Optional[int] = None  # epoch seconds, set when complete

    def absolute_file_path(self) -> str:
        return f"{self.table_path}/_delta_log/{self.file_name}"

    def absolute_temp_path(self) -> str:
        return f"{self.table_path}/{self.temp_path}"

    def as_complete(self, expiration_delay_s: int) -> "ExternalCommitEntry":
        return replace(self, complete=True,
                       expire_time=int(time.time()) + expiration_delay_s)


class CommitArbiter:
    """Strongly-consistent conditional-put table (the DynamoDB role).
    Keys are (table_path, file_name)."""

    def put_entry(self, entry: ExternalCommitEntry,
                  overwrite: bool) -> None:
        """Conditional put: raise FileAlreadyExistsError when an entry
        for (table_path, file_name) exists and overwrite is False."""
        raise NotImplementedError

    def put_entries(self, entries: List[ExternalCommitEntry],
                    overwrite: bool = False) -> int:
        """Conditional put of several version-consecutive entries, in
        order; returns how many were claimed. Two legal shapes, both
        satisfying the batched-write recovery contract
        (`ExternalArbiterLogStore.write_batch`):

        - **ordered prefix** (this default): claims stop at the first
          existing entry, so a partial claim is always a version-
          consecutive prefix — every claimed member's base versions are
          claimed too, and recovery can complete exactly the prefix.
        - **all-or-nothing** (sqlite transaction, DynamoDB
          TransactWriteItems): returns 0 or len(entries) — one
          conditional round trip, never a partial claim.
        """
        claimed = 0
        for e in entries:
            try:
                self.put_entry(e, overwrite)
            except FileAlreadyExistsError:
                return claimed
            claimed += 1
        return claimed

    def get_entry(self, table_path: str,
                  file_name: str) -> Optional[ExternalCommitEntry]:
        raise NotImplementedError

    def get_latest_entry(self,
                         table_path: str) -> Optional[ExternalCommitEntry]:
        raise NotImplementedError

    def get_incomplete_entries(
            self, table_path: str) -> List[ExternalCommitEntry]:
        """Every incomplete entry for the table, ascending by file
        name. The solo protocol leaves at most ONE (the latest); a
        batched writer SIGKILLed mid-batch leaves several consecutive
        ones, and recovery must fix them all — completing only the
        latest would surface version N+k while N..N+k-1 stay missing.
        The default derives from `get_latest_entry` (correct for
        arbiters that only ever see solo writes); batch-capable
        arbiters override with a real scan."""
        e = self.get_latest_entry(table_path)
        return [e] if e is not None and not e.complete else []


class InMemoryCommitArbiter(CommitArbiter):
    """Process-wide arbiter with DynamoDB conditional-put semantics —
    deterministic stand-in for tests and single-host deployments."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[Tuple[str, str], ExternalCommitEntry] = {}

    def put_entry(self, entry: ExternalCommitEntry,
                  overwrite: bool) -> None:
        key = (entry.table_path, entry.file_name)
        with self._lock:
            if not overwrite and key in self._rows:
                raise FileAlreadyExistsError(entry.file_name)
            self._rows[key] = entry

    def get_entry(self, table_path, file_name):
        with self._lock:
            return self._rows.get((table_path, file_name))

    def get_latest_entry(self, table_path):
        with self._lock:
            rows = [e for (tp, _), e in self._rows.items()
                    if tp == table_path]
        if not rows:
            return None
        return max(rows, key=lambda e: e.file_name)

    def put_entries(self, entries, overwrite=False) -> int:
        # all-or-nothing under one lock hold (the TransactWriteItems
        # shape): either every version is claimed or none is
        entries = list(entries)
        with self._lock:
            if not overwrite:
                for e in entries:
                    if (e.table_path, e.file_name) in self._rows:
                        return 0
            for e in entries:
                self._rows[(e.table_path, e.file_name)] = e
        return len(entries)

    def get_incomplete_entries(self, table_path):
        with self._lock:
            rows = [e for (tp, _), e in self._rows.items()
                    if tp == table_path and not e.complete]
        return sorted(rows, key=lambda e: e.file_name)


def _is_delta_file(name: str) -> bool:
    return name.endswith(".json") and name.split(".")[0].isdigit()


class ExternalArbiterLogStore(DelegatingLogStore):
    """The S3+DynamoDB commit protocol over any (non-mutually-exclusive)
    inner store. See the module docstring and
    `BaseExternalLogStore.java:154-270` for the algorithm.

    The `_write_copy_temp_file` / `_write_put_complete_entry` /
    `_fix_copy_temp_file` / `_fix_put_complete_entry` seams mirror the
    reference's @VisibleForTesting wrappers: fault-injection tests
    override them to crash a writer at each phase boundary and assert
    recovery."""

    EXPIRATION_DELAY_S = 24 * 3600  # BaseExternalLogStore.java:105

    _path_locks = _PathLocks()

    def __init__(self, inner: LogStore, arbiter: CommitArbiter):
        super().__init__(inner)
        self.arbiter = arbiter

    # -- helpers ---------------------------------------------------------

    @staticmethod
    def _table_path(path: str) -> str:
        # <table>/_delta_log/<name> -> <table>
        parent = path.rpartition("/")[0]
        return parent.rpartition("/")[0]

    @staticmethod
    def _is_delta_log_path(path: str) -> bool:
        return path.rpartition("/")[0].endswith("_delta_log")

    def _copy(self, src: str, dst: str) -> None:
        """Copy with best-effort no-overwrite (the inner store cannot do
        better — that is the entire reason the arbiter exists)."""
        data = self.inner.read(src)
        try:
            self.inner.write(dst, data, overwrite=False)
        except FileAlreadyExistsError:
            raise
        except NotImplementedError:
            self.inner.write(dst, data, overwrite=True)

    # test seams (reference @VisibleForTesting wrappers)
    def _write_copy_temp_file(self, src: str, dst: str) -> None:
        self._copy(src, dst)

    def _write_put_complete_entry(self, entry: ExternalCommitEntry) -> None:
        self.arbiter.put_entry(entry.as_complete(self.EXPIRATION_DELAY_S),
                               overwrite=True)

    def _fix_copy_temp_file(self, src: str, dst: str) -> None:
        self._copy(src, dst)

    def _fix_put_complete_entry(self, entry: ExternalCommitEntry) -> None:
        self.arbiter.put_entry(entry.as_complete(self.EXPIRATION_DELAY_S),
                               overwrite=True)

    def fix_delta_log(self, entry: ExternalCommitEntry) -> None:
        """Complete a half commit: copy T(N) into N.json if missing,
        then mark the entry complete (`BaseExternalLogStore.java:369`).
        Never raises FileAlreadyExists — that just means another
        writer/reader already did the copy."""
        if entry.complete:
            return
        _ARBITER_FIXES.inc()
        target = entry.absolute_file_path()
        lk = self._path_locks.acquire(target)
        try:
            with obs.span("storage.arbiter.fix", path=target) as sp:
                state = {"copied": False, "retries": 0}

                def attempt() -> None:
                    if not state["copied"] and not self.inner.exists(target):
                        try:
                            self._fix_copy_temp_file(
                                entry.absolute_temp_path(), target)
                        except FileAlreadyExistsError:
                            pass  # another fixer copied; still ack
                        state["copied"] = True
                    self._fix_put_complete_entry(entry)

                def on_retry(_attempt: int, _exc: BaseException) -> None:
                    _ARBITER_FIX_RETRIES.inc()
                    state["retries"] += 1

                default_policy().call(attempt, on_retry=on_retry)
                sp.set_attr("retries", state["retries"])
        finally:
            lk.release()

    def recover_all_incomplete(self, table_path: str,
                               below: Optional[str] = None) -> int:
        """Complete EVERY incomplete arbiter entry, ascending. The solo
        protocol leaves at most one half commit; a batched writer
        SIGKILLed mid-copy leaves a consecutive run of them, and they
        must be fixed lowest-first or readers would see a gapped log.
        Returns the number of entries fixed.

        ``below`` (a file name) restricts recovery to entries strictly
        below it. Writers pass their own attempt version: they already
        hold that version's path lock, so fixing a foreign claim AT the
        attempt version would self-deadlock — and it is pointless, the
        foreign claim defeats the attempt at the conditional put anyway
        and the post-failure refresh (``list_from``, which holds no
        path lock) recovers it."""
        fixed = 0
        for entry in self.arbiter.get_incomplete_entries(table_path):
            if below is not None and entry.file_name >= below:
                continue
            self.fix_delta_log(entry)
            fixed += 1
        return fixed

    # -- LogStore surface ------------------------------------------------

    def list_from(self, path: str) -> Iterator[FileStatus]:
        if self._is_delta_log_path(path):
            self.recover_all_incomplete(self._table_path(path))
        return self.inner.list_from(path)

    def write(self, path: str, data: bytes, overwrite: bool = False) -> None:
        if overwrite:
            self.inner.write(path, data, overwrite=True)
            return
        name = path.rpartition("/")[2]
        if not self._is_delta_log_path(path) or not _is_delta_file(name):
            # non-commit files keep best-effort semantics
            self.inner.write(path, data, overwrite=False)
            return
        lk = self._path_locks.acquire(path)
        try:
            with obs.span("storage.arbiter.write", path=path,
                          bytes=len(data)) as sp:
                # Step 0: fail fast if N.json is already visible
                if self.inner.exists(path):
                    raise FileAlreadyExistsError(path)
                table_path = self._table_path(path)
                version = int(name.split(".")[0])
                # Step 1: ensure N-1.json exists (recover if half-committed)
                if version > 0:
                    prev_name = f"{version - 1:020d}.json"
                    prev_entry = self.arbiter.get_entry(table_path, prev_name)
                    prev_path = f"{table_path}/_delta_log/{prev_name}"
                    if prev_entry is not None and not prev_entry.complete:
                        # a crashed BATCH may have left earlier half
                        # commits too; fix the whole run, not just N-1
                        sp.add_event("recover_previous", path=prev_path)
                        self.recover_all_incomplete(table_path, below=name)
                    elif not self.inner.exists(prev_path):
                        raise FileNotFoundError(
                            f"previous commit {prev_path} does not exist")
                # Step 2: PREPARE — write T(N), then claim the version with
                # a conditional put of E(N, T(N), complete=false)
                temp_rel = f"_delta_log/.tmp/{name}.{uuid.uuid4().hex}"
                entry = ExternalCommitEntry(table_path, name, temp_rel,
                                            complete=False)
                self.inner.write(entry.absolute_temp_path(), data,
                                 overwrite=True)
                sp.add_event("prepare")
                self.arbiter.put_entry(entry, overwrite=False)  # the race
                try:
                    # Step 3: COMMIT — copy T(N) into N.json
                    self._write_copy_temp_file(entry.absolute_temp_path(),
                                               path)
                    sp.add_event("commit")
                    # Step 4: ACKNOWLEDGE
                    self._write_put_complete_entry(entry)
                    sp.add_event("acknowledge")
                except Exception as e:
                    # recoverable: we own E(N); any reader/writer will
                    # finish the copy+ack via fix_delta_log
                    sp.set_attr("deferred_recovery", True)
                    _log.warning("commit %s prepared but copy/ack failed "
                                 "(%s); recovery via fix_delta_log", path, e)
        finally:
            lk.release()

    def write_batch(self, items, overwrite: bool = False) -> None:
        """Commit several consecutive versions with ONE arbiter round
        trip (the group-commit emit path). The batched generalization
        of `write`:

        - Step 0: fail fast if the first target is already visible.
        - Step 1: recover/verify version N-1, fixing ALL incomplete
          entries (a previously crashed batch leaves a run of them).
        - Step 2: PREPARE — write every member's temp file (durable),
          then claim every version with one conditional multi-put
          (`CommitArbiter.put_entries`).
        - Step 3: COMMIT — copy temps into place, ascending.
        - Step 4: ACKNOWLEDGE each claimed entry.

        Crash semantics: before the claim lands nothing is visible and
        the batch is cleanly abandoned (garbage temps only). After the
        claim, every claimed member has a durable temp, so ANY later
        reader or writer completes the run via `fix_delta_log` —
        recovery either completes the claimed prefix or the batch never
        existed; a partially-durable batch is never stranded.

        Raises FileAlreadyExistsError naming the first unclaimed member
        when the claim lost a race. With an ordered-prefix arbiter the
        claimed prefix still lands (callers resolve member fates by
        read-back); with an all-or-nothing arbiter nothing landed.
        """
        items = list(items)
        if overwrite or len(items) <= 1:
            for path, data in items:
                self.write(path, data, overwrite=overwrite)
            return
        names = [p.rpartition("/")[2] for p, _ in items]
        if not all(self._is_delta_log_path(p) and _is_delta_file(n)
                   for (p, _), n in zip(items, names)):
            raise ValueError("write_batch requires _delta_log commit files")
        versions = [int(n.split(".")[0]) for n in names]
        if versions != list(range(versions[0], versions[0] + len(items))):
            raise ValueError(f"batch versions not consecutive: {versions}")
        table_path = self._table_path(items[0][0])
        if any(self._table_path(p) != table_path for p, _ in items):
            raise ValueError("batch spans multiple tables")
        first_path = items[0][0]
        lk = self._path_locks.acquire(first_path)
        try:
            with obs.span("storage.arbiter.write_batch", path=first_path,
                          members=len(items),
                          bytes=sum(len(d) for _, d in items)) as sp:
                # Step 0: fail fast if N.json is already visible
                if self.inner.exists(first_path):
                    raise FileAlreadyExistsError(first_path)
                version = versions[0]
                # Step 1: ensure N-1.json exists (recover half commits)
                if version > 0:
                    prev_name = f"{version - 1:020d}.json"
                    prev_entry = self.arbiter.get_entry(table_path,
                                                        prev_name)
                    prev_path = f"{table_path}/_delta_log/{prev_name}"
                    if prev_entry is not None and not prev_entry.complete:
                        sp.add_event("recover_previous", path=prev_path)
                        self.recover_all_incomplete(table_path,
                                                    below=names[0])
                    elif not self.inner.exists(prev_path):
                        raise FileNotFoundError(
                            f"previous commit {prev_path} does not exist")
                # Step 2: PREPARE — all temps first (durable before any
                # claim exists), then ONE conditional multi-put
                entries = []
                for (path, data), name in zip(items, names):
                    temp_rel = f"_delta_log/.tmp/{name}.{uuid.uuid4().hex}"
                    entry = ExternalCommitEntry(table_path, name, temp_rel,
                                                complete=False)
                    self.inner.write(entry.absolute_temp_path(), data,
                                     overwrite=True)
                    entries.append(entry)
                sp.add_event("prepare", members=len(entries))
                claimed = self.arbiter.put_entries(entries, overwrite=False)
                sp.set_attr("claimed", claimed)
                if claimed == 0:
                    # lost the race outright; nothing of ours landed
                    raise FileAlreadyExistsError(first_path)
                try:
                    # Steps 3+4: copy ascending, then acknowledge. A
                    # crash anywhere in here leaves claimed entries
                    # with durable temps — recoverable by anyone.
                    for entry, (path, _) in zip(entries[:claimed], items):
                        self._write_copy_temp_file(
                            entry.absolute_temp_path(), path)
                    sp.add_event("commit")
                    for entry in entries[:claimed]:
                        self._write_put_complete_entry(entry)
                    sp.add_event("acknowledge")
                except Exception as e:
                    sp.set_attr("deferred_recovery", True)
                    _log.warning(
                        "batch %s..%s claimed but copy/ack failed (%s); "
                        "recovery via fix_delta_log", names[0],
                        names[claimed - 1], e)
                if claimed < len(entries):
                    # ordered-prefix arbiter: the prefix is ours (and
                    # durable); the rest lost. Callers resolve member
                    # fates by read-back on this error.
                    raise FileAlreadyExistsError(items[claimed][0])
        finally:
            lk.release()

    def is_partial_write_visible(self, path: str) -> bool:
        return False

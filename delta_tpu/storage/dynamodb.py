"""DynamoDB-protocol commit arbiter — the `S3DynamoDBLogStore` role
without the vendor SDK.

The reference's multi-writer S3 story is an AWS SDK v1 client doing a
conditional PutItem against a DynamoDB table
(`storage-s3-dynamodb/src/main/java/io/delta/storage/S3DynamoDBLogStore.java:72`,
conditional put built at :234-260, arbitration protocol in
`BaseExternalLogStore.java:321`). This module implements the same
component at the wire level: AWS JSON 1.0 requests
(`X-Amz-Target: DynamoDB_20120810.*`) signed with a hand-rolled
Signature V4, over the same injectable `Transport` shape as the
GCS/Azure clients (`cloud.py`/`azure.py`) — tests run a live mock
endpoint that *recomputes and checks the signature*.

`DynamoDbCommitArbiter` maps `ExternalCommitEntry` to the reference's
exact item schema (`S3DynamoDBLogStore.java:95-101`): `tablePath`
(HASH, S), `fileName` (RANGE, S), `tempPath` (S), `complete`
(S "true"/"false"), `expireTime` (N, optional — the table's TTL
attribute). The conditional put uses
`attribute_not_exists(fileName)`, the modern spelling of the SDK's
`ExpectedAttributeValue(false)` (:255-257); exactly one of N racing
writers for a version wins, the rest get `FileAlreadyExistsError`,
and `ExternalArbiterLogStore.fix_delta_log` (cloud.py) recovers
half-commits — unchanged over this arbiter.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import urllib.parse
from typing import Dict, Optional

from delta_tpu.storage.cloud import (
    CommitArbiter,
    ExternalArbiterLogStore,
    ExternalCommitEntry,
    HttpTransport,
    Transport,
)
from delta_tpu.storage.logstore import FileAlreadyExistsError, LogStore

_ALGO = "AWS4-HMAC-SHA256"


def _hmac256(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def sign_v4(
    method: str,
    url: str,
    headers: Dict[str, str],
    body: bytes,
    *,
    access_key: str,
    secret_key: str,
    region: str,
    service: str = "dynamodb",
    session_token: Optional[str] = None,
    now: Optional[datetime.datetime] = None,
) -> Dict[str, str]:
    """AWS Signature Version 4 over the given request; returns the
    full header set (input headers + Host/X-Amz-Date/Authorization
    [+X-Amz-Security-Token]). Pure stdlib; deterministic given `now`
    (injectable so tests can pin the scope date)."""
    parsed = urllib.parse.urlsplit(url)
    if now is None:
        now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    scope_date = now.strftime("%Y%m%d")

    out = dict(headers)
    out["Host"] = parsed.netloc
    out["X-Amz-Date"] = amz_date
    if session_token:
        out["X-Amz-Security-Token"] = session_token

    # canonical request: sorted, lowercased headers; sorted query
    canon_headers = sorted((k.lower(), " ".join(v.split()))
                           for k, v in out.items())
    signed_names = ";".join(k for k, _ in canon_headers)
    canon_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(urllib.parse.parse_qsl(
            parsed.query, keep_blank_values=True)))
    canonical = "\n".join([
        method.upper(),
        urllib.parse.quote(parsed.path or "/", safe="/-_.~"),
        canon_query,
        "".join(f"{k}:{v}\n" for k, v in canon_headers),
        signed_names,
        _sha256_hex(body or b""),
    ])

    scope = f"{scope_date}/{region}/{service}/aws4_request"
    to_sign = "\n".join([
        _ALGO, amz_date, scope, _sha256_hex(canonical.encode())])
    key = _hmac256(_hmac256(_hmac256(_hmac256(
        ("AWS4" + secret_key).encode(), scope_date),
        region), service), "aws4_request")
    signature = hmac.new(key, to_sign.encode(), hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"{_ALGO} Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_names}, Signature={signature}")
    return out


class DynamoDbError(IOError):
    """A DynamoDB service error; `error_type` is the bare `__type`
    suffix (e.g. 'ConditionalCheckFailedException')."""

    def __init__(self, error_type: str, message: str, status: int):
        super().__init__(f"{error_type}: {message} (http {status})")
        self.error_type = error_type
        self.status = status


class DynamoDbClient:
    """Minimal AWS-JSON-1.0 DynamoDB client: exactly the five
    operations the log-store role needs (`S3DynamoDBLogStore.java`
    uses PutItem/GetItem/Query/DescribeTable/CreateTable)."""

    def __init__(
        self,
        endpoint: str,
        region: str = "us-east-1",
        access_key: str = "",
        secret_key: str = "",
        session_token: Optional[str] = None,
        transport: Optional[Transport] = None,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        self.session_token = session_token
        self.transport = transport or HttpTransport()

    def _call(self, target: str, payload: dict) -> dict:
        body = json.dumps(payload).encode()
        headers = {
            "Content-Type": "application/x-amz-json-1.0",
            "X-Amz-Target": f"DynamoDB_20120810.{target}",
        }
        headers = sign_v4(
            "POST", self.endpoint + "/", headers, body,
            access_key=self.access_key, secret_key=self.secret_key,
            region=self.region, session_token=self.session_token)
        status, _, resp = self.transport(
            "POST", self.endpoint + "/", headers, body)
        if status == 200:
            return json.loads(resp.decode() or "{}")
        try:
            err = json.loads(resp.decode())
            etype = (err.get("__type") or "UnknownError").split("#")[-1]
            msg = err.get("message") or err.get("Message") or ""
        except (ValueError, AttributeError):
            etype, msg = "UnknownError", resp.decode(errors="replace")
        raise DynamoDbError(etype, msg, status)

    # -- operations ----------------------------------------------------

    def put_item(self, table: str, item: Dict[str, dict],
                 condition_expression: Optional[str] = None) -> None:
        payload = {"TableName": table, "Item": item}
        if condition_expression:
            payload["ConditionExpression"] = condition_expression
        self._call("PutItem", payload)

    def get_item(self, table: str,
                 key: Dict[str, dict]) -> Optional[Dict[str, dict]]:
        out = self._call("GetItem", {
            "TableName": table, "Key": key, "ConsistentRead": True})
        return out.get("Item")

    def query_latest(self, table: str, hash_name: str,
                     hash_value: str) -> Optional[Dict[str, dict]]:
        """Newest item for a partition key: descending sort-key scan,
        limit 1, consistent (`S3DynamoDBLogStore.java:205-210`)."""
        out = self._call("Query", {
            "TableName": table,
            "KeyConditionExpression": f"{hash_name} = :tp",
            "ExpressionAttributeValues": {":tp": {"S": hash_value}},
            "ScanIndexForward": False,
            "Limit": 1,
            "ConsistentRead": True,
        })
        items = out.get("Items") or []
        return items[0] if items else None

    def transact_write_puts(self, table: str, items,
                            condition_expression: Optional[str] = None
                            ) -> None:
        """All-or-nothing multi-put via TransactWriteItems: every item
        lands or none does, with the same condition applied per item
        (the batched spelling of the conditional PutItem)."""
        puts = []
        for item in items:
            put: Dict[str, object] = {"TableName": table, "Item": item}
            if condition_expression:
                put["ConditionExpression"] = condition_expression
            puts.append({"Put": put})
        self._call("TransactWriteItems", {"TransactItems": puts})

    def query_partition(self, table: str, hash_name: str, hash_value: str,
                        filter_expression: Optional[str] = None,
                        expr_names: Optional[Dict[str, str]] = None,
                        expr_values: Optional[Dict[str, dict]] = None):
        """All items for a partition key, ascending by sort key,
        consistent, paginated through LastEvaluatedKey."""
        values = {":tp": {"S": hash_value}}
        if expr_values:
            values.update(expr_values)
        payload: Dict[str, object] = {
            "TableName": table,
            "KeyConditionExpression": f"{hash_name} = :tp",
            "ExpressionAttributeValues": values,
            "ScanIndexForward": True,
            "ConsistentRead": True,
        }
        if filter_expression:
            payload["FilterExpression"] = filter_expression
        if expr_names:
            payload["ExpressionAttributeNames"] = expr_names
        items = []
        while True:
            out = self._call("Query", payload)
            items.extend(out.get("Items") or [])
            last = out.get("LastEvaluatedKey")
            if not last:
                return items
            payload["ExclusiveStartKey"] = last

    def describe_table(self, table: str) -> dict:
        return self._call("DescribeTable", {"TableName": table})

    def create_table(self, table: str, hash_name: str, range_name: str,
                     rcu: int = 5, wcu: int = 5) -> dict:
        return self._call("CreateTable", {
            "TableName": table,
            "AttributeDefinitions": [
                {"AttributeName": hash_name, "AttributeType": "S"},
                {"AttributeName": range_name, "AttributeType": "S"},
            ],
            "KeySchema": [
                {"AttributeName": hash_name, "KeyType": "HASH"},
                {"AttributeName": range_name, "KeyType": "RANGE"},
            ],
            "ProvisionedThroughput": {
                "ReadCapacityUnits": rcu, "WriteCapacityUnits": wcu},
        })


# DynamoDB item attribute names (`S3DynamoDBLogStore.java:95-101`)
ATTR_TABLE_PATH = "tablePath"
ATTR_FILE_NAME = "fileName"
ATTR_TEMP_PATH = "tempPath"
ATTR_COMPLETE = "complete"
ATTR_EXPIRE_TIME = "expireTime"


class DynamoDbCommitArbiter(CommitArbiter):
    """`CommitArbiter` over a DynamoDB table, item-compatible with the
    reference's deployment (a table written by this arbiter is
    readable by the reference's `S3DynamoDBLogStore` and vice versa)."""

    def __init__(self, client: DynamoDbClient,
                 table_name: str = "delta_log",
                 ensure_table: bool = False,
                 create_timeout_s: float = 30.0):
        self.client = client
        self.table_name = table_name
        if ensure_table:
            self._ensure_table(create_timeout_s)

    def _ensure_table(self, timeout_s: float) -> None:
        """DescribeTable; CreateTable on ResourceNotFound; poll until
        ACTIVE (`S3DynamoDBLogStore.java:262` tryEnsureTableExists).

        The poll runs under the shared `RetryPolicy` (deadline =
        ``timeout_s``): each not-yet-ACTIVE probe raises a retryable
        marker so the policy owns the sleeping and the give-up."""
        from delta_tpu.resilience import default_policy

        def probe() -> None:
            try:
                desc = self.client.describe_table(self.table_name)
                status = desc.get("Table", {}).get("TableStatus",
                                                   "ACTIVE")
                if status == "ACTIVE":
                    return
            except DynamoDbError as e:
                if e.error_type != "ResourceNotFoundException":
                    raise
                try:
                    self.client.create_table(
                        self.table_name, ATTR_TABLE_PATH, ATTR_FILE_NAME)
                except DynamoDbError as ce:
                    # ResourceInUse = a concurrent creator won the
                    # race — fine, fall through to the status poll
                    if ce.error_type != "ResourceInUseException":
                        raise
            err = DynamoDbError(
                "TableNotActive",
                f"table {self.table_name} not ACTIVE after "
                f"{timeout_s}s", 0)
            err.retryable = True  # poll again until the deadline
            raise err

        policy = default_policy().with_overrides(
            max_attempts=10_000, base_s=0.2, cap_s=0.5,
            deadline_s=timeout_s)
        policy.call(probe)

    # -- entry mapping -------------------------------------------------

    @staticmethod
    def _to_item(entry: ExternalCommitEntry) -> Dict[str, dict]:
        item = {
            ATTR_TABLE_PATH: {"S": entry.table_path},
            ATTR_FILE_NAME: {"S": entry.file_name},
            ATTR_TEMP_PATH: {"S": entry.temp_path},
            # string, not BOOL: the reference SDK writes S "true"/"false"
            ATTR_COMPLETE: {"S": "true" if entry.complete else "false"},
        }
        if entry.expire_time is not None:
            item[ATTR_EXPIRE_TIME] = {"N": str(entry.expire_time)}
        return item

    @staticmethod
    def _from_item(item: Optional[Dict[str, dict]]
                   ) -> Optional[ExternalCommitEntry]:
        if item is None:
            return None
        expire = item.get(ATTR_EXPIRE_TIME)
        return ExternalCommitEntry(
            table_path=item[ATTR_TABLE_PATH]["S"],
            file_name=item[ATTR_FILE_NAME]["S"],
            temp_path=item[ATTR_TEMP_PATH]["S"],
            complete=item[ATTR_COMPLETE]["S"] == "true",
            expire_time=int(expire["N"]) if expire else None,
        )

    # -- CommitArbiter -------------------------------------------------

    def put_entry(self, entry: ExternalCommitEntry,
                  overwrite: bool) -> None:
        cond = None if overwrite else \
            f"attribute_not_exists({ATTR_FILE_NAME})"
        try:
            self.client.put_item(self.table_name, self._to_item(entry),
                                 condition_expression=cond)
        except DynamoDbError as e:
            if e.error_type == "ConditionalCheckFailedException":
                raise FileAlreadyExistsError(entry.file_name)
            raise

    def put_entries(self, entries, overwrite: bool = False) -> int:
        """All-or-nothing batch claim via TransactWriteItems. Returns
        len(entries) when every member's conditional put succeeded, 0
        when the transaction was cancelled by any condition failure —
        DynamoDB transactions never partially apply."""
        entries = list(entries)
        if not entries:
            return 0
        if len(entries) == 1:
            try:
                self.put_entry(entries[0], overwrite=overwrite)
            except FileAlreadyExistsError:
                return 0
            return 1
        cond = None if overwrite else \
            f"attribute_not_exists({ATTR_FILE_NAME})"
        try:
            self.client.transact_write_puts(
                self.table_name, [self._to_item(e) for e in entries],
                condition_expression=cond)
        except DynamoDbError as e:
            if e.error_type in ("TransactionCanceledException",
                                "ConditionalCheckFailedException"):
                return 0
            raise
        return len(entries)

    def get_entry(self, table_path: str,
                  file_name: str) -> Optional[ExternalCommitEntry]:
        return self._from_item(self.client.get_item(self.table_name, {
            ATTR_TABLE_PATH: {"S": table_path},
            ATTR_FILE_NAME: {"S": file_name},
        }))

    def get_latest_entry(
            self, table_path: str) -> Optional[ExternalCommitEntry]:
        return self._from_item(self.client.query_latest(
            self.table_name, ATTR_TABLE_PATH, table_path))

    def get_incomplete_entries(self, table_path: str):
        # `complete` is a reserved-ish attribute name; alias it to be
        # safe with the expression grammar.
        items = self.client.query_partition(
            self.table_name, ATTR_TABLE_PATH, table_path,
            filter_expression="#c = :f",
            expr_names={"#c": ATTR_COMPLETE},
            expr_values={":f": {"S": "false"}})
        return [self._from_item(i) for i in items]


def dynamodb_arbiter_store(
    client: DynamoDbClient,
    inner: LogStore,
    table_name: str = "delta_log",
    ensure_table: bool = False,
) -> ExternalArbiterLogStore:
    """The `S3DynamoDBLogStore` deployment shape: an S3-semantics
    inner store arbitrated by a DynamoDB table. Writers anywhere that
    reach the same endpoint+table get real commit arbitration."""
    return ExternalArbiterLogStore(
        inner,
        DynamoDbCommitArbiter(client, table_name,
                              ensure_table=ensure_table))

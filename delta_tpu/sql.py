"""Delta SQL statement surface.

The reference extends Spark SQL with delta-specific statements
(`DeltaSqlBase.g4:74-95`) and resolves table names through its catalog
(`catalog/DeltaCatalog.scala`). This module provides the same statement
set over table *paths*, or over *names* when a `Catalog` is passed:

    VACUUM <t> [RETAIN n HOURS] [LITE|FULL] [DRY RUN]
    OPTIMIZE <t> [WHERE <pred>] [ZORDER BY (c1, c2)]
    DESCRIBE HISTORY <t> [LIMIT n]
    DESCRIBE DETAIL <t>
    RESTORE TABLE <t> TO VERSION AS OF n
    RESTORE TABLE <t> TO TIMESTAMP AS OF <ms|'iso'>
    CONVERT TO DELTA parquet.'/path' [PARTITIONED BY (c type, ...)]
    ALTER TABLE <t> ADD CONSTRAINT name CHECK (<pred>)
    ALTER TABLE <t> DROP CONSTRAINT [IF EXISTS] name
    ALTER TABLE <t> CLUSTER BY (c1, c2) | CLUSTER BY NONE
    ALTER TABLE <t> SET TBLPROPERTIES (k = v, ...)

Catalog statements (require `catalog=`):
    CREATE TABLE [IF NOT EXISTS] name (col type, ...) USING DELTA
        [PARTITIONED BY (c1, ...)] [CLUSTER BY (c1, ...)]
        [LOCATION '/path'] [TBLPROPERTIES (k = v, ...)]
    DROP TABLE [IF EXISTS] name
    SHOW TABLES

Query/DML (paths or names):
    SELECT <cols|*> FROM <t> [VERSION AS OF n | TIMESTAMP AS OF <ms|'iso'>]
        [WHERE <pred>] [LIMIT n]
    INSERT INTO <t> [(cols)] VALUES (v1, v2, ...)[, (...)]
    INSERT OVERWRITE <t> [(cols)] [REPLACE WHERE <pred>] VALUES (...)
    DELETE FROM <t> [WHERE <pred>]
    UPDATE <t> SET col = <literal>[, ...] [WHERE <pred>]
    MERGE INTO <t> [AS a] USING <t2> [AS b] ON <cond>
        WHEN MATCHED [AND c] THEN UPDATE SET ... | UPDATE SET * | DELETE
        WHEN NOT MATCHED [AND c] THEN INSERT * | INSERT (cols) VALUES (...)
        WHEN NOT MATCHED BY SOURCE [AND c] THEN DELETE | UPDATE SET ...

`<t>` = '/path', delta.`/path`, "/path", or a bare identifier resolved
through the catalog. Returns command-specific results (VacuumResult,
OptimizeMetrics, history dicts, Arrow tables for SELECT...).
WHERE/CHECK predicates use the persisted-expression subset
(`expressions/parser.py`).
"""

from __future__ import annotations

import re
from typing import Optional

from delta_tpu.errors import CatalogTableError, DeltaError, DuplicateColumnError, SqlParseError, UnresolvedColumnError
from delta_tpu.expressions.parser import ParseError, parse_expression
from delta_tpu.table import Table

_PATH = (r"(?:'(?P<path>[^']+)'|delta\.`(?P<path2>[^`]+)`|\"(?P<path3>[^\"]+)\""
         r"|(?P<ident>[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)?))")
# quoted-path-only variant (no catalog ident) — e.g. CONVERT TO DELTA parquet.`/p`
_QUOTED_PATH = (r"(?:'(?P<path>[^']+)'|`(?P<path2>[^`]+)`"
                r"|\"(?P<path3>[^\"]+)\")")

_SQL_TYPES = {
    "int": "integer", "integer": "integer", "bigint": "long", "long": "long",
    "smallint": "short", "short": "short", "tinyint": "byte", "byte": "byte",
    "string": "string", "varchar": "string", "text": "string",
    "double": "double", "float": "float", "real": "float",
    "boolean": "boolean", "bool": "boolean", "date": "date",
    "timestamp": "timestamp", "binary": "binary",
}


def normalize_sql_type(t: str) -> str:
    """SQL type text → delta primitive name: BIGINT→long,
    VARCHAR(20)→string, DECIMAL(10,2) passes through intact."""
    type_text = re.sub(r"\s+", "", t.lower())
    base = type_text.split("(", 1)[0]
    if base in ("varchar", "char", "text"):
        return "string"  # length parameter is advisory
    return _SQL_TYPES.get(type_text, type_text)  # decimal(p,s) etc.


import contextvars

# optional callable(path) -> None that raises for disallowed paths; set
# by embedders (e.g. the connect server's allowed_root confinement) for
# the duration of a sql() call
_PATH_GUARD: contextvars.ContextVar = contextvars.ContextVar(
    "delta_sql_path_guard", default=None)


def _path_of(m) -> str:
    path = m.group("path") or m.group("path2") or m.group("path3")
    guard = _PATH_GUARD.get()
    if guard is not None:
        guard(path)
    return path


def _table(m, engine, catalog=None) -> Table:
    ident = m.groupdict().get("ident")
    if ident is not None:
        if catalog is None:
            raise CatalogTableError(
                f"table name {ident!r} requires a catalog (pass catalog=)",
                error_class="DELTA_MISSING_CATALOG",
            )
        return catalog.table(ident)
    return Table.for_path(_path_of(m), engine)


def sql(statement: str, engine=None, catalog=None, path_guard=None):
    """Execute one Delta SQL statement against a table path or (with a
    catalog) a table name. `path_guard(path)` — when given — is invoked
    for every table path the statement references and may raise to
    reject it."""
    if path_guard is not None:
        token = _PATH_GUARD.set(path_guard)
        try:
            return sql(statement, engine=engine, catalog=catalog)
        finally:
            _PATH_GUARD.reset(token)
    s = statement.strip().rstrip(";").strip()
    if catalog is not None and engine is None:
        engine = catalog.engine

    result = _catalog_statement(s, engine, catalog)
    if result is not NotImplemented:
        return result
    result = _query_statement(s, engine, catalog)
    if result is not NotImplemented:
        return result

    m = re.fullmatch(
        # modifiers compose in any order, like the reference grammar
        # (`DeltaSqlBase.g4:198` — `(vacuumType|retain|dryRun)*`)
        rf"VACUUM\s+{_PATH}"
        r"(?P<mods>(?:\s+(?:RETAIN\s+[\d.]+\s+HOURS|LITE|FULL"
        r"|DRY\s+RUN))*)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.vacuum import vacuum

        mods = m.group("mods") or ""
        hours = re.search(r"RETAIN\s+([\d.]+)\s+HOURS", mods,
                          re.IGNORECASE)
        vtype = re.search(r"\b(LITE|FULL)\b", mods, re.IGNORECASE)
        return vacuum(
            _table(m, engine, catalog),
            retention_hours=float(hours.group(1)) if hours else None,
            dry_run=re.search(r"DRY\s+RUN", mods, re.IGNORECASE)
            is not None,
            vacuum_type=vtype.group(1).upper() if vtype else "FULL",
        )

    m = re.fullmatch(
        rf"OPTIMIZE\s+{_PATH}(?P<full>\s+FULL)?"
        r"(?:\s+WHERE\s+(?P<where>.+?))?"
        r"(?:\s+ZORDER\s+BY\s+\((?P<zcols>[^)]+)\))?",
        s, re.IGNORECASE,
    )
    if m:
        builder = _table(m, engine, catalog).optimize()
        if m.group("where"):
            builder = builder.where(parse_expression(m.group("where")))
        if m.group("full"):
            if m.group("zcols"):
                from delta_tpu.errors import OptimizeArgumentError

                raise OptimizeArgumentError(
                    "OPTIMIZE FULL re-clusters by the table's "
                    "clustering columns; ZORDER BY cannot be combined "
                    "with it",
                    error_class="DELTA_CLUSTERING_WITH_ZORDER_BY")
            # OPTIMIZE ... FULL (clustered tables only)
            return builder.execute_full()
        if m.group("zcols"):
            cols = [c.strip().strip("`") for c in m.group("zcols").split(",")]
            return builder.execute_zorder_by(*cols)
        return builder.execute_compaction()

    m = re.fullmatch(
        rf"(?:DESC|DESCRIBE)\s+HISTORY\s+{_PATH}(?:\s+LIMIT\s+(?P<limit>\d+))?",
        s, re.IGNORECASE,
    )
    if m:
        limit = int(m.group("limit")) if m.group("limit") else None
        return [r.to_dict() for r in _table(m, engine, catalog).history(limit)]

    m = re.fullmatch(rf"(?:DESC|DESCRIBE)\s+DETAIL\s+{_PATH}", s, re.IGNORECASE)
    if m:
        return describe_detail(_table(m, engine, catalog))

    m = re.fullmatch(
        rf"RESTORE\s+(?:TABLE\s+)?{_PATH}\s+TO\s+VERSION\s+AS\s+OF\s+(?P<v>\d+)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.restore import restore

        return restore(_table(m, engine, catalog), version=int(m.group("v")))

    m = re.fullmatch(
        rf"RESTORE\s+(?:TABLE\s+)?{_PATH}\s+TO\s+TIMESTAMP\s+AS\s+OF\s+"
        r"(?:(?P<ms>\d+)|'(?P<iso>[^']+)')",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.restore import restore

        raw = m.group("ms") or f"'{m.group('iso')}'"
        return restore(_table(m, engine, catalog),
                       timestamp_ms=_timestamp_ms(raw))

    m = re.fullmatch(
        rf"CONVERT\s+TO\s+DELTA\s+(?:(?P<prov>\w+)\.)?{_QUOTED_PATH}"
        r"(?:\s+PARTITIONED\s+BY\s+\((?P<parts>[^)]+)\))?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.restore import convert_to_delta
        from delta_tpu.errors import ConvertTargetError

        prov = m.group("prov")
        if prov is None:
            # `DeltaErrors.missingProviderForConvertException`
            raise ConvertTargetError(
                "CONVERT TO DELTA requires a provider prefix, e.g. "
                "parquet.`/path`",
                error_class="DELTA_MISSING_PROVIDER_FOR_CONVERT")
        if prov.lower() != "parquet":
            # `DeltaErrors.convertNonParquetTablesException`
            raise ConvertTargetError(
                f"CONVERT TO DELTA only supports parquet tables, got "
                f"provider {prov!r}",
                error_class="DELTA_CONVERT_NON_PARQUET_TABLE")

        part_schema = None
        if m.group("parts"):
            part_schema = {}
            for item in m.group("parts").split(","):
                name, _, typ = item.strip().partition(" ")
                part_schema[name.strip("`")] = typ.strip() or "string"
        return convert_to_delta(_path_of(m), partition_schema=part_schema,
                                engine=engine)

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+ADD\s+CONSTRAINT\s+(?P<name>\w+)\s+"
        r"CHECK\s*\((?P<expr>.+)\)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.constraints import add_constraint

        return add_constraint(_table(m, engine, catalog), m.group("name"), m.group("expr"))

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+DROP\s+CONSTRAINT\s+"
        r"(?P<ife>IF\s+EXISTS\s+)?(?P<name>\w+)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.constraints import drop_constraint

        return drop_constraint(
            _table(m, engine, catalog), m.group("name"), if_exists=m.group("ife") is not None
        )

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+CLUSTER\s+BY\s+"
        r"(?:\((?P<cols>[^)]+)\)|(?P<none>NONE))",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.clustering import set_clustering_columns

        cols = ([] if m.group("none")
                else [c.strip().strip("`") for c in m.group("cols").split(",")])
        return set_clustering_columns(_table(m, engine, catalog), cols)

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+SET\s+TBLPROPERTIES\s*"
        r"\((?P<props>.+)\)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.alter import set_properties

        return set_properties(
            _table(m, engine, catalog), _parse_properties(m.group("props"))
        )

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+UNSET\s+TBLPROPERTIES\s*"
        r"(?P<ife>IF\s+EXISTS\s*)?"
        r"\((?P<props>.+)\)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.alter import unset_properties

        keys = [k.strip().strip("'\"`") for k in
                _split_top_level_commas(m.group("props"))]
        return unset_properties(_table(m, engine, catalog), keys,
                                if_exists=m.group("ife") is not None)

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+ADD\s+COLUMNS?\s*\((?P<cols>.+)\)",
        s, re.IGNORECASE | re.DOTALL,
    )
    if m:
        from delta_tpu.commands.alter import add_columns

        return add_columns(
            _table(m, engine, catalog), _parse_column_defs(m.group("cols")))

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+RENAME\s+COLUMN\s+"
        r"`?(?P<old>\w+)`?\s+TO\s+`?(?P<new>\w+)`?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.alter import rename_column

        return rename_column(
            _table(m, engine, catalog), m.group("old"), m.group("new"))

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+DROP\s+COLUMN\s+`?(?P<col>\w+)`?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.alter import drop_column

        return drop_column(_table(m, engine, catalog), m.group("col"))

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+(?:ALTER|CHANGE)\s+COLUMN\s+"
        r"`?(?P<col>\w+)`?\s+TYPE\s+(?P<typ>\w+)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.alter import change_column_type
        from delta_tpu.models.schema import PrimitiveType

        typ = m.group("typ").lower()
        try:
            new_type = PrimitiveType(_SQL_TYPES.get(typ, typ))
        except (ValueError, DeltaError) as e:
            raise SqlParseError(str(e)) from e
        return change_column_type(
            _table(m, engine, catalog), m.group("col"), new_type)

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+DROP\s+FEATURE\s+"
        r"`?(?P<feat>\w+)`?(?P<trunc>\s+TRUNCATE\s+HISTORY)?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.dropfeature import drop_feature

        return drop_feature(
            _table(m, engine, catalog), m.group("feat"),
            truncate_history=m.group("trunc") is not None)

    m = re.fullmatch(
        rf"REORG\s+TABLE\s+{_PATH}\s+APPLY\s*\(\s*PURGE\s*\)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.reorg import reorg_purge

        return reorg_purge(_table(m, engine, catalog))

    m = re.fullmatch(
        rf"REORG\s+TABLE\s+{_PATH}\s+APPLY\s*\(\s*UPGRADE\s+UNIFORM\s*"
        r"\(\s*ICEBERG_COMPAT_VERSION\s*=\s*(?P<v>\d+)\s*\)\s*\)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.reorg import reorg_upgrade_uniform

        return reorg_upgrade_uniform(_table(m, engine, catalog),
                                     iceberg_compat_version=int(m.group("v")))

    m = re.fullmatch(
        rf"GENERATE\s+symlink_format_manifest\s+FOR\s+TABLE\s+{_PATH}",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.generate import generate_symlink_manifest

        return generate_symlink_manifest(_table(m, engine, catalog))

    if re.match(r"MERGE\s+INTO\s+", s, re.IGNORECASE):
        return _handle_merge_into(s, engine, catalog)

    m = re.fullmatch(
        rf"DELETE\s+FROM\s+{_PATH}(?:\s+WHERE\s+(?P<where>.+))?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.dml import delete

        pred = parse_expression(m.group("where")) if m.group("where") else None
        return delete(_table(m, engine, catalog), pred)

    m = re.fullmatch(
        rf"UPDATE\s+{_PATH}\s+SET\s+(?P<sets>.+?)(?:\s+WHERE\s+(?P<where>.+))?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.dml import update

        assignments = {}
        for part in _split_top_level_commas(m.group("sets")):
            col_name, _, value = part.partition("=")
            assignments[col_name.strip().strip("`")] = parse_expression(value.strip())
        pred = parse_expression(m.group("where")) if m.group("where") else None
        return update(_table(m, engine, catalog), assignments, pred)

    raise SqlParseError(f"cannot parse Delta SQL statement: {statement!r}")


def _parse_properties(text: str) -> dict:
    """`'k' = 'v', k2 = v2` → dict (quotes optional)."""
    props = {}
    for part in _split_top_level_commas(text):
        k, _, v = part.partition("=")
        props[k.strip().strip("'\"` ")] = v.strip().strip("'\"` ")
    return props


def _parse_column_defs(text: str):
    from delta_tpu.colgen import CURRENT_DEFAULT_KEY
    from delta_tpu.models.schema import PrimitiveType, StructField

    fields = []
    for part in _split_top_level_commas(text):
        part = part.strip()
        m = re.match(
            r"(?:`(?P<q>[^`]+)`|(?P<name>\w+))\s+"
            r"(?P<type>\w+(?:\s*\([^)]*\))?)\s*(?P<rest>.*)",
            part, re.IGNORECASE | re.DOTALL,
        )
        if not m:
            raise SqlParseError(f"cannot parse column definition: {part!r}")
        name = m.group("q") or m.group("name")
        typ = normalize_sql_type(m.group("type"))
        nullable = True
        default = None
        rest = m.group("rest").strip()
        while rest:
            c = re.match(r"NOT\s+NULL\b\s*", rest, re.IGNORECASE)
            if c:
                nullable = False
                rest = rest[c.end():].strip()
                continue
            c = re.match(r"DEFAULT\s+(?P<d>'[^']*'|\S+)\s*", rest, re.IGNORECASE)
            if c:
                default = c.group("d")
                try:
                    d_expr = parse_expression(default)  # fail at CREATE, not on write
                except Exception as e:
                    raise SqlParseError(
                        f"cannot parse DEFAULT expression {default!r}: {e}"
                    ) from None
                if d_expr.references():
                    # protocol: column defaults must be constant expressions
                    raise SqlParseError(
                        f"DEFAULT must be a constant expression, got {default!r}"
                    )
                rest = rest[c.end():].strip()
                continue
            raise SqlParseError(
                f"cannot parse column constraint {rest!r} in {part!r}"
            )
        metadata = {CURRENT_DEFAULT_KEY: default} if default is not None else {}
        try:
            dtype = PrimitiveType(typ)
        except (ValueError, DeltaError) as e:
            raise SqlParseError(
                f"unsupported column type in {part!r}: {e}",
                error_class="DELTA_PARSING_UNSUPPORTED_DATA_TYPE") from None
        fields.append(
            StructField(name, dtype, nullable=nullable, metadata=metadata)
        )
    return fields


def _catalog_statement(s: str, engine, catalog):
    m = re.fullmatch(
        r"CREATE\s+TABLE\s+(?P<ine>IF\s+NOT\s+EXISTS\s+)?"
        r"(?P<name>[A-Za-z_][A-Za-z0-9_.]*)\s*"
        r"\((?P<cols>.+?)\)\s*USING\s+DELTA"
        r"(?:\s+PARTITIONED\s+BY\s+\((?P<parts>[^)]+)\))?"
        r"(?:\s+CLUSTER\s+BY\s+\((?P<clust>[^)]+)\))?"
        r"(?:\s+LOCATION\s+'(?P<loc>[^']+)')?"
        r"(?:\s+TBLPROPERTIES\s*\((?P<props>.+)\))?",
        s, re.IGNORECASE | re.DOTALL,
    )
    if m:
        if catalog is None:
            raise CatalogTableError("CREATE TABLE <name> requires a catalog")
        from delta_tpu.models.schema import StructType

        schema = StructType(_parse_column_defs(m.group("cols")))
        split = lambda g: ([c.strip().strip("`") for c in m.group(g).split(",")]
                           if m.group(g) else None)
        catalog.create_table(
            m.group("name"),
            schema=schema,
            location=m.group("loc"),
            partition_by=split("parts"),
            cluster_by=split("clust"),
            properties=_parse_properties(m.group("props")) if m.group("props") else None,
            if_not_exists=m.group("ine") is not None,
        )
        return m.group("name")

    m = re.fullmatch(
        r"DROP\s+TABLE\s+(?P<ife>IF\s+EXISTS\s+)?"
        r"(?P<name>[A-Za-z_][A-Za-z0-9_.]*)",
        s, re.IGNORECASE,
    )
    if m:
        if catalog is None:
            raise CatalogTableError("DROP TABLE <name> requires a catalog")
        return catalog.drop(m.group("name"), if_exists=m.group("ife") is not None)

    if re.fullmatch(r"SHOW\s+TABLES", s, re.IGNORECASE):
        if catalog is None:
            raise CatalogTableError("SHOW TABLES requires a catalog")
        return catalog.tables()

    return NotImplemented


_AGG_FNS = {"count": "count", "sum": "sum", "min": "min", "max": "max",
            "avg": "mean"}


def _rewrite_columns(expr, mapping):
    """Rebuild an expression tree with column references resolved through
    `mapping` (('a','x') or ('x',) -> physical joined-column name)."""
    import dataclasses

    from delta_tpu.expressions.tree import Column, Expression

    if isinstance(expr, Column):
        key = tuple(expr.name_path)
        if key in mapping:
            return Column((mapping[key],))
        raise UnresolvedColumnError(
            f"column {'.'.join(key)!r} is not in scope; available: "
            f"{sorted({'.'.join(k) if len(k) > 1 else k[0] for k in mapping})}")
    if not isinstance(expr, Expression) or not dataclasses.is_dataclass(expr):
        return expr
    changes = {}
    for f in dataclasses.fields(expr):
        v = getattr(expr, f.name)
        if isinstance(v, Expression):
            changes[f.name] = _rewrite_columns(v, mapping)
        elif isinstance(v, (list, tuple)) and any(
                isinstance(x, Expression) for x in v):
            changes[f.name] = type(v)(
                _rewrite_columns(x, mapping) if isinstance(x, Expression)
                else x for x in v)
    return dataclasses.replace(expr, **changes) if changes else expr


def _parse_table_ref(text: str, engine, catalog):
    """One FROM/JOIN table reference with optional alias."""
    m = re.match(rf"{_PATH}(?:\s+(?:AS\s+)?(?P<alias>[A-Za-z_][A-Za-z0-9_]*))?\s*$",
                 text.strip(), re.IGNORECASE)
    if not m:
        raise SqlParseError(f"cannot parse table reference {text!r}")
    table = _table(m, engine, catalog)
    alias = m.group("alias")
    return table, alias


def _exec_select_extended(s: str, engine, catalog):
    """SELECT beyond simple projection — joins (implicit comma +
    INNER/LEFT/RIGHT/FULL OUTER), aggregates, GROUP BY / HAVING,
    subqueries, CASE, BETWEEN, date arithmetic: the query subset the
    reference delegates to Spark SQL, executed by the sqlengine
    parser/planner (`delta_tpu/sqlengine/`) with scan pushdown into
    Delta snapshots. Runs verbatim TPC-DS query shapes."""
    from delta_tpu.sqlengine import execute_select

    return execute_select(s, engine=engine, catalog=catalog)


def _simple_select(s: str, engine, catalog):
    """Arrow-native fast path for `SELECT <plain cols|*> FROM <one
    table> [time travel] [WHERE <pushdown-parseable pred>] [LIMIT n]`.
    Returns NotImplemented for anything richer. Exists for type
    fidelity, not just speed: the sqlengine's pandas round-trip turns
    nullable int64 into float64 (lossy above 2^53) and date32 into
    timestamps, while this path stays `Snapshot.scan().to_arrow()`
    end-to-end."""
    m = re.fullmatch(
        rf"SELECT\s+(?P<cols>.+?)\s+FROM\s+{_PATH}"
        r"(?:\s+VERSION\s+AS\s+OF\s+(?P<tt_version>\d+)"
        r"|\s+TIMESTAMP\s+AS\s+OF\s+(?P<tt_ts>\d+|'[^']+'))?"
        r"(?:\s+WHERE\s+(?P<where>.+?))?(?:\s+LIMIT\s+(?P<limit>\d+))?",
        s, re.IGNORECASE | re.DOTALL,
    )
    if not m:
        return NotImplemented
    cols_text = m.group("cols").strip()
    if cols_text == "*":
        columns = None
    else:
        columns = [c.strip().strip("`")
                   for c in _split_top_level_commas(cols_text)]
        if not all(re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", c)
                   for c in columns):
            return NotImplemented  # expressions/aliases → sqlengine
    if m.group("where"):
        # NULL literals outside IS [NOT] NULL need three-valued logic
        # the pushdown evaluator doesn't implement — sqlengine handles
        stripped = re.sub(r"\bIS\s+(?:NOT\s+)?NULL\b", "",
                          m.group("where"), flags=re.IGNORECASE)
        if re.search(r"\bNULL\b", stripped, re.IGNORECASE):
            return NotImplemented
        try:
            pred = parse_expression(m.group("where"))
        except ParseError:
            return NotImplemented  # richer predicate → sqlengine
    else:
        pred = None
    table = _table(m, engine, catalog)
    if m.group("tt_version") is not None:
        snap = table.snapshot_at(int(m.group("tt_version")))
    elif m.group("tt_ts") is not None:
        snap = table.snapshot_as_of_timestamp(
            _timestamp_ms(m.group("tt_ts")))
    else:
        snap = table.latest_snapshot()
    known = ({f.name for f in snap.schema.fields}
             if snap.schema is not None else set())
    # Spark-style case-insensitive resolution, matching the sqlengine
    # path: map requested names onto actual schema field names
    by_lower = {k.lower(): k for k in known}
    requested = None
    if columns is not None and known:
        resolved, unknown = [], []
        for c in columns:
            actual = c if c in known else by_lower.get(c.lower())
            (resolved.append(actual) if actual is not None
             else unknown.append(c))
        if unknown:
            raise UnresolvedColumnError(
                f"column(s) {unknown} not found in table schema "
                f"{sorted(known)}")
        requested, columns = columns, resolved
    if pred is not None and known:
        refs = {r[0] for r in pred.references()}
        bad = sorted(r for r in refs
                     if r not in known and r.lower() not in by_lower)
        if bad:
            raise UnresolvedColumnError(
                f"WHERE references unknown column(s) {bad}; table "
                f"schema is {sorted(known)}")
        if any(r not in known for r in refs):
            return NotImplemented  # case-folding predicate → sqlengine
    out = snap.scan(filter=pred, columns=columns).to_arrow()
    if requested is not None and requested != columns:
        # output columns carry the case the query wrote (sqlengine
        # behavior), while the scan used the schema's actual names
        out = out.rename_columns(requested)
    if m.group("limit"):
        out = out.slice(0, int(m.group("limit")))
    return out


def _query_statement(s: str, engine, catalog):
    # table_changes('<path>' | name, start [, end]) — the reference's CDC
    # SQL table function (DeltaTableValueFunctions): returns change rows
    # with _change_type/_commit_version/_commit_timestamp columns
    m = re.fullmatch(
        rf"SELECT\s+\*\s+FROM\s+table_changes\s*\(\s*{_PATH}\s*,\s*"
        r"(?P<start>\d+)\s*(?:,\s*(?P<end>\d+)\s*)?\)"
        r"(?:\s+LIMIT\s+(?P<limit>\d+))?",
        s, re.IGNORECASE | re.DOTALL,
    )
    if m:
        from delta_tpu.read.cdc import table_changes

        table = _table(m, engine, catalog)
        out = table_changes(
            table, int(m.group("start")),
            int(m.group("end")) if m.group("end") else None)
        if m.group("limit"):
            out = out.slice(0, int(m.group("limit")))
        return out

    if re.match(r"WITH\b", s, re.IGNORECASE):
        return _exec_select_extended(s, engine, catalog)
    if re.match(r"SELECT\b", s, re.IGNORECASE):
        # plain single-table scans take the Arrow-native fast path
        # (type fidelity); everything richer runs through the
        # sqlengine parser/planner
        result = _simple_select(s, engine, catalog)
        if result is not NotImplemented:
            return result
        return _exec_select_extended(s, engine, catalog)

    m = re.fullmatch(
        rf"INSERT\s+(?:INTO|(?P<overwrite>OVERWRITE))\s+{_PATH}\s*"
        r"(?:\((?P<collist>[^)]+)\)\s*)?(?P<rest>.+)",
        s, re.IGNORECASE | re.DOTALL,
    )
    if m:
        import pyarrow as pa

        import delta_tpu.api as dta
        from delta_tpu.expressions.tree import Literal

        rest = m.group("rest").strip()
        replace_where = None
        rw = re.match(r"REPLACE\s+WHERE\s+", rest, re.IGNORECASE)
        if rw:
            if not m.group("overwrite"):
                raise SqlParseError(
                    "REPLACE WHERE requires INSERT OVERWRITE",
                    error_class="DELTA_OPERATION_NOT_ALLOWED")
            pred_str, rest = _split_before_keyword(rest[rw.end():], "VALUES")
            if rest is None:
                raise SqlParseError("REPLACE WHERE must be followed by VALUES")
            replace_where = parse_expression(pred_str.strip())
        vm = re.match(r"VALUES\s+(?P<vals>.+)", rest,
                      re.IGNORECASE | re.DOTALL)
        if not vm:
            raise SqlParseError("INSERT requires a VALUES clause")
        vals_str = vm.group("vals")

        table = _table(m, engine, catalog)
        meta = table.latest_snapshot().metadata
        fields = {f.name: f for f in meta.schema.fields}
        if m.group("collist"):
            targets = [c.strip().strip("`")
                       for c in m.group("collist").split(",")]
            unknown = [c for c in targets if c not in fields]
            if unknown:
                raise UnresolvedColumnError(f"INSERT column(s) {unknown} not in schema")
            if len(set(targets)) != len(targets):
                raise DuplicateColumnError(
                f"duplicate INSERT column(s) in {targets}",
                error_class="DELTA_DUPLICATE_COLUMNS_ON_INSERT")
        else:
            targets = list(fields)
        rows = []
        for tup in _split_values_tuples(vals_str):
            vals = []
            for item in _split_top_level_commas(tup):
                expr = parse_expression(item.strip())
                if not isinstance(expr, Literal):
                    raise SqlParseError(
                        f"INSERT VALUES must be literals, got {item!r}")
                vals.append(expr.value)
            rows.append(vals)
        if not rows:
            raise SqlParseError("INSERT requires at least one VALUES tuple")
        if any(len(r) != len(targets) for r in rows):
            raise SqlParseError(
                error_class="DELTA_INSERT_COLUMN_ARITY_MISMATCH",
                message=f"each VALUES tuple must have exactly {len(targets)} "
                f"value(s) for columns {targets}"
            )
        from delta_tpu.models.schema import to_arrow_type

        data = pa.table({
            n: pa.array([r[i] for r in rows],
                        to_arrow_type(fields[n].dataType))
            for i, n in enumerate(targets)
        })
        mode = "overwrite" if m.group("overwrite") else "append"
        return dta.write_table(table.path, data, mode=mode,
                               replace_where=replace_where,
                               engine=table.engine)

    return NotImplemented


def _handle_merge_into(s: str, engine, catalog):
    """MERGE INTO <t> [AS a] USING <t2> [AS b] ON <cond> WHEN ... —
    the reference's SQL MERGE surface, parsed stepwise so table tokens,
    aliases, and quote-embedded keywords all resolve safely."""
    head = re.match(r"MERGE\s+INTO\s+", s, re.IGNORECASE)
    if not head:
        return NotImplemented

    def take_table(text):
        m = re.match(_PATH, text)
        if not m:
            raise SqlParseError(f"cannot parse table reference near {text[:40]!r}")
        return m, text[m.end():].lstrip()

    def take_alias(text):
        m = re.match(r"(?:AS\s+)?([A-Za-z_][A-Za-z0-9_]*)\s+", text,
                     re.IGNORECASE)
        if m and m.group(1).upper() not in ("USING", "ON", "WHEN"):
            return m.group(1), text[m.end():]
        return None, text

    rest = s[head.end():]
    t_m, rest = take_table(rest)
    alias_t, rest = take_alias(rest)
    um = re.match(r"USING\s+", rest, re.IGNORECASE)
    if not um:
        raise SqlParseError("MERGE INTO requires a USING clause")
    s_m, rest = take_table(rest[um.end():])
    alias_s, rest = take_alias(rest)
    onm = re.match(r"ON\s+", rest, re.IGNORECASE)
    if not onm:
        raise SqlParseError("MERGE INTO requires an ON condition")
    on_text, rest = _split_before_keyword(rest[onm.end():], "WHEN")
    if rest is None:
        raise SqlParseError("MERGE INTO requires at least one WHEN clause")

    # split the WHEN clauses at top level
    clause_texts = []
    while rest is not None:
        rest = rest[len("WHEN"):].strip() if rest[:4].upper() == "WHEN" \
            else rest
        body, rest = _split_before_keyword(rest, "WHEN")
        clause_texts.append(body.strip())

    from delta_tpu.commands.merge import merge as _merge
    from delta_tpu.expressions.tree import Column as _Col

    def requalify(expr):
        """Rewrite alias roots onto the merge namespace at the TREE
        level (string literals are untouched by construction): every
        Expression node's children live in Expression-typed dataclass
        fields, so a generic dataclasses.replace rebuild is exact."""
        import dataclasses as _dc

        from delta_tpu.expressions.tree import Expression as _Expr

        if isinstance(expr, _Col):
            root = expr.name_path[0]
            if alias_t is not None and root == alias_t:
                return _Col(("target",) + tuple(expr.name_path[1:]))
            if alias_s is not None and root == alias_s:
                return _Col(("source",) + tuple(expr.name_path[1:]))
            return expr
        if not expr.children():
            return expr
        updates = {}
        for f in _dc.fields(expr):
            v = getattr(expr, f.name)
            if isinstance(v, _Expr):
                nv = requalify(v)
                if nv is not v:
                    updates[f.name] = nv
        return _dc.replace(expr, **updates) if updates else expr

    target_table = _table(t_m, engine, catalog)
    source_table = _table(s_m, engine, catalog)
    source_data = source_table.latest_snapshot().scan().to_arrow()
    on_expr = requalify(parse_expression(on_text.strip()))
    builder = _merge(target_table, source_data, on=on_expr)

    def parse_sets(text):
        out = {}
        for part in _split_top_level_commas(text):
            lhs, _, rhs = part.partition("=")
            name = lhs.strip().strip("`")
            for pre in (f"{alias_t}." if alias_t else None, "target."):
                if pre and name.startswith(pre):
                    name = name[len(pre):]
            out[name] = requalify(parse_expression(rhs.strip()))
        return out

    for text in clause_texts:
        # split the condition from the action at a quote-safe THEN, so a
        # literal like 'a THEN b' inside the AND condition parses
        before_then, from_then = _split_before_keyword(text, "THEN")
        if from_then is None:
            raise SqlParseError(f"cannot parse MERGE clause: {text[:60]!r}")
        km = re.match(
            r"(?P<kind>MATCHED|NOT\s+MATCHED\s+BY\s+SOURCE|NOT\s+MATCHED)"
            r"(?:\s+AND\s+(?P<cond>.+))?\s*$",
            before_then.strip(), re.IGNORECASE | re.DOTALL)
        if not km:
            raise SqlParseError(f"cannot parse MERGE clause: {text[:60]!r}")
        kind = re.sub(r"\s+", " ", km.group("kind").upper())
        cond = (requalify(parse_expression(km.group("cond").strip()))
                if km.group("cond") else None)
        action = from_then[len("THEN"):].strip()
        # keyword comparisons are whitespace-normalized (formatted SQL
        # uses newlines/extra spaces); the SET payload keeps its text
        a_up = re.sub(r"\s+", " ", action.upper())
        if kind == "MATCHED":
            if a_up == "DELETE":
                builder = builder.when_matched_delete(condition=cond)
            elif a_up in ("UPDATE SET *", "UPDATE *"):
                builder = builder.when_matched_update_all(condition=cond)
            elif a_up.startswith("UPDATE SET"):
                builder = builder.when_matched_update(
                    set=parse_sets(re.sub(r"^UPDATE\s+SET\s*", "",
                                        action, flags=re.IGNORECASE)),
                    condition=cond)
            else:
                raise SqlParseError(f"unsupported MATCHED action {action!r}")
        elif kind == "NOT MATCHED":
            if a_up in ("INSERT *",):
                builder = builder.when_not_matched_insert_all(condition=cond)
            else:
                im = re.match(r"INSERT\s*\((?P<cols>[^)]+)\)\s*VALUES\s*"
                              r"\((?P<vals>.+)\)\s*$", action,
                              re.IGNORECASE | re.DOTALL)
                if not im:
                    raise SqlParseError(
                        f"unsupported NOT MATCHED action {action!r}")
                cols = [c.strip().strip("`")
                        for c in im.group("cols").split(",")]
                vals = [requalify(parse_expression(v.strip()))
                        for v in _split_top_level_commas(im.group("vals"))]
                if len(cols) != len(vals):
                    raise SqlParseError(
                        "INSERT column/value count mismatch",
                        error_class="DELTA_INSERT_COLUMN_ARITY_MISMATCH")
                builder = builder.when_not_matched_insert(
                    values=dict(zip(cols, vals)), condition=cond)
        else:  # NOT MATCHED BY SOURCE
            if a_up == "DELETE":
                builder = builder.when_not_matched_by_source_delete(
                    condition=cond)
            elif a_up.startswith("UPDATE SET"):
                builder = builder.when_not_matched_by_source_update(
                    set=parse_sets(re.sub(r"^UPDATE\s+SET\s*", "",
                                        action, flags=re.IGNORECASE)),
                    condition=cond)
            else:
                raise SqlParseError(
                    f"unsupported NOT MATCHED BY SOURCE action {action!r}")
    return builder.execute()


def _timestamp_ms(raw: str) -> int:
    """`<ms>` or `'<iso>'` → epoch millis; malformed input raises
    DeltaError like every other bad-SQL path."""
    if raw.startswith("'"):
        import datetime as dt

        text = raw.strip("'")
        if text.endswith(("Z", "z")):
            text = text[:-1] + "+00:00"  # py3.10 fromisoformat lacks Z
        try:
            return int(dt.datetime.fromisoformat(text).timestamp() * 1000)
        except ValueError as e:
            raise SqlParseError(
                f"cannot parse timestamp {raw}: {e}",
                error_class="DELTA_INVALID_TIMESTAMP_FORMAT") from None
    return int(raw)


def _split_before_keyword(s: str, keyword: str):
    """Split `s` at the first whitespace-delimited `keyword` OUTSIDE
    single-quoted literals; returns (before, from_keyword) or (s, None)
    when absent — so a predicate string containing the word is safe."""
    kw = keyword.lower()
    in_str = False
    i, n = 0, len(s)
    while i < n:
        ch = s[i]
        if in_str:
            if ch == "'":
                in_str = False
            i += 1
            continue
        if ch == "'":
            in_str = True
            i += 1
            continue
        if s[i:i + len(kw)].lower() == kw:
            before_ok = i == 0 or s[i - 1].isspace()
            after = i + len(kw)
            after_ok = after >= n or s[after].isspace()
            if before_ok and after_ok:
                return s[:i], s[i:]
        i += 1
    return s, None


def _split_values_tuples(s: str):
    """`(1, 'a(b)'), (2, 'c,d')` → ["1, 'a(b)'", "2, 'c,d'"] — tuple
    bodies at paren depth 1, honoring string literals."""
    out, cur, depth, in_str = [], [], 0, False
    for ch in s:
        if in_str:
            cur.append(ch)
            if ch == "'":
                in_str = False
            continue
        if ch == "'":
            in_str = True
            cur.append(ch)
        elif ch == "(":
            depth += 1
            if depth > 1:
                cur.append(ch)
        elif ch == ")":
            depth -= 1
            if depth == 0:
                out.append("".join(cur))
                cur = []
            else:
                cur.append(ch)
        elif depth >= 1:
            cur.append(ch)
        elif not ch.isspace() and ch != ",":
            raise SqlParseError(f"cannot parse VALUES tuples near {ch!r} in {s!r}")
    if depth != 0 or in_str:
        raise SqlParseError(f"unbalanced VALUES tuples: {s!r}")
    if cur:
        raise SqlParseError(
            f"unexpected content outside VALUES tuples: {''.join(cur)!r}"
        )
    return out


def _split_top_level_commas(s: str):
    out, depth, cur = [], 0, []
    in_str = False
    for ch in s:
        if ch == "'":
            in_str = not in_str
        elif not in_str:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "," and depth == 0:
                out.append("".join(cur))
                cur = []
                continue
        cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def describe_detail(table: Table) -> dict:
    """DESCRIBE DETAIL row (reference `DeltaTableV2` detail schema)."""
    snap = table.latest_snapshot()
    meta = snap.metadata
    return {
        "format": meta.format.provider,
        "id": meta.id,
        "name": meta.name,
        "description": meta.description,
        "location": table.path,
        "createdAt": meta.createdTime,
        "lastModified": snap.timestamp_ms,
        "partitionColumns": list(meta.partitionColumns),
        "numFiles": snap.num_files,
        "sizeInBytes": snap.size_in_bytes,
        "properties": dict(meta.configuration),
        "minReaderVersion": snap.protocol.minReaderVersion,
        "minWriterVersion": snap.protocol.minWriterVersion,
        "tableFeatures": sorted(
            snap.protocol.reader_feature_set() | snap.protocol.writer_feature_set()
        ),
        "version": snap.version,
    }

"""Delta SQL statement surface.

The reference extends Spark SQL with delta-specific statements
(`DeltaSqlBase.g4:74-95`). This module provides the same statement set
over table *paths* (there is no external catalog in-process):

    VACUUM '/path' [RETAIN n HOURS] [DRY RUN]
    OPTIMIZE '/path' [WHERE <pred>] [ZORDER BY (c1, c2)]
    DESCRIBE HISTORY '/path' [LIMIT n]
    DESCRIBE DETAIL '/path'
    RESTORE TABLE '/path' TO VERSION AS OF n
    RESTORE TABLE '/path' TO TIMESTAMP AS OF <ms|'iso'>
    CONVERT TO DELTA parquet.'/path' [PARTITIONED BY (c type, ...)]
    ALTER TABLE '/path' ADD CONSTRAINT name CHECK (<pred>)
    ALTER TABLE '/path' DROP CONSTRAINT [IF EXISTS] name

Plus (not in the reference grammar, for symmetry with our API):
    DELETE FROM '/path' [WHERE <pred>]
    UPDATE '/path' SET col = <literal>[, ...] [WHERE <pred>]

Returns command-specific results (VacuumResult, OptimizeMetrics, history
records as dicts, an Arrow table for DESCRIBE DETAIL, commit versions...).
WHERE/CHECK predicates use the persisted-expression subset
(`expressions/parser.py`).
"""

from __future__ import annotations

import re
from typing import Optional

from delta_tpu.errors import DeltaError
from delta_tpu.expressions.parser import parse_expression
from delta_tpu.table import Table

_PATH = r"(?:'(?P<path>[^']+)'|delta\.`(?P<path2>[^`]+)`|\"(?P<path3>[^\"]+)\")"


def _path_of(m) -> str:
    return m.group("path") or m.group("path2") or m.group("path3")


def _table(m, engine) -> Table:
    return Table.for_path(_path_of(m), engine)


def sql(statement: str, engine=None):
    """Execute one Delta SQL statement against a table path."""
    s = statement.strip().rstrip(";").strip()

    m = re.fullmatch(
        rf"VACUUM\s+{_PATH}(?:\s+RETAIN\s+(?P<hours>[\d.]+)\s+HOURS)?"
        r"(?P<dry>\s+DRY\s+RUN)?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.vacuum import vacuum

        return vacuum(
            _table(m, engine),
            retention_hours=float(m.group("hours")) if m.group("hours") else None,
            dry_run=m.group("dry") is not None,
        )

    m = re.fullmatch(
        rf"OPTIMIZE\s+{_PATH}(?:\s+WHERE\s+(?P<where>.+?))?"
        r"(?:\s+ZORDER\s+BY\s+\((?P<zcols>[^)]+)\))?",
        s, re.IGNORECASE,
    )
    if m:
        builder = _table(m, engine).optimize()
        if m.group("where"):
            builder = builder.where(parse_expression(m.group("where")))
        if m.group("zcols"):
            cols = [c.strip().strip("`") for c in m.group("zcols").split(",")]
            return builder.execute_zorder_by(*cols)
        return builder.execute_compaction()

    m = re.fullmatch(
        rf"(?:DESC|DESCRIBE)\s+HISTORY\s+{_PATH}(?:\s+LIMIT\s+(?P<limit>\d+))?",
        s, re.IGNORECASE,
    )
    if m:
        limit = int(m.group("limit")) if m.group("limit") else None
        return [r.to_dict() for r in _table(m, engine).history(limit)]

    m = re.fullmatch(rf"(?:DESC|DESCRIBE)\s+DETAIL\s+{_PATH}", s, re.IGNORECASE)
    if m:
        return describe_detail(_table(m, engine))

    m = re.fullmatch(
        rf"RESTORE\s+(?:TABLE\s+)?{_PATH}\s+TO\s+VERSION\s+AS\s+OF\s+(?P<v>\d+)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.restore import restore

        return restore(_table(m, engine), version=int(m.group("v")))

    m = re.fullmatch(
        rf"RESTORE\s+(?:TABLE\s+)?{_PATH}\s+TO\s+TIMESTAMP\s+AS\s+OF\s+"
        r"(?:(?P<ms>\d+)|'(?P<iso>[^']+)')",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.restore import restore

        if m.group("ms"):
            ts = int(m.group("ms"))
        else:
            import datetime as dt

            ts = int(dt.datetime.fromisoformat(m.group("iso")).timestamp() * 1000)
        return restore(_table(m, engine), timestamp_ms=ts)

    m = re.fullmatch(
        rf"CONVERT\s+TO\s+DELTA\s+parquet\.{_PATH}"
        r"(?:\s+PARTITIONED\s+BY\s+\((?P<parts>[^)]+)\))?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.restore import convert_to_delta

        part_schema = None
        if m.group("parts"):
            part_schema = {}
            for item in m.group("parts").split(","):
                name, _, typ = item.strip().partition(" ")
                part_schema[name.strip("`")] = typ.strip() or "string"
        return convert_to_delta(_path_of(m), partition_schema=part_schema,
                                engine=engine)

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+ADD\s+CONSTRAINT\s+(?P<name>\w+)\s+"
        r"CHECK\s*\((?P<expr>.+)\)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.constraints import add_constraint

        return add_constraint(_table(m, engine), m.group("name"), m.group("expr"))

    m = re.fullmatch(
        rf"ALTER\s+TABLE\s+{_PATH}\s+DROP\s+CONSTRAINT\s+"
        r"(?P<ife>IF\s+EXISTS\s+)?(?P<name>\w+)",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.constraints import drop_constraint

        return drop_constraint(
            _table(m, engine), m.group("name"), if_exists=m.group("ife") is not None
        )

    m = re.fullmatch(
        rf"DELETE\s+FROM\s+{_PATH}(?:\s+WHERE\s+(?P<where>.+))?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.dml import delete

        pred = parse_expression(m.group("where")) if m.group("where") else None
        return delete(_table(m, engine), pred)

    m = re.fullmatch(
        rf"UPDATE\s+{_PATH}\s+SET\s+(?P<sets>.+?)(?:\s+WHERE\s+(?P<where>.+))?",
        s, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.dml import update

        assignments = {}
        for part in _split_top_level_commas(m.group("sets")):
            col_name, _, value = part.partition("=")
            assignments[col_name.strip().strip("`")] = parse_expression(value.strip())
        pred = parse_expression(m.group("where")) if m.group("where") else None
        return update(_table(m, engine), assignments, pred)

    raise DeltaError(f"cannot parse Delta SQL statement: {statement!r}")


def _split_top_level_commas(s: str):
    out, depth, cur = [], 0, []
    in_str = False
    for ch in s:
        if ch == "'":
            in_str = not in_str
        elif not in_str:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "," and depth == 0:
                out.append("".join(cur))
                cur = []
                continue
        cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def describe_detail(table: Table) -> dict:
    """DESCRIBE DETAIL row (reference `DeltaTableV2` detail schema)."""
    snap = table.latest_snapshot()
    meta = snap.metadata
    return {
        "format": meta.format.provider,
        "id": meta.id,
        "name": meta.name,
        "description": meta.description,
        "location": table.path,
        "createdAt": meta.createdTime,
        "lastModified": snap.timestamp_ms,
        "partitionColumns": list(meta.partitionColumns),
        "numFiles": snap.num_files,
        "sizeInBytes": snap.size_in_bytes,
        "properties": dict(meta.configuration),
        "minReaderVersion": snap.protocol.minReaderVersion,
        "minWriterVersion": snap.protocol.minWriterVersion,
        "tableFeatures": sorted(
            snap.protocol.reader_feature_set() | snap.protocol.writer_feature_set()
        ),
        "version": snap.version,
    }

"""CHECK constraints + invariants.

Reference `constraints/Constraints.scala` / `Invariants.scala`: CHECK
constraints persist as `delta.constraints.<name> = <sql>` table
properties and are enforced on every write; NOT NULL comes from schema
nullability (enforced in the writer). Adding a constraint validates the
existing data first (`AlterTableAddConstraint`).
"""

from __future__ import annotations

from typing import Dict

from delta_tpu.errors import ConstraintAlreadyExistsError, ConstraintNotFoundError, DeltaError, InvalidArgumentError, InvariantViolationError, MissingTransactionLogError
from delta_tpu.expressions.parser import parse_expression, to_sql
from delta_tpu.expressions.tree import Expression

CONSTRAINT_PREFIX = "delta.constraints."


def constraint_key(name: str) -> str:
    return CONSTRAINT_PREFIX + name.lower()


def table_constraints(configuration: Dict[str, str]) -> Dict[str, Expression]:
    """name -> parsed predicate, from table properties."""
    out = {}
    for k, v in configuration.items():
        if k.startswith(CONSTRAINT_PREFIX):
            out[k[len(CONSTRAINT_PREFIX):]] = parse_expression(v)
    return out


def _empty_batch(meta):
    import pyarrow as pa

    from delta_tpu.models.schema import to_arrow_schema

    return pa.Table.from_arrays(
        [pa.array([], f.type) for f in to_arrow_schema(meta.schema)],
        schema=to_arrow_schema(meta.schema))


def add_constraint(table, name: str, expr) -> int:
    """ALTER TABLE ADD CONSTRAINT name CHECK (expr). Validates existing
    rows before committing. Returns the commit version."""
    import dataclasses

    import numpy as np
    import pyarrow as pa

    from delta_tpu.expressions.eval import evaluate_predicate_host
    from delta_tpu.txn.transaction import Operation

    if isinstance(expr, str):
        expr = parse_expression(expr)
    txn = table.create_transaction_builder(Operation.ADD_CONSTRAINT).build()
    snapshot = txn.read_snapshot
    if snapshot is None:
        raise MissingTransactionLogError(f"no table at {table.path}")
    meta = snapshot.metadata
    key = constraint_key(name)
    if key in meta.configuration:
        raise ConstraintAlreadyExistsError(f"constraint {name} already exists")
    try:
        # type-probe on an empty batch: a CHECK body must be boolean
        from delta_tpu.expressions.eval import evaluate_host

        probe = (evaluate_host(expr, _empty_batch(meta))
                 if meta.schema is not None else None)
        probe_type = getattr(probe, "type", None)
    # delta-lint: disable=except-swallow (audited: the probe evaluates an
    # arbitrary user expression on an empty batch — any failure means
    # "cannot type statically" and per-row validation decides instead)
    except Exception:
        probe_type = None  # unevaluable-on-empty: row validation decides
    if probe_type is not None and probe_type != pa.bool_():
        raise InvalidArgumentError(
            f"CHECK constraint {name} must be a boolean expression, got "
            f"{probe_type}",
            error_class="DELTA_NON_BOOLEAN_CHECK_CONSTRAINT")

    # validate current data
    data = snapshot.scan().to_arrow()
    if data.num_rows:
        ok = evaluate_predicate_host(expr, data)
        bad = int((~np.asarray(ok)).sum())
        if bad:
            raise InvariantViolationError(
                error_class="DELTA_NEW_CHECK_CONSTRAINT_VIOLATION",
                message=f"{bad} existing row(s) violate new constraint {name}: "
                f"{to_sql(expr)}"
            )
    txn.mark_read_whole_table()

    new_conf = dict(meta.configuration)
    new_conf[key] = to_sql(expr)
    txn.update_metadata(dataclasses.replace(meta, configuration=new_conf))

    from delta_tpu.features import CHECK_CONSTRAINTS, upgraded_protocol

    proto = txn.protocol()
    new_proto = upgraded_protocol(proto, CHECK_CONSTRAINTS)
    if new_proto != proto:
        txn.update_protocol(new_proto)
    txn.set_operation_parameters({"name": name, "expr": to_sql(expr)})
    return txn.commit().version


def drop_constraint(table, name: str, if_exists: bool = False) -> int:
    import dataclasses

    from delta_tpu.txn.transaction import Operation

    txn = table.create_transaction_builder(Operation.DROP_CONSTRAINT).build()
    meta = txn.metadata()
    key = constraint_key(name)
    if key not in meta.configuration:
        if if_exists:
            return txn.read_version
        raise ConstraintNotFoundError(f"constraint {name} does not exist")
    new_conf = {k: v for k, v in meta.configuration.items() if k != key}
    txn.update_metadata(dataclasses.replace(meta, configuration=new_conf))
    txn.set_operation_parameters({"name": name})
    return txn.commit().version

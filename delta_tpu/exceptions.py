"""`delta.exceptions`-compatible names (reference
`python/delta/exceptions.py:33-106`): the delta-spark concurrency
exception taxonomy, aliased onto this engine's error classes so
migrated `except` clauses keep working:

    from delta_tpu.exceptions import ConcurrentAppendException
    try:
        txn.commit()
    except ConcurrentAppendException:
        retry()

Each name IS the corresponding native class (no wrapping), so catching
either spelling works.
"""

from delta_tpu.errors import (
    ConcurrentAppendError,
    ConcurrentDeleteDeleteError,
    ConcurrentDeleteReadError,
    ConcurrentModificationError,
    ConcurrentTransactionError,
    ConcurrentWriteError,
    MetadataChangedError,
    ProtocolChangedError,
)

DeltaConcurrentModificationException = ConcurrentModificationError
ConcurrentWriteException = ConcurrentWriteError
MetadataChangedException = MetadataChangedError
ProtocolChangedException = ProtocolChangedError
ConcurrentAppendException = ConcurrentAppendError
ConcurrentDeleteReadException = ConcurrentDeleteReadError
ConcurrentDeleteDeleteException = ConcurrentDeleteDeleteError
ConcurrentTransactionException = ConcurrentTransactionError

__all__ = [
    "DeltaConcurrentModificationException",
    "ConcurrentWriteException",
    "MetadataChangedException",
    "ProtocolChangedException",
    "ConcurrentAppendException",
    "ConcurrentDeleteReadException",
    "ConcurrentDeleteDeleteException",
    "ConcurrentTransactionException",
]

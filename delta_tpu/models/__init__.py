"""Data model: the typed action schemas of the Delta transaction log.

(The reference calls this the "actions" model — spark
`actions/actions.scala`, kernel `internal/actions/`.)
"""

from delta_tpu.models.actions import (
    Action,
    AddFile,
    RemoveFile,
    AddCDCFile,
    Metadata,
    Protocol,
    SetTransaction,
    DomainMetadata,
    CommitInfo,
    CheckpointMetadata,
    Sidecar,
    DeletionVectorDescriptor,
    Format,
    action_from_json_dict,
    actions_from_commit_bytes,
    actions_to_commit_bytes,
)
from delta_tpu.models.schema import (
    DataType,
    PrimitiveType,
    ArrayType,
    MapType,
    StructField,
    StructType,
    schema_from_json,
    schema_to_json,
)

__all__ = [
    "Action",
    "AddFile",
    "RemoveFile",
    "AddCDCFile",
    "Metadata",
    "Protocol",
    "SetTransaction",
    "DomainMetadata",
    "CommitInfo",
    "CheckpointMetadata",
    "Sidecar",
    "DeletionVectorDescriptor",
    "Format",
    "action_from_json_dict",
    "actions_from_commit_bytes",
    "actions_to_commit_bytes",
    "DataType",
    "PrimitiveType",
    "ArrayType",
    "MapType",
    "StructField",
    "StructType",
    "schema_from_json",
    "schema_to_json",
]

"""Table schema model.

Delta serializes table schemas as Spark-SQL-style JSON in
`metaData.schemaString` (PROTOCOL.md Schema Serialization Format): a
`struct` of fields, each `{name, type, nullable, metadata}`, where type is a
primitive name string, or a nested `struct` / `array` / `map` object, or a
`decimal(p,s)` string. This module models that format and converts to/from
pyarrow schemas for the host Parquet/Arrow I/O layer.

Column-mapping metadata keys (`delta.columnMapping.id` / `.physicalName`)
live in field metadata; the columnmapping module consumes them.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import pyarrow as pa

_DECIMAL_RE = re.compile(r"^decimal\(\s*(\d+)\s*,\s*(-?\d+)\s*\)$")

PRIMITIVES = {
    "string",
    "long",
    "integer",
    "short",
    "byte",
    "float",
    "double",
    "boolean",
    "binary",
    "date",
    "timestamp",
    "timestamp_ntz",
    "variant",
}

COLUMN_MAPPING_ID_KEY = "delta.columnMapping.id"
COLUMN_MAPPING_PHYSICAL_NAME_KEY = "delta.columnMapping.physicalName"


class DataType:
    def to_json_value(self) -> Any:  # pragma: no cover - abstract
        raise NotImplementedError

    def __eq__(self, other):
        return type(self) is type(other) and self.to_json_value() == other.to_json_value()

    def __hash__(self):
        return hash(json.dumps(self.to_json_value(), sort_keys=True))

    def __repr__(self):
        return f"{type(self).__name__}({self.to_json_value()!r})"


@dataclass(frozen=True, eq=False)
class PrimitiveType(DataType):
    name: str  # one of PRIMITIVES or "decimal(p,s)"

    def __post_init__(self):
        if self.name not in PRIMITIVES and not _DECIMAL_RE.match(self.name):
            from delta_tpu.errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"unknown primitive type: {self.name}",
                error_class="DELTA_PARSING_UNSUPPORTED_DATA_TYPE")

    @property
    def is_decimal(self) -> bool:
        return self.name.startswith("decimal")

    def decimal_precision_scale(self) -> tuple[int, int]:
        m = _DECIMAL_RE.match(self.name)
        assert m, self.name
        return int(m.group(1)), int(m.group(2))

    def to_json_value(self) -> Any:
        return self.name


STRING = PrimitiveType("string")
LONG = PrimitiveType("long")
INTEGER = PrimitiveType("integer")
SHORT = PrimitiveType("short")
BYTE = PrimitiveType("byte")
FLOAT = PrimitiveType("float")
DOUBLE = PrimitiveType("double")
BOOLEAN = PrimitiveType("boolean")
BINARY = PrimitiveType("binary")
DATE = PrimitiveType("date")
TIMESTAMP = PrimitiveType("timestamp")
TIMESTAMP_NTZ = PrimitiveType("timestamp_ntz")


def decimal(precision: int, scale: int) -> PrimitiveType:
    return PrimitiveType(f"decimal({precision},{scale})")


@dataclass(eq=False)
class ArrayType(DataType):
    elementType: DataType
    containsNull: bool = True

    def to_json_value(self) -> Any:
        return {
            "type": "array",
            "elementType": self.elementType.to_json_value(),
            "containsNull": self.containsNull,
        }


@dataclass(eq=False)
class MapType(DataType):
    keyType: DataType
    valueType: DataType
    valueContainsNull: bool = True

    def to_json_value(self) -> Any:
        return {
            "type": "map",
            "keyType": self.keyType.to_json_value(),
            "valueType": self.valueType.to_json_value(),
            "valueContainsNull": self.valueContainsNull,
        }


@dataclass(eq=False)
class StructField:
    name: str
    dataType: DataType = STRING
    nullable: bool = True
    metadata: Dict[str, Any] = field(default_factory=dict)

    def to_json_value(self) -> Any:
        return {
            "name": self.name,
            "type": self.dataType.to_json_value(),
            "nullable": self.nullable,
            "metadata": self.metadata,
        }

    def __eq__(self, other):
        return (
            isinstance(other, StructField)
            and self.to_json_value() == other.to_json_value()
        )

    @property
    def column_mapping_id(self) -> Optional[int]:
        v = self.metadata.get(COLUMN_MAPPING_ID_KEY)
        return int(v) if v is not None else None

    @property
    def physical_name(self) -> str:
        return self.metadata.get(COLUMN_MAPPING_PHYSICAL_NAME_KEY, self.name)


@dataclass(eq=False)
class StructType(DataType):
    fields: List[StructField] = field(default_factory=list)

    def to_json_value(self) -> Any:
        return {"type": "struct", "fields": [f.to_json_value() for f in self.fields]}

    def field_names(self) -> List[str]:
        return [f.name for f in self.fields]

    def __getitem__(self, name: str) -> StructField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def __len__(self):
        return len(self.fields)

    def add(self, name: str, dt: DataType, nullable: bool = True, metadata=None) -> "StructType":
        return StructType(
            self.fields + [StructField(name, dt, nullable, dict(metadata or {}))]
        )

    def leaves(self, prefix: tuple = ()) -> List[tuple[tuple, StructField]]:
        """Depth-first leaf columns as (name-path, field) pairs — the unit
        for stats collection / data skipping (first 32 leaves by default)."""
        out = []
        for f in self.fields:
            if isinstance(f.dataType, StructType):
                out.extend(f.dataType.leaves(prefix + (f.name,)))
            else:
                out.append((prefix + (f.name,), f))
        return out


def _type_from_json_value(v: Any) -> DataType:
    if isinstance(v, str):
        return PrimitiveType(v)
    if isinstance(v, dict):
        t = v.get("type")
        if t == "struct":
            return StructType(
                [
                    StructField(
                        name=f["name"],
                        dataType=_type_from_json_value(f["type"]),
                        nullable=bool(f.get("nullable", True)),
                        metadata=dict(f.get("metadata") or {}),
                    )
                    for f in v.get("fields", [])
                ]
            )
        if t == "array":
            return ArrayType(
                elementType=_type_from_json_value(v["elementType"]),
                containsNull=bool(v.get("containsNull", True)),
            )
        if t == "map":
            return MapType(
                keyType=_type_from_json_value(v["keyType"]),
                valueType=_type_from_json_value(v["valueType"]),
                valueContainsNull=bool(v.get("valueContainsNull", True)),
            )
    from delta_tpu.errors import InvalidArgumentError

    raise InvalidArgumentError(
        f"cannot parse schema type: {v!r}",
        error_class="DELTA_PARSING_UNSUPPORTED_DATA_TYPE")


def schema_from_json(s: str) -> StructType:
    dt = _type_from_json_value(json.loads(s))
    if not isinstance(dt, StructType):
        raise ValueError("top-level schema must be a struct")
    return dt


def schema_to_json(st: StructType) -> str:
    return json.dumps(st.to_json_value(), separators=(",", ":"))


# ---------------------------------------------------------------------------
# pyarrow conversion (host I/O layer)
# ---------------------------------------------------------------------------

_PRIM_TO_ARROW = {
    "string": pa.string(),
    "long": pa.int64(),
    "integer": pa.int32(),
    "short": pa.int16(),
    "byte": pa.int8(),
    "float": pa.float32(),
    "double": pa.float64(),
    "boolean": pa.bool_(),
    "binary": pa.binary(),
    "date": pa.date32(),
    "timestamp": pa.timestamp("us", tz="UTC"),
    "timestamp_ntz": pa.timestamp("us"),
}


def to_arrow_type(dt: DataType) -> pa.DataType:
    if isinstance(dt, PrimitiveType):
        if dt.is_decimal:
            p, s = dt.decimal_precision_scale()
            return pa.decimal128(p, s)
        try:
            return _PRIM_TO_ARROW[dt.name]
        except KeyError:
            from delta_tpu.errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"no arrow mapping for {dt.name}",
                error_class="DELTA_UNSUPPORTED_DATA_TYPES")
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow_type(dt.elementType))
    if isinstance(dt, MapType):
        return pa.map_(to_arrow_type(dt.keyType), to_arrow_type(dt.valueType))
    if isinstance(dt, StructType):
        return pa.struct(
            [pa.field(f.name, to_arrow_type(f.dataType), f.nullable) for f in dt.fields]
        )
    raise ValueError(f"cannot convert {dt!r}")


def to_arrow_schema(st: StructType, use_physical_names: bool = False) -> pa.Schema:
    return pa.schema(
        [
            pa.field(
                f.physical_name if use_physical_names else f.name,
                to_arrow_type(f.dataType),
                f.nullable,
            )
            for f in st.fields
        ]
    )


_ARROW_TO_PRIM = {
    pa.string(): "string",
    pa.large_string(): "string",
    pa.int64(): "long",
    pa.int32(): "integer",
    pa.int16(): "short",
    pa.int8(): "byte",
    pa.float32(): "float",
    pa.float64(): "double",
    pa.bool_(): "boolean",
    pa.binary(): "binary",
    pa.large_binary(): "binary",
    pa.date32(): "date",
}


def from_arrow_type(t: pa.DataType) -> DataType:
    if t in _ARROW_TO_PRIM:
        return PrimitiveType(_ARROW_TO_PRIM[t])
    if pa.types.is_timestamp(t):
        return TIMESTAMP if t.tz is not None else TIMESTAMP_NTZ
    if pa.types.is_decimal(t):
        return decimal(t.precision, t.scale)
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return ArrayType(from_arrow_type(t.value_type))
    if pa.types.is_map(t):
        return MapType(from_arrow_type(t.key_type), from_arrow_type(t.item_type))
    if pa.types.is_struct(t):
        return StructType(
            [
                StructField(t.field(i).name, from_arrow_type(t.field(i).type), t.field(i).nullable)
                for i in range(t.num_fields)
            ]
        )
    from delta_tpu.errors import InvalidArgumentError

    raise InvalidArgumentError(
        f"cannot convert arrow type {t}",
        error_class="DELTA_UNSUPPORTED_DATA_TYPES")


def from_arrow_schema(schema: pa.Schema) -> StructType:
    return StructType(
        [StructField(f.name, from_arrow_type(f.type), f.nullable) for f in schema]
    )

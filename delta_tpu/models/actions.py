"""Typed schemas for transaction-log actions.

Each line of a commit file (`%020d.json`) is a JSON object with exactly one
top-level key naming the action type: `commitInfo`, `protocol`, `metaData`,
`add`, `remove`, `txn`, `domainMetadata`, `cdc`; checkpoint-only actions are
`checkpointMetadata` and `sidecar` (never in commits — PROTOCOL.md:841).
Field lists follow `PROTOCOL.md:418-822`; reference implementations are
spark `actions/actions.scala` and kernel `internal/actions/*.java`.

Design notes for the TPU rebuild:
- Dataclasses keep an `extra` dict so unknown fields from future writers
  round-trip unchanged (forward compatibility).
- `AddFile.stats` stays a raw JSON string here; parsing into columnar
  min/max arrays is the stats module's job (device-side skipping index).
- The replay identity of a logical file is `(path, dv_unique_id)` — see
  `logical_file_key()` — which the device replay hashes to fixed-width
  keys.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, ClassVar, Dict, Iterable, List, Optional


def _prune(d: Dict[str, Any]) -> Dict[str, Any]:
    """Drop None values — Delta JSON omits absent optional fields."""
    return {k: v for k, v in d.items() if v is not None}


@dataclass
class DeletionVectorDescriptor:
    """Pointer to a deletion vector (PROTOCOL.md Deletion Vectors section).

    storageType: 'u' = relative path derived from UUID (pathOrInlineDv =
    `<random prefix><base85 uuid>`), 'i' = inline (base85 bitmap bytes),
    'p' = absolute path.
    """

    storageType: str
    pathOrInlineDv: str
    sizeInBytes: int
    cardinality: int
    offset: Optional[int] = None
    maxRowIndex: Optional[int] = None

    UUID_DV: ClassVar[str] = "u"
    INLINE_DV: ClassVar[str] = "i"
    PATH_DV: ClassVar[str] = "p"

    @property
    def unique_id(self) -> str:
        """Stable identity of this DV, part of the logical-file replay key
        (reference `DeletionVectorDescriptor.scala` uniqueId)."""
        base = self.storageType + self.pathOrInlineDv
        if self.offset is not None:
            return f"{base}@{self.offset}"
        return base

    def to_dict(self) -> Dict[str, Any]:
        return _prune(
            {
                "storageType": self.storageType,
                "pathOrInlineDv": self.pathOrInlineDv,
                "offset": self.offset,
                "sizeInBytes": self.sizeInBytes,
                "cardinality": self.cardinality,
                "maxRowIndex": self.maxRowIndex,
            }
        )

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["DeletionVectorDescriptor"]:
        if d is None:
            return None
        return DeletionVectorDescriptor(
            storageType=d["storageType"],
            pathOrInlineDv=d["pathOrInlineDv"],
            sizeInBytes=int(d["sizeInBytes"]),
            cardinality=int(d["cardinality"]),
            offset=(int(d["offset"]) if d.get("offset") is not None else None),
            maxRowIndex=(int(d["maxRowIndex"]) if d.get("maxRowIndex") is not None else None),
        )


class Action:
    """Base for all log actions. Subclasses set `WRAPPER_KEY` — the single
    top-level JSON key that wraps them in a commit line."""

    WRAPPER_KEY: ClassVar[str] = ""

    def to_dict(self) -> Dict[str, Any]:  # pragma: no cover - abstract
        raise NotImplementedError

    def wrap(self) -> Dict[str, Any]:
        return {self.WRAPPER_KEY: self.to_dict()}

    def to_json(self) -> str:
        return json.dumps(self.wrap(), separators=(",", ":"))


@dataclass
class Format:
    provider: str = "parquet"
    options: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"provider": self.provider, "options": dict(self.options)}

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "Format":
        if d is None:
            return Format()
        return Format(provider=d.get("provider", "parquet"), options=dict(d.get("options") or {}))


@dataclass
class Metadata(Action):
    """Table metadata (`metaData` action). Latest-seen wins in replay."""

    WRAPPER_KEY: ClassVar[str] = "metaData"

    id: str
    schemaString: str = ""
    partitionColumns: List[str] = field(default_factory=list)
    configuration: Dict[str, str] = field(default_factory=dict)
    format: Format = field(default_factory=Format)
    name: Optional[str] = None
    description: Optional[str] = None
    createdTime: Optional[int] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def schema(self):
        from delta_tpu.models.schema import schema_from_json

        return schema_from_json(self.schemaString) if self.schemaString else None

    def to_dict(self) -> Dict[str, Any]:
        d = _prune(
            {
                "id": self.id,
                "name": self.name,
                "description": self.description,
                "format": self.format.to_dict(),
                "schemaString": self.schemaString,
                "partitionColumns": list(self.partitionColumns),
                "configuration": dict(self.configuration),
                "createdTime": self.createdTime,
            }
        )
        d.update(self.extra)
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Metadata":
        known = {
            "id",
            "name",
            "description",
            "format",
            "schemaString",
            "partitionColumns",
            "configuration",
            "createdTime",
        }
        return Metadata(
            id=d["id"],
            name=d.get("name"),
            description=d.get("description"),
            format=Format.from_dict(d.get("format")),
            schemaString=d.get("schemaString", ""),
            partitionColumns=list(d.get("partitionColumns") or []),
            configuration=dict(d.get("configuration") or {}),
            createdTime=d.get("createdTime"),
            extra={k: v for k, v in d.items() if k not in known},
        )


@dataclass
class Protocol(Action):
    """Protocol action: reader/writer version + optional feature sets.

    readerFeatures may only be present at (3, 7); writerFeatures at writer
    version 7 (PROTOCOL.md:844-876).
    """

    WRAPPER_KEY: ClassVar[str] = "protocol"

    minReaderVersion: int = 1
    minWriterVersion: int = 2
    readerFeatures: Optional[List[str]] = None
    writerFeatures: Optional[List[str]] = None

    def reader_feature_set(self) -> frozenset:
        return frozenset(self.readerFeatures or [])

    def writer_feature_set(self) -> frozenset:
        return frozenset(self.writerFeatures or [])

    def to_dict(self) -> Dict[str, Any]:
        return _prune(
            {
                "minReaderVersion": self.minReaderVersion,
                "minWriterVersion": self.minWriterVersion,
                "readerFeatures": (
                    sorted(self.readerFeatures) if self.readerFeatures is not None else None
                ),
                "writerFeatures": (
                    sorted(self.writerFeatures) if self.writerFeatures is not None else None
                ),
            }
        )

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Protocol":
        return Protocol(
            minReaderVersion=int(d.get("minReaderVersion", 1)),
            minWriterVersion=int(d.get("minWriterVersion", 2)),
            readerFeatures=(
                list(d["readerFeatures"]) if d.get("readerFeatures") is not None else None
            ),
            writerFeatures=(
                list(d["writerFeatures"]) if d.get("writerFeatures") is not None else None
            ),
        )


@dataclass
class AddFile(Action):
    """`add` action: a logical file joining the table."""

    WRAPPER_KEY: ClassVar[str] = "add"

    path: str
    partitionValues: Dict[str, Optional[str]] = field(default_factory=dict)
    size: int = 0
    modificationTime: int = 0
    dataChange: bool = True
    stats: Optional[str] = None
    tags: Optional[Dict[str, str]] = None
    deletionVector: Optional[DeletionVectorDescriptor] = None
    baseRowId: Optional[int] = None
    defaultRowCommitVersion: Optional[int] = None
    clusteringProvider: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def dv_unique_id(self) -> Optional[str]:
        return self.deletionVector.unique_id if self.deletionVector else None

    def logical_file_key(self) -> tuple:
        return (self.path, self.dv_unique_id)

    def num_records(self) -> Optional[int]:
        if not self.stats:
            return None
        try:
            return json.loads(self.stats).get("numRecords")
        except (ValueError, AttributeError):
            return None

    def remove(self, deletion_timestamp: int, data_change: bool = True) -> "RemoveFile":
        """Tombstone for this file (reference `actions.scala` AddFile.remove)."""
        return RemoveFile(
            path=self.path,
            deletionTimestamp=deletion_timestamp,
            dataChange=data_change,
            extendedFileMetadata=True,
            partitionValues=dict(self.partitionValues),
            size=self.size,
            stats=self.stats,
            tags=self.tags,
            deletionVector=self.deletionVector,
            baseRowId=self.baseRowId,
            defaultRowCommitVersion=self.defaultRowCommitVersion,
        )

    def to_dict(self) -> Dict[str, Any]:
        d = _prune(
            {
                "path": self.path,
                "partitionValues": dict(self.partitionValues),
                "size": self.size,
                "modificationTime": self.modificationTime,
                "dataChange": self.dataChange,
                "stats": self.stats,
                "tags": self.tags,
                "deletionVector": (
                    self.deletionVector.to_dict() if self.deletionVector else None
                ),
                "baseRowId": self.baseRowId,
                "defaultRowCommitVersion": self.defaultRowCommitVersion,
                "clusteringProvider": self.clusteringProvider,
            }
        )
        d.update(self.extra)
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AddFile":
        known = {
            "path",
            "partitionValues",
            "size",
            "modificationTime",
            "dataChange",
            "stats",
            "tags",
            "deletionVector",
            "baseRowId",
            "defaultRowCommitVersion",
            "clusteringProvider",
        }
        return AddFile(
            path=d["path"],
            partitionValues=dict(d.get("partitionValues") or {}),
            size=int(d.get("size") or 0),
            modificationTime=int(d.get("modificationTime") or 0),
            dataChange=bool(d.get("dataChange", True)),
            stats=d.get("stats"),
            tags=(dict(d["tags"]) if d.get("tags") is not None else None),
            deletionVector=DeletionVectorDescriptor.from_dict(d.get("deletionVector")),
            baseRowId=d.get("baseRowId"),
            defaultRowCommitVersion=d.get("defaultRowCommitVersion"),
            clusteringProvider=d.get("clusteringProvider"),
            extra={k: v for k, v in d.items() if k not in known},
        )


@dataclass
class RemoveFile(Action):
    """`remove` action: a tombstone for a logical file."""

    WRAPPER_KEY: ClassVar[str] = "remove"

    path: str
    deletionTimestamp: Optional[int] = None
    dataChange: bool = True
    extendedFileMetadata: Optional[bool] = None
    partitionValues: Optional[Dict[str, Optional[str]]] = None
    size: Optional[int] = None
    stats: Optional[str] = None
    tags: Optional[Dict[str, str]] = None
    deletionVector: Optional[DeletionVectorDescriptor] = None
    baseRowId: Optional[int] = None
    defaultRowCommitVersion: Optional[int] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def dv_unique_id(self) -> Optional[str]:
        return self.deletionVector.unique_id if self.deletionVector else None

    def logical_file_key(self) -> tuple:
        return (self.path, self.dv_unique_id)

    def to_dict(self) -> Dict[str, Any]:
        d = _prune(
            {
                "path": self.path,
                "deletionTimestamp": self.deletionTimestamp,
                "dataChange": self.dataChange,
                "extendedFileMetadata": self.extendedFileMetadata,
                "partitionValues": self.partitionValues,
                "size": self.size,
                "stats": self.stats,
                "tags": self.tags,
                "deletionVector": (
                    self.deletionVector.to_dict() if self.deletionVector else None
                ),
                "baseRowId": self.baseRowId,
                "defaultRowCommitVersion": self.defaultRowCommitVersion,
            }
        )
        d.update(self.extra)
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "RemoveFile":
        known = {
            "path",
            "deletionTimestamp",
            "dataChange",
            "extendedFileMetadata",
            "partitionValues",
            "size",
            "stats",
            "tags",
            "deletionVector",
            "baseRowId",
            "defaultRowCommitVersion",
        }
        return RemoveFile(
            path=d["path"],
            deletionTimestamp=d.get("deletionTimestamp"),
            dataChange=bool(d.get("dataChange", True)),
            extendedFileMetadata=d.get("extendedFileMetadata"),
            partitionValues=(
                dict(d["partitionValues"]) if d.get("partitionValues") is not None else None
            ),
            size=d.get("size"),
            stats=d.get("stats"),
            tags=(dict(d["tags"]) if d.get("tags") is not None else None),
            deletionVector=DeletionVectorDescriptor.from_dict(d.get("deletionVector")),
            baseRowId=d.get("baseRowId"),
            defaultRowCommitVersion=d.get("defaultRowCommitVersion"),
            extra={k: v for k, v in d.items() if k not in known},
        )


@dataclass
class AddCDCFile(Action):
    """`cdc` action: a change-data file under `_change_data/`. CDC files do
    not participate in add/remove reconciliation."""

    WRAPPER_KEY: ClassVar[str] = "cdc"

    path: str
    partitionValues: Dict[str, Optional[str]] = field(default_factory=dict)
    size: int = 0
    dataChange: bool = False
    tags: Optional[Dict[str, str]] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = _prune(
            {
                "path": self.path,
                "partitionValues": dict(self.partitionValues),
                "size": self.size,
                "dataChange": self.dataChange,
                "tags": self.tags,
            }
        )
        d.update(self.extra)
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AddCDCFile":
        known = {"path", "partitionValues", "size", "dataChange", "tags"}
        return AddCDCFile(
            path=d["path"],
            partitionValues=dict(d.get("partitionValues") or {}),
            size=int(d.get("size") or 0),
            dataChange=bool(d.get("dataChange", False)),
            tags=(dict(d["tags"]) if d.get("tags") is not None else None),
            extra={k: v for k, v in d.items() if k not in known},
        )


@dataclass
class SetTransaction(Action):
    """`txn` action: idempotence watermark per application id. Latest-seen
    version wins per appId."""

    WRAPPER_KEY: ClassVar[str] = "txn"

    appId: str
    version: int
    lastUpdated: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        return _prune(
            {"appId": self.appId, "version": self.version, "lastUpdated": self.lastUpdated}
        )

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SetTransaction":
        return SetTransaction(
            appId=d["appId"], version=int(d["version"]), lastUpdated=d.get("lastUpdated")
        )


@dataclass
class DomainMetadata(Action):
    """`domainMetadata` action: per-domain configuration, latest-seen wins;
    `removed=True` entries are tombstones not returned by reads."""

    WRAPPER_KEY: ClassVar[str] = "domainMetadata"

    domain: str
    configuration: str = ""
    removed: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "domain": self.domain,
            "configuration": self.configuration,
            "removed": self.removed,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DomainMetadata":
        return DomainMetadata(
            domain=d["domain"],
            configuration=d.get("configuration", ""),
            removed=bool(d.get("removed", False)),
        )


@dataclass
class CommitInfo(Action):
    """`commitInfo` action: provenance (operation name/params, engine info,
    ICT). Not part of reconciled state; must be the first line of a commit
    when in-commit timestamps are enabled."""

    WRAPPER_KEY: ClassVar[str] = "commitInfo"

    timestamp: Optional[int] = None
    operation: Optional[str] = None
    operationParameters: Optional[Dict[str, Any]] = None
    operationMetrics: Optional[Dict[str, Any]] = None
    engineInfo: Optional[str] = None
    txnId: Optional[str] = None
    inCommitTimestamp: Optional[int] = None
    isBlindAppend: Optional[bool] = None
    readVersion: Optional[int] = None
    isolationLevel: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = _prune(
            {
                "timestamp": self.timestamp,
                "inCommitTimestamp": self.inCommitTimestamp,
                "operation": self.operation,
                "operationParameters": self.operationParameters,
                "operationMetrics": self.operationMetrics,
                "readVersion": self.readVersion,
                "isolationLevel": self.isolationLevel,
                "isBlindAppend": self.isBlindAppend,
                "engineInfo": self.engineInfo,
                "txnId": self.txnId,
            }
        )
        d.update(self.extra)
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "CommitInfo":
        known = {
            "timestamp",
            "inCommitTimestamp",
            "operation",
            "operationParameters",
            "operationMetrics",
            "readVersion",
            "isolationLevel",
            "isBlindAppend",
            "engineInfo",
            "txnId",
        }
        return CommitInfo(
            timestamp=d.get("timestamp"),
            inCommitTimestamp=d.get("inCommitTimestamp"),
            operation=d.get("operation"),
            operationParameters=d.get("operationParameters"),
            operationMetrics=d.get("operationMetrics"),
            readVersion=d.get("readVersion"),
            isolationLevel=d.get("isolationLevel"),
            isBlindAppend=d.get("isBlindAppend"),
            engineInfo=d.get("engineInfo"),
            txnId=d.get("txnId"),
            extra={k: v for k, v in d.items() if k not in known},
        )


@dataclass
class CheckpointMetadata(Action):
    """V2-checkpoint-only action (never in commits; PROTOCOL.md:841)."""

    WRAPPER_KEY: ClassVar[str] = "checkpointMetadata"

    version: int
    tags: Optional[Dict[str, str]] = None

    def to_dict(self) -> Dict[str, Any]:
        return _prune({"version": self.version, "tags": self.tags})

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "CheckpointMetadata":
        return CheckpointMetadata(version=int(d["version"]), tags=d.get("tags"))


@dataclass
class Sidecar(Action):
    """V2-checkpoint-only pointer to a `_sidecars/<uuid>.parquet` file."""

    WRAPPER_KEY: ClassVar[str] = "sidecar"

    path: str
    sizeInBytes: int = 0
    modificationTime: int = 0
    tags: Optional[Dict[str, str]] = None

    def to_dict(self) -> Dict[str, Any]:
        return _prune(
            {
                "path": self.path,
                "sizeInBytes": self.sizeInBytes,
                "modificationTime": self.modificationTime,
                "tags": self.tags,
            }
        )

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Sidecar":
        return Sidecar(
            path=d["path"],
            sizeInBytes=int(d.get("sizeInBytes") or 0),
            modificationTime=int(d.get("modificationTime") or 0),
            tags=d.get("tags"),
        )


_WRAPPER_TO_CLASS = {
    "add": AddFile,
    "remove": RemoveFile,
    "cdc": AddCDCFile,
    "metaData": Metadata,
    "protocol": Protocol,
    "txn": SetTransaction,
    "domainMetadata": DomainMetadata,
    "commitInfo": CommitInfo,
    "checkpointMetadata": CheckpointMetadata,
    "sidecar": Sidecar,
}


def action_from_json_dict(wrapped: Dict[str, Any]) -> Optional[Action]:
    """Decode one wrapped action object; unknown wrappers return None
    (readers must ignore action types they don't know)."""
    for key, cls in _WRAPPER_TO_CLASS.items():
        body = wrapped.get(key)
        if body is not None:
            return cls.from_dict(body)
    return None


def actions_from_commit_bytes(data: bytes) -> List[Action]:
    """Parse a commit file (newline-delimited JSON) into actions."""
    out: List[Action] = []
    for line in data.split(b"\n"):
        line = line.strip()
        if not line:
            continue
        act = action_from_json_dict(json.loads(line))
        if act is not None:
            out.append(act)
    return out


def actions_to_commit_bytes(actions: Iterable[Action]) -> bytes:
    """Serialize actions to commit-file bytes (one JSON object per line)."""
    return ("\n".join(a.to_json() for a in actions) + "\n").encode("utf-8")

"""delta-tpu: a TPU-native lakehouse framework.

A ground-up reimplementation of the Delta Lake transaction-log protocol
(reference: vkorukanti/delta — PROTOCOL.md) designed for TPU execution:

- The transaction log (`_delta_log/`) is the unit of truth: numbered JSON
  commits, Parquet checkpoints, `_last_checkpoint`, `.crc` checksums.
- Snapshot state reconstruction — the replay of AddFile/RemoveFile actions
  into the live file set — runs as a jitted sort + segmented last-wins
  reduce over `(path_hash, dv_hash, version)` keys on TPU, instead of the
  reference's per-row JVM hash maps (spark `InMemoryLogReplay.scala:38`,
  kernel `ActiveAddFilesIterator.java:54`).
- Data skipping, checkpoint stats, Z-order curve keys, and deletion-vector
  bitmaps reuse the same device columnar kernels.
- All I/O and compute the core needs is behind an Engine SPI mirroring the
  Delta Kernel `Engine` boundary (kernel-api `engine/Engine.java:30`):
  JsonHandler / ParquetHandler / ExpressionHandler / FileSystemClient /
  MetricsReporter.

Public API (mirrors kernel-api `Table.java` / `Snapshot` / `Scan` /
`Transaction` plus the spark-side `DeltaTable` conveniences):

    from delta_tpu import Table
    table = Table.for_path("/data/events")
    snap = table.latest_snapshot()
    files = snap.scan().add_files()
"""

from delta_tpu.version import __version__

# Lazy exports (PEP 562): importing a storage/tools submodule must not
# drag in the full table stack (pyarrow/pandas/jax) — multi-process
# workers and cold-start paths pay ~3s otherwise. `from delta_tpu
# import Table` still works; it just resolves on first access.
_LAZY = {
    "Table": ("delta_tpu.table", "Table"),
    "Snapshot": ("delta_tpu.snapshot", "Snapshot"),
    "Scan": ("delta_tpu.scan", "Scan"),
    "ScanBuilder": ("delta_tpu.scan", "ScanBuilder"),
    "Transaction": ("delta_tpu.txn.transaction", "Transaction"),
    "TransactionBuilder": ("delta_tpu.txn.transaction", "TransactionBuilder"),
    "Operation": ("delta_tpu.txn.transaction", "Operation"),
    "DeltaTable": ("delta_tpu.tables", "DeltaTable"),
    "DeltaError": ("delta_tpu.errors", "DeltaError"),
    "TableNotFoundError": ("delta_tpu.errors", "TableNotFoundError"),
    "ConcurrentModificationError": (
        "delta_tpu.errors", "ConcurrentModificationError"),
    "ProtocolChangedError": ("delta_tpu.errors", "ProtocolChangedError"),
    "MetadataChangedError": ("delta_tpu.errors", "MetadataChangedError"),
    "ConcurrentAppendError": ("delta_tpu.errors", "ConcurrentAppendError"),
    "ConcurrentDeleteReadError": (
        "delta_tpu.errors", "ConcurrentDeleteReadError"),
    "ConcurrentDeleteDeleteError": (
        "delta_tpu.errors", "ConcurrentDeleteDeleteError"),
    "ConcurrentTransactionError": (
        "delta_tpu.errors", "ConcurrentTransactionError"),
    "VersionNotFoundError": ("delta_tpu.errors", "VersionNotFoundError"),
    "CommitFailedError": ("delta_tpu.errors", "CommitFailedError"),
    "InvariantViolationError": (
        "delta_tpu.errors", "InvariantViolationError"),
}

__all__ = ["__version__"] + sorted(_LAZY)


def __getattr__(name):
    import importlib

    try:
        module, attr = _LAZY[name]
    except KeyError:
        # the eager imports used to bind submodules (delta_tpu.errors,
        # delta_tpu.table, ...) as package attributes; keep that working
        try:
            value = importlib.import_module(f"delta_tpu.{name}")
        except ModuleNotFoundError:
            raise AttributeError(
                f"module 'delta_tpu' has no attribute {name!r}")
    else:
        value = getattr(importlib.import_module(module), attr)
    globals()[name] = value  # cache: resolve once
    return value


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))

"""delta-tpu: a TPU-native lakehouse framework.

A ground-up reimplementation of the Delta Lake transaction-log protocol
(reference: vkorukanti/delta — PROTOCOL.md) designed for TPU execution:

- The transaction log (`_delta_log/`) is the unit of truth: numbered JSON
  commits, Parquet checkpoints, `_last_checkpoint`, `.crc` checksums.
- Snapshot state reconstruction — the replay of AddFile/RemoveFile actions
  into the live file set — runs as a jitted sort + segmented last-wins
  reduce over `(path_hash, dv_hash, version)` keys on TPU, instead of the
  reference's per-row JVM hash maps (spark `InMemoryLogReplay.scala:38`,
  kernel `ActiveAddFilesIterator.java:54`).
- Data skipping, checkpoint stats, Z-order curve keys, and deletion-vector
  bitmaps reuse the same device columnar kernels.
- All I/O and compute the core needs is behind an Engine SPI mirroring the
  Delta Kernel `Engine` boundary (kernel-api `engine/Engine.java:30`):
  JsonHandler / ParquetHandler / ExpressionHandler / FileSystemClient /
  MetricsReporter.

Public API (mirrors kernel-api `Table.java` / `Snapshot` / `Scan` /
`Transaction` plus the spark-side `DeltaTable` conveniences):

    from delta_tpu import Table
    table = Table.for_path("/data/events")
    snap = table.latest_snapshot()
    files = snap.scan().add_files()
"""

from delta_tpu.version import __version__
from delta_tpu.table import Table
from delta_tpu.snapshot import Snapshot
from delta_tpu.scan import Scan, ScanBuilder
from delta_tpu.txn.transaction import Transaction, TransactionBuilder, Operation
from delta_tpu.tables import DeltaTable
from delta_tpu.errors import (
    DeltaError,
    TableNotFoundError,
    ConcurrentModificationError,
    ProtocolChangedError,
    MetadataChangedError,
    ConcurrentAppendError,
    ConcurrentDeleteReadError,
    ConcurrentDeleteDeleteError,
    ConcurrentTransactionError,
    VersionNotFoundError,
    CommitFailedError,
    InvariantViolationError,
)

__all__ = [
    "__version__",
    "Table",
    "DeltaTable",
    "Snapshot",
    "Scan",
    "ScanBuilder",
    "Transaction",
    "TransactionBuilder",
    "Operation",
    "DeltaError",
    "TableNotFoundError",
    "ConcurrentModificationError",
    "ProtocolChangedError",
    "MetadataChangedError",
    "ConcurrentAppendError",
    "ConcurrentDeleteReadError",
    "ConcurrentDeleteDeleteError",
    "ConcurrentTransactionError",
    "VersionNotFoundError",
    "CommitFailedError",
    "InvariantViolationError",
]

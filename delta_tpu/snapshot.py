"""Snapshot: an immutable view of the table at one version.

Counterpart of kernel `SnapshotImpl.java` / spark `Snapshot.scala:81`.
State is reconstructed lazily on first access and cached on the object;
`Table` caches the newest snapshot and reuses it across `update()` calls
when the version is unchanged.
"""

from __future__ import annotations

from typing import Dict, Optional

from delta_tpu.log.segment import LogSegment
from delta_tpu.models.actions import DomainMetadata, Metadata, Protocol, SetTransaction
from delta_tpu.replay.state import SnapshotState, reconstruct_state


class Snapshot:
    def __init__(self, table, segment: LogSegment, engine=None):
        self._table = table
        self._segment = segment
        self._engine = engine if engine is not None else table.engine
        self._state: Optional[SnapshotState] = None

    @property
    def version(self) -> int:
        return self._segment.version

    @property
    def log_segment(self) -> LogSegment:
        return self._segment

    @property
    def table_path(self) -> str:
        return self._table.path

    @property
    def state(self) -> SnapshotState:
        if self._state is None:
            self._state = reconstruct_state(self._engine, self._segment)
        return self._state

    @property
    def protocol(self) -> Protocol:
        return self.state.protocol

    @property
    def metadata(self) -> Metadata:
        return self.state.metadata

    @property
    def schema(self):
        return self.state.metadata.schema

    @property
    def partition_columns(self) -> list:
        return list(self.state.metadata.partitionColumns)

    @property
    def timestamp_ms(self) -> int:
        """Commit timestamp of this version: in-commit timestamp when the
        feature is enabled, else file modification time."""
        ci = self.state.commit_infos.get(self.version)
        if ci is not None and ci.inCommitTimestamp is not None:
            return ci.inCommitTimestamp
        return self.state.timestamp_ms

    @property
    def num_files(self) -> int:
        return self.state.num_files

    @property
    def size_in_bytes(self) -> int:
        return self.state.size_in_bytes

    def set_transaction_version(self, app_id: str) -> Optional[int]:
        txn = self.state.set_transactions.get(app_id)
        return txn.version if txn else None

    def set_transactions(self) -> Dict[str, SetTransaction]:
        return dict(self.state.set_transactions)

    def domain_metadata(self, domain: str) -> Optional[DomainMetadata]:
        dm = self.state.domain_metadata.get(domain)
        if dm is None or dm.removed:
            return None
        return dm

    def scan_builder(self):
        from delta_tpu.scan import ScanBuilder

        return ScanBuilder(self)

    def scan(self, filter=None, columns=None):
        b = self.scan_builder()
        if filter is not None:
            b = b.with_filter(filter)
        if columns is not None:
            b = b.with_columns(columns)
        return b.build()

    def table_configuration(self) -> Dict[str, str]:
        return dict(self.state.metadata.configuration)

    def get_config(self, key: str, default=None):
        from delta_tpu.config import TABLE_CONFIGS

        cfg = TABLE_CONFIGS.get(key)
        raw = self.state.metadata.configuration.get(key)
        if cfg is not None:
            return cfg.parse(raw) if raw is not None else (
                cfg.default if default is None else default
            )
        return raw if raw is not None else default

    def __repr__(self):
        return f"Snapshot(path={self._table.path!r}, version={self.version})"

"""Snapshot: an immutable view of the table at one version.

Counterpart of kernel `SnapshotImpl.java` / spark `Snapshot.scala:81`.
State is reconstructed lazily on first access and cached on the object;
`Table` caches the newest snapshot and reuses it across `update()` calls
when the version is unchanged.
"""

from __future__ import annotations

import logging
import re
from typing import Dict, Optional

from delta_tpu import obs
from delta_tpu.log.segment import LogSegment
from delta_tpu.obs import hbm
from delta_tpu.models.actions import DomainMetadata, Metadata, Protocol, SetTransaction
from delta_tpu.replay.state import (
    SmallState,
    SnapshotState,
    reconstruct_small_state,
    reconstruct_state,
)

_log = logging.getLogger(__name__)

_CHECKPOINT_FALLBACKS = obs.counter("snapshot.checkpoint_fallbacks")
_TORN_FALLBACKS = obs.counter("snapshot.torn_commit_fallbacks")
_CRC_QUARANTINED = obs.counter("snapshot.crc_quarantined")

# a commit file in a FileNotFoundError message (vs a checkpoint part)
_COMMIT_JSON_RE = re.compile(r"\d{20}\.json")


class Snapshot:
    def __init__(self, table, segment: LogSegment, engine=None):
        self._table = table
        self._segment = segment
        self._engine = engine if engine is not None else table.engine
        self._state: Optional[SnapshotState] = None
        self._small: Optional[SmallState] = None
        self._pm: Optional[SmallState] = None  # crc-derived P&M only

    @property
    def version(self) -> int:
        return self._segment.version

    @property
    def log_segment(self) -> LogSegment:
        return self._segment

    @property
    def table_path(self) -> str:
        return self._table.path

    @property
    def state(self) -> SnapshotState:
        if self._state is None:
            with obs.span("snapshot.load", table=self._table.path,
                          version=self.version):
                self._state = self._load_state()
        return self._state

    def _load_state(self) -> SnapshotState:
        # ambient table attribution for any device artifact the replay
        # establishes (resident key lanes, checkpoint handoff lanes)
        with hbm.table_scope(self._table.path):
            state = self._replay_degrading(reconstruct_state)
        self._validate_crc(state)
        return state

    def _replay_degrading(self, replay_fn):
        """Run one replay function (full or small state) over the
        segment with the degradation ladder: a corrupt or incomplete
        checkpoint falls back to the previous complete checkpoint (or
        pure JSON replay), and a torn trailing commit — an interrupted
        writer's half-line, not a real commit — falls back to the last
        intact version. Both paths warn and count; corruption that no
        fallback can route around still raises. On fallback the
        snapshot's segment is replaced so later accesses reuse the
        repaired view."""
        import pyarrow as pa

        from delta_tpu.errors import LogCorruptedError, TornCommitError
        from delta_tpu.log.segment import build_log_segment

        seg = self._segment
        while True:
            try:
                state = replay_fn(self._engine, seg)
                break
            except TornCommitError as e:
                torn_v = e.context.get("version")
                if torn_v is None or torn_v != seg.version or torn_v <= 0:
                    # torn line below the tip: the log itself is
                    # damaged, no earlier version is trustworthy
                    raise
                _TORN_FALLBACKS.inc()
                _log.warning(
                    "commit %d of %s has a torn trailing line "
                    "(interrupted write); serving version %d",
                    torn_v, self._table.path, torn_v - 1)
                seg = build_log_segment(
                    self._engine.fs, seg.log_path,
                    target_version=torn_v - 1)
            except (LogCorruptedError, pa.ArrowException,
                    OSError) as e:
                # OSError covers pyarrow's footer/thrift damage too:
                # decoders raise it bare (not via ArrowException) when
                # the parquet magic or metadata length is garbled
                if not seg.checkpoints:
                    raise
                if isinstance(e, FileNotFoundError) and \
                        _COMMIT_JSON_RE.search(str(e)):
                    # a vanished commit file is not a checkpoint
                    # problem — excluding the checkpoint cannot bring
                    # the commit back, so don't burn a rebuild on it
                    raise
                cp_v = seg.checkpoint_version
                _CHECKPOINT_FALLBACKS.inc()
                _log.warning(
                    "checkpoint %d of %s unreadable (%s); rebuilding "
                    "from an earlier checkpoint or the JSON log",
                    cp_v, self._table.path, e)
                seg = build_log_segment(
                    self._engine.fs, seg.log_path,
                    target_version=seg.version,
                    max_checkpoint_version=cp_v - 1)
        if seg is not self._segment:
            self._segment = seg
        return state

    def _validate_crc(self, state: SnapshotState) -> None:
        """Check the replayed state against this version's `.crc` file
        when one exists. A mismatch means the checksum chain is lying —
        quarantine it by reseeding from the (authoritative) replayed
        state, warn and count, and never fail the read: the .crc is an
        accelerator, the log is the source of truth."""
        from delta_tpu.errors import ChecksumMismatchError
        from delta_tpu.log.checksum import (
            read_checksum,
            validate_state_against_checksum,
            write_checksum_from_state,
        )

        try:
            crc = read_checksum(self._engine.fs, self._table.log_path,
                                state.version)
        except Exception as e:
            _log.debug("checksum read failed at version %d (%s)",
                       state.version, e)
            return
        if crc is None:
            return
        try:
            validate_state_against_checksum(state, crc)
        except ChecksumMismatchError as e:
            _CRC_QUARANTINED.inc()
            _log.warning(
                "checksum at version %d of %s disagrees with replayed "
                "state (%s); quarantining by reseeding from state",
                state.version, self._table.path, e)
            try:
                write_checksum_from_state(self._engine,
                                          self._table.log_path, state)
            except Exception as e2:
                _log.debug("checksum reseed failed: %s", e2)

    @property
    def _small_state(self):
        """Small actions WITHOUT the file replay (P&M fast path,
        `Snapshot.scala:440`): metadata-only consumers on a large table
        never pay for decoding the checkpoint's add/remove columns. The
        full state, once materialized, serves as the small state too."""
        if self._state is not None:
            return self._state
        if self._small is None:
            if not self._segment.checkpoints:
                # JSON-only segment: the small projection saves no I/O
                # (there are no parquet columns to skip), but a later
                # full-state access would re-read and re-parse the whole
                # log — reconstruct once and serve both
                with obs.span("snapshot.load", table=self._table.path,
                              version=self.version):
                    self._state = self._load_state()
                return self._state
            with obs.span("snapshot.load_small", table=self._table.path,
                          version=self.version):
                # same degradation ladder as the full load: the small
                # projection reads the same checkpoint parts and commit
                # tail, so a torn artifact must fall back here too
                self._small = self._replay_degrading(reconstruct_small_state)
        return self._small

    @property
    def _pm_state(self):
        """Cheapest protocol/metadata source: full state if present,
        else an already-parsed small state, else this version's `.crc`
        checksum (one tiny read — the reference ChecksumReader path,
        `LogReplay.java:384-426`), else the small-action parse. Only
        protocol/metadata/timestamp come from a crc-derived view — txn
        and domain accessors always use the real small state."""
        if self._state is not None:
            return self._state
        if self._small is not None:
            return self._small
        if self._pm is None:
            from delta_tpu.log.checksum import read_checksum

            try:
                crc = read_checksum(self._engine.fs, self._table.log_path,
                                    self.version)
            except Exception as e:
                # the .crc is an accelerator: unreadable/corrupt means
                # fall back to log replay, never fail the read
                _log.debug("checksum read failed at version %d (%s); "
                           "using log replay", self.version, e)
                crc = None
            if crc is not None:
                from delta_tpu.config import IN_COMMIT_TIMESTAMPS, get_table_config
                from delta_tpu.replay.state import check_read_supported

                if (get_table_config(crc.metadata.configuration,
                                     IN_COMMIT_TIMESTAMPS)
                        and crc.inCommitTimestamp is None):
                    # an older crc without the ICT can't serve
                    # timestamp_ms on an ICT table (monotonicity feeds
                    # the next commit's ICT): use the real small parse
                    return self._small_state
                check_read_supported(crc.protocol)
                ts = self._segment.last_commit_timestamp
                if crc.inCommitTimestamp is not None:
                    ts = crc.inCommitTimestamp
                self._pm = SmallState(
                    version=self.version,
                    protocol=crc.protocol,
                    metadata=crc.metadata,
                    set_transactions={},
                    domain_metadata={},
                    timestamp_ms=ts,
                )
            else:
                return self._small_state
        return self._pm

    @property
    def protocol(self) -> Protocol:
        return self._pm_state.protocol

    @property
    def metadata(self) -> Metadata:
        return self._pm_state.metadata

    @property
    def schema(self):
        return self._pm_state.metadata.schema

    @property
    def partition_columns(self) -> list:
        return list(self._pm_state.metadata.partitionColumns)

    @property
    def timestamp_ms(self) -> int:
        """Commit timestamp of this version: in-commit timestamp when the
        feature is enabled, else file modification time."""
        pm = self._pm_state
        ci = pm.commit_infos.get(self.version)
        if ci is not None and ci.inCommitTimestamp is not None:
            return ci.inCommitTimestamp
        return pm.timestamp_ms

    @property
    def num_files(self) -> int:
        return self.state.num_files

    @property
    def size_in_bytes(self) -> int:
        return self.state.size_in_bytes

    def set_transaction_version(self, app_id: str) -> Optional[int]:
        txn = self._small_state.set_transactions.get(app_id)
        return txn.version if txn else None

    def set_transactions(self) -> Dict[str, SetTransaction]:
        return dict(self._small_state.set_transactions)

    def domain_metadata(self, domain: str) -> Optional[DomainMetadata]:
        dm = self._small_state.domain_metadata.get(domain)
        if dm is None or dm.removed:
            return None
        return dm

    def update(self, engine=None) -> Optional["Snapshot"]:
        """Incrementally advance to the latest version: LIST only commits
        past this one, parse just those, and replay them ON TOP of this
        snapshot's retained state (`SnapshotManagement.updateAfterCommit`
        semantics — one prefix listing, O(new commits) work).

        Returns `self` when nothing new landed (zero reads, zero
        parses), a new Snapshot sharing this one's columnar arrays when
        commits appended cleanly, or None when incremental maintenance
        is unavailable — a checkpoint/compaction boundary intervened, a
        listing gap appeared, or the protocol changed — and the caller
        must fall back to a full `latest_snapshot()` load. The advanced
        state is bit-identical to a cold replay at the same version.
        """
        from delta_tpu.log.segment import (
            _IncrementalUnavailable,
            extend_log_segment,
        )

        eng = engine if engine is not None else self._engine
        with obs.span("snapshot.update", table=self._table.path,
                      from_version=self.version) as sp:
            try:
                ext = extend_log_segment(eng.fs, self._segment)
            except _IncrementalUnavailable:
                sp.set_attr("outcome", "fallback_full_load")
                return None
            if ext is None:
                sp.set_attr("outcome", "unchanged")
                return self
            new_segment, new_deltas = ext
            advanced = self._update_advance(eng, new_segment, new_deltas)
            if advanced is None:
                sp.set_attr("outcome", "fallback_full_load")
            else:
                sp.set_attrs(outcome="advanced",
                             to_version=new_segment.version,
                             new_commits=len(new_deltas))
            return advanced

    def _update_advance(self, eng, new_segment, new_deltas):
        if self._state is None:
            # no replayed state retained to advance — a lazy snapshot
            # over the extended segment costs the same as advancing
            # would, and the parsed-commit cache still spares any
            # re-parse of commits this segment shares with prior loads
            return Snapshot(self._table, new_segment, self._engine)

        import dataclasses

        from delta_tpu.replay.columnar import columnarize_log_segment
        from delta_tpu.replay.state import advance_state

        delta_seg = dataclasses.replace(
            new_segment,
            deltas=new_deltas,
            checkpoints=[],
            compacted_deltas=[],
            checkpoint_version=None,
        )
        # early_replay=False: the delta is replayed host-side by
        # advance_state; an early device dispatch would go unused
        delta = columnarize_log_segment(eng, delta_seg, early_replay=False)
        if delta.protocol is not None:
            # a protocol change can alter how existing actions must be
            # read — never replay across it incrementally
            return None
        with hbm.table_scope(self._table.path):
            new_state = advance_state(eng, self._state, delta, new_segment)
        snap = Snapshot(self._table, new_segment, self._engine)
        snap._state = new_state
        return snap

    def _advanced_with_blobs(self, blobs) -> Optional["Snapshot"]:
        """Advance with commit bytes already in memory (the post-commit
        fast path: a transaction hands over the actions it just wrote,
        so its own commit is never re-listed or re-read). `blobs` is
        [(version, bytes)] contiguous from `self.version + 1`. Returns
        None when this snapshot can't host the advancement (no retained
        state, version gap, or a protocol change in the blobs)."""
        if self._state is None:
            return None
        versions = [v for v, _ in blobs]
        if versions != list(range(self.version + 1,
                                  self.version + 1 + len(blobs))):
            return None
        with obs.span("snapshot.advance_blobs", table=self._table.path,
                      from_version=self.version, commits=len(blobs)):
            return self._advance_with_blobs_inner(blobs, versions)

    def _advance_with_blobs_inner(self, blobs, versions):

        import dataclasses
        import time

        from delta_tpu.replay.columnar import columnarize_commit_blobs
        from delta_tpu.replay.state import advance_state
        from delta_tpu.storage.logstore import FileStatus
        from delta_tpu.utils import filenames

        delta = columnarize_commit_blobs(blobs)
        if delta.protocol is not None:
            return None
        fs = self._engine.fs
        files = []
        last_ts = self._segment.last_commit_timestamp
        for v, data in blobs:
            path = filenames.delta_file(self._table.log_path, v)
            try:
                mtime = fs.file_status(path).modification_time
            except OSError:
                mtime = int(time.time() * 1000)
            files.append(FileStatus(path, len(data), mtime))
            last_ts = max(last_ts, mtime)
        new_segment = dataclasses.replace(
            self._segment,
            version=versions[-1],
            deltas=list(self._segment.deltas) + files,
            last_commit_timestamp=last_ts,
        )
        with hbm.table_scope(self._table.path):
            new_state = advance_state(self._engine, self._state, delta,
                                      new_segment)
        snap = Snapshot(self._table, new_segment, self._engine)
        snap._state = new_state
        return snap

    def scan_builder(self):
        from delta_tpu.scan import ScanBuilder

        return ScanBuilder(self)

    def scan(self, filter=None, columns=None):
        b = self.scan_builder()
        if filter is not None:
            b = b.with_filter(filter)
        if columns is not None:
            b = b.with_columns(columns)
        return b.build()

    def table_configuration(self) -> Dict[str, str]:
        return dict(self._pm_state.metadata.configuration)

    def get_config(self, key: str, default=None):
        from delta_tpu.config import TABLE_CONFIGS

        cfg = TABLE_CONFIGS.get(key)
        raw = self._pm_state.metadata.configuration.get(key)
        if cfg is not None:
            return cfg.parse(raw) if raw is not None else (
                cfg.default if default is None else default
            )
        return raw if raw is not None else default

    def __repr__(self):
        return f"Snapshot(path={self._table.path!r}, version={self.version})"

"""Change Data Feed reader: `table_changes(table, start, end)`.

Reference `commands/cdc/CDCReader.scala:63,485`: for each commit in
range, emit rows with `_change_type`, `_commit_version`,
`_commit_timestamp`. Commits that wrote `cdc` actions are served from
their `_change_data/` files (authoritative — DML wrote exact
pre/post-images); commits without cdc actions synthesize inserts from
data-changing adds and deletes from data-changing removes (reading the
removed file's content).
"""

from __future__ import annotations

from typing import List, Optional

import pyarrow as pa

from delta_tpu.config import ENABLE_CDF, cdf_enabled, get_table_config
from delta_tpu.errors import CdcNotEnabledError, DeltaError
from delta_tpu.models.actions import (
    AddCDCFile,
    AddFile,
    CommitInfo,
    Metadata,
    RemoveFile,
    actions_from_commit_bytes,
)
from delta_tpu.utils import filenames

CDC_TYPE_COL = "_change_type"
COMMIT_VERSION_COL = "_commit_version"
COMMIT_TIMESTAMP_COL = "_commit_timestamp"


def _with_meta(tbl: pa.Table, change_type: Optional[str], version: int, ts: int) -> pa.Table:
    n = tbl.num_rows
    if change_type is not None:
        tbl = tbl.append_column(CDC_TYPE_COL, pa.array([change_type] * n, pa.string()))
    tbl = tbl.append_column(COMMIT_VERSION_COL, pa.array([version] * n, pa.int64()))
    tbl = tbl.append_column(COMMIT_TIMESTAMP_COL, pa.array([ts] * n, pa.int64()))
    return tbl


def table_changes(
    table,
    starting_version: Optional[int] = None,
    ending_version: Optional[int] = None,
    starting_timestamp: Optional[int] = None,
    ending_timestamp: Optional[int] = None,
) -> pa.Table:
    from delta_tpu.errors import InvalidArgumentError

    if starting_version is not None and starting_timestamp is not None:
        # `DeltaErrors.multipleCDCBoundaryException`
        raise InvalidArgumentError(
            "multiple starting arguments provided for CDC read; please "
            "provide one of either startingVersion or startingTimestamp",
            error_class="DELTA_MULTIPLE_CDC_BOUNDARY")
    if ending_version is not None and ending_timestamp is not None:
        raise InvalidArgumentError(
            "multiple ending arguments provided for CDC read; please "
            "provide one of either endingVersion or endingTimestamp",
            error_class="DELTA_MULTIPLE_CDC_BOUNDARY")
    if starting_version is None and starting_timestamp is None:
        # `DeltaErrors.noStartVersionForCDC`
        raise InvalidArgumentError(
            "no startingVersion or startingTimestamp provided for CDC "
            "read", error_class="DELTA_NO_START_FOR_CDC_READ")
    if starting_timestamp is not None:
        # start boundary is AT-OR-AFTER the timestamp (changes
        # committed before the requested time must not be returned)
        from delta_tpu.history import version_at_or_after_timestamp

        starting_version = version_at_or_after_timestamp(
            table, starting_timestamp)
    if ending_timestamp is not None:
        from delta_tpu.history import version_at_timestamp

        ending_version = version_at_timestamp(
            table, ending_timestamp, can_return_last_commit=True)
    snap = table.latest_snapshot()
    conf = snap.metadata.configuration
    if not cdf_enabled(conf):
        raise CdcNotEnabledError(
            "change data feed is not enabled on this table "
            "(set delta.enableChangeDataFeed=true)"
        )
    end = ending_version if ending_version is not None else snap.version
    if end < starting_version:
        from delta_tpu.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"invalid CDC range [{starting_version}, {end}]: start is "
            "after end", error_class="DELTA_INVALID_CDC_RANGE")
    fs = table.engine.fs
    # CDF coverage check (`DeltaErrors.changeDataNotRecordedException`):
    # if the range reaches back before CDF was enabled, those commits
    # never recorded change data and the read must fail rather than
    # silently fabricate it
    enabled = True
    if starting_version <= snap.version:
        try:
            enabled = cdf_enabled(
                table.snapshot_at(starting_version)
                .metadata.configuration)
        except DeltaError:
            pass  # start predates reconstructable history: best effort
    out: List[pa.Table] = []
    for v in range(starting_version, end + 1):
        try:
            data = fs.read_file(filenames.delta_file(table.log_path, v))
        except FileNotFoundError:
            continue
        actions = actions_from_commit_bytes(data)
        metas = [a for a in actions if isinstance(a, Metadata)]
        if metas:
            enabled = cdf_enabled(metas[-1].configuration)
        if not enabled and any(
                isinstance(a, AddCDCFile)
                or (isinstance(a, (AddFile, RemoveFile)) and a.dataChange)
                for a in actions):
            from delta_tpu.errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"error getting change data for range "
                f"[{starting_version}, {end}]: change data was not "
                f"recorded for version {v}",
                error_class="DELTA_MISSING_CHANGE_DATA")
        ts = 0
        for a in actions:
            if isinstance(a, CommitInfo):
                ts = a.inCommitTimestamp or a.timestamp or 0
                break
        cdc_files = [a for a in actions if isinstance(a, AddCDCFile)]
        if cdc_files:
            for c in cdc_files:
                tbl = _read_rel(table, c.path)
                out.append(_with_meta(tbl, None, v, ts))  # _change_type in file
            continue
        for a in actions:
            if isinstance(a, AddFile) and a.dataChange:
                tbl = _read_add_with_partitions(table, snap, a)
                out.append(_with_meta(tbl, "insert", v, ts))
            elif isinstance(a, RemoveFile) and a.dataChange:
                tbl = _read_remove(table, snap, a)
                if tbl is not None:
                    out.append(_with_meta(tbl, "delete", v, ts))
    if not out:
        return pa.table({})
    return pa.concat_tables(out, promote_options="permissive")


def _read_rel(table, rel_path: str) -> pa.Table:
    from delta_tpu.read.reader import _absolute_path

    return next(
        iter(table.engine.parquet.read_parquet_files([_absolute_path(table.path, rel_path)]))
    )


def _read_add_with_partitions(table, snap, add: AddFile) -> pa.Table:
    from delta_tpu.commands.dml import _read_file_with_partitions

    return _read_file_with_partitions(table, snap, add)


def _read_remove(table, snap, remove: RemoveFile) -> Optional[pa.Table]:
    add_like = AddFile(
        path=remove.path,
        partitionValues=dict(remove.partitionValues or {}),
        size=remove.size or 0,
        deletionVector=remove.deletionVector,
    )
    try:
        return _read_add_with_partitions(table, snap, add_like)
    except FileNotFoundError:
        return None  # data file already vacuumed

"""Scan execution: materialize the scanned rows as one Arrow table.

The `DeltaParquetFileFormat` role (`DeltaParquetFileFormat.scala:189`):
per surviving file — read the Parquet data, drop rows deleted by the
file's deletion vector, splice in partition-column values from
`partitionValues`, apply residual filters, project requested columns.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.models.schema import PrimitiveType, to_arrow_type
from delta_tpu.stats.partition import deserialize_partition_value


def _absolute_path(table_path: str, file_path: str) -> str:
    if "://" in file_path or file_path.startswith("/"):
        return file_path
    return f"{table_path}/{file_path}"


def _dv_row_mask(engine, table_path: str, dv_row: dict, num_rows: int) -> Optional[np.ndarray]:
    """Boolean keep-mask from a deletion vector descriptor row (None = keep
    all)."""
    if dv_row is None or dv_row.get("storageType") is None:
        return None
    from delta_tpu.dv.descriptor import load_deletion_vector

    deleted = load_deletion_vector(engine, table_path, dv_row)
    mask = np.ones(num_rows, dtype=bool)
    idx = deleted[deleted < num_rows]
    mask[idx] = False
    return mask


def read_scan(scan) -> pa.Table:
    snapshot = scan.snapshot
    engine = snapshot._engine
    table_path = snapshot.table_path
    schema = snapshot.schema
    partition_columns = snapshot.partition_columns
    files = scan.add_files_table()

    requested = scan.columns
    data_columns = None
    if requested is not None:
        data_columns = [c for c in requested if c not in partition_columns]

    ptypes = {}
    for c in partition_columns:
        dtype = PrimitiveType("string")
        if schema is not None and c in schema:
            f = schema[c]
            if isinstance(f.dataType, PrimitiveType):
                dtype = f.dataType
        ptypes[c] = dtype

    batches: List[pa.Table] = []
    paths = files.column("path").to_pylist()
    pvs = files.column("partition_values").to_pylist()
    dvs = files.column("deletion_vector").to_pylist()
    for path, pv, dv in zip(paths, pvs, dvs):
        abs_path = _absolute_path(table_path, path)
        tbl = next(iter(engine.parquet.read_parquet_files([abs_path], columns=data_columns)))
        mask = _dv_row_mask(engine, table_path, dv, tbl.num_rows)
        if mask is not None:
            tbl = tbl.filter(pa.array(mask))
        pv_dict = {k: v for k, v in pv} if isinstance(pv, list) else (pv or {})
        for c in partition_columns:
            if requested is not None and c not in requested:
                continue
            value = deserialize_partition_value(pv_dict.get(c), ptypes[c])
            arr = pa.array([value] * tbl.num_rows, to_arrow_type(ptypes[c]))
            tbl = tbl.append_column(c, arr)
        batches.append(tbl)

    if not batches:
        cols = requested or (
            [f.name for f in schema.fields] if schema is not None else []
        )
        empty = {}
        for c in cols:
            t = to_arrow_type(schema[c].dataType) if schema and c in schema else pa.string()
            empty[c] = pa.array([], t)
        return pa.table(empty)

    result = pa.concat_tables(batches, promote_options="permissive")
    if scan.filter is not None:
        from delta_tpu.expressions.eval import evaluate_predicate_host

        try:
            keep = evaluate_predicate_host(scan.filter, result)
            result = result.filter(pa.array(keep))
        except KeyError:
            pass  # filter references columns not projected
    if requested is not None:
        result = result.select([c for c in requested if c in result.column_names])
    return result

"""Scan execution: materialize the scanned rows as one Arrow table.

The `DeltaParquetFileFormat` role (`DeltaParquetFileFormat.scala:189`):
per surviving file — read the Parquet data, drop rows deleted by the
file's deletion vector, splice in partition-column values from
`partitionValues`, apply residual filters, project requested columns.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.models.schema import PrimitiveType, to_arrow_type
from delta_tpu.stats.partition import deserialize_partition_value


def _absolute_path(table_path: str, file_path: str) -> str:
    if "://" in file_path or file_path.startswith("/"):
        return file_path
    return f"{table_path}/{file_path}"


def _dv_row_mask(engine, table_path: str, dv_row: dict, num_rows: int) -> Optional[np.ndarray]:
    """Boolean keep-mask from a deletion vector descriptor row (None = keep
    all)."""
    if dv_row is None or dv_row.get("storageType") is None:
        return None
    from delta_tpu.dv.descriptor import load_deletion_vector_mask

    deleted = load_deletion_vector_mask(engine, table_path, dv_row, num_rows)
    return ~deleted


def _align_to_logical(tbl: pa.Table, schema, partition_columns, p2l,
                      needed=None) -> pa.Table:
    """Physical→logical renames + schema alignment for one file's rows:
    dropped columns disappear, columns added after the file was written
    read as null (restricted to `needed` when projecting), and files
    written before a type-widening change cast up."""
    if p2l:
        tbl = tbl.rename_columns([p2l.get(c, c) for c in tbl.column_names])
    if schema is None:
        return tbl
    known = {f.name: f for f in schema.fields if f.name not in partition_columns}
    tbl = tbl.select([c for c in tbl.column_names if c in known])
    for idx, c in enumerate(tbl.column_names):
        target_t = to_arrow_type(known[c].dataType)
        if tbl.schema.field(idx).type != target_t:
            try:
                tbl = tbl.set_column(
                    idx, pa.field(c, target_t), tbl.column(c).cast(target_t))
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                pass  # non-widening mismatch: surface as-is
    for f in schema.fields:
        if f.name in partition_columns or f.name in tbl.column_names:
            continue
        if needed is not None and f.name not in needed:
            continue
        tbl = tbl.append_column(
            f.name, pa.nulls(tbl.num_rows, to_arrow_type(f.dataType)))
    return tbl


def _append_partition_columns(tbl: pa.Table, pv_dict, partition_columns,
                              schema, mapped: bool, needed=None) -> pa.Table:
    """Splice partition-column values (serialized strings in
    `partitionValues`, keyed by physical name under column mapping) back
    into the row set as typed columns."""
    for c in partition_columns:
        if needed is not None and c not in needed:
            continue
        dtype = PrimitiveType("string")
        pv_key = c
        if schema is not None and c in schema:
            f = schema[c]
            if isinstance(f.dataType, PrimitiveType):
                dtype = f.dataType
            if mapped:
                pv_key = f.physical_name
        value = deserialize_partition_value(
            pv_dict.get(pv_key, pv_dict.get(c)), dtype)
        tbl = tbl.append_column(
            c, pa.array([value] * tbl.num_rows, to_arrow_type(dtype)))
    return tbl


def read_add_file_logical(engine, table_path: str, snapshot, add,
                          apply_dv: bool = True) -> pa.Table:
    """Read one AddFile as a logical-schema Arrow table: physical→logical
    column renames, schema alignment (missing columns as null, widened
    types cast up), deletion-vector rows dropped, partition columns
    appended. The shared read half of every file-rewrite command
    (OPTIMIZE / REORG PURGE / copy-on-write DML) — the reference does the
    same via `DeltaParquetFileFormat` (`DeltaParquetFileFormat.scala:189`).
    """
    from delta_tpu.columnmapping import mapping_mode, physical_to_logical_names

    schema = snapshot.schema
    meta = snapshot.metadata
    partition_columns = snapshot.partition_columns
    mapped = mapping_mode(meta.configuration) != "none" and schema is not None
    p2l = physical_to_logical_names(schema) if mapped else {}

    try:
        tbl = next(iter(engine.parquet.read_parquet_files(
            [_absolute_path(table_path, add.path)])))
    except FileNotFoundError as e:
        from delta_tpu.errors import FileNotFoundInLogError

        raise FileNotFoundInLogError(
            f"data file referenced by the log is missing: {add.path} "
            "(removed by VACUUM, or the log is ahead of storage)") from e
    tbl = _align_to_logical(tbl, schema, partition_columns, p2l)
    if apply_dv and add.deletionVector is not None:
        mask = _dv_row_mask(engine, table_path, add.deletionVector.to_dict(),
                            tbl.num_rows)
        if mask is not None:
            tbl = tbl.filter(pa.array(mask))
    return _append_partition_columns(
        tbl, add.partitionValues or {}, partition_columns, schema, mapped)


def read_scan(scan) -> pa.Table:
    from delta_tpu.columnmapping import (
        logical_to_physical_names,
        mapping_mode,
        physical_to_logical_names,
    )

    snapshot = scan.snapshot
    engine = snapshot._engine
    table_path = snapshot.table_path
    schema = snapshot.schema
    meta = snapshot.metadata
    partition_columns = snapshot.partition_columns
    files = scan.add_files_table()

    mapped = mapping_mode(meta.configuration) != "none" and schema is not None
    l2p = logical_to_physical_names(schema) if mapped else {}
    p2l = physical_to_logical_names(schema) if mapped else {}

    requested = scan.columns
    # Columns the residual filter references must be read even when not
    # projected (SELECT name ... WHERE id = 2); projection happens last.
    needed = requested
    if requested is not None and scan.filter is not None:
        refs = [r[0] for r in scan.filter.references()]
        needed = requested + [c for c in dict.fromkeys(refs) if c not in requested]
    data_columns = None
    if needed is not None:
        data_columns = [
            l2p.get(c, c) for c in needed if c not in partition_columns
        ]

    batches: List[pa.Table] = []
    paths = files.column("path").to_pylist()
    pvs = files.column("partition_values").to_pylist()
    dvs = files.column("deletion_vector").to_pylist()
    for path, pv, dv in zip(paths, pvs, dvs):
        abs_path = _absolute_path(table_path, path)
        try:
            tbl = next(
                iter(engine.parquet.read_parquet_files([abs_path], columns=data_columns))
            )
        except (pa.ArrowInvalid, KeyError):
            # file predates newly added columns — read everything it has
            tbl = next(iter(engine.parquet.read_parquet_files([abs_path])))
        tbl = _align_to_logical(tbl, schema, partition_columns, p2l, needed)
        mask = _dv_row_mask(engine, table_path, dv, tbl.num_rows)
        if mask is not None:
            tbl = tbl.filter(pa.array(mask))
        pv_dict = {k: v for k, v in pv} if isinstance(pv, list) else (pv or {})
        tbl = _append_partition_columns(
            tbl, pv_dict, partition_columns, schema, mapped, needed)
        batches.append(tbl)

    if not batches:
        cols = requested or (
            [f.name for f in schema.fields] if schema is not None else []
        )
        empty = {}
        for c in cols:
            t = to_arrow_type(schema[c].dataType) if schema and c in schema else pa.string()
            empty[c] = pa.array([], t)
        return pa.table(empty)

    result = pa.concat_tables(batches, promote_options="permissive")
    if scan.filter is not None:
        from delta_tpu.expressions.eval import evaluate_predicate_host

        try:
            keep = evaluate_predicate_host(scan.filter, result)
            result = result.filter(pa.array(keep))
        except KeyError:
            pass  # filter references columns not projected
    if requested is not None:
        result = result.select([c for c in requested if c in result.column_names])
    return result

"""`delta.tables`-compatible Python surface.

The reference ships `python/delta/tables.py:37` (`DeltaTable`) as the
user-facing API: camelCase methods, string SQL predicates, a fluent
merge builder. This module mirrors that surface 1:1 over the native
engine so a `delta-spark` user can switch with their code shape intact:

    from delta_tpu.tables import DeltaTable
    dt = DeltaTable.forPath("/data/events")
    dt.update(condition="id = 3", set={"v": "'fixed'"})
    (dt.merge(source_arrow, "target.id = source.id")
       .whenMatchedUpdateAll()
       .whenNotMatchedInsertAll()
       .execute())

DataFrames are Arrow tables here (`toDF()` returns `pyarrow.Table`);
conditions and set-expressions accept either SQL strings (parsed by the
expression parser) or `delta_tpu.expressions` trees.
"""

from __future__ import annotations

from typing import Dict, Optional, Union

import pyarrow as pa

from delta_tpu.errors import DeltaError, InvalidArgumentError
from delta_tpu.expressions.parser import parse_expression
from delta_tpu.expressions.tree import Expression
from delta_tpu.table import Table

ExprOrStr = Union[str, Expression, None]


def _expr(e: ExprOrStr):
    if e is None or isinstance(e, Expression):
        return e
    return parse_expression(e)


def _exprs(d: Optional[Dict[str, object]]):
    if d is None:
        return None
    return {k: (_expr(v) if isinstance(v, str) else v)
            for k, v in d.items()}


class DeltaTable:
    """Mirror of the reference `DeltaTable` (python/delta/tables.py:37)."""

    def __init__(self, table: Table):
        self._table = table

    # -- constructors --------------------------------------------------
    @classmethod
    def forPath(cls, path: str, engine=None) -> "DeltaTable":
        t = Table.for_path(path, engine)
        if not t.exists():
            raise InvalidArgumentError(f"{path} is not a Delta table",
                                       error_class="DELTA_MISSING_DELTA_TABLE")
        return cls(t)

    @classmethod
    def forName(cls, name: str, catalog=None) -> "DeltaTable":
        if catalog is None:
            raise InvalidArgumentError("forName requires a catalog")
        return cls(catalog.table(name))

    @classmethod
    def isDeltaTable(cls, path: str) -> bool:
        return Table.for_path(path).exists()

    @classmethod
    def convertToDelta(cls, path: str, partitionSchema=None,
                       engine=None) -> "DeltaTable":
        from delta_tpu.commands.restore import convert_to_delta

        convert_to_delta(path, partition_schema=partitionSchema,
                         engine=engine)
        return cls.forPath(path, engine)

    # -- reads ---------------------------------------------------------
    def toDF(self) -> pa.Table:
        return self._table.latest_snapshot().scan().to_arrow()

    def history(self, limit: Optional[int] = None):
        return [h.to_dict() for h in self._table.history(limit)]

    def detail(self) -> dict:
        from delta_tpu.sql import describe_detail

        return describe_detail(self._table)

    # -- DML -----------------------------------------------------------
    def delete(self, condition: ExprOrStr = None):
        from delta_tpu.commands.dml import delete

        return delete(self._table, predicate=_expr(condition))

    def update(self, condition: ExprOrStr = None,
               set: Optional[Dict[str, object]] = None):
        if not set:
            raise InvalidArgumentError("update requires a set mapping")
        from delta_tpu.commands.dml import update

        return update(self._table, _exprs(set), predicate=_expr(condition))

    def merge(self, source: pa.Table, condition: ExprOrStr
              ) -> "DeltaMergeBuilder":
        from delta_tpu.commands.merge import merge

        return DeltaMergeBuilder(merge(self._table, source,
                                       on=_expr(condition)))

    # -- maintenance ---------------------------------------------------
    def vacuum(self, retentionHours: Optional[float] = None,
               dryRun: bool = False, inventory=None,
               vacuumType: str = "FULL"):
        return self._table.vacuum(retention_hours=retentionHours,
                                  dry_run=dryRun, inventory=inventory,
                                  vacuum_type=vacuumType)

    def optimize(self) -> "DeltaOptimizeBuilder":
        return DeltaOptimizeBuilder(self._table.optimize())

    def generate(self, mode: str) -> None:
        if mode != "symlink_format_manifest":
            raise InvalidArgumentError(f"unsupported generate mode {mode!r}")
        from delta_tpu.commands.generate import generate_symlink_manifest

        generate_symlink_manifest(self._table)

    # -- history management --------------------------------------------
    def restoreToVersion(self, version: int):
        from delta_tpu.commands.restore import restore

        return restore(self._table, version=version)

    def restoreToTimestamp(self, timestamp) -> None:
        from delta_tpu.commands.restore import restore
        from delta_tpu.sql import _timestamp_ms

        ts = (_timestamp_ms(f"'{timestamp}'") if isinstance(timestamp, str)
              else int(timestamp))
        return restore(self._table, timestamp_ms=ts)

    # -- protocol ------------------------------------------------------
    def upgradeTableProtocol(self, readerVersion: int,
                             writerVersion: int) -> None:
        from delta_tpu.commands.alter import upgrade_protocol

        upgrade_protocol(self._table, min_reader=readerVersion,
                         min_writer=writerVersion)

    def addFeatureSupport(self, featureName: str) -> None:
        from delta_tpu.commands.alter import upgrade_protocol

        upgrade_protocol(self._table, feature=featureName)

    def dropFeatureSupport(self, featureName: str,
                           truncateHistory: Optional[bool] = None) -> None:
        from delta_tpu.commands.dropfeature import drop_feature

        drop_feature(self._table, featureName,
                     truncate_history=bool(truncateHistory))

    # -- DDL builders ---------------------------------------------------
    @classmethod
    def create(cls, catalog=None) -> "DeltaTableBuilder":
        return DeltaTableBuilder("create", catalog)

    @classmethod
    def createIfNotExists(cls, catalog=None) -> "DeltaTableBuilder":
        return DeltaTableBuilder("createIfNotExists", catalog)

    @classmethod
    def replace(cls, catalog=None) -> "DeltaTableBuilder":
        return DeltaTableBuilder("replace", catalog)

    @classmethod
    def createOrReplace(cls, catalog=None) -> "DeltaTableBuilder":
        return DeltaTableBuilder("createOrReplace", catalog)

    # escape hatch to the native surface
    @property
    def table(self) -> Table:
        return self._table


class DeltaTableBuilder:
    """DDL builder mirror (reference python/delta/tables.py:1124):
    `DeltaTable.create().location(path).addColumn("id", "BIGINT")
    .partitionedBy("p").property("delta.appendOnly", "true").execute()`.
    `tableName` requires a catalog; `location` works standalone."""

    def __init__(self, mode: str, catalog=None):
        self._mode = mode
        self._catalog = catalog
        self._name: Optional[str] = None
        self._location: Optional[str] = None
        self._comment: Optional[str] = None
        self._columns: list = []
        self._partitioning: list = []
        self._properties: Dict[str, str] = {}

    def tableName(self, identifier: str) -> "DeltaTableBuilder":
        self._name = identifier
        return self

    def location(self, location: str) -> "DeltaTableBuilder":
        self._location = location
        return self

    def comment(self, comment: str) -> "DeltaTableBuilder":
        self._comment = comment
        return self

    def addColumn(self, colName: str, dataType: str,
                  nullable: bool = True,
                  comment: Optional[str] = None) -> "DeltaTableBuilder":
        from delta_tpu.models.schema import PrimitiveType, StructField
        from delta_tpu.sql import normalize_sql_type

        md = {"comment": comment} if comment else {}
        self._columns.append(StructField(
            colName, PrimitiveType(normalize_sql_type(dataType)),
            nullable=nullable, metadata=md))
        return self

    def addColumns(self, cols) -> "DeltaTableBuilder":
        from delta_tpu.models.schema import StructField, StructType

        if isinstance(cols, StructType):
            cols = cols.fields
        cols = list(cols)
        bad = [c for c in cols if not isinstance(c, StructField)]
        if bad:
            raise InvalidArgumentError(
                f"addColumns takes StructFields or a StructType, got "
                f"{type(bad[0]).__name__}")
        self._columns.extend(cols)
        return self

    def partitionedBy(self, *cols: str) -> "DeltaTableBuilder":
        self._partitioning = list(cols)
        return self

    def property(self, key: str, value: str) -> "DeltaTableBuilder":
        self._properties[key] = value
        return self

    def execute(self) -> "DeltaTable":
        from delta_tpu.models.schema import StructType

        if not self._columns:
            raise InvalidArgumentError(
                "table builder requires at least one column",
                error_class="DELTA_TARGET_TABLE_FINAL_SCHEMA_EMPTY")
        if self._location is None:
            if self._name is None or self._catalog is None:
                raise InvalidArgumentError(
                    "table builder needs a location (or a tableName plus "
                    "a catalog)",
                    error_class="DELTA_CREATE_TABLE_MISSING_TABLE_NAME_OR_LOCATION")
            self._location = self._catalog.default_location(self._name)
        table = Table.for_path(self._location)
        # a catalog-name conflict must surface BEFORE any commit, so a
        # typo never leaves an orphaned unregistered table on disk
        if self._name is not None and self._catalog is not None and \
                self._catalog.exists(self._name):
            registered = self._catalog.table(self._name).path
            if registered != table.path:
                raise InvalidArgumentError(
                    f"catalog already maps {self._name!r} to "
                    f"{registered}, not {table.path}",
                    error_class="DELTA_TABLE_LOCATION_MISMATCH")
        exists = table.exists()
        if not exists and self._mode == "replace":
            # matches the reference: replace() demands an existing table
            raise InvalidArgumentError(
                f"table {self._location} cannot be replaced as it does "
                "not exist; use createOrReplace()",
                error_class="DELTA_CANNOT_REPLACE_MISSING_TABLE")
        if exists and self._mode == "create":
            raise InvalidArgumentError(f"table {self._location} already exists",
                                       error_class="DELTA_TABLE_ALREADY_EXISTS")

        import dataclasses

        from delta_tpu.txn.transaction import Operation

        props = dict(self._properties)
        schema = StructType(self._columns)
        if not exists:
            txn = (table.create_transaction_builder(Operation.CREATE_TABLE)
                   .with_schema(schema)
                   .with_partition_columns(self._partitioning)
                   .with_table_properties(props)
                   .build())
            if self._comment:
                txn.update_metadata(dataclasses.replace(
                    txn.metadata(), description=self._comment))
            txn.commit()
        elif self._mode != "createIfNotExists":
            # replace/createOrReplace: new definition, drop old files.
            # Feature-activating properties (column mapping, CDF, DVs,
            # ...) must upgrade the protocol and assign field ids, as
            # the create path and ALTER ... SET TBLPROPERTIES do.
            import time as _t

            from delta_tpu.columnmapping import assign_column_mapping, mapping_mode
            from delta_tpu.features import FEATURES, upgraded_protocol
            from delta_tpu.models.schema import schema_to_json

            txn = table.create_transaction_builder(
                Operation.REPLACE_TABLE).build()
            if mapping_mode(props) != "none":
                schema, props = assign_column_mapping(schema, props)
            new_meta = dataclasses.replace(
                txn.metadata(),
                schemaString=schema_to_json(schema),
                partitionColumns=list(self._partitioning),
                configuration=props,
                description=self._comment,
            )
            proto = txn.protocol()
            for feat in FEATURES.values():
                if feat.activated_by is not None and feat.activated_by(new_meta):
                    proto = upgraded_protocol(proto, feat)
            if proto != txn.protocol():
                txn.update_protocol(proto)
            txn.update_metadata(new_meta)
            for f in txn.scan_files():
                txn.remove_file(f.remove(
                    deletion_timestamp=int(_t.time() * 1000)))
            txn.commit()
        if self._name is not None and self._catalog is not None:
            from delta_tpu.catalog import TableAlreadyExistsError

            try:
                self._catalog.register(self._name, self._location)
            except TableAlreadyExistsError:
                # the pre-check passed, so either it's our own location
                # (fine) or another writer raced us to the name
                registered = self._catalog.table(self._name).path
                if registered != table.path:
                    raise InvalidArgumentError(
                        f"catalog already maps {self._name!r} to "
                        f"{registered}, not {table.path}",
                        error_class="DELTA_TABLE_LOCATION_MISMATCH") from None
        return DeltaTable(table)


class DeltaOptimizeBuilder:
    """camelCase facade over the native OPTIMIZE builder (reference
    python/delta/tables.py:1459)."""

    def __init__(self, builder):
        self._b = builder

    def where(self, partitionFilter: ExprOrStr) -> "DeltaOptimizeBuilder":
        self._b = self._b.where(_expr(partitionFilter))
        return self

    def executeCompaction(self):
        return self._b.execute_compaction()

    def executeZOrderBy(self, *cols: str):
        return self._b.execute_zorder_by(*cols)


class DeltaMergeBuilder:
    """camelCase facade over the native merge builder, mirroring the
    reference's clause set (python/delta/tables.py:757)."""

    def __init__(self, builder):
        self._b = builder

    def withSchemaEvolution(self) -> "DeltaMergeBuilder":
        self._b = self._b.with_schema_evolution()
        return self

    def whenMatchedUpdate(self, condition: ExprOrStr = None,
                          set: Optional[Dict[str, object]] = None
                          ) -> "DeltaMergeBuilder":
        if not set:
            raise InvalidArgumentError("whenMatchedUpdate requires a set mapping")
        self._b = self._b.when_matched_update(set=_exprs(set),
                                              condition=_expr(condition))
        return self

    def whenMatchedUpdateAll(self, condition: ExprOrStr = None
                             ) -> "DeltaMergeBuilder":
        self._b = self._b.when_matched_update_all(condition=_expr(condition))
        return self

    def whenMatchedDelete(self, condition: ExprOrStr = None
                          ) -> "DeltaMergeBuilder":
        self._b = self._b.when_matched_delete(condition=_expr(condition))
        return self

    def whenNotMatchedInsert(self, condition: ExprOrStr = None,
                             values: Optional[Dict[str, object]] = None
                             ) -> "DeltaMergeBuilder":
        if not values:
            raise InvalidArgumentError("whenNotMatchedInsert requires values")
        self._b = self._b.when_not_matched_insert(
            values=_exprs(values), condition=_expr(condition))
        return self

    def whenNotMatchedInsertAll(self, condition: ExprOrStr = None
                                ) -> "DeltaMergeBuilder":
        self._b = self._b.when_not_matched_insert_all(
            condition=_expr(condition))
        return self

    def whenNotMatchedBySourceUpdate(
        self, condition: ExprOrStr = None,
        set: Optional[Dict[str, object]] = None,
    ) -> "DeltaMergeBuilder":
        if not set:
            raise InvalidArgumentError(
                "whenNotMatchedBySourceUpdate requires a set mapping")
        self._b = self._b.when_not_matched_by_source_update(
            set=_exprs(set), condition=_expr(condition))
        return self

    def whenNotMatchedBySourceDelete(self, condition: ExprOrStr = None
                                     ) -> "DeltaMergeBuilder":
        self._b = self._b.when_not_matched_by_source_delete(
            condition=_expr(condition))
        return self

    def execute(self):
        return self._b.execute()

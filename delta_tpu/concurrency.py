"""Deterministic transaction interleaving for concurrency tests.

The rebuild of the reference's phase-locking fuzzer
(`TransactionExecutionObserver.scala:43`, `fuzzer/AtomicBarrier.scala`):
a `PhaseLockingObserver` attached to `Transaction.observer` blocks the
transaction at named phases until the test unblocks it, so two-writer
races are driven to exact interleavings instead of sleeps.

Phases (mirroring the reference's `OptimisticTransactionPhases`:
initialPhase -> preparePhase -> commitPhase -> backfillPhase):

- `before_commit` — before each attempt's prepare+write (initial phase
  exit; fires once per retry attempt);
- `after_prepare` — actions validated and serialized, commit file not
  yet written (the prepare/commit phase boundary: a writer parked here
  holds a fully-prepared commit while others race past it);
- `conflict` — entered the lost-race path;
- `after_backfill` — coordinated-commit only: the coordinator accepted
  the commit (and ran any batch backfill) but the transaction hasn't
  finished;
- `after_commit`.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class AtomicBarrier:
    """unblocked -> (block) -> blocked -> (pass/unblock) -> passed."""

    def __init__(self, blocked: bool = True):
        self._event = threading.Event()
        if not blocked:
            self._event.set()
        self.arrivals = 0
        self._arrived = threading.Event()

    def wait(self, timeout: Optional[float] = 30.0) -> None:
        self.arrivals += 1
        self._arrived.set()
        if not self._event.wait(timeout):
            raise TimeoutError("barrier never unblocked")

    def unblock(self) -> None:
        self._event.set()

    def wait_for_arrival(self, timeout: float = 30.0) -> None:
        if not self._arrived.wait(timeout):
            raise TimeoutError("no transaction arrived at barrier")


class PhaseLockingObserver:
    def __init__(
        self,
        block_before_commit: bool = False,
        block_on_conflict: bool = False,
        block_after_prepare: bool = False,
        block_after_backfill: bool = False,
    ):
        self.before_commit_barrier = AtomicBarrier(blocked=block_before_commit)
        self.conflict_barrier = AtomicBarrier(blocked=block_on_conflict)
        self.after_prepare_barrier = AtomicBarrier(blocked=block_after_prepare)
        self.after_backfill_barrier = AtomicBarrier(
            blocked=block_after_backfill)
        self.events: List[tuple] = []
        self._lock = threading.Lock()

    def _record(self, kind: str, version: int) -> None:
        with self._lock:
            self.events.append((kind, version))

    # -- Transaction hook points -------------------------------------------

    def before_commit_attempt(self, txn, version: int) -> None:
        self._record("attempt", version)
        self.before_commit_barrier.wait()

    def after_prepare(self, txn, version: int) -> None:
        self._record("prepared", version)
        self.after_prepare_barrier.wait()

    def on_commit_conflict(self, txn, version: int) -> None:
        self._record("conflict", version)
        self.conflict_barrier.wait()

    def after_backfill(self, txn, version: int) -> None:
        self._record("backfilled", version)
        self.after_backfill_barrier.wait()

    def after_commit(self, txn, version: int) -> None:
        self._record("committed", version)


def run_txn_async(fn) -> "TxnThread":
    t = TxnThread(fn)
    t.start()
    return t


class TxnThread(threading.Thread):
    def __init__(self, fn):
        super().__init__(daemon=True)
        self._fn = fn
        self.result = None
        self.error: Optional[BaseException] = None

    def run(self):
        try:
            self.result = self._fn()
        except BaseException as e:  # noqa: BLE001 - surfaced to the test
            self.error = e

    def join_result(self, timeout: float = 60.0):
        self.join(timeout)
        if self.is_alive():
            raise TimeoutError("transaction thread did not finish")
        if self.error is not None:
            raise self.error
        return self.result

"""Table properties (`delta.*` keys in `Metadata.configuration`).

The rebuild's `DeltaConfig.scala` analogue: typed accessors with defaults
and validation. Session-level tuning knobs live in `delta_tpu.settings`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


def _parse_bool(s: str) -> bool:
    return s.strip().lower() == "true"


def _parse_interval_ms(s: str) -> int:
    """Parse 'interval <n> <unit>' (Spark CalendarInterval subset) or a
    plain millisecond count."""
    s = s.strip().lower()
    if s.startswith("interval"):
        from delta_tpu.errors import InvalidTablePropertyError

        parts = s.split()
        if len(parts) < 2:
            raise InvalidTablePropertyError(
                "interval value is empty; expected 'interval <n> <unit>'",
                error_class="DELTA_INVALID_CALENDAR_INTERVAL_EMPTY")
        scale = {
            "millisecond": 1,
            "second": 1000,
            "minute": 60_000,
            "hour": 3_600_000,
            "day": 86_400_000,
            "week": 7 * 86_400_000,
        }
        unit = parts[2].rstrip("s") if len(parts) > 2 else "millisecond"
        try:
            n = float(parts[1])
            return int(n * scale[unit])
        except (ValueError, KeyError):
            raise InvalidTablePropertyError(
                f"invalid interval {s!r}; expected 'interval <n> "
                f"<{'|'.join(scale)}>'",
                error_class="DELTA_INVALID_INTERVAL") from None
    return int(s)


@dataclass(frozen=True)
class TableConfig:
    key: str
    default: Any
    parse: Callable[[str], Any]
    doc: str = ""


def _cfg(key: str, default, parse, doc="") -> TableConfig:
    c = TableConfig(key, default, parse, doc)
    TABLE_CONFIGS[key] = c
    return c


TABLE_CONFIGS: Dict[str, TableConfig] = {}

CHECKPOINT_INTERVAL = _cfg(
    "delta.checkpointInterval", 10, int,
    "Write a checkpoint every N commits (reference default 10, "
    "`DeltaConfig.scala:402`).",
)
LOG_RETENTION = _cfg(
    "delta.logRetentionDuration", 30 * 86_400_000, _parse_interval_ms,
    "How long commit files are kept before metadata cleanup (30 days).",
)
TOMBSTONE_RETENTION = _cfg(
    "delta.deletedFileRetentionDuration", 7 * 86_400_000, _parse_interval_ms,
    "How long remove tombstones are kept in checkpoints / how soon VACUUM "
    "may delete data files (7 days).",
)
ENABLE_EXPIRED_LOG_CLEANUP = _cfg(
    "delta.enableExpiredLogCleanup", True, _parse_bool,
    "Clean expired commits after checkpointing.",
)
APPEND_ONLY = _cfg(
    "delta.appendOnly", False, _parse_bool,
    "Reject deletes/updates when true.",
)
ENABLE_CDF = _cfg(
    "delta.enableChangeDataFeed", False, _parse_bool,
    "Write change-data files for DML.",
)
IN_COMMIT_TIMESTAMPS = _cfg(
    "delta.enableInCommitTimestamps", False, _parse_bool,
    "Commit timestamps from commitInfo.inCommitTimestamp (monotonic) "
    "instead of file modification times.",
)
COLUMN_MAPPING_MODE = _cfg(
    "delta.columnMapping.mode", "none", str,
    "none | name | id logical->physical column indirection.",
)
COLUMN_MAPPING_MAX_ID = _cfg("delta.columnMapping.maxColumnId", 0, int)
DATA_SKIPPING_NUM_INDEXED_COLS = _cfg(
    "delta.dataSkippingNumIndexedCols", 32, int,
    "Collect min/max/nullCount stats for the first N leaf columns "
    "(`DataSkippingReader.scala:176`).",
)
DATA_SKIPPING_STATS_COLUMNS = _cfg(
    "delta.dataSkippingStatsColumns", None, lambda s: [c.strip() for c in s.split(",")],
    "Explicit stats column list (overrides the first-N rule).",
)
ROW_TRACKING_ENABLED = _cfg("delta.enableRowTracking", False, _parse_bool)
DELETION_VECTORS_ENABLED = _cfg("delta.enableDeletionVectors", False, _parse_bool)
CHECKPOINT_POLICY = _cfg(
    "delta.checkpointPolicy", "classic", str, "classic | v2",
)
TARGET_FILE_SIZE = _cfg("delta.targetFileSize", 256 * 1024 * 1024, int)
AUTO_OPTIMIZE_AUTO_COMPACT = _cfg("delta.autoOptimize.autoCompact", False, _parse_bool)
OPTIMIZE_WRITE = _cfg("delta.autoOptimize.optimizeWrite", False, _parse_bool)
CHECKPOINT_WRITE_STATS_AS_JSON = _cfg(
    "delta.checkpoint.writeStatsAsJson", True, _parse_bool,
    "Write the per-file stats JSON string into checkpoint add rows "
    "(`Checkpoints.scala` buildCheckpoint stats shaping).",
)
CHECKPOINT_WRITE_STATS_AS_STRUCT = _cfg(
    "delta.checkpoint.writeStatsAsStruct", False, _parse_bool,
    "Additionally write parsed `stats_parsed` structs into checkpoint "
    "add rows (faster skipping for engines that read the struct form).",
)
SET_TXN_RETENTION = _cfg(
    "delta.setTransactionRetentionDuration", None,
    _parse_interval_ms,
    "Expire SetTransaction (streaming idempotence) entries older than "
    "this when writing checkpoints (`InMemoryLogReplay.scala:84-91`). "
    "Default: keep forever.",
)
CHECKPOINT_RETENTION = _cfg(
    "delta.checkpointRetentionDuration", 2 * 86_400_000, _parse_interval_ms,
    "How long shadowed checkpoint files are kept before metadata "
    "cleanup deletes them (reference default 2 days).",
)
RANDOMIZE_FILE_PREFIXES = _cfg(
    "delta.randomizeFilePrefixes", False, _parse_bool,
    "Prefix data file paths with a random bucket instead of partition "
    "directories first — spreads object-store key space under high "
    "write concurrency.",
)
RANDOM_PREFIX_LENGTH = _cfg(
    "delta.randomPrefixLength", 2, int,
    "Length of the random file-prefix bucket when "
    "delta.randomizeFilePrefixes is on.",
)


def _parse_isolation(s: str) -> str:
    lv = s.strip()
    if lv not in ("Serializable", "WriteSerializable",
                  "SnapshotIsolation"):
        from delta_tpu.errors import InvalidTablePropertyError

        raise InvalidTablePropertyError(
            f"invalid delta.isolationLevel {s!r}",
            error_class="DELTA_INVALID_ISOLATION_LEVEL")
    return lv


def _parse_formats(s: str):
    out = [f.strip().lower() for f in s.split(",") if f.strip()]
    bad = [f for f in out if f not in ("iceberg", "hudi")]
    if bad:
        raise ValueError(
            f"invalid delta.universalFormat.enabledFormats entries {bad}")
    return out


# -- remainder of the reference's DeltaConfig catalog
# (`DeltaConfig.scala`, 46 buildConfig entries) -------------------------

MIN_READER_VERSION = _cfg(
    "delta.minReaderVersion", 1, int,
    "Protocol floor at table creation (consumed, not persisted); "
    "enforced in features.protocol_for_new_table.",
)
MIN_WRITER_VERSION = _cfg("delta.minWriterVersion", 2, int)
IGNORE_PROTOCOL_DEFAULTS = _cfg(
    "delta.ignoreProtocolDefaults", False, _parse_bool,
    "Drop the (1,2) creation default to the protocol minimum (1,1).",
)
SAMPLE_RETENTION = _cfg(
    "delta.sampleRetentionDuration", 7 * 86_400_000, _parse_interval_ms,
    "Retention for sampled tables (reference default 7 days). Registered for parse/compat; no sampling subsystem consults it yet.",
)
ENABLE_FULL_RETENTION_ROLLBACK = _cfg(
    "delta.enableFullRetentionRollback", True, _parse_bool,
    "Allow RESTORE to any version within logRetentionDuration. Registered; RESTORE does not enforce a shorter window yet.",
)
DROP_FEATURE_TRUNCATE_RETENTION = _cfg(
    "delta.dropFeatureTruncateHistory.retentionDuration",
    24 * 3_600_000, _parse_interval_ms,
    "History-truncation wait for DROP FEATURE (24 hours); consumed by "
    "commands/dropfeature.py.",
)
ENABLE_CDC_ALIAS = _cfg(
    "delta.enableChangeDataCapture", False, _parse_bool,
    "Legacy alias of delta.enableChangeDataFeed (honored everywhere via config.cdf_enabled).",
)
ISOLATION_LEVEL = _cfg(
    "delta.isolationLevel", "WriteSerializable", _parse_isolation,
    "Serializable | WriteSerializable | SnapshotIsolation "
    "(txn/isolation.py).",
)
ICT_ENABLEMENT_VERSION = _cfg(
    "delta.inCommitTimestampEnablementVersion", None, int,
    "Version at which inCommitTimestamps were enabled (written by the "
    "txn when the feature turns on mid-history; history.py reads it).",
)
ICT_ENABLEMENT_TIMESTAMP = _cfg(
    "delta.inCommitTimestampEnablementTimestamp", None, int,
    "Timestamp pair of inCommitTimestampEnablementVersion.",
)
REQUIRE_CHECKPOINT_PROTECTION = _cfg(
    "delta.requireCheckpointProtectionBeforeVersion", 0, int,
    "Metadata cleanup must not rewrite checkpoints covering versions "
    "below this (checkpoint-protection table feature). Registered; "
    "log cleanup does not consult it yet.",
)
UNIFORM_ENABLED_FORMATS = _cfg(
    "delta.universalFormat.enabledFormats", [], _parse_formats,
    "UniForm targets: iceberg and/or hudi (interop/ converters run as "
    "post-commit hooks).",
)
ICEBERG_COMPAT_V1 = _cfg(
    "delta.enableIcebergCompatV1", False, _parse_bool,
    "IcebergCompat v1 invariants (icebergcompat.py).",
)
ICEBERG_COMPAT_V2 = _cfg(
    "delta.enableIcebergCompatV2", False, _parse_bool,
    "IcebergCompat v2 invariants (icebergcompat.py).",
)
CAST_ICEBERG_TIME_TYPE = _cfg(
    "delta.castIcebergTimeType", False, _parse_bool,
    "Cast Iceberg TIME columns to long on conversion. Registered for parse/compat; the Iceberg converter has no TIME source type yet.",
)
AUTO_OPTIMIZE_LEGACY = _cfg(
    "delta.autoOptimize", False, _parse_bool,
    "Legacy umbrella switch implying autoCompact (honored by hooks.auto_compact_hook).",
)
COORDINATED_COMMITS_COORDINATOR = _cfg(
    "delta.coordinatedCommits.commitCoordinator-preview", None, str,
    "Commit-coordinator name; presence routes commits through "
    "coordinatedcommits/ instead of LogStore put-if-absent.",
)
COORDINATED_COMMITS_COORDINATOR_CONF = _cfg(
    "delta.coordinatedCommits.commitCoordinatorConf-preview", None, str,
    "JSON configuration blob for the commit coordinator.",
)
COORDINATED_COMMITS_TABLE_CONF = _cfg(
    "delta.coordinatedCommits.tableConf-preview", None, str,
    "Coordinator-issued per-table configuration blob.",
)
REDIRECT_READER_WRITER = _cfg(
    "delta.redirectReaderWriter-preview", None, str,
    "Table-redirect spec (reads + writes routed to another table). Registered for parse/compat; redirects are not implemented.",
)
REDIRECT_WRITER_ONLY = _cfg(
    "delta.redirectWriterOnly-preview", None, str,
    "Table-redirect spec for writes only. Registered for parse/compat; redirects are not implemented.",
)
ENABLE_TYPE_WIDENING = _cfg(
    "delta.enableTypeWidening", False, _parse_bool,
    "Allow in-place widening type changes (schema_evolution.py).",
)
SYMLINK_MANIFEST_ENABLED = _cfg(
    "delta.compatibility.symlinkFormatManifest.enabled", False,
    _parse_bool,
    "Regenerate the symlink manifest after every commit "
    "(commands/generate.py + hooks).",
)


def get_table_config(configuration: Dict[str, str], cfg: TableConfig):
    raw = configuration.get(cfg.key)
    if raw is None:
        return cfg.default
    return cfg.parse(raw)


def cdf_enabled(configuration: Dict[str, str]) -> bool:
    """Change data feed on? Honors both delta.enableChangeDataFeed and
    its legacy alias delta.enableChangeDataCapture (the reference keeps
    both keys live)."""
    return (get_table_config(configuration, ENABLE_CDF)
            or get_table_config(configuration, ENABLE_CDC_ALIAS))


@dataclass
class Settings:
    """Session-level knobs (the `DeltaSQLConf` analogue, pared to what the
    engine actually consults)."""

    max_commit_retries: int = 200            # spark DELTA_MAX_RETRY default
    checkpoint_part_size: Optional[int] = None  # actions per checkpoint part
    replay_min_device_rows: int = 4096       # below this, host replay wins
    stats_collection_enabled: bool = True
    write_checksum_enabled: bool = True
    vacuum_parallelism: int = 16
    verify_checkpoint_row_count: bool = True


settings = Settings()


# properties recognized beyond the typed registry: feature-support
# flags and constraint definitions carry open-ended suffixes
_OPEN_PREFIXES = ("delta.feature.", "delta.constraints.")


def validate_table_properties(properties: Dict[str, str]) -> None:
    """SET-time validation (`DeltaConfigs.validateConfigurations`):
    unknown `delta.`-namespace keys are rejected (typo protection — a
    misspelled property would otherwise silently do nothing), and known
    keys must parse."""
    from delta_tpu.errors import DeltaError, InvalidTablePropertyError

    for k, v in properties.items():
        if not k.startswith("delta.") or k.startswith(_OPEN_PREFIXES):
            continue
        cfg = TABLE_CONFIGS.get(k)
        if cfg is None:
            raise InvalidTablePropertyError(
                f"Unknown configuration was specified: {k}",
                error_class="DELTA_UNKNOWN_CONFIGURATION")
        if cfg.parse is _parse_bool and \
                str(v).strip().lower() not in ("true", "false"):
            # the read path is lenient (anything != 'true' is False),
            # so SET must be strict or a typo'd boolean silently
            # flips the property off
            if k == "delta.autoOptimize.autoCompact":
                raise InvalidTablePropertyError(
                    f"Invalid auto-compact type: {v}. Allowed values "
                    "are: (true, false)",
                    error_class="DELTA_INVALID_AUTO_COMPACT_TYPE")
            raise InvalidTablePropertyError(
                f"The validation of the properties of the table has "
                f"been violated: {k}={v!r} is not a boolean",
                error_class="DELTA_VIOLATE_TABLE_PROPERTY_VALIDATION_FAILED")
        try:
            cfg.parse(str(v))
        except DeltaError:
            raise
        except Exception as e:
            raise InvalidTablePropertyError(
                f"The validation of the properties of the table has "
                f"been violated: {k}={v!r} ({e})",
                error_class="DELTA_VIOLATE_TABLE_PROPERTY_VALIDATION_FAILED")

"""Table properties (`delta.*` keys in `Metadata.configuration`).

The rebuild's `DeltaConfig.scala` analogue: typed accessors with defaults
and validation. Session-level tuning knobs live in `delta_tpu.settings`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


def _parse_bool(s: str) -> bool:
    return s.strip().lower() == "true"


def _parse_interval_ms(s: str) -> int:
    """Parse 'interval <n> <unit>' (Spark CalendarInterval subset) or a
    plain millisecond count."""
    s = s.strip().lower()
    if s.startswith("interval"):
        parts = s.split()
        n = float(parts[1])
        unit = parts[2].rstrip("s") if len(parts) > 2 else "millisecond"
        scale = {
            "millisecond": 1,
            "second": 1000,
            "minute": 60_000,
            "hour": 3_600_000,
            "day": 86_400_000,
            "week": 7 * 86_400_000,
        }[unit]
        return int(n * scale)
    return int(s)


@dataclass(frozen=True)
class TableConfig:
    key: str
    default: Any
    parse: Callable[[str], Any]
    doc: str = ""


def _cfg(key: str, default, parse, doc="") -> TableConfig:
    c = TableConfig(key, default, parse, doc)
    TABLE_CONFIGS[key] = c
    return c


TABLE_CONFIGS: Dict[str, TableConfig] = {}

CHECKPOINT_INTERVAL = _cfg(
    "delta.checkpointInterval", 10, int,
    "Write a checkpoint every N commits (reference default 10, "
    "`DeltaConfig.scala:402`).",
)
LOG_RETENTION = _cfg(
    "delta.logRetentionDuration", 30 * 86_400_000, _parse_interval_ms,
    "How long commit files are kept before metadata cleanup (30 days).",
)
TOMBSTONE_RETENTION = _cfg(
    "delta.deletedFileRetentionDuration", 7 * 86_400_000, _parse_interval_ms,
    "How long remove tombstones are kept in checkpoints / how soon VACUUM "
    "may delete data files (7 days).",
)
ENABLE_EXPIRED_LOG_CLEANUP = _cfg(
    "delta.enableExpiredLogCleanup", True, _parse_bool,
    "Clean expired commits after checkpointing.",
)
APPEND_ONLY = _cfg(
    "delta.appendOnly", False, _parse_bool,
    "Reject deletes/updates when true.",
)
ENABLE_CDF = _cfg(
    "delta.enableChangeDataFeed", False, _parse_bool,
    "Write change-data files for DML.",
)
IN_COMMIT_TIMESTAMPS = _cfg(
    "delta.enableInCommitTimestamps", False, _parse_bool,
    "Commit timestamps from commitInfo.inCommitTimestamp (monotonic) "
    "instead of file modification times.",
)
COLUMN_MAPPING_MODE = _cfg(
    "delta.columnMapping.mode", "none", str,
    "none | name | id logical->physical column indirection.",
)
COLUMN_MAPPING_MAX_ID = _cfg("delta.columnMapping.maxColumnId", 0, int)
DATA_SKIPPING_NUM_INDEXED_COLS = _cfg(
    "delta.dataSkippingNumIndexedCols", 32, int,
    "Collect min/max/nullCount stats for the first N leaf columns "
    "(`DataSkippingReader.scala:176`).",
)
DATA_SKIPPING_STATS_COLUMNS = _cfg(
    "delta.dataSkippingStatsColumns", None, lambda s: [c.strip() for c in s.split(",")],
    "Explicit stats column list (overrides the first-N rule).",
)
ROW_TRACKING_ENABLED = _cfg("delta.enableRowTracking", False, _parse_bool)
DELETION_VECTORS_ENABLED = _cfg("delta.enableDeletionVectors", False, _parse_bool)
CHECKPOINT_POLICY = _cfg(
    "delta.checkpointPolicy", "classic", str, "classic | v2",
)
TARGET_FILE_SIZE = _cfg("delta.targetFileSize", 256 * 1024 * 1024, int)
AUTO_OPTIMIZE_AUTO_COMPACT = _cfg("delta.autoOptimize.autoCompact", False, _parse_bool)
OPTIMIZE_WRITE = _cfg("delta.autoOptimize.optimizeWrite", False, _parse_bool)
CHECKPOINT_WRITE_STATS_AS_JSON = _cfg(
    "delta.checkpoint.writeStatsAsJson", True, _parse_bool,
    "Write the per-file stats JSON string into checkpoint add rows "
    "(`Checkpoints.scala` buildCheckpoint stats shaping).",
)
CHECKPOINT_WRITE_STATS_AS_STRUCT = _cfg(
    "delta.checkpoint.writeStatsAsStruct", False, _parse_bool,
    "Additionally write parsed `stats_parsed` structs into checkpoint "
    "add rows (faster skipping for engines that read the struct form).",
)
SET_TXN_RETENTION = _cfg(
    "delta.setTransactionRetentionDuration", None,
    _parse_interval_ms,
    "Expire SetTransaction (streaming idempotence) entries older than "
    "this when writing checkpoints (`InMemoryLogReplay.scala:84-91`). "
    "Default: keep forever.",
)
CHECKPOINT_RETENTION = _cfg(
    "delta.checkpointRetentionDuration", 2 * 86_400_000, _parse_interval_ms,
    "How long shadowed checkpoint files are kept before metadata "
    "cleanup deletes them (reference default 2 days).",
)
RANDOMIZE_FILE_PREFIXES = _cfg(
    "delta.randomizeFilePrefixes", False, _parse_bool,
    "Prefix data file paths with a random bucket instead of partition "
    "directories first — spreads object-store key space under high "
    "write concurrency.",
)
RANDOM_PREFIX_LENGTH = _cfg(
    "delta.randomPrefixLength", 2, int,
    "Length of the random file-prefix bucket when "
    "delta.randomizeFilePrefixes is on.",
)


def get_table_config(configuration: Dict[str, str], cfg: TableConfig):
    raw = configuration.get(cfg.key)
    if raw is None:
        return cfg.default
    return cfg.parse(raw)


@dataclass
class Settings:
    """Session-level knobs (the `DeltaSQLConf` analogue, pared to what the
    engine actually consults)."""

    max_commit_retries: int = 200            # spark DELTA_MAX_RETRY default
    checkpoint_part_size: Optional[int] = None  # actions per checkpoint part
    replay_min_device_rows: int = 4096       # below this, host replay wins
    stats_collection_enabled: bool = True
    write_checksum_enabled: bool = True
    vacuum_parallelism: int = 16
    verify_checkpoint_row_count: bool = True


settings = Settings()

"""Device-memory observability: the process-wide ResidentLedger.

PRs 7/14/16 moved replay key lanes, scan-planning stats indexes, and
checkpoint decode handoff codes into HBM — and each artifact managed
its own lifecycle with at best an ad-hoc gauge. ROADMAP item 6 (HBM as
a managed fleet cache over thousands of tenant tables) needs one budget
view instead: every device-resident artifact registers here at
creation, carrying ``(table_path, kind, version, nbytes,
rebuild_cost_class, created_at, last_access)``, touches on read, grows
in place on donated-buffer appends, and releases on eviction or
version advance. Three surfaces sit on the ledger:

- **Reconciliation audit** (`audit()`) — the runtime twin of the
  transfer-budget audit: every registered artifact's device arrays are
  weakly referenced, and the audit cross-checks them against
  ``jax.live_arrays()`` — an array gone without `release()`, or a byte
  count that no longer matches what was registered (an unrecorded
  grow), is drift. **Leak detection** rides `weakref.finalize`: an
  owner GC'd without `release()` bumps ``hbm.resident_leaks`` and is
  auto-deregistered so the gauges never go stale; ``strict`` mode
  makes the next `audit()` raise on both drift and leaks.
- **Ledger-derived gauges** — ``hbm.resident_bytes`` /
  ``hbm.resident_artifacts`` / ``hbm.resident_bytes_peak``, plus the
  pre-ledger names ``replay.resident_hbm_bytes`` and
  ``scan.stats_index_hbm_bytes`` re-derived as per-kind totals (same
  exported names, no dashboard break). Release and leak events ride
  the active span into the flight recorder.
- **`delta-hbm` CLI** (`tools/hbm_cli.py`) — rollups by table/kind,
  top-N residents, leak report, all from `dump_ledger()` JSONL.

Gating mirrors `device.py`: ``DELTA_TPU_HBM_OBS=off|on|strict`` — but
the default is **on**: ledger ops run at artifact-lifecycle frequency
(per snapshot load/advance/eviction, not per row), and the subsumed
gauges must stay live by default. ``off`` is a true no-op —
`register()` returns a process-wide stateless singleton handle whose
`touch`/`grow`/`release` do nothing (the bench's
``hbm_accounting_overhead_pct`` gate measures exactly this path).
``strict`` arms raise-on-drift/leak in `audit()` for tests and canary
lanes.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import logging
import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

from delta_tpu.obs import trace as _trace
from delta_tpu.obs.registry import counter, gauge

_log = logging.getLogger(__name__)

MODE_OFF = 0
MODE_ON = 1
MODE_STRICT = 2

_MODES = {"off": MODE_OFF, "on": MODE_ON, "strict": MODE_STRICT,
          "0": MODE_OFF, "1": MODE_ON, "2": MODE_STRICT}

# artifact kinds currently registered by the instrumented owners; the
# per-kind gauges below key on these (free-form strings are accepted —
# a new resident subsystem just picks a new kind)
KIND_REPLAY_KEYS = "replay-keys"      # parallel/resident.py
KIND_STATS_INDEX = "stats-index"      # stats/device_index.py
KIND_CKPT_HANDOFF = "ckpt-handoff"    # ops/page_decode.py (transient)
KIND_SQL_OPERANDS = "sql-operands"    # sqlengine/operands.py

UNKNOWN_TABLE = "unknown"

_LEAK_RING = 256


def _mode_from_env() -> int:
    raw = os.environ.get("DELTA_TPU_HBM_OBS", "on").strip().lower()
    mode = _MODES.get(raw)
    if mode is None:
        _log.warning("unknown DELTA_TPU_HBM_OBS=%r; hbm obs stays on", raw)
        return MODE_ON
    return mode


_mode: int = _mode_from_env()


def hbm_obs_mode() -> int:
    return _mode


def hbm_obs_enabled() -> bool:
    return _mode != MODE_OFF


def set_hbm_obs_mode(mode: Optional[str]) -> None:
    """Programmatically set the ledger mode ('off'|'on'|'strict');
    None re-reads `DELTA_TPU_HBM_OBS`. Tests and bench use this;
    production uses the env var."""
    global _mode
    if mode is None:
        _mode = _mode_from_env()
    else:
        try:
            _mode = _MODES[mode.strip().lower()]
        except KeyError:
            raise ValueError(
                f"unknown hbm obs mode {mode!r}; expected off|on|strict"
            ) from None


# -- instruments (resolved once; see resources/metric_names.json) ------------

_REGISTRATIONS = counter("hbm.registrations")
_RELEASES = counter("hbm.releases")
_LEAKS = counter("hbm.resident_leaks")
_SHEDS = counter("hbm.sheds")
_SHED_BYTES = counter("hbm.shed_bytes")


# -- ambient table scope -----------------------------------------------------

# Registration sites deep in the replay/decode stack don't receive the
# table path; `Snapshot` opens this scope around load/update so every
# artifact established inside lands under the right table in rollups.
_SCOPE: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "delta_tpu_hbm_table_scope", default=None)


@contextlib.contextmanager
def table_scope(table_path: Optional[str]):
    """Attribute every `register()` inside the block (that doesn't pass
    an explicit ``table_path``) to `table_path`."""
    token = _SCOPE.set(table_path)
    try:
        yield
    finally:
        _SCOPE.reset(token)


def current_table_scope() -> Optional[str]:
    return _SCOPE.get()


# -- handles -----------------------------------------------------------------


class _NoopHandle:
    """Disabled-path singleton: stateless, reentrant, thread-safe.
    Every lifecycle method is a no-op so instrumented sites read
    identically in both modes."""

    __slots__ = ()

    def touch(self) -> None:
        pass

    def grow(self, arrays: Sequence[object] = (),
             nbytes: Optional[int] = None) -> None:
        pass

    def release(self) -> None:
        pass


_NOOP_HANDLE = _NoopHandle()


def noop_handle() -> _NoopHandle:
    """The shared no-op handle — a safe initial value for owner slots
    (`self._hbm = hbm.noop_handle()`) so touch/release never need a
    None check."""
    return _NOOP_HANDLE


def _sum_nbytes(arrays: Sequence[object]) -> int:
    return sum(int(getattr(a, "nbytes", 0) or 0) for a in arrays)


def _wrap_evictor(evictor):
    """Normalize an evictor into a zero-arg resolver -> callable|None.

    Bound methods are held via `weakref.WeakMethod`: a strong reference
    from the ledger to the owner would keep the owner alive forever and
    blind the finalize-based leak detector. Free functions are held
    strongly (they don't pin an owner)."""
    if evictor is None:
        return None
    if getattr(evictor, "__self__", None) is not None:
        return weakref.WeakMethod(evictor)
    return lambda: evictor


# Shed ordering: cheapest-to-rebuild first, then least-recently-used.
# Unknown classes sort with "normal"; "transient" artifacts are
# mid-flight handoffs — evicting one tears an in-progress decode, so
# they rank just above "expensive" and in practice never register an
# evictor at all.
_SHED_COST_RANK = {"cheap": 0, "normal": 1, "transient": 2, "expensive": 3}


class ResidentHandle:
    """Ledger entry for one device-resident artifact. Obtained from
    `register()`; the owner calls `touch()` on read paths, `grow()`
    when a donated in-place append swaps/extends the device buffer,
    and `release()` exactly once at end of life (idempotent)."""

    __slots__ = ("table_path", "kind", "version", "nbytes",
                 "rebuild_cost_class", "created_at", "last_access",
                 "_seq", "_ledger", "_refs", "_finalizer", "_released",
                 "_evictor")

    def __init__(self, ledger: "ResidentLedger", seq: int, table_path: str,
                 kind: str, version: Optional[int], nbytes: int,
                 rebuild_cost_class: str, refs):
        self.table_path = table_path
        self.kind = kind
        self.version = version
        self.nbytes = nbytes
        self.rebuild_cost_class = rebuild_cost_class
        self.created_at = time.time()
        self.last_access = self.created_at
        self._seq = seq
        self._ledger = ledger
        self._refs = refs          # list of weakref.ref | None (untracked)
        self._finalizer = None     # wired by ResidentLedger.register
        self._released = False
        self._evictor = None       # zero-arg resolver -> callable | None

    def touch(self) -> None:
        """Record an access (recency feeds future eviction policy)."""
        if not self._released:
            self.last_access = time.time()
            # plain int add: telemetry tolerance, same trade as Counter
            self._ledger.touches += 1

    def grow(self, arrays: Sequence[object] = (),
             nbytes: Optional[int] = None) -> None:
        """Re-account an in-place buffer swap/growth: `arrays` re-point
        the audit weakrefs (a donated append yields a NEW device array
        object at the same logical artifact), `nbytes` overrides the
        recomputed total."""
        self._ledger._grow(self, arrays, nbytes)

    def release(self) -> None:
        """Deregister (idempotent): the artifact's device memory is
        being dropped on purpose."""
        self._ledger._release(self)

    def to_dict(self) -> Dict[str, object]:
        return {
            "type": "hbm_resident",
            "seq": self._seq,
            "table_path": self.table_path,
            "kind": self.kind,
            "version": self.version,
            "nbytes": self.nbytes,
            "rebuild_cost_class": self.rebuild_cost_class,
            "created_at": self.created_at,
            "last_access": self.last_access,
        }


# -- the ledger --------------------------------------------------------------


class ResidentLedger:
    """Process-wide registry of device-resident artifacts.

    The lock is reentrant on purpose: `weakref.finalize` leak callbacks
    run whenever the cyclic GC happens to fire — including during an
    allocation made while a ledger method already holds the lock."""

    def __init__(self):
        self._lock = threading.RLock()
        self._handles: Dict[int, ResidentHandle] = {}
        self._next_seq = 1
        self._total = 0
        self._peak = 0
        self._leaks: collections.deque = collections.deque(maxlen=_LEAK_RING)
        self.touches = 0

    # -- lifecycle -----------------------------------------------------

    def register(self, owner, *, kind: str, table_path: Optional[str],
                 version: Optional[int], nbytes: Optional[int],
                 rebuild_cost_class: str,
                 arrays: Sequence[object],
                 evictor=None) -> ResidentHandle:
        if nbytes is None:
            nbytes = _sum_nbytes(arrays)
        if table_path is None:
            table_path = _SCOPE.get() or UNKNOWN_TABLE
        refs: Optional[List[weakref.ref]] = []
        for a in arrays:
            try:
                refs.append(weakref.ref(a))
            except TypeError:
                # not weakref-able (host ndarray fixture): the handle
                # stays byte-accounted but exempt from the identity
                # half of the audit
                refs = None
                break
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            h = ResidentHandle(self, seq, table_path, kind, version,
                               int(nbytes), rebuild_cost_class, refs)
            h._evictor = _wrap_evictor(evictor)
            self._handles[seq] = h
            self._total += h.nbytes
            if self._total > self._peak:
                self._peak = self._total
        if owner is not None:
            f = weakref.finalize(owner, self._leaked, seq)
            # an exiting process is not leaking HBM — don't fire the
            # whole backlog of pending finalizers at interpreter exit
            f.atexit = False
            h._finalizer = f
        _REGISTRATIONS.inc()
        return h

    def _grow(self, h: ResidentHandle, arrays: Sequence[object],
              nbytes: Optional[int]) -> None:
        with self._lock:
            if h._released:
                return
            new_bytes = int(nbytes if nbytes is not None
                            else _sum_nbytes(arrays))
            if arrays:
                refs: Optional[List[weakref.ref]] = []
                for a in arrays:
                    try:
                        refs.append(weakref.ref(a))
                    except TypeError:
                        refs = None
                        break
                h._refs = refs
            self._total += new_bytes - h.nbytes
            h.nbytes = new_bytes
            if self._total > self._peak:
                self._peak = self._total
            h.last_access = time.time()

    def _release(self, h: ResidentHandle) -> None:
        with self._lock:
            if h._released:
                return
            h._released = True
            self._handles.pop(h._seq, None)
            self._total -= h.nbytes
        if h._finalizer is not None:
            h._finalizer.detach()
        _RELEASES.inc()
        _trace.add_event("hbm.release", kind=h.kind, table=h.table_path,
                         nbytes=h.nbytes)

    def shed(self, max_artifacts: Optional[int] = None,
             need_bytes: Optional[int] = None) -> Tuple[int, int]:
        """Evict resident artifacts under HBM pressure; returns
        ``(artifacts_evicted, bytes_freed)``.

        Candidates are the handles registered with an ``evictor`` whose
        owner is still alive, ordered cheapest-to-rebuild first
        (`rebuild_cost_class`), then least recently used. Evictors run
        outside the ledger lock and must end in the handle's
        ``release()`` — an eviction only counts once the handle reports
        released. Stops after ``max_artifacts`` evictions or once
        ``need_bytes`` have been freed (whichever comes first)."""
        with self._lock:
            cands = []
            for h in self._handles.values():
                ev = h._evictor() if h._evictor is not None else None
                if ev is not None:
                    cands.append(
                        (_SHED_COST_RANK.get(h.rebuild_cost_class, 1),
                         h.last_access, h._seq, h, ev))
        cands.sort(key=lambda t: t[:3])
        n = freed = 0
        for _, _, _, h, ev in cands:
            if max_artifacts is not None and n >= max_artifacts:
                break
            if need_bytes is not None and freed >= need_bytes:
                break
            nbytes = h.nbytes
            ev()
            if h._released:
                n += 1
                freed += nbytes
                _trace.add_event("hbm.shed", kind=h.kind,
                                 table=h.table_path, nbytes=nbytes)
        if n:
            _SHEDS.inc(n)
            _SHED_BYTES.inc(freed)
        return n, freed

    def _leaked(self, seq: int) -> None:
        """Finalizer callback: the owner was GC'd with the handle still
        registered. Deregister (the device arrays die with the owner by
        refcount, so keeping the entry would make every gauge lie) and
        record the leak."""
        with self._lock:
            h = self._handles.pop(seq, None)
            if h is None or h._released:
                return
            h._released = True
            self._total -= h.nbytes
            rec = {
                "type": "hbm_leak",
                "seq": seq,
                "table_path": h.table_path,
                "kind": h.kind,
                "version": h.version,
                "nbytes": h.nbytes,
                "created_at": h.created_at,
                "last_access": h.last_access,
                "ts": time.time(),
            }
            self._leaks.append(rec)
        _LEAKS.inc()
        _log.warning(
            "hbm leak: %s artifact of %s (%d B) owner GC'd without "
            "release() — call release_snapshot_resident (or the owner's "
            "release) before dropping the last reference",
            h.kind, h.table_path, h.nbytes)
        _trace.add_event("hbm.leak", kind=h.kind, table=h.table_path,
                         nbytes=h.nbytes)

    # -- read side -----------------------------------------------------

    def total_bytes(self) -> int:
        return self._total

    def peak_bytes(self) -> int:
        return self._peak

    def artifact_count(self) -> int:
        return len(self._handles)

    def kind_bytes(self, kind: str) -> int:
        with self._lock:
            return sum(h.nbytes for h in self._handles.values()
                       if h.kind == kind)

    def op_count(self) -> int:
        """Ledger operations so far (register + release + leak +
        touch) — the multiplier for the bench's disabled-path overhead
        projection."""
        return (_REGISTRATIONS.value + _RELEASES.value + _LEAKS.value
                + self.touches)

    def residents(self, top: Optional[int] = None) -> List[dict]:
        """Registered artifacts as dicts, largest first."""
        with self._lock:
            out = [h.to_dict() for h in self._handles.values()]
        out.sort(key=lambda d: (-int(d["nbytes"]), d["seq"]))
        return out[:top] if top else out

    def leak_records(self) -> List[dict]:
        with self._lock:
            return list(self._leaks)

    def rollup(self, by: str = "table") -> Dict[str, dict]:
        """Per-table (or per-kind) byte/artifact totals with the cross
        dimension nested: ``{key: {nbytes, artifacts, by_kind|by_table:
        {sub: nbytes}}}``."""
        if by not in ("table", "kind"):
            raise ValueError(f"rollup by {by!r}; expected 'table' or 'kind'")
        sub_key = "by_kind" if by == "table" else "by_table"
        out: Dict[str, dict] = {}
        with self._lock:
            handles = list(self._handles.values())
        for h in handles:
            key = h.table_path if by == "table" else h.kind
            sub = h.kind if by == "table" else h.table_path
            ent = out.setdefault(key, {"nbytes": 0, "artifacts": 0,
                                       sub_key: {}})
            ent["nbytes"] += h.nbytes
            ent["artifacts"] += 1
            ent[sub_key][sub] = ent[sub_key].get(sub, 0) + h.nbytes
        return out

    # -- reconciliation audit ------------------------------------------

    def audit(self) -> Dict[str, object]:
        """Cross-check the ledger against `jax.live_arrays()`: every
        registered artifact's weakly-referenced device arrays must
        still be live, and their actual byte counts must sum to the
        registered figure (byte-exact — an unrecorded `grow()` is
        drift, not noise). Handles registered without weakref-able
        arrays are byte-accounted but identity-exempt (reported under
        ``unverified_bytes``)."""
        drift: List[str] = []
        by_device: Dict[str, int] = {}
        verified = 0
        unverified = 0
        live_ids: Optional[set] = None
        try:
            import jax

            live_ids = {id(a) for a in jax.live_arrays()}
        # delta-lint: disable=except-swallow (audited: a host without a
        # configured jax backend still runs the ledger; the audit then
        # checks weakref liveness only, never crashes)
        except Exception:
            pass
        with self._lock:
            handles = list(self._handles.values())
            total = self._total
            leaks = list(self._leaks)
        for h in handles:
            if h._refs is None:
                unverified += h.nbytes
                continue
            got = 0
            dead = False
            for r in h._refs:
                a = r()
                if a is None or (live_ids is not None
                                 and id(a) not in live_ids):
                    dead = True
                    break
                got += int(getattr(a, "nbytes", 0) or 0)
                for dev, nb in _attribute_devices(a):
                    by_device[dev] = by_device.get(dev, 0) + nb
            if dead:
                drift.append(
                    f"{h.kind} artifact of {h.table_path} "
                    f"({h.nbytes} B): registered device array is no "
                    f"longer live but the handle was never released")
            elif got != h.nbytes:
                drift.append(
                    f"{h.kind} artifact of {h.table_path}: ledger says "
                    f"{h.nbytes} B but live arrays hold {got} B "
                    f"(unrecorded grow/shrink — call handle.grow())")
            else:
                verified += got
        return {
            "ok": not drift and not leaks,
            "ledger_bytes": total,
            "verified_bytes": verified,
            "unverified_bytes": unverified,
            "artifacts": len(handles),
            "by_device": by_device,
            "drift": drift,
            "leaks": leaks,
        }

    def reset(self) -> None:
        """Forget every handle and leak record (tests/bench). Detaches
        finalizers so owners created before the reset can't report
        stale leaks into the fresh epoch."""
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
            self._total = 0
            self._peak = 0
            self._leaks.clear()
            self.touches = 0
        for h in handles:
            h._released = True
            if h._finalizer is not None:
                h._finalizer.detach()


def _attribute_devices(a) -> List[Tuple[str, int]]:
    """(device label, nbytes) attribution for one live array — exact
    per-shard when the array exposes addressable shards, whole-array
    otherwise."""
    try:
        shards = a.addressable_shards
        out: Dict[str, int] = {}
        for s in shards:
            dev = str(s.device)
            out[dev] = out.get(dev, 0) + int(s.data.nbytes)
        if out:
            return list(out.items())
    # delta-lint: disable=except-swallow (audited: device attribution
    # is reporting garnish; an exotic array type degrades to a single
    # "unknown" bucket rather than failing the audit)
    except Exception:
        pass
    return [("unknown", int(getattr(a, "nbytes", 0) or 0))]


_LEDGER = ResidentLedger()


def ledger() -> ResidentLedger:
    return _LEDGER


# -- module-level API (what instrumented sites call) -------------------------


def register(owner, *, kind: str, table_path: Optional[str] = None,
             version: Optional[int] = None, nbytes: Optional[int] = None,
             rebuild_cost_class: str = "normal",
             arrays: Sequence[object] = (),
             evictor=None):
    """Register one device-resident artifact; returns its handle (the
    shared no-op handle when the ledger is off).

    ``owner``   the Python object whose lifetime bounds the artifact —
                GC'd without `release()` counts as a leak;
    ``kind``    artifact kind (`KIND_*` or a new string);
    ``arrays``  the device arrays backing the artifact (weakly held,
                audited against `jax.live_arrays()`);
    ``nbytes``  registered size; computed from `arrays` when omitted;
    ``table_path`` rollup key; the ambient `table_scope()` when omitted;
    ``evictor`` optional zero-arg callable `shed()` may invoke under
                HBM pressure — must drop the artifact's device memory
                and end in the handle's ``release()``; bound methods
                are weakly held so the ledger never pins the owner.
    """
    if _mode == MODE_OFF:
        return _NOOP_HANDLE
    return _LEDGER.register(owner, kind=kind, table_path=table_path,
                            version=version, nbytes=nbytes,
                            rebuild_cost_class=rebuild_cost_class,
                            arrays=arrays, evictor=evictor)


def shed(max_artifacts: Optional[int] = None,
         need_bytes: Optional[int] = None) -> Tuple[int, int]:
    """Evict cheapest-to-rebuild resident artifacts under HBM pressure
    (the shed half of shed-and-retry; see
    `resilience/device_faults.py`). No-op ``(0, 0)`` when the ledger is
    off — without byte accounting there is nothing principled to shed.
    ``DELTA_TPU_HBM_SHED_MAX`` (default 4) caps evictions per call when
    ``max_artifacts`` is omitted."""
    if _mode == MODE_OFF:
        return (0, 0)
    if max_artifacts is None:
        max_artifacts = int(os.environ.get("DELTA_TPU_HBM_SHED_MAX") or 4)
    return _LEDGER.shed(max_artifacts=max_artifacts, need_bytes=need_bytes)


def audit() -> Dict[str, object]:
    """Run the reconciliation audit; in ``strict`` mode raise on any
    drift or recorded leak."""
    result = _LEDGER.audit()
    if _mode >= MODE_STRICT and not result["ok"]:
        problems = list(result["drift"])
        problems += [f"leaked {r['kind']} artifact of {r['table_path']} "
                     f"({r['nbytes']} B)" for r in result["leaks"]]
        raise RuntimeError("hbm ledger reconciliation failed: "
                           + "; ".join(problems))
    return result


def rollup(by: str = "table") -> Dict[str, dict]:
    return _LEDGER.rollup(by=by)


def residents(top: Optional[int] = None) -> List[dict]:
    return _LEDGER.residents(top=top)


def leak_records() -> List[dict]:
    return _LEDGER.leak_records()


def ledger_op_count() -> int:
    return _LEDGER.op_count()


def health_summary() -> Dict[str, object]:
    """Compact ledger view for serve health: totals, peak, leak count,
    per-kind bytes."""
    return {
        "resident_bytes": _LEDGER.total_bytes(),
        "resident_artifacts": _LEDGER.artifact_count(),
        "peak_bytes": _LEDGER.peak_bytes(),
        "leaks": _LEAKS.value,
        "by_kind": {k: e["nbytes"]
                    for k, e in _LEDGER.rollup(by="kind").items()},
    }


def dump_ledger(path: str) -> int:
    """Write every resident record and leak record as JSONL; returns
    the record count. The `delta-hbm` CLI consumes this artifact."""
    import json

    records = _LEDGER.residents() + _LEDGER.leak_records()
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    return len(records)


def reset_hbm_obs() -> None:
    """Clear the ledger (handles, leaks, peak, touch count) for tests
    and bench epochs; registry counters are reset separately."""
    _LEDGER.reset()


# -- ledger-derived gauges ---------------------------------------------------

# The pre-ledger ad-hoc gauges (`replay.resident_hbm_bytes` in
# parallel/resident.py, `scan.stats_index_hbm_bytes` in
# stats/device_index.py) are subsumed: same exported names, now derived
# from per-kind ledger totals at scrape time. Callbacks take the ledger
# lock briefly; scrape frequency makes that free.
gauge("hbm.resident_bytes").set_fn(_LEDGER.total_bytes)
gauge("hbm.resident_artifacts").set_fn(_LEDGER.artifact_count)
gauge("hbm.resident_bytes_peak").set_fn(_LEDGER.peak_bytes)
gauge("replay.resident_hbm_bytes").set_fn(
    lambda: _LEDGER.kind_bytes(KIND_REPLAY_KEYS))
gauge("scan.stats_index_hbm_bytes").set_fn(
    lambda: _LEDGER.kind_bytes(KIND_STATS_INDEX))
gauge("sql.operand_cache_bytes").set_fn(
    lambda: _LEDGER.kind_bytes(KIND_SQL_OPERANDS))

"""Hierarchical span tracing: contextvar span stack, ring buffer, exporters.

Dapper-style traces (Sigelman et al. 2010) shaped after the reference's
`recordDeltaOperation` timing scopes (`DeltaLogging.scala:118`): every
instrumented operation opens a span; nested operations become child
spans sharing the root's trace id, so one `Table.latest_snapshot()`
stitches listing, parse, columnarize, and replay-kernel phases — across
threads and storage layers — into a single connected tree.

Gating: `DELTA_TPU_TRACE=off|on|verbose` (default off).  The disabled
path is near-zero cost: `span()` returns a process-wide no-op context
manager singleton — no allocation, no clock read, no contextvar touch.
`verbose` additionally enables high-cardinality spans (per-file storage
reads) that `on` folds into counters.

Sampling: `DELTA_TPU_TRACE_SAMPLE=<0..1>` (default 1.0) keeps each new
trace ROOT with that probability — head-based, so a kept trace is
always complete and a dropped one costs one RNG draw. The decision is
made once at the root and inherited by every descendant (including
cross-thread children via `wrap()` and cross-process children via the
envelope ids, which an unsampled client simply never stamps).

Finished spans land in a bounded in-process ring buffer
(`get_finished_spans`) and are fanned out to registered exporters;
`DELTA_TPU_TRACE_FILE=<path>` auto-installs a JSONL exporter.
"""

from __future__ import annotations

import collections
import contextvars
import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional

_log = logging.getLogger(__name__)

MODE_OFF = 0
MODE_ON = 1
MODE_VERBOSE = 2

_MODES = {"off": MODE_OFF, "on": MODE_ON, "verbose": MODE_VERBOSE,
          "0": MODE_OFF, "1": MODE_ON, "2": MODE_VERBOSE}


def _mode_from_env() -> int:
    raw = os.environ.get("DELTA_TPU_TRACE", "off").strip().lower()
    mode = _MODES.get(raw)
    if mode is None:
        _log.warning("unknown DELTA_TPU_TRACE=%r; tracing stays off", raw)
        return MODE_OFF
    return mode


_mode: int = _mode_from_env()


def _sample_from_env() -> float:
    raw = os.environ.get("DELTA_TPU_TRACE_SAMPLE")
    if not raw:
        return 1.0
    try:
        rate = float(raw)
    except ValueError:
        _log.warning("bad DELTA_TPU_TRACE_SAMPLE=%r; sampling stays at 1",
                     raw)
        return 1.0
    return min(1.0, max(0.0, rate))


_sample_rate: float = _sample_from_env()
_sample_rng = random.Random()  # trace keep/drop only — not security


def set_trace_sample(rate: Optional[float]) -> None:
    """Set the head-sampling rate (fraction of new trace roots kept,
    clamped to [0, 1]); None re-reads `DELTA_TPU_TRACE_SAMPLE`."""
    global _sample_rate
    if rate is None:
        _sample_rate = _sample_from_env()
    else:
        _sample_rate = min(1.0, max(0.0, float(rate)))


def trace_sample() -> float:
    return _sample_rate

# the active span of the calling context; child contexts (threads) do
# NOT inherit it automatically — use wrap() to propagate across pools
_CURRENT: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "delta_tpu_current_span", default=None
)

# human label for this process in merged multi-process traces (the
# Chrome exporter's process_name metadata); CLI entry points set it
# ("delta-serve", "delta-connect"), libraries leave it None
_process_label: Optional[str] = os.environ.get("DELTA_TPU_TRACE_PROCESS")


def set_process_label(label: Optional[str]) -> None:
    """Name this process for multi-process trace rendering. Spans record
    the label at creation, so set it before serving traffic."""
    global _process_label
    _process_label = label


def process_label() -> Optional[str]:
    return _process_label


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class Span:
    """One finished or in-flight operation: half-open interval + metadata.

    `start_unix_ns` anchors the span on the wall clock (exporters need
    absolute timestamps); `duration_ns` is measured on the monotonic
    clock so it survives wall-clock steps.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "start_unix_ns", "monotonic_start_ns", "duration_ns",
                 "attrs", "events", "status", "thread_id", "thread_name",
                 "pid", "process")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict[str, object]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_unix_ns = time.time_ns()
        self.monotonic_start_ns = time.perf_counter_ns()
        self.duration_ns: Optional[int] = None
        self.attrs = attrs
        self.events: List[Dict[str, object]] = []
        self.status = "ok"
        cur = threading.current_thread()
        self.thread_id = cur.ident or 0
        self.thread_name = cur.name
        self.pid = os.getpid()
        self.process = _process_label

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def set_attrs(self, **attrs) -> None:
        self.attrs.update(attrs)

    def add_event(self, name: str, **attrs) -> None:
        self.events.append({"name": name, "ts_unix_ns": time.time_ns(),
                            "attrs": attrs})

    @property
    def recording(self) -> bool:
        return True

    def to_dict(self) -> Dict[str, object]:
        return {
            "type": "span",
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_unix_ns": self.start_unix_ns,
            "duration_ns": self.duration_ns,
            "status": self.status,
            "thread_id": self.thread_id,
            "thread_name": self.thread_name,
            "pid": self.pid,
            "process": self.process,
            "attrs": self.attrs,
            "events": self.events,
        }

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id[:8]}, "
                f"span={self.span_id}, parent={self.parent_id}, "
                f"status={self.status})")


class _NoopSpan:
    """The recorded-nothing span: every mutator is a no-op. A single
    process-wide instance backs the disabled path."""

    __slots__ = ()

    trace_id = None
    span_id = None
    parent_id = None
    name = None
    status = "ok"
    duration_ns = None

    def set_attr(self, key: str, value) -> None:
        pass

    def set_attrs(self, **attrs) -> None:
        pass

    def add_event(self, name: str, **attrs) -> None:
        pass

    @property
    def recording(self) -> bool:
        return False


class _NoopCtx:
    """Reusable, reentrant, thread-safe no-op context manager: carries no
    per-use state, so one singleton serves every disabled `span()` call."""

    __slots__ = ()

    def __enter__(self) -> _NoopSpan:
        return _NOOP_SPAN

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NOOP_SPAN = _NoopSpan()
_NOOP_CTX = _NoopCtx()


class _SuppressedMarker:
    """Sentinel installed in `_CURRENT` for the extent of an UNSAMPLED
    trace root: descendants (same-thread, and cross-thread via wrap())
    see it and record nothing, so a dropped trace is dropped whole —
    never a parent-less fragment."""

    __slots__ = ()


_SUPPRESSED = _SuppressedMarker()


class _SpanCtx:
    """Live-path context manager: creates the span on __enter__ (so the
    parent is read from the entering context, not the creating one)."""

    __slots__ = ("_name", "_attrs", "_span", "_token")

    def __init__(self, name: str, attrs: Dict[str, object]):
        self._name = name
        self._attrs = attrs
        self._span: Optional[Span] = None
        self._token = None

    def __enter__(self):
        parent = _CURRENT.get()
        if parent is _SUPPRESSED:
            return _NOOP_SPAN  # inside an unsampled trace
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            # new trace root: the head-sampling decision happens here,
            # once, and binds the whole (cross-thread) subtree below
            if _sample_rate < 1.0 and _sample_rng.random() >= _sample_rate:
                self._token = _CURRENT.set(_SUPPRESSED)
                return _NOOP_SPAN
            trace_id, parent_id = _new_id(16), None
        s = Span(self._name, trace_id, _new_id(8), parent_id, self._attrs)
        self._span = s
        self._token = _CURRENT.set(s)
        return s

    def __exit__(self, exc_type, exc, tb) -> bool:
        s = self._span
        if s is None:
            # suppressed (unsampled root, or child of one): unwind the
            # sentinel if this ctx installed it, record nothing
            if self._token is not None:
                _CURRENT.reset(self._token)
                self._token = None
            return False
        s.duration_ns = time.perf_counter_ns() - s.monotonic_start_ns
        if exc_type is not None:
            s.status = "error"
            s.attrs.setdefault("error.type", exc_type.__name__)
            if exc is not None:
                s.attrs.setdefault("error.message", str(exc)[:200])
        _CURRENT.reset(self._token)
        _finish(s)
        return False


def span(name: str, _verbose: bool = False, **attrs):
    """Open a span named `name` with initial attributes `attrs`.

    Use as a context manager: ``with span("snapshot.load", table=p) as s:``.
    `_verbose=True` marks a high-cardinality span recorded only under
    `DELTA_TPU_TRACE=verbose` (e.g. per-file storage reads). When tracing
    is disabled (or the span is verbose-only and the mode is `on`) a
    shared no-op context manager is returned — near-zero cost.
    """
    if _mode == MODE_OFF or (_verbose and _mode < MODE_VERBOSE):
        return _NOOP_CTX
    if _CURRENT.get() is _SUPPRESSED:
        return _NOOP_CTX  # unsampled trace: skip the ctx allocation too
    return _SpanCtx(name, attrs)


def current_span() -> Optional[Span]:
    """The context's active span, or None outside any span (or when
    tracing is off / the trace was not sampled)."""
    cur = _CURRENT.get()
    return None if cur is _SUPPRESSED else cur


def trace_context() -> Optional[tuple]:
    """(trace_id, span_id) of the active span for wire propagation, or
    None outside any span / tracing off / trace unsampled (so remote
    children of a dropped trace are dropped too). Stamp these into an
    outgoing request envelope; the server side adopts them via
    remote_parent()."""
    cur = _CURRENT.get()
    if cur is None or cur is _SUPPRESSED:
        return None
    return (cur.trace_id, cur.span_id)


# envelope trace ids arrive from untrusted peers; accept only plain hex
# strings of sane length so a hostile client can't bloat span records
_MAX_WIRE_ID_LEN = 64


def _valid_wire_id(value) -> bool:
    return (isinstance(value, str) and 0 < len(value) <= _MAX_WIRE_ID_LEN
            and all(c in "0123456789abcdefABCDEF-" for c in value))


class _AdoptCtx:
    """Adopt a remote (trace_id, parent_span_id) as the ambient parent.

    Installs a synthetic, never-finished Span carrying the remote ids so
    spans opened inside the scope parent *directly* under the client's
    span — the placeholder itself is never buffered or exported (the
    real span lives in the client process)."""

    __slots__ = ("_trace_id", "_parent_span_id", "_token")

    def __init__(self, trace_id: str, parent_span_id: str):
        self._trace_id = trace_id
        self._parent_span_id = parent_span_id
        self._token = None

    def __enter__(self):
        placeholder = Span("remote.parent", self._trace_id,
                           self._parent_span_id, None, {})
        self._token = _CURRENT.set(placeholder)
        return placeholder

    def __exit__(self, exc_type, exc, tb) -> bool:
        _CURRENT.reset(self._token)
        return False


def remote_parent(trace_id, parent_span_id):
    """Continue a trace started in another process: spans opened inside
    the returned context parent under (`trace_id`, `parent_span_id`) as
    read from a request envelope. No-op (shared singleton) when tracing
    is off or either id is missing/malformed — untrusted wire values
    never abort request handling."""
    if (_mode == MODE_OFF or not _valid_wire_id(trace_id)
            or not _valid_wire_id(parent_span_id)):
        return _NOOP_CTX
    return _AdoptCtx(trace_id, parent_span_id)


def set_attr(key: str, value) -> None:
    """Attach `key=value` to the active span; no-op outside a span."""
    cur = _CURRENT.get()
    if cur is not None and cur is not _SUPPRESSED:
        cur.attrs[key] = value


def set_attrs(**attrs) -> None:
    cur = _CURRENT.get()
    if cur is not None and cur is not _SUPPRESSED:
        cur.attrs.update(attrs)


def add_event(name: str, **attrs) -> None:
    """Append a point-in-time event to the active span; no-op outside."""
    cur = _CURRENT.get()
    if cur is not None and cur is not _SUPPRESSED:
        cur.add_event(name, **attrs)


def wrap(fn):
    """Bind the caller's active span to `fn` so running it on another
    thread parents its spans correctly.

    contextvars do not propagate into ThreadPoolExecutor workers; submit
    ``wrap(fn)`` instead of ``fn`` and the callee joins the caller's
    trace. Returns `fn` unchanged when tracing is off. Inside an
    UNSAMPLED trace the suppression marker is what gets bound, so the
    worker's spans are dropped with the rest of the trace.
    """
    if _mode == MODE_OFF:
        return fn
    parent = _CURRENT.get()
    if parent is None:
        return fn

    def bound(*args, **kwargs):
        token = _CURRENT.set(parent)
        try:
            return fn(*args, **kwargs)
        finally:
            _CURRENT.reset(token)

    return bound


# -- mode control ------------------------------------------------------------


def trace_mode() -> int:
    return _mode


def trace_enabled() -> bool:
    return _mode != MODE_OFF


def set_trace_mode(mode: Optional[str]) -> None:
    """Programmatically set the trace mode ('off'|'on'|'verbose'); None
    re-reads `DELTA_TPU_TRACE` from the environment. Tests and bench use
    this; production uses the env var."""
    global _mode
    if mode is None:
        _mode = _mode_from_env()
    else:
        try:
            _mode = _MODES[mode.strip().lower()]
        except KeyError:
            raise ValueError(
                f"unknown trace mode {mode!r}; expected off|on|verbose"
            ) from None
    if _mode != MODE_OFF:
        _install_env_exporter_once()


# -- collection + export -----------------------------------------------------

_BUFFER_DEFAULT = 200_000
_buffer: collections.deque = collections.deque(
    maxlen=int(os.environ.get("DELTA_TPU_TRACE_BUFFER", _BUFFER_DEFAULT))
)
_exporters: List[object] = []
_exporters_lock = threading.Lock()
_env_exporter_installed = False


def _finish(s: Span) -> None:
    _buffer.append(s)
    # snapshot the exporter list so a concurrent add/remove cannot
    # invalidate the iteration
    for exp in tuple(_exporters):
        try:
            exp(s)
        except Exception as e:
            _log.warning("trace exporter %r failed: %s", exp, e)


def get_finished_spans() -> List[Span]:
    """Finished spans in finish order (bounded ring buffer)."""
    return list(_buffer)


def reset_trace_buffer() -> None:
    _buffer.clear()


def add_exporter(exporter) -> None:
    """Register a callable(span) invoked for every finished span."""
    with _exporters_lock:
        if exporter not in _exporters:
            _exporters.append(exporter)


def remove_exporter(exporter) -> None:
    with _exporters_lock:
        if exporter in _exporters:
            _exporters.remove(exporter)


def _install_env_exporter_once() -> None:
    """Honor DELTA_TPU_TRACE_FILE: append every finished span as a JSONL
    record to the named file. Installed at most once per process."""
    global _env_exporter_installed
    if _env_exporter_installed:
        return
    path = os.environ.get("DELTA_TPU_TRACE_FILE")
    if not path:
        return
    with _exporters_lock:
        if _env_exporter_installed:
            return
        _env_exporter_installed = True
    from delta_tpu.obs.export import JsonlExporter

    try:
        add_exporter(JsonlExporter(path))
    except OSError as e:
        _log.warning("cannot open DELTA_TPU_TRACE_FILE=%r: %s", path, e)


# NOTE: the enabled-at-startup install happens in delta_tpu.obs.__init__
# (and in set_trace_mode), never at this module's import: export.py
# imports trace.py, so importing JsonlExporter from module level here
# would hit export mid-initialization and crash the whole package
# whenever DELTA_TPU_TRACE=on + DELTA_TPU_TRACE_FILE are both set.

"""Device-execution observability: the `device_dispatch()` funnel.

PRs 7/11/12/14 moved the replay/checkpoint/parse/skipping hot paths
onto XLA kernels routed by `parallel/gate.py` cost models — and none of
that execution layer was observable: the telemetry plane (PR 8) stops
at the request level, the transfer-budget lint (PR 9) proves what
*should* cross the link, and nothing records what *did*. This module is
the runtime half of both:

- **Dispatch profiler** — every jit/shard_map launch in `ops/` and
  `parallel/` runs inside ``with device_dispatch(name, key=...) as dd``,
  recording per-kernel wall time, whether this launch compiled (first
  sighting of a shape-bucket `key`) or ran steady-state, and actual
  H2D/D2H bytes per named lane (``dd.h2d("lane_bytes", arr, units=n)``).
  Recompile storms from shape churn become a counted, alarmable event
  (`device.recompile_storms`) instead of a silent bench mystery.
- **Runtime transfer-budget audit** — observed lane bytes are
  reconciled against `resources/transfer_budget.json` at dispatch exit:
  each recorded lane must match its manifest declaration byte-exactly
  (dtype lanes at ``units * itemsize``, bitplanes at ``units / 8`` —
  exact because `pad_bucket` sizes are multiples of 8; scalars are
  excluded, and undeclared lanes are violations only for
  ``device_put_exhaustive`` entries). Overruns bump
  `device.budget_violations`; ``strict`` mode raises.
- **Gate calibration** — every `replay_route`/`parse_route`/`skip_route`
  decision emits a structured record (inputs, predicted per-route cost,
  chosen route, reason) which later observations join: device routes
  join automatically at `device_dispatch` exit, host routes through
  ``gate_observation(gate, "host")``, and mid-flight fallbacks are
  marked by ``gate_fell_back()`` with the fallback cost accumulated
  onto the same record. The per-decision relative error between
  observed and predicted-for-the-chosen-route lands in the
  `gate.calibration_error` histogram and the `delta-gate` CLI; a bench
  run's records export as a fresh DEVICE_MERIT-shaped capture.

Gating mirrors `trace.py`: ``DELTA_TPU_DEVICE_OBS=off|on|strict``
(default off). The disabled path is a true no-op — `device_dispatch()`
returns a process-wide stateless singleton: no allocation, no clock
read, no counter touch (the lone exception is `gate.decisions`, an
always-on counter bumped per routing decision, orders of magnitude
colder than the dispatch path). ``strict`` is ``on`` plus raise-on-
budget-violation, for tests and canary lanes.

The audit intentionally leaves the `jax.device_put` calls at the sites
untouched — the static transfer-budget lint keys on them, and this
module only *observes* around them.
"""

from __future__ import annotations

import collections
import contextvars
import functools
import json
import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from delta_tpu.obs import trace as _trace
from delta_tpu.obs.registry import counter, histogram

_log = logging.getLogger(__name__)

MODE_OFF = 0
MODE_ON = 1
MODE_STRICT = 2

_MODES = {"off": MODE_OFF, "on": MODE_ON, "strict": MODE_STRICT,
          "0": MODE_OFF, "1": MODE_ON, "2": MODE_STRICT}


def _mode_from_env() -> int:
    raw = os.environ.get("DELTA_TPU_DEVICE_OBS", "off").strip().lower()
    mode = _MODES.get(raw)
    if mode is None:
        _log.warning("unknown DELTA_TPU_DEVICE_OBS=%r; device obs stays off",
                     raw)
        return MODE_OFF
    return mode


_mode: int = _mode_from_env()


def device_obs_mode() -> int:
    return _mode


def device_obs_enabled() -> bool:
    return _mode != MODE_OFF


def set_device_obs_mode(mode: Optional[str]) -> None:
    """Programmatically set the device-obs mode ('off'|'on'|'strict');
    None re-reads `DELTA_TPU_DEVICE_OBS`. Tests and bench use this;
    production uses the env var."""
    global _mode
    if mode is None:
        _mode = _mode_from_env()
    else:
        try:
            _mode = _MODES[mode.strip().lower()]
        except KeyError:
            raise ValueError(
                f"unknown device obs mode {mode!r}; expected off|on|strict"
            ) from None


# -- instruments (resolved once; see resources/metric_names.json) ------------

_DISPATCHES = counter("device.dispatches")
_COMPILES = counter("device.compiles")
_RECOMPILE_STORMS = counter("device.recompile_storms")
_H2D = counter("device.h2d_bytes")
_D2H = counter("device.d2h_bytes")
_VIOLATIONS = counter("device.budget_violations")
_DECISIONS = counter("gate.decisions")
_FALLBACKS = counter("gate.fallbacks")
_DISPATCH_NS = histogram("device.dispatch_ns")
_CALIB_ERR = histogram("gate.calibration_error")


# -- budget manifest ---------------------------------------------------------

# dtype byte widths the manifest may commit to (keep in sync with the
# static pass — both sides must price a lane identically)
_DTYPE_BYTES = {
    "int8": 1, "uint8": 1, "bool": 1,
    "int16": 2, "uint16": 2, "float16": 2, "bfloat16": 2,
    "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8,
}


@functools.lru_cache(maxsize=1)
def _budget_manifest() -> Dict[str, dict]:
    """``paths`` table of the committed transfer-budget manifest.
    `DELTA_TPU_TRANSFER_BUDGET` overrides the packaged resource (tests
    inject doctored manifests through it); unreadable manifests degrade
    to an empty table — the audit then flags every budgeted dispatch as
    unknown-entry rather than crashing the hot path."""
    path = os.environ.get("DELTA_TPU_TRANSFER_BUDGET")
    if not path:
        path = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                            "resources", "transfer_budget.json")
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        paths = data.get("paths", {})
        return paths if isinstance(paths, dict) else {}
    except (OSError, ValueError) as e:
        _log.warning("transfer-budget manifest unreadable (%s): %s", path, e)
        return {}


def _lane_expected_bytes(decl: dict, units: Optional[int]) -> Optional[int]:
    """Byte-exact expectation for one declared lane at `units` units, or
    None when the lane is exempt (scalar) or unpriceable (no units,
    unknown dtype)."""
    kind = decl.get("kind")
    if kind == "scalar" or units is None:
        return None
    if kind == "bitplane":
        # packbits emits whole bytes; pad_bucket unit counts are
        # multiples of 8 so this is exact, and the ceil covers fixture
        # lanes that are not bucket-padded
        return (int(units) + 7) // 8
    itemsize = _DTYPE_BYTES.get(decl.get("dtype", ""))
    if itemsize is None:
        return None
    return int(units) * itemsize


# -- record rings ------------------------------------------------------------

_RING_MAX = int(os.environ.get("DELTA_TPU_DEVICE_OBS_RING", 8192))
_dispatch_ring: collections.deque = collections.deque(maxlen=_RING_MAX)
_gate_ring: collections.deque = collections.deque(maxlen=_RING_MAX)

# first-sighting shape keys per kernel name: a dispatch whose key has
# not been seen is a compile; a kernel accumulating more distinct keys
# than the alarm threshold is a recompile storm (shape churn defeating
# pad_bucket)
_seen_lock = threading.Lock()
_seen_keys: Dict[str, set] = {}


def _storm_threshold() -> int:
    try:
        return int(os.environ.get("DELTA_TPU_RECOMPILE_ALARM", 8))
    except ValueError:
        return 8


# the calling context's pending (not yet finalized) gate decisions,
# keyed by gate name. Same-thread by construction: every route function
# is called on the thread that then executes the routed work, so the
# contextvar joins decision -> observation without any cross-thread
# hand-off.
_PENDING: contextvars.ContextVar[Optional[Dict[str, dict]]] = (
    contextvars.ContextVar("delta_tpu_pending_gates", default=None))


# -- gate decision records ---------------------------------------------------


def record_gate_decision(gate: str, chosen: str, inputs: Dict[str, object],
                         predicted: Dict[str, float],
                         reason: str = "economics") -> None:
    """Record one routing decision: `predicted` maps route name to the
    model's predicted seconds (empty when the decision bypassed the
    economics — env override, forced caller intent, empty input). The
    record stays pending until observations join it; a later decision
    for the same gate finalizes it."""
    _DECISIONS.inc()
    if _mode == MODE_OFF:
        return
    rec = {
        "type": "gate_decision",
        "gate": gate,
        "chosen": chosen,
        "reason": reason,
        "inputs": dict(inputs),
        "predicted_s": {k: float(v) for k, v in predicted.items()},
        "ts_unix_ns": time.time_ns(),
        "observed_s": None,
        "observed_routes": [],
        "fell_back_to": None,
        "calibration_error_pct": None,
    }
    pend = dict(_PENDING.get() or {})
    prev = pend.get(gate)
    if prev is not None:
        _finalize_decision(prev)
    pend[gate] = rec
    _PENDING.set(pend)
    _gate_ring.append(rec)
    # ride the active request span (flight recorder + Chrome export pick
    # events up from there): the trace answers "which route did this
    # dispatch take, and why"
    _trace.add_event("gate.decision", gate=gate, route=chosen, reason=reason,
                     **{f"predicted_{k}_ms": round(v * 1e3, 4)
                        for k, v in rec["predicted_s"].items()})


def gate_fell_back(gate: str, to_route: str, reason: str = "") -> None:
    """Mark the pending decision for `gate` as having fallen back
    mid-flight (device parse returned None, resident lanes evicted,
    ...): the fallback route's cost joins the same record, so the
    calibration error prices the total cost actually paid."""
    _FALLBACKS.inc()
    if _mode == MODE_OFF:
        return
    rec = (_PENDING.get() or {}).get(gate)
    if rec is not None:
        rec["fell_back_to"] = to_route
        if reason:
            rec["fallback_reason"] = reason
    _trace.add_event("gate.fallback", gate=gate, to_route=to_route,
                     reason=reason)


def _observe_gate(gate: str, route: str, seconds: float) -> None:
    """Accumulate one observed execution onto the pending decision for
    `gate` (a fallen-back decision accumulates both the abandoned
    attempt and the fallback route)."""
    rec = (_PENDING.get() or {}).get(gate)
    if rec is None:
        return
    rec["observed_s"] = (rec["observed_s"] or 0.0) + float(seconds)
    rec["observed_routes"].append(route)


def _finalize_decision(rec: dict) -> None:
    """Compute the calibration error for a decision whose observations
    are complete. Signed error is kept on the record; the histogram gets
    the absolute percentage (its export buckets are positive)."""
    if rec.get("_final"):
        return
    rec["_final"] = True
    obs_s = rec.get("observed_s")
    pred = rec.get("predicted_s") or {}
    pred_chosen = pred.get(rec.get("chosen"))
    if obs_s is None or not pred_chosen or pred_chosen <= 0:
        return
    err_pct = (obs_s - pred_chosen) / pred_chosen * 100.0
    rec["calibration_error_pct"] = err_pct
    _CALIB_ERR.observe(abs(err_pct))


def flush_gate_decisions() -> None:
    """Finalize every pending decision in the calling context (bench /
    CLI / test boundary — after this, calibration errors are computed
    and the histogram is settled)."""
    pend = _PENDING.get() or {}
    for rec in pend.values():
        _finalize_decision(rec)
    _PENDING.set({})


def get_gate_records() -> List[dict]:
    """Finalized gate-decision records, oldest first (bounded ring)."""
    flush_gate_decisions()
    return list(_gate_ring)


class _GateObsCtx:
    """Times a host-route execution and joins it onto the pending
    decision: ``with gate_observation("replay", "host"): ...``."""

    __slots__ = ("_gate", "_route", "_t0")

    def __init__(self, gate: str, route: str):
        self._gate = gate
        self._route = route
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            dt = (time.perf_counter_ns() - self._t0) / 1e9
            _observe_gate(self._gate, self._route, dt)
        return False


def gate_observation(gate: str, route: str):
    """Context manager observing a non-dispatch (host-route) execution
    for gate calibration; the shared no-op singleton when disabled."""
    if _mode == MODE_OFF:
        return _NOOP_DISPATCH
    return _GateObsCtx(gate, route)


# -- the dispatch funnel -----------------------------------------------------


class _NoopDispatch:
    """Disabled-path singleton: stateless, reentrant, thread-safe. Every
    recorder method is a no-op; `h2d`/`d2h` pass their array through so
    instrumented sites read identically in both modes."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def h2d(self, lane, obj, units=None):
        return obj

    def d2h(self, lane, obj, units=None):
        return obj

    def set(self, **attrs) -> None:
        pass


_NOOP_DISPATCH = _NoopDispatch()

# Device-fault chaos hook (resilience/device_chaos.py). When armed,
# every device_dispatch() call — obs on or off — passes through the
# engine's on_dispatch() before a context is built: it may sleep
# (transfer stall), salt the compile key (recompile storm), or raise
# (dispatch error / simulated RESOURCE_EXHAUSTED). Injected exceptions
# surface at the call site's `with` statement, indistinguishable from
# a real launch failure.
_dispatch_chaos = None


def set_dispatch_chaos(engine) -> None:
    """Arm (or, with None, disarm) the device-fault chaos engine."""
    global _dispatch_chaos
    _dispatch_chaos = engine


class _DispatchCtx:
    """Live-path recorder for one kernel launch."""

    __slots__ = ("_name", "_key", "_budget", "_units", "_gate", "_route",
                 "_attrs", "_lanes", "_h2d_total", "_d2h_total", "_t0")

    def __init__(self, name: str, key, budget: Optional[str],
                 units: Optional[int], gate: Optional[str], route: str):
        self._name = name
        self._key = key
        self._budget = budget
        self._units = units
        self._gate = gate
        self._route = route
        self._attrs: Dict[str, object] = {}
        self._lanes: List[Tuple[str, str, int, Optional[int]]] = []
        self._h2d_total = 0
        self._d2h_total = 0
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def h2d(self, lane: str, obj, units: Optional[int] = None):
        """Record `obj` (an array about to cross host->device, or an
        int byte count) as lane `lane`; `units` prices the lane against
        its manifest declaration when it differs from the dispatch-level
        unit count (e.g. a [n_lanes, n_pad] matrix). Returns `obj`."""
        nbytes = getattr(obj, "nbytes", None)
        if nbytes is None:
            nbytes = int(obj)
        self._lanes.append((lane, "h2d", int(nbytes), units))
        self._h2d_total += int(nbytes)
        return obj

    def d2h(self, lane: str, obj, units: Optional[int] = None):
        """Record device->host result bytes for lane `lane`."""
        nbytes = getattr(obj, "nbytes", None)
        if nbytes is None:
            nbytes = int(obj)
        self._lanes.append((lane, "d2h", int(nbytes), units))
        self._d2h_total += int(nbytes)
        return obj

    def set(self, **attrs) -> None:
        self._attrs.update(attrs)

    def _audit(self) -> List[str]:
        """Reconcile recorded H2D lanes against the manifest entry."""
        entry = _budget_manifest().get(self._budget)
        if entry is None:
            return [f"budget entry {self._budget!r} not in manifest"]
        decls = {d.get("name"): d for d in entry.get("lanes", [])}
        exhaustive = bool(entry.get("device_put_exhaustive"))
        out: List[str] = []
        for lane, direction, nbytes, lane_units in self._lanes:
            if direction != "h2d":
                continue
            decl = decls.get(lane)
            if decl is None:
                if exhaustive:
                    out.append(f"undeclared lane {lane!r} shipped "
                               f"{nbytes} B (entry {self._budget!r} is "
                               f"device_put_exhaustive)")
                continue
            units = lane_units if lane_units is not None else self._units
            expected = _lane_expected_bytes(decl, units)
            if expected is not None and nbytes > expected:
                out.append(f"lane {lane!r} shipped {nbytes} B > budgeted "
                           f"{expected} B ({units} x "
                           f"{decl.get('kind')}/{decl.get('dtype', '1bit')}, "
                           f"entry {self._budget!r})")
        return out

    def __exit__(self, exc_type, exc, tb) -> bool:
        wall_ns = time.perf_counter_ns() - self._t0
        compiled = False
        n_keys = 0
        if self._key is not None:
            with _seen_lock:
                seen = _seen_keys.setdefault(self._name, set())
                if self._key not in seen:
                    seen.add(self._key)
                    compiled = True
                n_keys = len(seen)
            if compiled:
                _COMPILES.inc()
                if n_keys > _storm_threshold():
                    _RECOMPILE_STORMS.inc()
                    _log.warning(
                        "recompile storm: kernel %s has compiled %d distinct "
                        "shape keys (alarm threshold %d) — shape churn is "
                        "defeating pad_bucket", self._name, n_keys,
                        _storm_threshold())
        _DISPATCHES.inc()
        _DISPATCH_NS.observe(wall_ns)
        if self._h2d_total:
            _H2D.inc(self._h2d_total)
        if self._d2h_total:
            _D2H.inc(self._d2h_total)
        violations = self._audit() if self._budget is not None else []
        rec = {
            "type": "device_dispatch",
            "kernel": self._name,
            "key": repr(self._key) if self._key is not None else None,
            "compile": compiled,
            "distinct_keys": n_keys,
            "wall_ns": wall_ns,
            "h2d_bytes": self._h2d_total,
            "d2h_bytes": self._d2h_total,
            "lanes": [{"name": ln, "dir": d, "nbytes": nb, "units": u}
                      for ln, d, nb, u in self._lanes],
            "budget": self._budget,
            "units": self._units,
            "violations": violations,
            "gate": self._gate,
            "route": self._route,
            "status": "error" if exc_type is not None else "ok",
            "ts_unix_ns": time.time_ns(),
        }
        if self._attrs:
            rec["attrs"] = self._attrs
        _dispatch_ring.append(rec)
        if self._gate is not None:
            # failed dispatches feed calibration too: a route that burns
            # wall time and then falls back to host must look *more*
            # expensive to the gate, not invisible
            _observe_gate(self._gate, self._route, wall_ns / 1e9)
        _trace.add_event("device.dispatch", kernel=self._name,
                         route=self._route, wall_ms=round(wall_ns / 1e6, 4),
                         compile=compiled, h2d_bytes=self._h2d_total,
                         violations=len(violations))
        if violations:
            _VIOLATIONS.inc(len(violations))
            _log.warning("transfer-budget audit: %s", "; ".join(violations))
            if _mode >= MODE_STRICT and exc_type is None:
                raise RuntimeError(
                    "transfer budget exceeded: " + "; ".join(violations))
        return False


def device_dispatch(name: str, *, key=None, budget: Optional[str] = None,
                    units: Optional[int] = None, gate: Optional[str] = None,
                    route: str = "device"):
    """Open the dispatch funnel around one kernel launch.

    ``name``   stable kernel identity ("json_parse.window", ...);
    ``key``    hashable shape-bucket signature — first sighting per name
               counts as a compile, churn past the alarm threshold is a
               recompile storm;
    ``budget`` transfer-budget manifest entry to audit recorded lanes
               against (``dd.h2d(lane, arr, units=...)`` before each
               device_put);
    ``units``  default unit count for lane pricing;
    ``gate``   routing gate this dispatch executes for ("replay",
               "parse", "skip") — the observed wall time joins the
               pending decision;
    ``route``  the route label recorded on the join.

    Returns the shared no-op singleton when device obs is off."""
    if _dispatch_chaos is not None:
        key = _dispatch_chaos.on_dispatch(name, key=key, gate=gate,
                                          route=route)
    if _mode == MODE_OFF:
        return _NOOP_DISPATCH
    return _DispatchCtx(name, key, budget, units, gate, route)


def get_dispatch_records() -> List[dict]:
    """Dispatch records, oldest first (bounded ring)."""
    return list(_dispatch_ring)


def reset_device_obs() -> None:
    """Clear rings, compile-tracking state, and pending decisions
    (tests/bench); the manifest cache drops so env overrides re-read."""
    _dispatch_ring.clear()
    _gate_ring.clear()
    with _seen_lock:
        _seen_keys.clear()
    _PENDING.set({})
    _budget_manifest.cache_clear()


# -- capture conditions ------------------------------------------------------

CONDITIONS_SCHEMA = "delta-tpu/capture-conditions/v1"

# sentinel stamped onto pre-schema bench artifacts by the backfill tool
# (obs/bench_trend.py) so trend analysis can refuse to mix them with
# conditioned captures instead of silently comparing across platforms
CONDITIONS_UNKNOWN = "unknown-pre-r20"

# Every env knob that can change a routing decision or the shape of
# what a capture measured. The delta-lint `route-contract` and
# `env-knob-capture-stamp` passes parse this tuple statically: a route
# knob (or any env_knobs.json entry marked `"capture": true`) missing
# here fails lint, so a new route can't repeat the PR 16 "forgot to
# stamp DELTA_TPU_DEVICE_DECODE" omission.
CAPTURE_ENV_KEYS = (
    "DELTA_TPU_REPLAY_ROUTE",
    "DELTA_TPU_DEVICE_PARSE",
    "DELTA_TPU_DEVICE_SKIP",
    "DELTA_TPU_DEVICE_DECODE",
    "DELTA_TPU_LINK_MODEL",
    "DELTA_TPU_LINK_H2D_BPS",
    "DELTA_TPU_LINK_RTT_S",
    "DELTA_TPU_H2D_CHUNK",
    "DELTA_TPU_SHARDED_MIN_ROWS",
    "DELTA_TPU_RESIDENT",
    "DELTA_TPU_DEVICE_CKPT_STATS",
    "DELTA_TPU_DEVICE_DV_PACK",
    "DELTA_TPU_DEVICE_DV_DECODE",
    "DELTA_TPU_DEVICE_SQL",
    "DELTA_TPU_TRACE",
    "DELTA_TPU_DEVICE_OBS",
    "DELTA_TPU_HBM_OBS",
    "DELTA_TPU_DEVICE_CHAOS",
    "JAX_PLATFORMS",
)


def capture_conditions(cache_state: str = "unknown",
                       extra: Optional[Dict[str, object]] = None
                       ) -> Dict[str, object]:
    """The versioned capture-conditions stamp: everything that made the
    r02->r05 headline ratios incomparable (platform, device count/kind,
    cache state, x64 mode) plus toolchain versions and the routing env
    overrides in force. Cheap, never raises — a half-configured backend
    records as unknown rather than failing a bench."""
    cond: Dict[str, object] = {
        "schema": CONDITIONS_SCHEMA,
        "platform": "unknown",
        "device_count": 0,
        "device_kind": "unknown",
        "x64": False,
        "cache_state": cache_state,
        "python": ".".join(map(str, sys.version_info[:3])),
        "pid_cpus": os.cpu_count() or 0,
    }
    try:
        import jax

        cond["platform"] = jax.default_backend()
        devs = jax.devices()
        cond["device_count"] = len(devs)
        cond["device_kind"] = getattr(devs[0], "device_kind", "unknown")
        cond["x64"] = bool(jax.config.jax_enable_x64)
        cond["jax"] = jax.__version__
    # delta-lint: disable=except-swallow (audited: backend discovery can
    # fail on hosts with no configured platform; conditions degrade to
    # "unknown" — a bench stamp must never abort the bench)
    except Exception:
        pass
    try:
        import numpy

        cond["numpy"] = numpy.__version__
    except ImportError:
        pass
    env = {k: v for k, v in os.environ.items()
           if k in CAPTURE_ENV_KEYS}
    if env:
        cond["env"] = env
    if extra:
        cond.update(extra)
    return cond


def conditions_fingerprint(cond) -> str:
    """Comparability key for trend analysis: captures with different
    fingerprints must never be compared in one noise band. Pre-schema
    string stamps fingerprint as themselves."""
    if isinstance(cond, str):
        return cond
    if not isinstance(cond, dict):
        return "missing"
    return "|".join(str(cond.get(k, "?")) for k in
                    ("platform", "device_count", "device_kind", "x64",
                     "cache_state"))


# -- artifacts: gate log + DEVICE_MERIT capture ------------------------------


def dump_gate_log(path: str) -> int:
    """Write every gate-decision and dispatch record as JSONL (gate
    records finalized first); returns the record count. The `delta-gate`
    CLI consumes this artifact."""
    gates = get_gate_records()
    dispatches = get_dispatch_records()
    with open(path, "w", encoding="utf-8") as f:
        for rec in gates + dispatches:
            f.write(json.dumps(
                {k: v for k, v in rec.items() if not k.startswith("_")},
                sort_keys=True) + "\n")
    return len(gates) + len(dispatches)


def export_device_merit(gates: Optional[List[dict]] = None,
                        dispatches: Optional[List[dict]] = None
                        ) -> Dict[str, object]:
    """Distill the session's records into a fresh DEVICE_MERIT.json-
    shaped capture: link bandwidth from observed (h2d_bytes, wall) pairs
    bucketed at the 8 MB fast-chunk boundary, replay_fa workload rates
    from joined gate decisions, conditions stamped. This is the artifact
    the ROADMAP's deferred real-TPU capture produces by just running the
    bench with device obs on."""
    gates = get_gate_records() if gates is None else gates
    dispatches = get_dispatch_records() if dispatches is None else dispatches
    fast, slow = [], []
    for d in dispatches:
        nb, ns = d.get("h2d_bytes", 0), d.get("wall_ns", 0)
        if nb and ns and not d.get("compile"):
            (fast if nb <= (8 << 20) else slow).append(nb / (ns / 1e9))
    link: Dict[str, object] = {"h2d_bytes_per_s": {}}
    if fast:
        link["h2d_bytes_per_s"][str(8 << 20)] = sorted(fast)[len(fast) // 2]
    if slow:
        link["h2d_bytes_per_s"][str(64 << 20)] = sorted(slow)[len(slow) // 2]
    replay: Dict[str, object] = {}
    host_s, dev_s, n_rows = [], [], 0
    for g in gates:
        if g.get("gate") != "replay" or g.get("observed_s") is None:
            continue
        n_rows = max(n_rows, int(g.get("inputs", {}).get("n_rows", 0)))
        if g.get("chosen") == "host":
            host_s.append(g["observed_s"])
        else:
            dev_s.append(g["observed_s"])
    if n_rows:
        replay["n"] = n_rows
        if host_s:
            replay["t_host_s"] = sorted(host_s)[len(host_s) // 2]
        if dev_s:
            replay["t_device_compute_s"] = sorted(dev_s)[len(dev_s) // 2]
    return {
        "schema": "delta-tpu/device-merit-capture/v1",
        "conditions": capture_conditions(),
        "link": link,
        "workloads": {"replay_fa": replay} if replay else {},
        "gate_calibration": summarize_gates(gates),
    }


def summarize_gates(records: Optional[List[dict]] = None
                    ) -> Dict[str, dict]:
    """Per-gate calibration summary: decision/fallback counts and, per
    chosen route, predicted vs observed medians and the median absolute
    calibration error percentage."""
    records = get_gate_records() if records is None else records
    out: Dict[str, dict] = {}
    for rec in records:
        if rec.get("type") != "gate_decision":
            continue
        g = out.setdefault(rec["gate"], {"decisions": 0, "fallbacks": 0,
                                         "routes": {}})
        g["decisions"] += 1
        if rec.get("fell_back_to"):
            g["fallbacks"] += 1
        r = g["routes"].setdefault(rec["chosen"],
                                   {"n": 0, "joined": 0, "predicted_s": [],
                                    "observed_s": [], "err_pct": []})
        r["n"] += 1
        pred = (rec.get("predicted_s") or {}).get(rec["chosen"])
        if rec.get("observed_s") is not None:
            r["joined"] += 1
            r["observed_s"].append(rec["observed_s"])
            if pred:
                r["predicted_s"].append(pred)
        if rec.get("calibration_error_pct") is not None:
            r["err_pct"].append(rec["calibration_error_pct"])
    for g in out.values():
        for r in g["routes"].values():
            for field in ("predicted_s", "observed_s"):
                vals = sorted(r.pop(field))
                r[f"median_{field}"] = vals[len(vals) // 2] if vals else None
            errs = sorted(abs(e) for e in r.pop("err_pct"))
            r["median_abs_err_pct"] = errs[len(errs) // 2] if errs else None
    return out

"""delta-bench-trend: regression verdicts over historical bench captures.

Every repo revision leaves a ``BENCH_r*.json`` artifact behind, but a
raw series of numbers answers the wrong question — benchmark noise on a
shared CPU container routinely swings 10-20%, so "is r06 slower than
r05" is meaningless without a noise model. This tool loads the whole
historical series, groups points by *capture conditions* (platform,
device kind/count, cache state — see
`obs.device.capture_conditions`), and judges the newest point of each
metric against the robust spread (median absolute deviation) of its
comparable history:

- ``regressed`` / ``improved``: the newest point sits outside the noise
  band ``max(--min-band-pct, 2*MAD/median)`` in the direction-adjusted
  worse/better sense;
- ``stable``: inside the band;
- ``insufficient-history``: fewer than ``--min-history`` comparable
  points (different conditions fingerprints never compare — a TPU
  capture is not a baseline for a CPU-container capture);
- ``unknown-direction``: the metric name matches no direction rule, so
  the tool refuses to call a winner.

Artifact heterogeneity is absorbed here, not in the artifacts: r01-r05
predate the ``metrics`` list (single ``parsed`` record plus
``{"metric": ...}`` JSON lines embedded in the captured ``tail``), r06+
carry a ``metrics`` list, r20+ stamp ``conditions``. ``--backfill``
annotates pre-conditions artifacts with the sentinel
``"unknown-pre-r20"`` so they form their own comparison group instead
of silently mixing with conditioned captures.

Usage::

    delta-bench-trend                        # verdicts over ./BENCH_r*.json
    delta-bench-trend --metric e2e_snapshot_load_actions_per_sec
    delta-bench-trend --json                 # verdicts as JSON
    delta-bench-trend --backfill             # stamp legacy artifacts
    python -m delta_tpu.obs.bench_trend      # same, without the script
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

from delta_tpu.obs.device import CONDITIONS_UNKNOWN, conditions_fingerprint

# Direction: +1 = higher is better, -1 = lower is better. Explicit
# entries first (names where suffix heuristics would guess wrong, e.g.
# reuse_pct is a hit rate, not an overhead), then suffix rules.
_DIRECTION: Dict[str, int] = {
    "checkpoint_read_actions_per_sec": +1,
    "incremental_checkpoint_reuse_pct": +1,
    "replay_kernel_vs_host_vectorized": +1,
    "analyzer_findings_total": -1,
    "serve_p99_ms_chaos": -1,
    "tpcds_query_seconds": -1,
    "sql_operand_cache_hit_pct": +1,  # hit rate, not an overhead
}

_LOWER_MARKERS = ("overhead", "latency", "findings")
_LOWER_SUFFIXES = ("_seconds", "_ms", "_ns", "_bytes", "_pct")
_HIGHER_SUFFIXES = ("_per_sec", "_per_s", "_qps", "_gbps")


def metric_direction(name: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 unknown."""
    if name in _DIRECTION:
        return _DIRECTION[name]
    if any(m in name for m in _LOWER_MARKERS) or name.startswith("cold_"):
        return -1
    if name.endswith(_LOWER_SUFFIXES):
        return -1
    if name.endswith(_HIGHER_SUFFIXES) or "speedup" in name:
        return +1
    return 0


_RUN_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _extract_metrics(artifact: Dict[str, Any]) -> Dict[str, float]:
    """Name -> value for one artifact, newest representation winning:
    tail-embedded JSON lines < ``parsed`` < ``metrics`` list."""
    out: Dict[str, float] = {}
    for line in str(artifact.get("tail", "")).splitlines():
        line = line.strip()
        if not line.startswith('{"metric"'):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec.get("value"), (int, float)):
            out[str(rec["metric"])] = float(rec["value"])
    for rec in ([artifact.get("parsed")] +
                list(artifact.get("metrics") or [])):
        if (isinstance(rec, dict) and "metric" in rec
                and isinstance(rec.get("value"), (int, float))):
            out[str(rec["metric"])] = float(rec["value"])
    return out


def load_bench_runs(paths: List[str]) -> List[Dict[str, Any]]:
    """Parse artifacts into uniform run records, ordered by run number:
    ``{"n", "path", "conditions", "fingerprint", "metrics"}``."""
    runs = []
    for path in paths:
        m = _RUN_RE.search(os.path.basename(path))
        try:
            with open(path) as f:
                artifact = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(artifact, dict):
            continue
        n = int(m.group(1)) if m else int(artifact.get("n", 0))
        cond = artifact.get("conditions", CONDITIONS_UNKNOWN)
        runs.append({
            "n": n,
            "path": path,
            "conditions": cond,
            "fingerprint": conditions_fingerprint(cond),
            "metrics": _extract_metrics(artifact),
        })
    runs.sort(key=lambda r: r["n"])
    return runs


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    k = len(s)
    mid = k // 2
    return s[mid] if k % 2 else (s[mid - 1] + s[mid]) / 2.0


def trend_verdicts(
    runs: List[Dict[str, Any]],
    min_history: int = 3,
    min_band_pct: float = 10.0,
    metrics: Optional[List[str]] = None,
) -> List[Dict[str, Any]]:
    """Judge each metric's newest point against its comparable history.

    Comparable = same conditions fingerprint as the newest point. The
    noise band widens with the history's own scatter (2x the MAD as a
    fraction of the median) but never below ``min_band_pct`` — a
    3-point history with zero variance should not flag a 0.1% wiggle.
    """
    by_metric: Dict[str, List[Dict[str, Any]]] = {}
    for run in runs:
        for name, value in run["metrics"].items():
            if metrics and name not in metrics:
                continue
            by_metric.setdefault(name, []).append(
                {"n": run["n"], "value": value,
                 "fingerprint": run["fingerprint"]})

    verdicts = []
    for name in sorted(by_metric):
        points = by_metric[name]
        latest = points[-1]
        history = [p["value"] for p in points[:-1]
                   if p["fingerprint"] == latest["fingerprint"]]
        v: Dict[str, Any] = {
            "metric": name,
            "latest_run": latest["n"],
            "latest_value": latest["value"],
            "comparable_points": len(history),
            "fingerprint": latest["fingerprint"],
        }
        if len(history) < min_history:
            v["verdict"] = "insufficient-history"
            verdicts.append(v)
            continue
        med = _median(history)
        mad = _median([abs(x - med) for x in history])
        if med == 0:
            band_pct = min_band_pct
            delta_pct = 0.0 if latest["value"] == 0 else float("inf")
        else:
            band_pct = max(min_band_pct, 200.0 * mad / abs(med))
            delta_pct = 100.0 * (latest["value"] - med) / abs(med)
        v.update(history_median=med, history_mad=mad,
                 band_pct=round(band_pct, 3),
                 delta_pct=round(delta_pct, 3)
                 if delta_pct != float("inf") else delta_pct)
        direction = metric_direction(name)
        if direction == 0:
            v["verdict"] = "unknown-direction"
        elif direction * delta_pct < -band_pct:
            v["verdict"] = "regressed"
        elif direction * delta_pct > band_pct:
            v["verdict"] = "improved"
        else:
            v["verdict"] = "stable"
        verdicts.append(v)
    return verdicts


def backfill_conditions(paths: List[str]) -> int:
    """Stamp ``"conditions": "unknown-pre-r20"`` into artifacts missing
    the key (idempotent). Returns how many files were rewritten."""
    changed = 0
    for path in paths:
        try:
            with open(path) as f:
                raw = f.read()
            artifact = json.loads(raw)
        except (OSError, ValueError):
            continue
        if not isinstance(artifact, dict) or "conditions" in artifact:
            continue
        artifact["conditions"] = CONDITIONS_UNKNOWN
        # preserve whatever indent the artifact was written with
        m = re.search(r"\{\n( +)", raw)
        indent = len(m.group(1)) if m else 2
        with open(path, "w") as f:
            json.dump(artifact, f, indent=indent)
            f.write("\n")
        changed += 1
    return changed


def _find_artifacts(root: str, pattern: str) -> List[str]:
    return sorted(_glob.glob(os.path.join(root, pattern)))


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="delta-bench-trend",
        description="Noise-banded regression verdicts over historical "
                    "BENCH_r*.json captures.")
    parser.add_argument("--root", default=".",
                        help="directory holding the artifacts (default .)")
    parser.add_argument("--glob", default="BENCH_r*.json",
                        help="artifact filename pattern")
    parser.add_argument("--metric", action="append", metavar="NAME",
                        help="only judge NAME (repeatable)")
    parser.add_argument("--min-history", type=int, default=3,
                        help="comparable points required for a verdict "
                             "(default 3)")
    parser.add_argument("--min-band-pct", type=float, default=10.0,
                        help="noise-band floor in percent (default 10)")
    parser.add_argument("--json", action="store_true",
                        help="print verdicts as JSON")
    parser.add_argument("--backfill", action="store_true",
                        help="stamp legacy artifacts missing 'conditions' "
                             "with the unknown-pre-r20 sentinel and exit")
    parser.add_argument("--fail-on-regress", action="store_true",
                        help="exit 1 if any metric regressed")
    args = parser.parse_args(argv)

    paths = _find_artifacts(args.root, args.glob)
    if not paths:
        print(f"delta-bench-trend: no artifacts match "
              f"{os.path.join(args.root, args.glob)}", file=sys.stderr)
        return 2

    if args.backfill:
        changed = backfill_conditions(paths)
        print(f"backfilled {changed} of {len(paths)} artifacts")
        return 0

    runs = load_bench_runs(paths)
    verdicts = trend_verdicts(runs, min_history=args.min_history,
                              min_band_pct=args.min_band_pct,
                              metrics=args.metric)
    if args.json:
        print(json.dumps(verdicts, indent=2))
    else:
        width = max((len(v["metric"]) for v in verdicts), default=6)
        for v in verdicts:
            detail = ""
            if "delta_pct" in v:
                detail = (f"  {v['delta_pct']:+.1f}% vs median "
                          f"{v['history_median']:.4g} "
                          f"(band ±{v['band_pct']:.1f}%, "
                          f"{v['comparable_points']} pts)")
            elif v["verdict"] == "insufficient-history":
                detail = (f"  ({v['comparable_points']} comparable pts, "
                          f"need {args.min_history})")
            print(f"{v['metric']:<{width}}  r{v['latest_run']:02d}  "
                  f"{v['verdict']:<20}{detail}")
    if args.fail_on_regress and any(
            v["verdict"] == "regressed" for v in verdicts):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

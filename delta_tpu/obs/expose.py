"""Prometheus text exposition for the metrics registry.

`render_prometheus` turns `Registry.snapshot()` into the Prometheus
text format (version 0.0.4): counters as `_total` series, histograms as
cumulative `_bucket`/`_sum`/`_count` families over the fixed
`EXPORT_BUCKETS` ladder (identical boundaries fleet-wide, so scrapes
from any process aggregate), gauges as plain series. Circuit-breaker
states are rendered as one labeled gauge series per endpoint.

Dotted registry names map to Prometheus names as
``delta_tpu_<name with . → _>``; the mapping is deterministic and
reversible for catalogued names.

The render unions the live snapshot with `resources/metric_names.json`
(the same catalog the `metric-name-conformance` lint pass enforces):
catalogued instruments that no loaded module has touched yet are
emitted as zero, so a scrape's shape does not depend on import order —
and each catalogued series carries its catalog description as `# HELP`.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

from delta_tpu.obs.registry import EXPORT_BUCKETS, metrics_snapshot

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_PREFIX = "delta_tpu_"

_CATALOG_ENV = "DELTA_LINT_METRIC_CATALOG"

_catalog_cache: Optional[Dict[str, Dict[str, str]]] = None
_catalog_lock = threading.Lock()


def _catalog_path() -> str:
    override = os.environ.get(_CATALOG_ENV)
    if override:
        return override
    return os.path.join(os.path.dirname(os.path.dirname(__file__)),
                        "resources", "metric_names.json")


def metric_catalog() -> Dict[str, Dict[str, str]]:
    """The metric-name catalog: {"counters"|"histograms"|"gauges":
    {dotted_name: help_text}}. Missing/unreadable file → empty catalog
    (exposition still renders whatever the registry holds)."""
    global _catalog_cache
    if _catalog_cache is not None and not os.environ.get(_CATALOG_ENV):
        return _catalog_cache
    try:
        with open(_catalog_path(), encoding="utf-8") as fh:
            raw = json.load(fh)
    except (OSError, ValueError):
        raw = {}
    catalog = {
        kind: dict(raw.get(kind) or {})
        for kind in ("counters", "histograms", "gauges")
    }
    if not os.environ.get(_CATALOG_ENV):
        with _catalog_lock:
            _catalog_cache = catalog
    return catalog


def prom_name(dotted: str, suffix: str = "") -> str:
    """`storage.read.calls` → `delta_tpu_storage_read_calls<suffix>`."""
    return _PREFIX + dotted.replace(".", "_").replace("-", "_") + suffix


def _fmt(value) -> str:
    if value is None:
        return "0"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, float):
        if value != value:  # NaN
            return "NaN"
        return repr(value)
    if isinstance(value, int):
        return str(value)
    return "0"


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


_BREAKER_STATE_CODES = {"closed": 0, "open": 1, "half_open": 2}


def _breaker_lines(lines) -> None:
    # imported lazily: resilience instruments itself through obs, so a
    # module-level import here would be a cycle
    try:
        from delta_tpu.resilience.breaker import breaker_states
    except ImportError:
        return
    states = breaker_states()
    if not states:
        return
    name = prom_name("resilience.breaker_state")
    lines.append(f"# HELP {name} Circuit-breaker state per endpoint "
                 "(0=closed, 1=open, 2=half_open).")
    lines.append(f"# TYPE {name} gauge")
    for endpoint in sorted(states):
        snap = states[endpoint]
        code = _BREAKER_STATE_CODES.get(str(snap.get("state")), 0)
        lines.append(
            f'{name}{{endpoint="{_escape_label(endpoint)}"}} {code}'
        )


def render_prometheus(snapshot: Optional[dict] = None,
                      catalog: Optional[dict] = None) -> str:
    """Render the registry (default: live `metrics_snapshot()`) as
    Prometheus exposition text. Catalogued-but-untouched instruments
    render as zero so the scrape shape is import-order independent."""
    if snapshot is None:
        snapshot = metrics_snapshot()
    if catalog is None:
        catalog = metric_catalog()
    counters = dict(snapshot.get("counters") or {})
    histograms = dict(snapshot.get("histograms") or {})
    gauges = dict(snapshot.get("gauges") or {})
    cat_counters = catalog.get("counters") or {}
    cat_histograms = catalog.get("histograms") or {}
    cat_gauges = catalog.get("gauges") or {}
    for name in cat_counters:
        counters.setdefault(name, 0)
    for name in cat_histograms:
        histograms.setdefault(name, None)
    for name in cat_gauges:
        gauges.setdefault(name, 0)

    lines = []
    for dotted in sorted(counters):
        name = prom_name(dotted, "_total")
        help_text = cat_counters.get(dotted)
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_fmt(counters[dotted])}")
    for dotted in sorted(gauges):
        name = prom_name(dotted)
        help_text = cat_gauges.get(dotted)
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(gauges[dotted])}")
    for dotted in sorted(histograms):
        name = prom_name(dotted)
        help_text = cat_histograms.get(dotted)
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} histogram")
        h = histograms[dotted]
        if h is None:
            h = {"count": 0, "sum": 0, "buckets": None}
        buckets = h.get("buckets")
        if buckets is None:
            buckets = {repr(b): 0 for b in EXPORT_BUCKETS}
            buckets["+Inf"] = h.get("count") or 0
        for bound, cumulative in buckets.items():
            lines.append(
                f'{name}_bucket{{le="{bound}"}} {_fmt(cumulative)}'
            )
        lines.append(f"{name}_sum {_fmt(h.get('sum'))}")
        lines.append(f"{name}_count {_fmt(h.get('count'))}")
    _breaker_lines(lines)
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> Dict[str, float]:
    """Parse exposition text back to {series_key: value} — series_key is
    the metric name plus any label block verbatim (`delta_tpu_x_total`,
    `delta_tpu_x_bucket{le="1.0"}`). Tests and the CLI's --grep use
    this; it handles exactly the subset `render_prometheus` emits."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, value = line.rpartition(" ")
        if not key:
            continue
        try:
            out[key] = float(value)
        except ValueError:
            continue
    return out

"""Process-wide metrics registry: named counters and histograms.

The kernel side of the reference keeps `Counter`/`Timer` objects inside
per-operation metric bags (`internal/metrics/`); cross-operation totals
(parse-cache hit rates, storage bytes, retry counts) need a process-wide
home instead. This registry is that home.

Fast path is lock-free: instrument sites resolve their Counter once at
module import (`_HITS = counter("parse_cache.hit_files")`) and the hot
call is a plain attribute increment — GIL-atomic for ints, no lock, no
dict lookup. The registry lock only guards instrument *creation*.

Counters are always on (a dict-free int add is cheaper than checking a
gate); the span machinery in `trace.py` carries the `DELTA_TPU_TRACE`
gating.
"""

from __future__ import annotations

import threading
from typing import Dict


class Counter:
    """Monotonic counter. `inc()` is GIL-atomic for the int add; exact
    totals under free-threaded builds are not guaranteed (telemetry
    tolerance, same trade the reference's SQLMetrics make)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def reset(self) -> None:
        self.value = 0

    def __repr__(self):
        return f"Counter({self.name!r}, {self.value})"


class Histogram:
    """Streaming summary: count/sum/min/max. No bucket vector — the
    per-operation latency distribution lives in spans; this is the cheap
    aggregate for code paths too hot to span."""

    __slots__ = ("name", "count", "sum", "min", "max")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0
        self.min = None
        self.max = None

    def observe(self, value) -> None:
        self.count += 1
        self.sum += value
        mn = self.min
        if mn is None or value < mn:
            self.min = value
        mx = self.max
        if mx is None or value > mx:
            self.max = value

    def reset(self) -> None:
        self.count = 0
        self.sum = 0
        self.min = None
        self.max = None

    @property
    def mean(self):
        return self.sum / self.count if self.count else None

    def __repr__(self):
        return f"Histogram({self.name!r}, n={self.count}, sum={self.sum})"


class Registry:
    """Named instrument table. Same name → same instrument, process-wide."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name))
        return h

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Point-in-time dump: {'counters': {name: value}, 'histograms':
        {name: {count, sum, min, max}}}. Zero-valued instruments are
        included — absence means never created, not never hit."""
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            histograms = {
                n: {"count": h.count, "sum": h.sum,
                    "min": h.min, "max": h.max}
                for n, h in self._histograms.items()
            }
        return {"counters": counters, "histograms": histograms}

    def reset(self) -> None:
        """Zero every instrument (tests/bench); instruments stay
        registered so module-cached references remain valid."""
        with self._lock:
            for c in self._counters.values():
                c.reset()
            for h in self._histograms.values():
                h.reset()


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def counter(name: str) -> Counter:
    """The process-wide counter named `name` (created on first use)."""
    return _REGISTRY.counter(name)


def histogram(name: str) -> Histogram:
    """The process-wide histogram named `name` (created on first use)."""
    return _REGISTRY.histogram(name)


def metrics_snapshot() -> Dict[str, Dict[str, object]]:
    """Snapshot of every registered counter/histogram."""
    return _REGISTRY.snapshot()

"""Process-wide metrics registry: named counters and histograms.

The kernel side of the reference keeps `Counter`/`Timer` objects inside
per-operation metric bags (`internal/metrics/`); cross-operation totals
(parse-cache hit rates, storage bytes, retry counts) need a process-wide
home instead. This registry is that home.

The Counter fast path is lock-free: instrument sites resolve their
Counter once at module import (`_HITS = counter("parse_cache.hit_files")`)
and the hot call is a plain attribute increment — a monotonic counter
tolerates the rare lost `+=` under thread interleaving (telemetry
tolerance). Gauges and histograms do NOT get that trade: an up/down
gauge drifts permanently when an inc/dec pair interleaves, and a
histogram update must keep `sum(buckets) == count`, so those take a
per-instrument lock. The registry lock only guards instrument
*creation*.

Counters are always on (a dict-free int add is cheaper than checking a
gate); the span machinery in `trace.py` carries the `DELTA_TPU_TRACE`
gating.
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Dict, Optional


class Counter:
    """Monotonic counter. `inc()` is GIL-atomic for the int add; exact
    totals under free-threaded builds are not guaranteed (telemetry
    tolerance, same trade the reference's SQLMetrics make)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def reset(self) -> None:
        self.value = 0

    def __repr__(self):
        return f"Counter({self.name!r}, {self.value})"


# Fixed export buckets shared by every histogram so scrapes from
# different processes aggregate cleanly (Prometheus-style cumulative
# buckets require identical boundaries fleet-wide). Log-spaced 13-point
# ladder covering sub-ms spins through multi-minute soaks; values are
# unit-agnostic (callers observe ns, ms, or depths — the ladder is wide
# enough for all three).
EXPORT_BUCKETS = (1.0, 5.0, 10.0, 50.0, 100.0, 500.0, 1_000.0,
                  5_000.0, 10_000.0, 100_000.0, 1_000_000.0,
                  100_000_000.0, 10_000_000_000.0)


class Histogram:
    """Streaming summary: count/sum/min/max plus a fixed-boundary bucket
    vector (`EXPORT_BUCKETS`) for aggregatable Prometheus exposition.
    The per-operation latency distribution still lives in spans; this is
    the cheap aggregate for code paths too hot to span.

    `observe()` takes a per-instrument lock: unlike a monotonic counter
    (where interleaved `+=` merely loses increments), a histogram update
    touches count/sum/min/max/buckets together — interleaving breaks the
    `sum(buckets) == count` invariant scrapes and burn-rate math rely
    on."""

    __slots__ = ("name", "count", "sum", "min", "max", "buckets",
                 "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0
        self.min = None
        self.max = None
        # per-boundary (non-cumulative) hit counts + overflow slot;
        # exposition cumulates at render time
        self.buckets = [0] * (len(EXPORT_BUCKETS) + 1)

    def observe(self, value) -> None:
        with self._lock:
            self.count += 1
            self.sum += value
            mn = self.min
            if mn is None or value < mn:
                self.min = value
            mx = self.max
            if mx is None or value > mx:
                self.max = value
            self.buckets[bisect.bisect_left(EXPORT_BUCKETS, value)] += 1

    def reset(self) -> None:
        with self._lock:
            self.count = 0
            self.sum = 0
            self.min = None
            self.max = None
            self.buckets = [0] * (len(EXPORT_BUCKETS) + 1)

    @property
    def mean(self):
        return self.sum / self.count if self.count else None

    def bucket_counts(self) -> Dict[str, int]:
        """Cumulative bucket counts keyed by upper bound ('+Inf' last)."""
        out: Dict[str, int] = {}
        running = 0
        for bound, n in zip(EXPORT_BUCKETS, self.buckets):
            running += n
            out[repr(bound)] = running
        out["+Inf"] = running + self.buckets[-1]
        return out

    def __repr__(self):
        return f"Histogram({self.name!r}, n={self.count}, sum={self.sum})"


class Gauge:
    """Point-in-time value: settable directly (`set`/`inc`/`dec`) or
    bound to a callback (`set_fn`) evaluated at read time — the callback
    form lets structures like the admission queue expose their depth
    without maintaining a shadow count on the hot path.

    Callbacks must be cheap, lock-free, and exception-safe candidates:
    `read()` swallows callback errors to None so a half-torn structure
    during shutdown can't break a scrape."""

    __slots__ = ("name", "value", "_fn", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.value = 0
        self._fn: Optional[Callable[[], object]] = None

    def set(self, value) -> None:
        self._fn = None
        self.value = value

    def inc(self, n=1) -> None:
        # unlike Counter's monotonic loss tolerance, an up/down gauge
        # drifts PERMANENTLY when an inc/dec pair interleaves (the
        # in-flight depth never returns to zero), so these take the lock
        with self._lock:
            self.value += n

    def dec(self, n=1) -> None:
        with self._lock:
            self.value -= n

    def set_fn(self, fn: Callable[[], object]) -> None:
        """Bind a zero-arg callback; subsequent `read()`s return its
        result. Callbacks run OUTSIDE the registry lock at snapshot."""
        self._fn = fn

    def read(self):
        fn = self._fn
        if fn is None:
            return self.value
        try:
            return fn()
        # delta-lint: disable=except-swallow (audited: a scrape must
        # never fail because one gauge callback raced its structure's
        # teardown; absent value renders as 0)
        except Exception:
            return None

    def reset(self) -> None:
        self.value = 0

    def __repr__(self):
        kind = "fn" if self._fn is not None else "value"
        return f"Gauge({self.name!r}, {kind}={self.read()})"


class Registry:
    """Named instrument table. Same name → same instrument, process-wide."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name))
        return h

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Point-in-time dump: {'counters': {name: value}, 'histograms':
        {name: {count, sum, min, max, buckets}}, 'gauges': {name:
        value}}. Zero-valued instruments are included — absence means
        never created, not never hit."""
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            histograms = {
                n: {"count": h.count, "sum": h.sum,
                    "min": h.min, "max": h.max,
                    "buckets": h.bucket_counts()}
                for n, h in self._histograms.items()
            }
            gauge_objs = list(self._gauges.values())
        # gauge callbacks may take the owning structure's locks (e.g.
        # len() over a guarded deque); evaluate them outside the registry
        # lock so no registry→structure lock order is ever established
        gauges = {g.name: g.read() for g in gauge_objs}
        return {"counters": counters, "histograms": histograms,
                "gauges": gauges}

    def reset(self) -> None:
        """Zero every instrument (tests/bench); instruments stay
        registered so module-cached references remain valid."""
        with self._lock:
            for c in self._counters.values():
                c.reset()
            for h in self._histograms.values():
                h.reset()
            for g in self._gauges.values():
                g.reset()


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def counter(name: str) -> Counter:
    """The process-wide counter named `name` (created on first use)."""
    return _REGISTRY.counter(name)


def histogram(name: str) -> Histogram:
    """The process-wide histogram named `name` (created on first use)."""
    return _REGISTRY.histogram(name)


def gauge(name: str) -> Gauge:
    """The process-wide gauge named `name` (created on first use)."""
    return _REGISTRY.gauge(name)


def metrics_snapshot() -> Dict[str, Dict[str, object]]:
    """Snapshot of every registered counter/histogram."""
    return _REGISTRY.snapshot()

"""Flight recorder: a bounded ring of recent *complete* request traces.

Black-box-style capture for postmortems: an exporter (register with
`obs.add_exporter(recorder)`) groups finished spans by trace id; when a
trace's root span finishes — parent-less, or named in `root_names`
(server request roots finish before their client-side parents, which
live in another process) — the assembled trace moves into a bounded
ring of completed traces. When an SLO gate fires (`obs/slo.py`), the
offending trace is still in the ring and `dump_jsonl` writes it out, so
a latency regression arrives with its own trace attached.

Memory is bounded on both sides: at most `max_open` in-flight traces
(oldest evicted first — a trace whose root never finishes cannot leak)
and `max_traces` completed ones.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Dict, Iterable, List, Optional

from delta_tpu.obs.export import span_to_dict


class FlightRecorder:
    """Span exporter assembling complete per-request traces.

    `root_names` marks span names that complete a trace even when the
    span has a remote parent (the in-process root of a server-side
    request). A parent-less span always completes its trace.
    """

    def __init__(self, max_traces: int = 256, max_open: int = 4096,
                 root_names: Optional[Iterable[str]] = None):
        self._max_open = max_open
        self._root_names = frozenset(root_names or ())
        self._open: "collections.OrderedDict[str, List[dict]]" = (
            collections.OrderedDict()
        )
        self._complete: collections.deque = collections.deque(
            maxlen=max_traces
        )
        self._index: Dict[str, List[dict]] = {}
        self._lock = threading.Lock()

    def __call__(self, span) -> None:
        d = span_to_dict(span)
        trace_id = d.get("trace_id")
        if not trace_id:
            return
        is_root = (d.get("parent_id") is None
                   or d.get("name") in self._root_names)
        with self._lock:
            spans = self._open.get(trace_id)
            if spans is None:
                spans = []
                self._open[trace_id] = spans
                while len(self._open) > self._max_open:
                    evicted_id, _ = self._open.popitem(last=False)
                    self._index.pop(evicted_id, None)
            spans.append(d)
            if is_root:
                self._open.pop(trace_id, None)
                existing = self._index.get(trace_id)
                if existing is not None:
                    # same trace completed again (e.g. the client-side
                    # root finishing after the server-side root in a
                    # single-process test, or a hedged duplicate):
                    # merge — in-place, so the ring entry updates too
                    existing.extend(spans)
                    return
                if len(self._complete) == self._complete.maxlen:
                    oldest = self._complete[0]
                    self._index.pop(oldest[0].get("trace_id"), None)
                self._complete.append(spans)
                self._index[trace_id] = spans

    def get(self, trace_id: str) -> Optional[List[dict]]:
        """The completed trace for `trace_id` (span dicts in finish
        order), or None if it never completed / already rolled off."""
        with self._lock:
            spans = self._index.get(trace_id)
            return list(spans) if spans is not None else None

    def trace_ids(self) -> List[str]:
        """Completed trace ids, oldest first."""
        with self._lock:
            return [t[0].get("trace_id") for t in self._complete]

    def __len__(self) -> int:
        return len(self._complete)

    def dump_jsonl(self, path: str,
                   trace_id: Optional[str] = None) -> int:
        """Write completed traces (or just `trace_id`'s) as JSONL span
        records readable by `delta-trace`. Returns spans written."""
        with self._lock:
            if trace_id is not None:
                spans = list(self._index.get(trace_id) or ())
            else:
                spans = [d for t in self._complete for d in t]
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            for d in spans:
                fh.write(json.dumps(d, sort_keys=True, default=str))
                fh.write("\n")
        return len(spans)

    def clear(self) -> None:
        with self._lock:
            self._open.clear()
            self._complete.clear()
            self._index.clear()

    def __repr__(self):
        return (f"FlightRecorder(complete={len(self._complete)}, "
                f"open={len(self._open)})")

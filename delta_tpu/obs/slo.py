"""Declarative SLOs with multi-window burn-rate alerting.

Objectives are declared, not hand-rolled: a latency objective ("p99 of
serve requests ≤ 50ms") or a ratio objective ("shed rate ≤ 2%") each
reduce to an error budget — the tolerated bad-event fraction — and an
alert fires on *burn rate*, the ratio of observed bad fraction to
budget, following the SRE-workbook multi-window recipe: breach only
when BOTH a short window (fast reaction, noisy alone) and a long
window (evidence, slow alone) burn above threshold. A p99-latency
objective is the ratio objective "fraction of events slower than the
threshold ≤ 1%" — one mechanism covers both shapes.

`SloEngine.record()` is called per finished request with its outcome
and latency; `evaluate()` returns a `SloVerdict` whose breaches carry a
`worst_trace_id` so the flight recorder (`obs/flight.py`) can dump the
offending trace. The clock is injectable for deterministic tests.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple


@dataclass(frozen=True)
class Objective:
    """One service-level objective.

    - latency form: `threshold_ms` set, `bad_outcomes` empty — an event
      is bad when it ran longer than `threshold_ms` (budget 0.01 ≡ "p99
      under threshold").
    - ratio form: `bad_outcomes` set — an event is bad when its outcome
      string is in the set (budget is the tolerated fraction).
    """

    name: str
    budget: float
    threshold_ms: Optional[float] = None
    bad_outcomes: FrozenSet[str] = frozenset()

    def is_bad(self, outcome: str, latency_ms: float) -> bool:
        if self.threshold_ms is not None:
            return latency_ms > self.threshold_ms
        return outcome in self.bad_outcomes


@dataclass
class Breach:
    objective: str
    burn_short: float
    burn_long: float
    bad_short: int
    total_short: int
    bad_long: int
    total_long: int
    worst_trace_id: Optional[str] = None


@dataclass
class SloVerdict:
    ok: bool
    breaches: List[Breach] = field(default_factory=list)
    burn_rates: Dict[str, Tuple[float, float]] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "breaches": [
                {"objective": b.objective,
                 "burn_short": round(b.burn_short, 3),
                 "burn_long": round(b.burn_long, 3),
                 "bad_long": b.bad_long, "total_long": b.total_long,
                 "worst_trace_id": b.worst_trace_id}
                for b in self.breaches
            ],
            "burn_rates": {
                name: [round(s, 3), round(lg, 3)]
                for name, (s, lg) in self.burn_rates.items()
            },
        }


class SloEngine:
    """Sliding-window burn-rate evaluator over recorded request events.

    `burn_threshold` is the multiple of budget-consumption-rate that
    constitutes a breach (SRE workbook's fast-burn pages use 14.4 over
    1h/5m; soaks here run seconds, so both windows shrink accordingly).
    `min_events` guards cold windows — a 1-of-2 blip is not a p99.
    """

    def __init__(self, objectives: List[Objective],
                 short_window_s: float = 5.0,
                 long_window_s: float = 60.0,
                 burn_threshold: float = 1.0,
                 min_events: int = 20,
                 clock=time.monotonic):
        if short_window_s > long_window_s:
            raise ValueError("short window must not exceed long window")
        self.objectives = list(objectives)
        self.short_window_s = short_window_s
        self.long_window_s = long_window_s
        self.burn_threshold = burn_threshold
        self.min_events = min_events
        self._clock = clock
        # (ts, outcome, latency_ms, trace_id); bounded by time-pruning
        # on record — a stalled evaluate() can't let it grow unbounded
        self._events: collections.deque = collections.deque()
        self._lock = threading.Lock()

    def record(self, outcome: str, latency_ms: float,
               trace_id: Optional[str] = None) -> None:
        now = self._clock()
        horizon = now - self.long_window_s
        with self._lock:
            self._events.append((now, outcome, latency_ms, trace_id))
            while self._events and self._events[0][0] < horizon:
                self._events.popleft()

    def _window_stats(self, events, objective: Objective, now: float,
                      window_s: float):
        cutoff = now - window_s
        total = bad = 0
        worst_latency = -1.0
        worst_trace = None
        for ts, outcome, latency_ms, trace_id in events:
            if ts < cutoff:
                continue
            total += 1
            if objective.is_bad(outcome, latency_ms):
                bad += 1
                if trace_id is not None and latency_ms >= worst_latency:
                    worst_latency = latency_ms
                    worst_trace = trace_id
        return total, bad, worst_trace

    def evaluate(self) -> SloVerdict:
        """Current verdict across every objective. A breach requires
        both windows to burn above `burn_threshold` AND the long window
        to hold at least `min_events` events."""
        now = self._clock()
        with self._lock:
            events = list(self._events)
        verdict = SloVerdict(ok=True)
        for obj in self.objectives:
            t_long, b_long, worst = self._window_stats(
                events, obj, now, self.long_window_s)
            t_short, b_short, _ = self._window_stats(
                events, obj, now, self.short_window_s)
            frac_long = b_long / t_long if t_long else 0.0
            frac_short = b_short / t_short if t_short else 0.0
            burn_long = frac_long / obj.budget if obj.budget else 0.0
            burn_short = frac_short / obj.budget if obj.budget else 0.0
            verdict.burn_rates[obj.name] = (burn_short, burn_long)
            if (t_long >= self.min_events
                    and burn_short > self.burn_threshold
                    and burn_long > self.burn_threshold):
                verdict.ok = False
                verdict.breaches.append(Breach(
                    objective=obj.name,
                    burn_short=burn_short, burn_long=burn_long,
                    bad_short=b_short, total_short=t_short,
                    bad_long=b_long, total_long=t_long,
                    worst_trace_id=worst,
                ))
        return verdict

    def event_count(self) -> int:
        return len(self._events)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()


def serve_objectives(p99_ms: float = 0.0, shed_rate: float = 0.0,
                     stale_rate: float = 0.0,
                     deadline_rate: float = 0.0) -> List[Objective]:
    """The serve path's standard objective set; a zero/negative knob
    disables that objective (ServeConfig wires DELTA_TPU_SERVE_SLO_*
    straight through)."""
    objectives: List[Objective] = []
    if p99_ms > 0:
        objectives.append(Objective(
            name="p99_latency", budget=0.01, threshold_ms=p99_ms))
    if shed_rate > 0:
        objectives.append(Objective(
            name="shed_rate", budget=shed_rate,
            bad_outcomes=frozenset({"shed"})))
    if stale_rate > 0:
        objectives.append(Objective(
            name="stale_serve_rate", budget=stale_rate,
            bad_outcomes=frozenset({"stale"})))
    if deadline_rate > 0:
        objectives.append(Objective(
            name="deadline_miss_rate", budget=deadline_rate,
            bad_outcomes=frozenset({"deadline"})))
    return objectives

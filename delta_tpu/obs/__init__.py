"""delta-tpu observability: hierarchical spans, metrics registry, exporters.

Zero-dependency tracing + telemetry spine (ROADMAP: observability).
Typical instrumentation site::

    from delta_tpu import obs

    with obs.span("snapshot.load", table=path) as s:
        ...
        s.set_attr("version", snap.version)

Gate with ``DELTA_TPU_TRACE=off|on|verbose`` (default off; the disabled
path returns a shared no-op context manager). ``DELTA_TPU_TRACE_FILE``
appends finished spans as JSONL; `delta-trace` (``python -m
delta_tpu.tools.trace``) summarizes either JSONL or Chrome trace files.

Counters/histograms (`counter`, `histogram`) are always on and
process-wide; resolve them once at module import and call ``.inc()`` on
the hot path.
"""

from delta_tpu.obs.export import (
    JsonlExporter,
    chrome_trace,
    load_spans,
    span_to_dict,
    write_chrome_trace,
)
from delta_tpu.obs.registry import (
    Counter,
    Histogram,
    Registry,
    counter,
    histogram,
    metrics_snapshot,
    registry,
)
from delta_tpu.obs.trace import (
    MODE_OFF,
    MODE_ON,
    MODE_VERBOSE,
    Span,
    add_event,
    add_exporter,
    current_span,
    get_finished_spans,
    remove_exporter,
    reset_trace_buffer,
    set_attr,
    set_attrs,
    set_trace_mode,
    span,
    trace_enabled,
    trace_mode,
    wrap,
)

# Both trace and export are fully initialized here, so honoring
# DELTA_TPU_TRACE_FILE at startup is now cycle-safe (trace.py itself
# must not do this at import time — export.py imports trace.py).
if trace_enabled():
    from delta_tpu.obs.trace import _install_env_exporter_once

    _install_env_exporter_once()
    del _install_env_exporter_once

__all__ = [
    "MODE_OFF",
    "MODE_ON",
    "MODE_VERBOSE",
    "Counter",
    "Histogram",
    "JsonlExporter",
    "Registry",
    "Span",
    "add_event",
    "add_exporter",
    "chrome_trace",
    "counter",
    "current_span",
    "get_finished_spans",
    "histogram",
    "load_spans",
    "metrics_snapshot",
    "registry",
    "remove_exporter",
    "reset_trace_buffer",
    "set_attr",
    "set_attrs",
    "set_trace_mode",
    "span",
    "span_to_dict",
    "trace_enabled",
    "trace_mode",
    "wrap",
    "write_chrome_trace",
]

"""delta-tpu observability: hierarchical spans, metrics registry, exporters.

Zero-dependency tracing + telemetry spine (ROADMAP: observability).
Typical instrumentation site::

    from delta_tpu import obs

    with obs.span("snapshot.load", table=path) as s:
        ...
        s.set_attr("version", snap.version)

Gate with ``DELTA_TPU_TRACE=off|on|verbose`` (default off; the disabled
path returns a shared no-op context manager). ``DELTA_TPU_TRACE_FILE``
appends finished spans as JSONL; `delta-trace` (``python -m
delta_tpu.tools.trace``) summarizes either JSONL or Chrome trace files.

Counters/histograms (`counter`, `histogram`) are always on and
process-wide; resolve them once at module import and call ``.inc()`` on
the hot path.
"""

from delta_tpu.obs.device import (
    CONDITIONS_SCHEMA,
    CONDITIONS_UNKNOWN,
    capture_conditions,
    conditions_fingerprint,
    device_dispatch,
    device_obs_enabled,
    device_obs_mode,
    dump_gate_log,
    export_device_merit,
    flush_gate_decisions,
    gate_fell_back,
    gate_observation,
    get_dispatch_records,
    get_gate_records,
    record_gate_decision,
    reset_device_obs,
    set_device_obs_mode,
    summarize_gates,
)
# Importing the submodule here (not just names) activates the
# ledger-derived gauges process-wide: hbm.py binds their set_fn
# callbacks at import time. Instrumented sites use the submodule
# directly (`from delta_tpu.obs import hbm`; `hbm.register(...)`).
from delta_tpu.obs import hbm
from delta_tpu.obs.export import (
    JsonlExporter,
    chrome_trace,
    load_spans,
    span_to_dict,
    write_chrome_trace,
)
from delta_tpu.obs.expose import (
    CONTENT_TYPE,
    metric_catalog,
    parse_prometheus,
    prom_name,
    render_prometheus,
)
from delta_tpu.obs.flight import FlightRecorder
from delta_tpu.obs.hbm import (
    hbm_obs_enabled,
    hbm_obs_mode,
    reset_hbm_obs,
    set_hbm_obs_mode,
)
from delta_tpu.obs.registry import (
    EXPORT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    counter,
    gauge,
    histogram,
    metrics_snapshot,
    registry,
)
from delta_tpu.obs.slo import (
    Breach,
    Objective,
    SloEngine,
    SloVerdict,
    serve_objectives,
)
from delta_tpu.obs.trace import (
    MODE_OFF,
    MODE_ON,
    MODE_VERBOSE,
    Span,
    add_event,
    add_exporter,
    current_span,
    get_finished_spans,
    process_label,
    remote_parent,
    remove_exporter,
    reset_trace_buffer,
    set_attr,
    set_attrs,
    set_process_label,
    set_trace_mode,
    set_trace_sample,
    span,
    trace_context,
    trace_enabled,
    trace_mode,
    trace_sample,
    wrap,
)

# Both trace and export are fully initialized here, so honoring
# DELTA_TPU_TRACE_FILE at startup is now cycle-safe (trace.py itself
# must not do this at import time — export.py imports trace.py).
if trace_enabled():
    from delta_tpu.obs.trace import _install_env_exporter_once

    _install_env_exporter_once()
    del _install_env_exporter_once

__all__ = [
    "CONDITIONS_SCHEMA",
    "CONDITIONS_UNKNOWN",
    "CONTENT_TYPE",
    "EXPORT_BUCKETS",
    "MODE_OFF",
    "MODE_ON",
    "MODE_VERBOSE",
    "Breach",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "JsonlExporter",
    "Objective",
    "Registry",
    "SloEngine",
    "SloVerdict",
    "Span",
    "add_event",
    "add_exporter",
    "capture_conditions",
    "chrome_trace",
    "conditions_fingerprint",
    "counter",
    "current_span",
    "device_dispatch",
    "device_obs_enabled",
    "device_obs_mode",
    "dump_gate_log",
    "export_device_merit",
    "flush_gate_decisions",
    "gate_fell_back",
    "gate_observation",
    "gauge",
    "get_dispatch_records",
    "get_finished_spans",
    "get_gate_records",
    "hbm",
    "hbm_obs_enabled",
    "hbm_obs_mode",
    "histogram",
    "load_spans",
    "metric_catalog",
    "metrics_snapshot",
    "record_gate_decision",
    "reset_device_obs",
    "reset_hbm_obs",
    "set_hbm_obs_mode",
    "parse_prometheus",
    "process_label",
    "prom_name",
    "registry",
    "remote_parent",
    "remove_exporter",
    "render_prometheus",
    "reset_trace_buffer",
    "serve_objectives",
    "set_attr",
    "set_attrs",
    "set_device_obs_mode",
    "set_process_label",
    "set_trace_mode",
    "summarize_gates",
    "set_trace_sample",
    "span",
    "span_to_dict",
    "trace_context",
    "trace_enabled",
    "trace_mode",
    "trace_sample",
    "wrap",
    "write_chrome_trace",
]

"""Trace exporters: JSONL span records and Chrome trace-event JSON.

Two on-disk shapes:

- JSONL — one JSON object per finished span (``{"type": "span", ...}``,
  see `Span.to_dict`). Appendable, greppable, stream-friendly; the
  `DELTA_TPU_TRACE_FILE` auto-exporter writes this.
- Chrome trace-event format — a ``{"traceEvents": [...]}`` document of
  ``ph: "X"`` complete events (ts/dur in microseconds) loadable in
  `chrome://tracing` or https://ui.perfetto.dev. `write_chrome_trace`
  converts; `delta-trace --chrome` does the same from the CLI.

`load_spans` reads either shape back into plain span dicts, so the CLI
and tests are format-agnostic.
"""

from __future__ import annotations

import io
import json
import os
import threading
from typing import Dict, Iterable, List, Optional

from delta_tpu.obs.trace import Span


def span_to_dict(span) -> Dict[str, object]:
    """Normalize a Span (or an already-dict record) to the JSONL shape."""
    if isinstance(span, Span):
        return span.to_dict()
    return dict(span)


class JsonlExporter:
    """Append finished spans to `path`, one JSON object per line.

    Thread-safe; lines are written+flushed under a lock so concurrent
    spans never interleave mid-line. Register with
    `obs.add_exporter(JsonlExporter(path))`, or set
    `DELTA_TPU_TRACE_FILE` to have one installed automatically.
    """

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh: Optional[io.TextIOWrapper] = open(  # noqa: SIM115
            path, "a", encoding="utf-8"
        )
        self._lock = threading.Lock()

    def __call__(self, span) -> None:
        line = json.dumps(span_to_dict(span), sort_keys=True,
                          default=str)
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __repr__(self):
        return f"JsonlExporter({self.path!r})"


def load_spans(path: str) -> List[Dict[str, object]]:
    """Read span dicts back from a JSONL or Chrome trace-event file."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    stripped = text.lstrip()
    if stripped.startswith("{") and '"traceEvents"' in stripped[:200]:
        return _spans_from_chrome(json.loads(stripped))
    spans = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        if rec.get("type", "span") == "span":
            spans.append(rec)
    return spans


def _spans_from_chrome(doc: Dict[str, object]) -> List[Dict[str, object]]:
    spans = []
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args", {}))
        spans.append({
            "type": "span",
            "name": ev.get("name"),
            "trace_id": args.pop("trace_id", None),
            "span_id": args.pop("span_id", None),
            "parent_id": args.pop("parent_id", None),
            "start_unix_ns": int(ev.get("ts", 0) * 1000),
            "duration_ns": int(ev.get("dur", 0) * 1000),
            "status": args.pop("status", "ok"),
            "thread_id": ev.get("tid", 0),
            "thread_name": None,
            "pid": ev.get("pid", 0),
            "process": args.pop("process", None),
            "attrs": args,
            "events": [],
        })
    return spans


def chrome_trace(spans: Iterable, pid: Optional[int] = None) -> Dict[str, object]:
    """Convert spans to a Chrome trace-event document.

    Every span becomes a ``ph: "X"`` complete event; trace/span/parent
    ids and attributes ride in ``args`` so the conversion is lossless
    enough for `load_spans` to round-trip. Each span keeps its recorded
    pid (merged multi-process dumps render as separate process rows) and
    `process_name`/`thread_name` ``ph: "M"`` metadata events group the
    timeline by process label and worker-thread name; `pid` only
    overrides spans that carry no pid of their own (legacy records).
    """
    default_pid = os.getpid() if pid is None else pid
    events: List[Dict[str, object]] = []
    thread_names: Dict[tuple, str] = {}
    process_names: Dict[int, str] = {}
    for s in spans:
        d = span_to_dict(s)
        span_pid = d.get("pid") or default_pid
        tid = d.get("thread_id") or 0
        tname = d.get("thread_name")
        if tname and (span_pid, tid) not in thread_names:
            thread_names[(span_pid, tid)] = tname
        pname = d.get("process")
        if pname and span_pid not in process_names:
            process_names[span_pid] = pname
        args = dict(d.get("attrs") or {})
        args["trace_id"] = d.get("trace_id")
        args["span_id"] = d.get("span_id")
        args["parent_id"] = d.get("parent_id")
        if pname:
            args["process"] = pname
        if d.get("status") and d["status"] != "ok":
            args["status"] = d["status"]
        for ev in d.get("events") or []:
            events.append({
                "name": ev.get("name"),
                "ph": "i",
                "ts": ev.get("ts_unix_ns", 0) / 1000.0,
                "pid": span_pid,
                "tid": tid,
                "s": "t",
                "args": dict(ev.get("attrs") or {}),
            })
        name = d.get("name") or "?"
        events.append({
            "name": name,
            "cat": name.split(".", 1)[0],
            "ph": "X",
            "ts": d.get("start_unix_ns", 0) / 1000.0,
            "dur": (d.get("duration_ns") or 0) / 1000.0,
            "pid": span_pid,
            "tid": tid,
            "args": args,
        })
        if span_pid not in process_names:
            process_names[span_pid] = f"pid {span_pid}"
    for (tpid, tid), tname in sorted(thread_names.items()):
        events.append({
            "name": "thread_name",
            "ph": "M",
            "pid": tpid,
            "tid": tid,
            "args": {"name": tname},
        })
    for ppid, pname in sorted(process_names.items()):
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": ppid,
            "args": {"name": pname},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: Iterable,
                       pid: Optional[int] = None) -> str:
    """Write spans as a Chrome trace-event JSON file; returns `path`."""
    doc = chrome_trace(spans, pid=pid)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, sort_keys=True, default=str)
    return path

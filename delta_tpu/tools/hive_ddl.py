"""Hive / Presto / Trino integration: external-table DDL over the
symlink manifest.

The reference ships a Hive connector (`connectors/hive/` — an
InputFormat/StorageHandler pair) whose end result is Hive reading the
CURRENT live-file set of a Delta table. The engine-portable route to
the same result — and the one the reference's own
`GenerateSymlinkManifest` hook exists for — is the
`_symlink_format_manifest/` directory plus a
`SymlinkTextInputFormat` external table. This module emits that DDL
(and the Presto/Trino equivalent) from a table's snapshot schema, so a
Hive/Presto/Trino deployment consumes delta-tpu tables with zero
connector code:

    from delta_tpu.tools.hive_ddl import hive_ddl
    print(hive_ddl(table, "db.events"))
    # -> CREATE EXTERNAL TABLE db.events (...) PARTITIONED BY (...)
    #    ROW FORMAT SERDE ...ParquetHiveSerDe
    #    STORED AS INPUTFORMAT ...SymlinkTextInputFormat ...

Refresh the manifest after writes with
`delta_tpu.commands.generate.generate_symlink_manifest` (or the
`delta.compatibility.symlinkFormatManifest.enabled` auto hook), then
`MSCK REPAIR TABLE` / `CALL system.sync_partition_metadata` picks up
new partitions.

CLI: python -m delta_tpu.tools.hive_ddl <table_path> <hive_name>
"""

from __future__ import annotations

from typing import List, Optional

_HIVE_TYPES = {
    "string": "STRING",
    "long": "BIGINT",
    "integer": "INT",
    "short": "SMALLINT",
    "byte": "TINYINT",
    "double": "DOUBLE",
    "float": "FLOAT",
    "boolean": "BOOLEAN",
    "binary": "BINARY",
    "date": "DATE",
    "timestamp": "TIMESTAMP",
}


def _hive_type(dt) -> str:
    """Delta type -> Hive DDL type (nested types recursively)."""
    from delta_tpu.models.schema import (
        ArrayType,
        MapType,
        PrimitiveType,
        StructType,
    )

    if isinstance(dt, PrimitiveType):
        name = dt.name
        if name.startswith("decimal"):
            return name.upper()
        try:
            return _HIVE_TYPES[name]
        except KeyError:
            raise ValueError(f"no Hive mapping for Delta type {name!r}")
    if isinstance(dt, ArrayType):
        return f"ARRAY<{_hive_type(dt.elementType)}>"
    if isinstance(dt, MapType):
        return f"MAP<{_hive_type(dt.keyType)}, {_hive_type(dt.valueType)}>"
    if isinstance(dt, StructType):
        fields = ", ".join(
            f"`{f.name}`: {_hive_type(f.dataType)}" for f in dt.fields)
        return f"STRUCT<{fields}>"
    raise ValueError(f"no Hive mapping for {dt!r}")


_TRINO_TYPES = {
    "string": "VARCHAR",
    "long": "BIGINT",
    "integer": "INTEGER",
    "short": "SMALLINT",
    "byte": "TINYINT",
    "double": "DOUBLE",
    "float": "REAL",
    "boolean": "BOOLEAN",
    "binary": "VARBINARY",
    "date": "DATE",
    "timestamp": "TIMESTAMP",
}


def _trino_type(dt) -> str:
    """Delta type -> Presto/Trino type (ARRAY(...)/MAP(...)/ROW(...))."""
    from delta_tpu.models.schema import (
        ArrayType,
        MapType,
        PrimitiveType,
        StructType,
    )

    if isinstance(dt, PrimitiveType):
        name = dt.name
        if name.startswith("decimal"):
            return name.upper()
        try:
            return _TRINO_TYPES[name]
        except KeyError:
            raise ValueError(f"no Trino mapping for Delta type {name!r}")
    if isinstance(dt, ArrayType):
        return f"ARRAY({_trino_type(dt.elementType)})"
    if isinstance(dt, MapType):
        return (f"MAP({_trino_type(dt.keyType)}, "
                f"{_trino_type(dt.valueType)})")
    if isinstance(dt, StructType):
        fields = ", ".join(
            f"\"{f.name}\" {_trino_type(f.dataType)}" for f in dt.fields)
        return f"ROW({fields})"
    raise ValueError(f"no Trino mapping for {dt!r}")


def _columns(snapshot, type_fn=_hive_type):
    schema = snapshot.schema
    part = list(snapshot.partition_columns)
    data_cols = [(f.name, type_fn(f.dataType))
                 for f in schema.fields if f.name not in part]
    # PARTITIONED BY must follow the manifest's DIRECTORY order
    # (snapshot.partition_columns) — Hive/Trino bind partition columns
    # to path levels positionally, so schema order would swap values
    # on multi-column partitioning
    by_name = {f.name: f for f in schema.fields}
    part_cols = [(n, type_fn(by_name[n].dataType)) for n in part]
    return data_cols, part_cols


def hive_ddl(table, hive_name: str,
             manifest_dir: Optional[str] = None) -> str:
    """CREATE EXTERNAL TABLE statement for Hive over the symlink
    manifest (SymlinkTextInputFormat + ParquetHiveSerDe)."""
    snapshot = table.latest_snapshot()
    data_cols, part_cols = _columns(snapshot)
    location = manifest_dir or f"{table.path}/_symlink_format_manifest"
    lines: List[str] = [f"CREATE EXTERNAL TABLE {hive_name} ("]
    lines.append(",\n".join(f"  `{n}` {t}" for n, t in data_cols))
    lines.append(")")
    if part_cols:
        parts = ", ".join(f"`{n}` {t}" for n, t in part_cols)
        lines.append(f"PARTITIONED BY ({parts})")
    lines += [
        "ROW FORMAT SERDE "
        "'org.apache.hadoop.hive.ql.io.parquet.serde.ParquetHiveSerDe'",
        "STORED AS INPUTFORMAT "
        "'org.apache.hadoop.hive.ql.io.SymlinkTextInputFormat'",
        "OUTPUTFORMAT "
        "'org.apache.hadoop.hive.ql.io"
        ".HiveIgnoreKeyTextOutputFormat'",
        f"LOCATION '{location}'",
    ]
    return "\n".join(lines)


def presto_ddl(table, catalog_schema_table: str,
               manifest_dir: Optional[str] = None) -> str:
    """Presto/Trino CREATE TABLE over the same manifest (hive
    connector with format = 'PARQUET' symlink table)."""
    snapshot = table.latest_snapshot()
    data_cols, part_cols = _columns(snapshot, type_fn=_trino_type)
    location = manifest_dir or f"{table.path}/_symlink_format_manifest"
    cols = data_cols + part_cols
    body = ",\n".join(f"  \"{n}\" {t}" for n, t in cols)
    props = [f"external_location = '{location}'", "format = 'PARQUET'"]
    if part_cols:
        names = ", ".join(f"'{n}'" for n, _t in part_cols)
        props.append(f"partitioned_by = ARRAY[{names}]")
    return (f"CREATE TABLE {catalog_schema_table} (\n{body}\n)\n"
            f"WITH (\n  " + ",\n  ".join(props) + "\n)")


def main(argv=None) -> int:
    import argparse

    from delta_tpu.table import Table

    ap = argparse.ArgumentParser(
        description="Emit Hive/Presto DDL for a Delta table "
                    "(reads via the symlink manifest)")
    ap.add_argument("table_path")
    ap.add_argument("hive_name")
    ap.add_argument("--dialect", choices=["hive", "presto"],
                    default="hive")
    ap.add_argument("--generate-manifest", action="store_true",
                    help="write/refresh _symlink_format_manifest first")
    args = ap.parse_args(argv)
    table = Table.for_path(args.table_path)
    if args.generate_manifest:
        from delta_tpu.commands.generate import generate_symlink_manifest

        generate_symlink_manifest(table)
    fn = hive_ddl if args.dialect == "hive" else presto_ddl
    print(fn(table, args.hive_name))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

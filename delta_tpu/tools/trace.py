"""delta-trace: summarize a delta-tpu trace file.

Usage::

    delta-trace trace.jsonl                    # per-operation summary table
    delta-trace trace.jsonl --sort self        # order by self-time
    delta-trace trace.jsonl --tree             # slowest trace as a span tree
    delta-trace trace.jsonl --chrome out.json  # convert to Chrome format
    python -m delta_tpu.tools.trace ...        # same, without the script

Accepts either shape `delta_tpu.obs` writes: JSONL span records
(`DELTA_TPU_TRACE_FILE`) or a Chrome trace-event document. The summary
is per span *name*: count, total wall time, self time (total minus time
attributed to child spans), mean/p95/max, and error count — the
latency/self-time table a slow snapshot load or txn retry storm is
diagnosed from.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from delta_tpu.obs.export import load_spans, write_chrome_trace


def _percentile(sorted_values: List[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list (q in [0, 100])."""
    if not sorted_values:
        return 0.0
    rank = max(0, min(len(sorted_values) - 1,
                      int(round(q / 100.0 * (len(sorted_values) - 1)))))
    return sorted_values[rank]


def compute_self_times(spans: List[Dict[str, object]]) -> Dict[str, float]:
    """Self time per span id: duration minus the sum of direct-children
    durations (clamped at zero — clock skew across threads can make the
    children nominally exceed the parent)."""
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    child_total: Dict[str, int] = {}
    for s in spans:
        parent = s.get("parent_id")
        if parent and parent in by_id:
            child_total[parent] = (child_total.get(parent, 0)
                                   + int(s.get("duration_ns") or 0))
    out: Dict[str, float] = {}
    for sid, s in by_id.items():
        dur = int(s.get("duration_ns") or 0)
        out[sid] = max(0, dur - child_total.get(sid, 0))
    return out


def summarize(spans: List[Dict[str, object]]) -> List[Dict[str, object]]:
    """Aggregate spans into per-operation rows (sorted by total time)."""
    self_ns = compute_self_times(spans)
    groups: Dict[str, List[Dict[str, object]]] = {}
    for s in spans:
        groups.setdefault(str(s.get("name")), []).append(s)
    rows = []
    for name, group in groups.items():
        durs_ms = sorted((int(s.get("duration_ns") or 0)) / 1e6
                         for s in group)
        total_ms = sum(durs_ms)
        self_ms = sum(self_ns.get(s.get("span_id"), 0) for s in group) / 1e6
        rows.append({
            "operation": name,
            "count": len(group),
            "total_ms": total_ms,
            "self_ms": self_ms,
            "avg_ms": total_ms / len(group) if group else 0.0,
            "p95_ms": _percentile(durs_ms, 95),
            "max_ms": durs_ms[-1] if durs_ms else 0.0,
            "errors": sum(1 for s in group if s.get("status") == "error"),
        })
    rows.sort(key=lambda r: r["total_ms"], reverse=True)
    return rows


def format_table(rows: List[Dict[str, object]]) -> str:
    headers = ["OPERATION", "COUNT", "TOTAL_MS", "SELF_MS", "AVG_MS",
               "P95_MS", "MAX_MS", "ERRORS"]
    body = [
        [r["operation"], str(r["count"]), f"{r['total_ms']:.3f}",
         f"{r['self_ms']:.3f}", f"{r['avg_ms']:.3f}", f"{r['p95_ms']:.3f}",
         f"{r['max_ms']:.3f}", str(r["errors"])]
        for r in rows
    ]
    widths = [max(len(h), *(len(row[i]) for row in body)) if body else len(h)
              for i, h in enumerate(headers)]
    lines = ["  ".join(h.ljust(widths[i]) for i, h in enumerate(headers))]
    for row in body:
        cells = [row[0].ljust(widths[0])]
        cells += [row[i].rjust(widths[i]) for i in range(1, len(headers))]
        lines.append("  ".join(cells))
    return "\n".join(lines)


def format_tree(spans: List[Dict[str, object]],
                trace_id: Optional[str] = None) -> str:
    """Render one trace (default: the one with the slowest root span) as
    an indented span tree with durations."""
    roots = [s for s in spans
             if not s.get("parent_id")
             or s["parent_id"] not in {x.get("span_id") for x in spans}]
    if trace_id is None:
        if not roots:
            return "(no root spans)"
        trace_id = max(roots,
                       key=lambda s: int(s.get("duration_ns") or 0)
                       )["trace_id"]
    in_trace = [s for s in spans if s.get("trace_id") == trace_id]
    children: Dict[Optional[str], List[Dict[str, object]]] = {}
    ids = {s.get("span_id") for s in in_trace}
    for s in in_trace:
        parent = s.get("parent_id")
        key = parent if parent in ids else None
        children.setdefault(key, []).append(s)
    for group in children.values():
        group.sort(key=lambda s: int(s.get("start_unix_ns") or 0))
    lines = [f"trace {trace_id}"]

    def walk(parent_key, depth):
        for s in children.get(parent_key, []):
            dur_ms = (int(s.get("duration_ns") or 0)) / 1e6
            mark = "" if s.get("status") != "error" else "  [ERROR]"
            lines.append(f"{'  ' * depth}{s.get('name')}  "
                         f"{dur_ms:.3f}ms{mark}")
            walk(s.get("span_id"), depth + 1)

    walk(None, 1)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="delta-trace",
        description="Summarize a delta-tpu trace file (JSONL or Chrome "
                    "trace-event JSON).",
    )
    parser.add_argument("trace_file", help="JSONL span file or Chrome "
                        "trace JSON")
    parser.add_argument("--sort", choices=["total", "self", "count", "name"],
                        default="total", help="summary ordering")
    parser.add_argument("--limit", type=int, default=0,
                        help="show at most N rows (0 = all)")
    parser.add_argument("--json", action="store_true",
                        help="emit the summary as JSON instead of a table")
    parser.add_argument("--tree", action="store_true",
                        help="also print the slowest trace as a span tree")
    parser.add_argument("--chrome", metavar="OUT",
                        help="convert the input to Chrome trace-event "
                             "format at OUT")
    args = parser.parse_args(argv)

    try:
        spans = load_spans(args.trace_file)
    except (OSError, json.JSONDecodeError) as e:
        print(f"delta-trace: cannot read {args.trace_file}: {e}",
              file=sys.stderr)
        return 2

    if args.chrome:
        write_chrome_trace(args.chrome, spans)
        print(f"wrote {len(spans)} spans to {args.chrome}", file=sys.stderr)

    rows = summarize(spans)
    key = {"total": "total_ms", "self": "self_ms", "count": "count",
           "name": "operation"}[args.sort]
    rows.sort(key=lambda r: r[key], reverse=(args.sort != "name"))
    if args.limit > 0:
        rows = rows[: args.limit]

    try:
        if args.json:
            print(json.dumps({"spans": len(spans), "operations": rows},
                             indent=2))
        else:
            print(f"{len(spans)} spans, {len(rows)} operations "
                  f"({args.trace_file})")
            print(format_table(rows))
            if args.tree:
                print()
                print(format_tree(spans))
    except BrokenPipeError:
        # downstream pager/head closed stdout; exit quietly like any CLI
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

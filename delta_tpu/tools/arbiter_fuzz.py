"""Multi-process kill-fuzz for the external-arbiter commit protocol.

N independent writer *processes* race commits against one table through
`ExternalArbiterLogStore(RacyLocalStore, SqliteCommitArbiter)` — the
S3+DynamoDB deployment shape — while being SIGKILLed (`os._exit`) at
randomized protocol phase boundaries:

- `before_claim` — temp file written, arbiter entry NOT yet put: the
  version stays unclaimed; another writer takes it. Only a garbage temp
  file remains.
- `after_claim`  — entry E(N, complete=false) put, N.json NOT copied:
  the classic half commit. Any later reader/writer must complete it via
  `fix_delta_log` (reference `BaseExternalLogStore.java:369-373`).
- `after_copy`   — N.json visible but E(N) still incomplete: recovery
  must acknowledge without double-copying.

Invariant checked after every round (the reference's multi-writer
correctness contract): the log is gapless, every commit file is intact
JSON attributable to exactly one writer attempt, every commit a writer
observed as successful is present verbatim, and recovery leaves the
arbiter's latest entry complete.

Run standalone for the long proof:

    python -m delta_tpu.tools.arbiter_fuzz --rounds 100

The pytest suite (`tests/test_multiprocess_arbiter.py`) runs a few
seeded rounds of the same driver.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import time
import uuid
from typing import List, Optional

CRASH_PHASES = ["before_claim", "after_claim", "after_copy"]
KILL_EXIT = 137


def _build_store(db_path: str, crash_plan):
    """ExternalArbiterLogStore wired for crash injection. `crash_plan`
    is a callable returning the phase to crash at for the NEXT commit
    attempt (or None)."""
    from delta_tpu.storage.arbiter import RacyLocalStore, SqliteCommitArbiter
    from delta_tpu.storage.cloud import ExternalArbiterLogStore

    state = {"phase": None}

    class _CrashArbiter(SqliteCommitArbiter):
        def put_entry(self, entry, overwrite):
            if not overwrite and not entry.complete:
                if state["phase"] == "before_claim":
                    os._exit(KILL_EXIT)
            super().put_entry(entry, overwrite)
            if (not overwrite and not entry.complete
                    and state["phase"] == "after_claim"):
                os._exit(KILL_EXIT)

    class _CrashStore(ExternalArbiterLogStore):
        def _write_copy_temp_file(self, src, dst):
            super()._write_copy_temp_file(src, dst)
            if state["phase"] == "after_copy":
                os._exit(KILL_EXIT)

        def write(self, path, data, overwrite=False):
            state["phase"] = crash_plan()
            super().write(path, data, overwrite)

    return _CrashStore(RacyLocalStore(), _CrashArbiter(db_path))


def _latest_version(store, table: str) -> int:
    log = os.path.join(table, "_delta_log")
    try:
        entries = list(store.list_from(os.path.join(log, f"{0:020d}.json")))
    except FileNotFoundError:
        return -1
    versions = [int(os.path.basename(fs.path).split(".")[0])
                for fs in entries
                if fs.path.endswith(".json")
                and os.path.basename(fs.path).split(".")[0].isdigit()]
    return max(versions, default=-1)


def worker_main(table: str, db_path: str, writer_id: int, seed: int,
                target_version: int, crash_prob: float) -> None:
    """Commit loop: race to advance the table to `target_version`,
    crashing at a random phase with probability `crash_prob` per
    attempt. Successful commits are recorded (fsync'd) BEFORE the next
    attempt so the checker can assert acknowledged-commit durability."""
    rng = random.Random(seed)

    def crash_plan() -> Optional[str]:
        if rng.random() < crash_prob:
            return rng.choice(CRASH_PHASES)
        return None

    store = _build_store(db_path, crash_plan)
    success_log = os.path.join(table, f"_writer_{writer_id}.log")
    fh = open(success_log, "a")
    while True:
        latest = _latest_version(store, table)
        if latest >= target_version:
            break
        v = latest + 1
        nonce = uuid.uuid4().hex
        payload = json.dumps({"commitInfo": {
            "writer": writer_id, "version": v, "nonce": nonce}}) + "\n"
        path = os.path.join(table, "_delta_log", f"{v:020d}.json")
        try:
            store.write(path, payload.encode())
        except (FileExistsError, FileNotFoundError):
            continue  # lost the race (or prev not yet visible): refresh
        fh.write(f"{v} {nonce}\n")
        fh.flush()
        os.fsync(fh.fileno())
    fh.close()


def _spawn_worker(table, db_path, writer_id, seed, target, crash_prob):
    return subprocess.Popen(
        [sys.executable, "-m", "delta_tpu.tools.arbiter_fuzz", "--worker",
         "--table", table, "--db", db_path, "--writer-id", str(writer_id),
         "--seed", str(seed), "--target", str(target),
         "--crash-prob", str(crash_prob)],
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
    )


def run_round(workdir: str, seed: int, n_writers: int = 3,
              target_version: int = 11, crash_prob: float = 0.25,
              timeout_s: float = 120.0) -> dict:
    """One fuzz round. Returns stats; raises AssertionError on any
    protocol violation."""
    rng = random.Random(seed)
    table = os.path.join(workdir, f"table_{seed}")
    os.makedirs(os.path.join(table, "_delta_log"), exist_ok=True)
    db_path = os.path.join(workdir, f"arbiter_{seed}.db")

    procs = {}
    crashes = 0
    spawned = 0
    for w in range(n_writers):
        procs[w] = _spawn_worker(table, db_path, w, rng.randrange(2**31),
                                 target_version, crash_prob)
        spawned += 1
    deadline = time.time() + timeout_s
    while procs and time.time() < deadline:
        for w, p in list(procs.items()):
            rc = p.poll()
            if rc is None:
                continue
            del procs[w]
            if rc == KILL_EXIT:
                crashes += 1
                # respawn: a new process inherits only durable state —
                # exactly the recovery the protocol must survive
                procs[w] = _spawn_worker(
                    table, db_path, w, rng.randrange(2**31),
                    target_version, crash_prob)
                spawned += 1
            elif rc != 0:
                raise AssertionError(f"writer {w} died rc={rc}")
        time.sleep(0.02)
    for p in procs.values():
        p.kill()
    if procs:
        raise AssertionError(
            f"round timed out with {len(procs)} writers still running")

    # --- recovery + invariant checks from a FRESH process-independent
    # store (a reader that never wrote) -------------------------------
    from delta_tpu.storage.arbiter import external_arbiter_store

    reader = external_arbiter_store(db_path)
    log = os.path.join(table, "_delta_log")
    listed = list(reader.list_from(os.path.join(log, f"{0:020d}.json")))
    versions = sorted(int(os.path.basename(fs.path).split(".")[0])
                      for fs in listed
                      if fs.path.endswith(".json")
                      and os.path.basename(fs.path).split(".")[0].isdigit())
    assert versions, "no commits at all"
    assert versions == list(range(versions[-1] + 1)), \
        f"log has gaps: {versions}"
    assert versions[-1] >= target_version, \
        f"never reached target: {versions[-1]} < {target_version}"

    # every commit intact + attributable, exactly one file per version
    by_version = {}
    for v in versions:
        raw = reader.read(os.path.join(log, f"{v:020d}.json"))
        doc = json.loads(raw)  # intact JSON or this throws
        ci = doc["commitInfo"]
        assert ci["version"] == v, f"v{v} holds payload for v{ci['version']}"
        by_version[v] = (ci["writer"], ci["nonce"])

    # acknowledged-commit durability: every success a writer recorded
    # must be present with that writer's exact nonce
    acked = 0
    for name in os.listdir(table):
        if not name.startswith("_writer_"):
            continue
        wid = int(name.split("_")[2].split(".")[0])
        for line in open(os.path.join(table, name)):
            v, nonce = line.split()
            assert by_version[int(v)] == (wid, nonce), \
                f"acked commit v{v} by writer {wid} lost or replaced"
            acked += 1

    # recovery leaves the arbiter consistent: latest entry complete
    latest_entry = reader.arbiter.get_latest_entry(table)
    assert latest_entry is not None and latest_entry.complete, \
        f"latest arbiter entry not complete after recovery: {latest_entry}"

    return {"seed": seed, "commits": len(versions), "crashes": crashes,
            "spawned": spawned, "acked": acked}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--table")
    ap.add_argument("--db")
    ap.add_argument("--writer-id", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--target", type=int, default=11)
    ap.add_argument("--crash-prob", type=float, default=0.25)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--writers", type=int, default=3)
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args(argv)

    if args.worker:
        worker_main(args.table, args.db, args.writer_id, args.seed,
                    args.target, args.crash_prob)
        return 0

    import tempfile

    workdir = args.workdir or tempfile.mkdtemp(prefix="arbiter_fuzz_")
    total_crashes = total_commits = 0
    t0 = time.time()
    for r in range(args.rounds):
        stats = run_round(workdir, seed=args.seed + r,
                          n_writers=args.writers,
                          target_version=args.target,
                          crash_prob=args.crash_prob)
        total_crashes += stats["crashes"]
        total_commits += stats["commits"]
        print(f"round {r}: {stats}", flush=True)
    print(json.dumps({
        "rounds": args.rounds, "writers": args.writers,
        "total_commits": total_commits, "total_crashes": total_crashes,
        "elapsed_s": round(time.time() - t0, 1), "ok": True}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Multi-process kill-fuzz for the external-arbiter commit protocol.

N independent writer *processes* race commits against one table through
`ExternalArbiterLogStore(RacyLocalStore, SqliteCommitArbiter)` — the
S3+DynamoDB deployment shape — while being SIGKILLed (`os._exit`) at
randomized protocol phase boundaries:

- `before_claim` — temp file written, arbiter entry NOT yet put: the
  version stays unclaimed; another writer takes it. Only a garbage temp
  file remains.
- `after_claim`  — entry E(N, complete=false) put, N.json NOT copied:
  the classic half commit. Any later reader/writer must complete it via
  `fix_delta_log` (reference `BaseExternalLogStore.java:369-373`).
- `after_copy`   — N.json visible but E(N) still incomplete: recovery
  must acknowledge without double-copying.

Invariant checked after every round (the reference's multi-writer
correctness contract): the log is gapless, every commit file is intact
JSON attributable to exactly one writer attempt, every commit a writer
observed as successful is present verbatim, and recovery leaves the
arbiter's latest entry complete.

`--batched` runs the same fight over the GROUP-commit emit path:
writers commit consecutive multi-member batches through
`write_batch` (one conditional multi-claim per batch) and are killed
at the batched phase seams, including the new one:

- `mid_copy` — the batch is claimed and SOME member files are copied
  but not all: the partially-durable batch the recovery contract says
  must never be stranded. `recover_all_incomplete` has to complete
  the claimed run, lowest-first.

Batched rounds additionally prove **convergence**: the pre-recovery
crash state is snapshotted, recovered twice by independent fresh
readers, and the two resulting `_delta_log/` trees must be
byte-identical — plus every member nonce appears in exactly one
version (no duplicate actions from ambiguous acks).

Run standalone for the long proof:

    python -m delta_tpu.tools.arbiter_fuzz --rounds 100
    python -m delta_tpu.tools.arbiter_fuzz --rounds 20 --batched

The pytest suite (`tests/test_multiprocess_arbiter.py`) runs a few
seeded rounds of the same driver.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import shutil
import subprocess
import sys
import time
import uuid
from typing import List, Optional

CRASH_PHASES = ["before_claim", "after_claim", "after_copy"]
# batched emits have a fourth seam: killed after copying only a prefix
# of the claimed members
BATCH_CRASH_PHASES = CRASH_PHASES + ["mid_copy"]
KILL_EXIT = 137


def _build_store(db_path: str, crash_plan):
    """ExternalArbiterLogStore wired for crash injection. `crash_plan`
    is a callable returning the phase to crash at for the NEXT commit
    attempt (or None)."""
    from delta_tpu.storage.arbiter import RacyLocalStore, SqliteCommitArbiter
    from delta_tpu.storage.cloud import ExternalArbiterLogStore

    state = {"phase": None, "copies": 0, "batch_n": 1}

    class _CrashArbiter(SqliteCommitArbiter):
        def put_entry(self, entry, overwrite):
            if not overwrite and not entry.complete:
                if state["phase"] == "before_claim":
                    os._exit(KILL_EXIT)
            super().put_entry(entry, overwrite)
            if (not overwrite and not entry.complete
                    and state["phase"] == "after_claim"):
                os._exit(KILL_EXIT)

        def put_entries(self, entries, overwrite=False):
            if not overwrite and state["phase"] == "before_claim":
                os._exit(KILL_EXIT)
            claimed = super().put_entries(entries, overwrite=overwrite)
            if not overwrite and state["phase"] == "after_claim":
                os._exit(KILL_EXIT)
            return claimed

    class _CrashStore(ExternalArbiterLogStore):
        def _write_copy_temp_file(self, src, dst):
            super()._write_copy_temp_file(src, dst)
            state["copies"] += 1
            phase = state["phase"]
            if phase == "after_copy" and state["copies"] >= state["batch_n"]:
                os._exit(KILL_EXIT)
            if (phase == "mid_copy" and state["batch_n"] > 1
                    and state["copies"] >= 1):
                # claimed batch, partial prefix of member files copied
                os._exit(KILL_EXIT)

        def write(self, path, data, overwrite=False):
            state["phase"] = crash_plan()
            state["copies"] = 0
            state["batch_n"] = 1
            super().write(path, data, overwrite)

        def write_batch(self, items, overwrite=False):
            items = list(items)
            state["phase"] = crash_plan()
            state["copies"] = 0
            state["batch_n"] = len(items)
            super().write_batch(items, overwrite=overwrite)

    return _CrashStore(RacyLocalStore(), _CrashArbiter(db_path))


def _latest_version(store, table: str) -> int:
    log = os.path.join(table, "_delta_log")
    try:
        entries = list(store.list_from(os.path.join(log, f"{0:020d}.json")))
    except FileNotFoundError:
        return -1
    versions = [int(os.path.basename(fs.path).split(".")[0])
                for fs in entries
                if fs.path.endswith(".json")
                and os.path.basename(fs.path).split(".")[0].isdigit()]
    return max(versions, default=-1)


def worker_main(table: str, db_path: str, writer_id: int, seed: int,
                target_version: int, crash_prob: float) -> None:
    """Commit loop: race to advance the table to `target_version`,
    crashing at a random phase with probability `crash_prob` per
    attempt. Successful commits are recorded (fsync'd) BEFORE the next
    attempt so the checker can assert acknowledged-commit durability."""
    rng = random.Random(seed)

    def crash_plan() -> Optional[str]:
        if rng.random() < crash_prob:
            return rng.choice(CRASH_PHASES)
        return None

    store = _build_store(db_path, crash_plan)
    success_log = os.path.join(table, f"_writer_{writer_id}.log")
    fh = open(success_log, "a")
    while True:
        latest = _latest_version(store, table)
        if latest >= target_version:
            break
        v = latest + 1
        nonce = uuid.uuid4().hex
        payload = json.dumps({"commitInfo": {
            "writer": writer_id, "version": v, "nonce": nonce}}) + "\n"
        path = os.path.join(table, "_delta_log", f"{v:020d}.json")
        try:
            store.write(path, payload.encode())
        except (FileExistsError, FileNotFoundError):
            continue  # lost the race (or prev not yet visible): refresh
        fh.write(f"{v} {nonce}\n")
        fh.flush()
        os.fsync(fh.fileno())
    fh.close()


def worker_batched_main(table: str, db_path: str, writer_id: int,
                        seed: int, target_version: int, crash_prob: float,
                        batch_members: int = 3) -> None:
    """Batched commit loop: each attempt claims a run of consecutive
    versions through ONE `write_batch` (the group-commit emit shape).
    Members are acked (fsync'd) only after the batch write returns —
    with the sqlite arbiter the claim is all-or-nothing, so a
    FileExistsError means NONE of our members landed and nothing is
    acked."""
    rng = random.Random(seed)

    def crash_plan() -> Optional[str]:
        if rng.random() < crash_prob:
            return rng.choice(BATCH_CRASH_PHASES)
        return None

    store = _build_store(db_path, crash_plan)
    success_log = os.path.join(table, f"_writer_{writer_id}.log")
    fh = open(success_log, "a")
    while True:
        latest = _latest_version(store, table)
        if latest >= target_version:
            break
        n = min(batch_members, target_version - latest)
        items = []
        members = []
        for i in range(n):
            v = latest + 1 + i
            nonce = uuid.uuid4().hex
            payload = json.dumps({"commitInfo": {
                "writer": writer_id, "version": v, "nonce": nonce,
                "member": i, "batch": n}}) + "\n"
            items.append((os.path.join(table, "_delta_log",
                                       f"{v:020d}.json"), payload.encode()))
            members.append((v, nonce))
        try:
            store.write_batch(items)
        except (FileExistsError, FileNotFoundError):
            continue  # lost the claim race / prev not visible: refresh
        for v, nonce in members:
            fh.write(f"{v} {nonce}\n")
        fh.flush()
        os.fsync(fh.fileno())
    fh.close()


def _spawn_worker(table, db_path, writer_id, seed, target, crash_prob,
                  batched=False):
    argv = [sys.executable, "-m", "delta_tpu.tools.arbiter_fuzz",
            "--worker", "--table", table, "--db", db_path,
            "--writer-id", str(writer_id), "--seed", str(seed),
            "--target", str(target), "--crash-prob", str(crash_prob)]
    if batched:
        argv.append("--batched")
    return subprocess.Popen(
        argv,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
    )


def _snapshot_state(table: str, db_path: str) -> dict:
    """Byte snapshot of the whole crash state: table dir (commit files,
    temps, ack logs) + the sqlite arbiter (db, -wal, -shm)."""
    snap = {}
    for root, _, files in os.walk(table):
        for f in files:
            p = os.path.join(root, f)
            with open(p, "rb") as fh:
                snap[p] = fh.read()
    for ext in ("", "-wal", "-shm"):
        p = db_path + ext
        if os.path.exists(p):
            with open(p, "rb") as fh:
                snap[p] = fh.read()
    return snap


def _restore_state(table: str, db_path: str, snap: dict) -> None:
    shutil.rmtree(table, ignore_errors=True)
    for ext in ("", "-wal", "-shm"):
        p = db_path + ext
        if os.path.exists(p):
            os.remove(p)
    for p, data in snap.items():
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as fh:
            fh.write(data)


def _log_digest(table: str) -> str:
    """sha256 over (name, bytes) of every commit file, sorted."""
    log = os.path.join(table, "_delta_log")
    h = hashlib.sha256()
    for name in sorted(os.listdir(log)):
        if not (name.endswith(".json") and name.split(".")[0].isdigit()):
            continue
        h.update(name.encode())
        with open(os.path.join(log, name), "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()


def run_round(workdir: str, seed: int, n_writers: int = 3,
              target_version: int = 11, crash_prob: float = 0.25,
              timeout_s: float = 120.0, batched: bool = False) -> dict:
    """One fuzz round. Returns stats; raises AssertionError on any
    protocol violation. With ``batched`` the writers commit multi-member
    batches and the round additionally proves convergence: the crash
    state is recovered twice by independent fresh readers and the two
    resulting logs must be byte-identical."""
    rng = random.Random(seed)
    table = os.path.join(workdir, f"table_{seed}")
    os.makedirs(os.path.join(table, "_delta_log"), exist_ok=True)
    db_path = os.path.join(workdir, f"arbiter_{seed}.db")

    procs = {}
    crashes = 0
    spawned = 0
    for w in range(n_writers):
        procs[w] = _spawn_worker(table, db_path, w, rng.randrange(2**31),
                                 target_version, crash_prob,
                                 batched=batched)
        spawned += 1
    deadline = time.time() + timeout_s
    while procs and time.time() < deadline:
        for w, p in list(procs.items()):
            rc = p.poll()
            if rc is None:
                continue
            del procs[w]
            if rc == KILL_EXIT:
                crashes += 1
                # respawn: a new process inherits only durable state —
                # exactly the recovery the protocol must survive
                procs[w] = _spawn_worker(
                    table, db_path, w, rng.randrange(2**31),
                    target_version, crash_prob, batched=batched)
                spawned += 1
            elif rc != 0:
                raise AssertionError(f"writer {w} died rc={rc}")
        time.sleep(0.02)
    for p in procs.values():
        p.kill()
    if procs:
        raise AssertionError(
            f"round timed out with {len(procs)} writers still running")

    # convergence proof needs the untouched crash state twice
    snap = _snapshot_state(table, db_path) if batched else None

    # --- recovery + invariant checks from a FRESH process-independent
    # store (a reader that never wrote) -------------------------------
    from delta_tpu.storage.arbiter import external_arbiter_store

    reader = external_arbiter_store(db_path)
    log = os.path.join(table, "_delta_log")
    listed = list(reader.list_from(os.path.join(log, f"{0:020d}.json")))
    versions = sorted(int(os.path.basename(fs.path).split(".")[0])
                      for fs in listed
                      if fs.path.endswith(".json")
                      and os.path.basename(fs.path).split(".")[0].isdigit())
    assert versions, "no commits at all"
    assert versions == list(range(versions[-1] + 1)), \
        f"log has gaps: {versions}"
    assert versions[-1] >= target_version, \
        f"never reached target: {versions[-1]} < {target_version}"

    # every commit intact + attributable, exactly one file per version
    by_version = {}
    for v in versions:
        raw = reader.read(os.path.join(log, f"{v:020d}.json"))
        doc = json.loads(raw)  # intact JSON or this throws
        ci = doc["commitInfo"]
        assert ci["version"] == v, f"v{v} holds payload for v{ci['version']}"
        by_version[v] = (ci["writer"], ci["nonce"])

    # no duplicate actions: every member nonce in exactly one version
    # (an ambiguous-ack rebase that re-committed a member would show
    # the same nonce twice)
    nonces = [nonce for _, nonce in by_version.values()]
    assert len(set(nonces)) == len(nonces), \
        "duplicate member payloads: same nonce in more than one version"

    # acknowledged-commit durability: every success a writer recorded
    # must be present with that writer's exact nonce
    acked = 0
    for name in os.listdir(table):
        if not name.startswith("_writer_"):
            continue
        wid = int(name.split("_")[2].split(".")[0])
        for line in open(os.path.join(table, name)):
            v, nonce = line.split()
            assert by_version[int(v)] == (wid, nonce), \
                f"acked commit v{v} by writer {wid} lost or replaced"
            acked += 1

    # recovery leaves the arbiter consistent: latest entry complete
    latest_entry = reader.arbiter.get_latest_entry(table)
    assert latest_entry is not None and latest_entry.complete, \
        f"latest arbiter entry not complete after recovery: {latest_entry}"

    stats = {"seed": seed, "commits": len(versions), "crashes": crashes,
             "spawned": spawned, "acked": acked}
    if batched:
        # convergence: restore the crash state and recover again with
        # an INDEPENDENT fresh reader; both recoveries must produce a
        # byte-identical _delta_log/
        digest_a = _log_digest(table)
        _restore_state(table, db_path, snap)
        reader_b = external_arbiter_store(db_path)
        list(reader_b.list_from(os.path.join(log, f"{0:020d}.json")))
        digest_b = _log_digest(table)
        assert digest_a == digest_b, (
            f"recovery diverged: {digest_a} != {digest_b} (seed {seed})")
        stats["digest"] = digest_a
    return stats


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--table")
    ap.add_argument("--db")
    ap.add_argument("--writer-id", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--target", type=int, default=11)
    ap.add_argument("--crash-prob", type=float, default=0.25)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--writers", type=int, default=3)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--batched", action="store_true",
                    help="fuzz the batched (group-commit) emit path")
    args = ap.parse_args(argv)

    if args.worker:
        if args.batched:
            worker_batched_main(args.table, args.db, args.writer_id,
                                args.seed, args.target, args.crash_prob)
        else:
            worker_main(args.table, args.db, args.writer_id, args.seed,
                        args.target, args.crash_prob)
        return 0

    import tempfile

    workdir = args.workdir or tempfile.mkdtemp(prefix="arbiter_fuzz_")
    total_crashes = total_commits = 0
    t0 = time.time()
    for r in range(args.rounds):
        stats = run_round(workdir, seed=args.seed + r,
                          n_writers=args.writers,
                          target_version=args.target,
                          crash_prob=args.crash_prob,
                          batched=args.batched)
        total_crashes += stats["crashes"]
        total_commits += stats["commits"]
        print(f"round {r}: {stats}", flush=True)
    print(json.dumps({
        "rounds": args.rounds, "writers": args.writers,
        "batched": args.batched,
        "total_commits": total_commits, "total_crashes": total_crashes,
        "elapsed_s": round(time.time() - t0, 1), "ok": True}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

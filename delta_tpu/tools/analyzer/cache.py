"""Scan cache: per-file fingerprints + the last report, for
``delta-lint --changed``.

The engine is whole-program (lock discipline, the race detector, and
the transfer budget all build one :class:`~.core.ProjectGraph` over
every module), so a single changed file can change findings anywhere —
per-file *finding* reuse would be unsound for any rule that accumulates
module-pass facts into its project pass. What IS sound is per-file
*change detection*: the cache keys every scanned file by
``(mtime_ns, size)`` with a content-hash fallback (a ``touch`` or a
checkout that rewrites identical bytes stays a hit), plus a stamp over
the analyzer's own sources, the catalogs its passes cross-reference
(error/metric/transfer/env-knob JSON, docs/architecture.md), and the
rule set. When nothing changed, the
previous report is reconstructed without parsing a single file —
that is the CI hot path (re-runs on unchanged trees) and the
``analyzer_cached_rescan`` bench path. When anything changed, the scan
re-runs in full and the cache is rewritten.

The cache file is plain JSON, defaulting to ``.delta-lint-cache.json``
in the current directory (override with ``--cache-file`` or
``DELTA_LINT_CACHE``). It is a pure accelerator: corrupt, stale, or
missing cache files degrade to a full scan, never to wrong output.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from delta_tpu.tools.analyzer.core import (
    Finding,
    Report,
    _iter_py_files,
    _run,
    load_modules,
)

CACHE_ENV = "DELTA_LINT_CACHE"
DEFAULT_CACHE_NAME = ".delta-lint-cache.json"
_SCHEMA = 1


def default_cache_path() -> str:
    return os.environ.get(CACHE_ENV) or DEFAULT_CACHE_NAME


# Env overrides that redirect a pass's catalog to another file; the
# pointed-at file is a scan input and must be part of the stamp too.
_CATALOG_ENVS = (
    "DELTA_LINT_CATALOG",
    "DELTA_LINT_METRIC_CATALOG",
    "DELTA_LINT_TRANSFER_BUDGET",
    "DELTA_LINT_ENV_CATALOG",
    "DELTA_LINT_ARCH_DOC",
)


def _catalog_files() -> List[str]:
    """Every non-Python input the passes consume: the packaged JSON
    catalogs (error/metric/transfer/env-knob), docs/architecture.md
    (route-contract anchors), and any env-override catalog paths."""
    out: List[str] = []
    try:
        import delta_tpu
    except ImportError:  # pragma: no cover - analyzer ships inside it
        return out
    pkg = os.path.dirname(os.path.abspath(delta_tpu.__file__))
    res = os.path.join(pkg, "resources")
    if os.path.isdir(res):
        out.extend(os.path.join(res, name)
                   for name in sorted(os.listdir(res))
                   if name.endswith(".json"))
    doc = os.path.join(os.path.dirname(pkg), "docs", "architecture.md")
    if os.path.exists(doc):
        out.append(doc)
    for env in _CATALOG_ENVS:
        p = os.environ.get(env)
        if p and os.path.exists(p) and p not in out:
            out.append(p)
    return out


def _toolprint() -> str:
    """Fingerprint of the analyzer's full input surface (stat-based):
    its own sources AND the catalogs the passes cross-reference. A rule
    edit — or a catalog edit (a new transfer-budget lane, a retired env
    knob, a renamed architecture heading) — must invalidate every
    cached report; findings depend on those files as much as on the
    scanned tree."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha1()
    for fp in sorted(_iter_py_files(pkg)):
        st = os.stat(fp)
        h.update(f"{os.path.relpath(fp, pkg)}|{st.st_mtime_ns}|"
                 f"{st.st_size}\n".encode())
    for fp in _catalog_files():
        st = os.stat(fp)
        h.update(f"{fp}|{st.st_mtime_ns}|{st.st_size}\n".encode())
    return h.hexdigest()


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def _finding_to_dict(f: Finding) -> Dict:
    return {"rule": f.rule, "path": f.path, "line": f.line,
            "col": f.col, "message": f.message, "severity": f.severity}


def _finding_from_dict(d: Dict) -> Finding:
    return Finding(d["rule"], d["path"], int(d["line"]), int(d["col"]),
                   d["message"], d.get("severity", "error"))


def _collect_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        out.extend(_iter_py_files(p))
    return out


def load_cache(cache_path: str) -> Optional[Dict]:
    try:
        with open(cache_path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != _SCHEMA:
        return None
    return doc


def _changed_files(cached: Optional[Dict], files: List[str],
                   stamp: Dict) -> Tuple[List[str], Dict[str, Dict]]:
    """Return (changed file list, fresh per-file fingerprint map).

    A file counts unchanged when (mtime_ns, size) match the cache, or
    — after a stat mismatch — its content hash still matches (touched
    but identical). Added and removed files both count as changes
    (removal shows up as a cache entry with no file on disk)."""
    prints: Dict[str, Dict] = {}
    if cached is None or cached.get("stamp") != stamp:
        for fp in files:
            st = os.stat(fp)
            prints[fp] = {"mtime_ns": st.st_mtime_ns,
                          "size": st.st_size, "sha256": _sha256(fp)}
        return list(files), prints

    old: Dict[str, Dict] = cached.get("files", {})
    changed: List[str] = []
    for fp in files:
        st = os.stat(fp)
        rec = old.get(fp)
        if rec is not None and rec.get("mtime_ns") == st.st_mtime_ns \
                and rec.get("size") == st.st_size:
            prints[fp] = rec
            continue
        sha = _sha256(fp)
        prints[fp] = {"mtime_ns": st.st_mtime_ns, "size": st.st_size,
                      "sha256": sha}
        if rec is None or rec.get("sha256") != sha:
            changed.append(fp)
    changed.extend(fp for fp in old if fp not in prints)  # deletions
    return changed, prints


def _report_from_cache(cached: Dict) -> Report:
    rep = cached["report"]
    return Report(
        findings=[_finding_from_dict(d) for d in rep["findings"]],
        suppressed=[_finding_from_dict(d) for d in rep["suppressed"]],
        files_scanned=int(rep["files_scanned"]),
        rules_run=list(rep["rules_run"]),
    )


def analyze_paths_cached(
        paths: Iterable[str],
        root: Optional[str] = None,
        rules: Optional[Iterable[str]] = None,
        cache_path: Optional[str] = None,
) -> Tuple[Report, Dict]:
    """``--changed``-mode entry point: full-fidelity report, but skip
    the scan entirely when no scanned file changed since the cached
    run. Returns ``(report, stats)`` where stats records the cache
    outcome for the CLI/bench (``hit`` | ``stale`` | ``cold``, plus the
    changed-file count)."""
    cache_path = cache_path or default_cache_path()
    rule_list = sorted(rules) if rules is not None else None
    stamp = {"schema": _SCHEMA, "tool": _toolprint(),
             "rules": rule_list, "root": root,
             "paths": sorted(os.path.abspath(p) for p in paths)}
    files = _collect_files(paths)
    cached = load_cache(cache_path)
    changed, prints = _changed_files(cached, files, stamp)

    if cached is not None and not changed:
        return _report_from_cache(cached), {
            "cache": "hit", "changed_files": 0, "files": len(files)}

    report = _run(load_modules(paths, root=root), rules)
    doc = {
        "schema": _SCHEMA,
        "stamp": stamp,
        "files": prints,
        "report": {
            "findings": [_finding_to_dict(f) for f in report.findings],
            "suppressed": [_finding_to_dict(f)
                           for f in report.suppressed],
            "files_scanned": report.files_scanned,
            "rules_run": report.rules_run,
        },
    }
    try:
        tmp = cache_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, cache_path)
    except OSError:
        pass  # unwritable cache location: still return the fresh report
    return report, {
        "cache": "cold" if cached is None else "stale",
        "changed_files": len(changed), "files": len(files)}

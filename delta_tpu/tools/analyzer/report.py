"""Reporters: human-readable text and SARIF-lite JSON.

The JSON shape is a deliberately small subset of SARIF 2.1 (tool /
results / ruleId / level / message / location) so CI systems that speak
SARIF can ingest it with a trivial adapter, without this module taking
on the full spec.
"""

from __future__ import annotations

import json
from typing import Dict

from delta_tpu.tools.analyzer.core import Finding, Report


def render_text(report: Report, verbose: bool = False) -> str:
    lines = []
    for f in report.findings:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule}: {f.message}")
    if verbose:
        for f in report.suppressed:
            lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule}: "
                         f"[suppressed] {f.message}")
    counts = report.by_rule()
    summary = ", ".join(f"{r}={n}" for r, n in sorted(counts.items()))
    lines.append(
        f"delta-lint: {len(report.findings)} finding(s)"
        + (f" ({summary})" if summary else "")
        + f", {len(report.suppressed)} suppressed, "
        f"{report.files_scanned} file(s), "
        f"rules: {', '.join(report.rules_run)}")
    return "\n".join(lines)


def _result(f: Finding) -> Dict:
    return {
        "ruleId": f.rule,
        "level": f.severity,
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": f.line, "startColumn": f.col + 1},
            },
        }],
    }


def render_json(report: Report) -> str:
    doc = {
        "version": "2.1.0-lite",
        "runs": [{
            "tool": {"driver": {"name": "delta-lint",
                                "rules": [{"id": r}
                                          for r in report.rules_run]}},
            "results": [_result(f) for f in report.findings],
            "suppressedResults": [_result(f) for f in report.suppressed],
            "summary": {
                "findings": len(report.findings),
                "suppressed": len(report.suppressed),
                "filesScanned": report.files_scanned,
                "byRule": report.by_rule(),
            },
        }],
    }
    return json.dumps(doc, indent=1, sort_keys=True)

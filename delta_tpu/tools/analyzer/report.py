"""Reporters: human-readable text and SARIF-lite JSON.

The JSON shape is a deliberately small subset of SARIF 2.1 (tool /
results / ruleId / level / message / location) so CI systems that speak
SARIF can ingest it with a trivial adapter, without this module taking
on the full spec. Each driver rule carries a ``helpUri`` pointing into
``docs/static_analysis.md`` (so CI annotations are clickable), findings
silenced by an in-source pragma are emitted with a SARIF
``suppressions`` record, and when a baseline check ran every result
carries a ``baselineState`` (``new`` for failing findings,
``unchanged`` for known debt matched against the committed baseline).
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from delta_tpu.tools.analyzer.core import Finding, Report, all_rules


def render_text(report: Report, verbose: bool = False) -> str:
    lines = []
    for f in report.findings:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule}: {f.message}")
    if verbose:
        for f in report.baselined:
            lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule}: "
                         f"[baselined] {f.message}")
        for f in report.suppressed:
            lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule}: "
                         f"[suppressed] {f.message}")
    counts = report.by_rule()
    summary = ", ".join(f"{r}={n}" for r, n in sorted(counts.items()))
    baseline_note = (f", {len(report.baselined)} baselined"
                     if report.baseline_checked else "")
    lines.append(
        f"delta-lint: {len(report.findings)} finding(s)"
        + (f" ({summary})" if summary else "")
        + baseline_note
        + f", {len(report.suppressed)} suppressed, "
        f"{report.files_scanned} file(s), "
        f"rules: {', '.join(report.rules_run)}")
    return "\n".join(lines)


def _result(f: Finding, baseline_state: Optional[str] = None,
            suppressed: bool = False) -> Dict:
    out = {
        "ruleId": f.rule,
        "level": f.severity,
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": f.line, "startColumn": f.col + 1},
            },
        }],
    }
    if baseline_state is not None:
        out["baselineState"] = baseline_state
    if suppressed:
        # the pragma lives in the scanned source, next to the finding
        out["suppressions"] = [{"kind": "inSource",
                                "status": "accepted"}]
    return out


def _driver_rules(report: Report) -> list:
    registry = all_rules()
    out = []
    for rid in report.rules_run:
        cls = registry.get(rid)
        entry: Dict = {"id": rid}
        if cls is not None:
            if cls.description:
                entry["shortDescription"] = {"text": cls.description}
            entry["helpUri"] = cls.help_uri()
        out.append(entry)
    return out


def render_json(report: Report) -> str:
    new_state = "new" if report.baseline_checked else None
    doc = {
        "version": "2.1.0-lite",
        "runs": [{
            "tool": {"driver": {"name": "delta-lint",
                                "rules": _driver_rules(report)}},
            "results": [_result(f, baseline_state=new_state)
                        for f in report.findings],
            "baselinedResults": [_result(f, baseline_state="unchanged")
                                 for f in report.baselined],
            "suppressedResults": [_result(f, suppressed=True)
                                  for f in report.suppressed],
            "summary": {
                "findings": len(report.findings),
                "baselined": len(report.baselined),
                "suppressed": len(report.suppressed),
                "filesScanned": report.files_scanned,
                "byRule": report.by_rule(),
            },
        }],
    }
    return json.dumps(doc, indent=1, sort_keys=True)

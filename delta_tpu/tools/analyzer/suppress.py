"""Suppression comments for delta-lint.

Two forms, both comments so they survive formatting and never affect
runtime behavior:

- line-scoped: ``# delta-lint: disable=RULE[,RULE2]`` on the line the
  finding is reported at (for multi-line statements that is the first
  line of the statement). Anything after the rule list is free-form
  audit rationale and is encouraged:
  ``with self._lock:  # delta-lint: disable=lock-io — put-if-absent``
  A pragma on a standalone comment line applies to the next code line,
  so multi-line audit rationale can sit between pragma and code.
- file-scoped: ``# delta-lint: file-disable=RULE[,RULE2]`` anywhere in
  the file (conventionally in the module docstring area) disables the
  rules for the whole file.

``disable=all`` matches every rule.
"""

from __future__ import annotations

import re
from typing import Dict, FrozenSet, Tuple

_LINE_RE = re.compile(
    r"#\s*delta-lint:\s*(file-)?disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


def parse_suppressions(
        source: str) -> Tuple[Dict[int, FrozenSet[str]], FrozenSet[str]]:
    """Return (per-line rule sets keyed by 1-based lineno, file-level
    rule set). Purely lexical: a pragma inside a string literal would
    also count, which is fine for a lint suppression."""
    per_line: Dict[int, FrozenSet[str]] = {}
    file_level: set = set()
    pending: FrozenSet[str] = frozenset()  # from standalone comment lines
    for lineno, line in enumerate(source.splitlines(), start=1):
        stripped = line.strip()
        comment_only = stripped.startswith("#")
        m = _LINE_RE.search(line)
        if m:
            rules = frozenset(r.strip() for r in m.group(2).split(","))
            if m.group(1):
                file_level |= rules
            elif comment_only:
                pending |= rules  # applies to the next code line
            else:
                per_line[lineno] = per_line.get(lineno, frozenset()) | rules
        if pending and stripped and not comment_only:
            per_line[lineno] = per_line.get(lineno, frozenset()) | pending
            pending = frozenset()
    return per_line, frozenset(file_level)


def is_suppressed(rule_id: str, line: int,
                  per_line: Dict[int, FrozenSet[str]],
                  file_level: FrozenSet[str]) -> bool:
    if "all" in file_level or rule_id in file_level:
        return True
    rules = per_line.get(line)
    return bool(rules) and ("all" in rules or rule_id in rules)

"""Serve-layer handler discipline rule: ``handler-discipline``.

The serve layer (``delta_tpu/serve/``) exists to make request handling
*bounded*: a fixed worker pool, an admission queue, and an ambient
deadline on everything a worker does. Two code shapes silently defeat
those bounds, so both are flagged inside the serve tree:

1. **direct ``threading.Thread(...)`` construction** anywhere except
   ``serve/pool.py``. A thread minted outside the pool module is
   unnamed, uncounted (misses the ``server.threads_spawned`` counter),
   and — the real hazard — unbounded: the old connect server's
   thread-per-connection growth is exactly the failure mode admission
   control replaced. Every serve thread goes through
   :func:`delta_tpu.serve.pool.spawn`.
2. **``io_call(...)`` outside a deadline scope.** The serve layer's
   contract is that storage work done on behalf of a request is
   abandoned when the client's budget expires; ``RetryPolicy`` only
   honours that when an ambient deadline is in scope. An ``io_call``
   lexically outside any ``with deadline_scope(...)`` /
   ``deadline_scope_at(...)`` block (and outside the worker execution
   path that establishes one) retries to its own private deadline,
   holding a bounded worker long after the client hung up. Handlers
   normally inherit the scope from
   ``AdmissionController._execute``; code that calls ``io_call``
   *directly* in the serve tree must establish its own scope.

Scope is ``delta_tpu/serve/`` only — everywhere else these are the
concern of ``threadpool-discipline`` and the resilience layer's
defaults. Audited exceptions carry a
``# delta-lint: disable=handler-discipline`` pragma.
"""

from __future__ import annotations

import ast
from typing import List, Set

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register
from delta_tpu.tools.analyzer.passes._astutil import call_name

_SCOPE_FNS = {"deadline_scope", "deadline_scope_at"}


def _thread_ctor_names(tree: ast.Module) -> Set[str]:
    """Dotted call names that resolve to ``threading.Thread`` in this
    module: ``import threading [as t]`` binds ``t.Thread``; ``from
    threading import Thread [as x]`` binds ``x``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "threading":
                for a in node.names:
                    if a.name == "Thread":
                        names.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "threading":
                    names.add(f"{a.asname or a.name}.Thread")
    return names


def _io_call_names(tree: ast.Module) -> Set[str]:
    """Dotted call names that resolve to
    ``delta_tpu.resilience.io_call``: direct import (optionally
    aliased) or attribute access through an imported resilience
    module."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module in ("delta_tpu.resilience", "delta_tpu"):
                for a in node.names:
                    if a.name == "io_call":
                        names.add(a.asname or a.name)
                    elif a.name == "resilience":
                        names.add(f"{a.asname or a.name}.io_call")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "delta_tpu.resilience":
                    names.add(f"{a.asname}.io_call" if a.asname
                              else "delta_tpu.resilience.io_call")
    return names


def _scope_call(item: ast.withitem) -> bool:
    if not isinstance(item.context_expr, ast.Call):
        return False
    name = call_name(item.context_expr)
    return bool(name) and name.rsplit(".", 1)[-1] in _SCOPE_FNS


class _IoCallVisitor(ast.NodeVisitor):
    """Collects io_call sites, tracking whether each is lexically under
    a ``with deadline_scope(...)`` item."""

    def __init__(self, io_names: Set[str]):
        self.io_names = io_names
        self.depth = 0  # nested deadline-scope with-blocks
        self.bad: List[ast.Call] = []

    def visit_With(self, node: ast.With) -> None:
        scoped = any(_scope_call(i) for i in node.items)
        if scoped:
            self.depth += 1
        self.generic_visit(node)
        if scoped:
            self.depth -= 1

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node)
        if name in self.io_names and self.depth == 0:
            self.bad.append(node)
        self.generic_visit(node)


@register
class HandlerDisciplineRule(Rule):
    id = "handler-discipline"
    description = ("serve-layer handler spawning raw threads or doing "
                   "storage IO outside a deadline scope — route threads "
                   "through serve/pool.spawn and io_call through "
                   "`with deadline_scope(...)`")

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        rel = mod.rel.replace("\\", "/")
        if "delta_tpu/serve/" not in rel:
            return []
        findings: List[Finding] = []

        # 1. raw thread construction (pool.py is the one allowed owner)
        if not rel.endswith("serve/pool.py"):
            ctors = _thread_ctor_names(tree)
            if ctors:
                for node in ast.walk(tree):
                    if isinstance(node, ast.Call) \
                            and call_name(node) in ctors:
                        findings.append(Finding(
                            self.id, mod.path, node.lineno, node.col_offset,
                            "raw threading.Thread(...) in the serve layer "
                            "— spawn through delta_tpu.serve.pool.spawn so "
                            "the thread is named, counted, and bounded"))

        # 2. io_call outside any deadline scope
        io_names = _io_call_names(tree)
        if io_names:
            v = _IoCallVisitor(io_names)
            v.visit(tree)
            for node in v.bad:
                findings.append(Finding(
                    self.id, mod.path, node.lineno, node.col_offset,
                    "io_call(...) outside a deadline scope in the serve "
                    "layer — wrap in `with deadline_scope(...)` (or "
                    "deadline_scope_at) so the request's budget bounds "
                    "the storage retries"))
        return findings

"""Recompile-risk lint: ``recompile-risk``.

The static twin of the runtime ``DELTA_TPU_RECOMPILE_ALARM`` (PR 15):
a jit/shard_map/pallas callsite whose operand shape tracks a
data-dependent length compiles a fresh executable per distinct length
— the recompile storms the dispatch profiler alarms on at runtime are
*statically visible* in the operand constructors. Inside the covered
kernel modules, this pass flags calls to jitted callables whose
operands take their shape from ``len(...)``, ``.shape`` of an
unpadded input, or an appended-to list build, without the length
first flowing through a recognized pad-to-bucket helper
(``ops/replay.py::pad_bucket`` — the repo-wide bucketing quantum).

The taint model is deliberately local and conservative (near-zero
noise beats exhaustive recall — the runtime alarm still backstops):

- a local becomes a *tainted scalar* when assigned from an expression
  containing ``len(...)`` or ``.shape`` with no pad-helper call;
- a local assigned from a pad-helper call is *padded*, and scalar
  arithmetic over a padded local stays padded (``pad = m - n`` is the
  bucket complement — the canonical top-up idiom
  ``np.concatenate([x, np.zeros(pad)])`` is bucket-sized by
  construction, so it must not flag);
- a local list that is ``.append``-ed to is a *tainted list* (its
  length is data-dependent by construction);
- a local becomes a *tainted array* when an array constructor's
  **shape position** is data-dependent — ``zeros/ones/empty/full``
  judge their shape argument, ``arange`` any argument,
  ``asarray/array`` taint only from a tainted list/array input (a
  0-d ``np.asarray(n)`` scalar operand carries value, not shape),
  ``concatenate/stack`` from tainted list/array inputs or a nested
  shape-tainted constructor — and taint propagates through
  array-to-array assignment;
- passing a tainted array (or an inline shape-tainted constructor)
  to a jitted callable is the finding, one per callsite.

Jitted callables are recognized module-locally: defs decorated with
``jit``/``jax.jit``/``partial(jax.jit, ...)``/``pjit``/``pallas_call``
and names assigned from those calls.

Intentionally shape-polymorphic sites carry a *typed exemption*: the
in-code registry below maps ``rel.py::qualname`` to (kind, reason) —
``bounded-polymorphism`` (the varying axis is schema-bound to a
handful of values), ``cached-wrapper`` (the callee memoizes per padded
shape elsewhere), ``host-fallback`` (the call only runs off the hot
path), or ``measured`` (churn is priced and alarmed at runtime).
Overrides, mostly for fixture tests:

  DELTA_LINT_RECOMPILE_MODULES      comma-separated rel paths
                                    replacing the covered-module set
  DELTA_LINT_RECOMPILE_PAD_HELPERS  comma-separated callable names
                                    replacing the pad-helper set
  DELTA_LINT_RECOMPILE_EXEMPT       comma-separated ``rel.py::qualname``
                                    entries replacing the exemption
                                    registry
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from delta_tpu.tools.analyzer.core import (
    Finding,
    ModuleInfo,
    Rule,
    register,
)
from delta_tpu.tools.analyzer.passes._astutil import call_name

# The covered kernel modules: every jit launch in these files is on a
# hot path where a recompile storm is a production incident.
_DEFAULT_MODULES = (
    "delta_tpu/ops/json_parse.py",
    "delta_tpu/ops/page_decode.py",
    "delta_tpu/ops/skipping.py",
    "delta_tpu/ops/stats.py",
    "delta_tpu/ops/replay.py",
    "delta_tpu/ops/replay_blockwise.py",
    "delta_tpu/ops/zorder.py",
    "delta_tpu/parallel/resident.py",
    "delta_tpu/parallel/sharded_replay.py",
    "delta_tpu/parallel/sharded_blockwise.py",
    "delta_tpu/stats/device_index.py",
    "delta_tpu/sqlengine/device.py",
)

_DEFAULT_PAD_HELPERS = ("pad_bucket",)

# Typed exemptions: intentionally shape-polymorphic sites.
# kind: bounded-polymorphism | cached-wrapper | host-fallback | measured
_EXEMPTIONS: Dict[str, Tuple[str, str]] = {
    "delta_tpu/ops/zorder.py::zorder_sort_indices": (
        "bounded-polymorphism",
        "the stacked key matrix's first axis is the clustering column "
        "count — schema-bound to a handful of distinct values per "
        "table, while the row axis pads to pad_bucket; OPTIMIZE "
        "compiles one program per column count by design and the "
        "runtime recompile alarm prices any storm"),
}

_JIT_DECOS = {"jit", "pjit", "pallas_call", "shard_map"}
_ARRAY_CTORS = {"zeros", "ones", "full", "empty", "arange", "asarray",
                "array", "concatenate", "stack"}


def _covered_modules() -> Set[str]:
    env = os.environ.get("DELTA_LINT_RECOMPILE_MODULES")
    if env is not None:
        return {p.strip() for p in env.split(",") if p.strip()}
    return set(_DEFAULT_MODULES)


def _pad_helpers() -> Set[str]:
    env = os.environ.get("DELTA_LINT_RECOMPILE_PAD_HELPERS")
    if env is not None:
        return {p.strip() for p in env.split(",") if p.strip()}
    return set(_DEFAULT_PAD_HELPERS)


def _exempt_sites() -> Set[str]:
    env = os.environ.get("DELTA_LINT_RECOMPILE_EXEMPT")
    if env is not None:
        return {p.strip() for p in env.split(",") if p.strip()}
    return set(_EXEMPTIONS)


def _tail(name: Optional[str]) -> str:
    return name.rpartition(".")[2] if name else ""


def _is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``pl.pallas_call(...)`` / ``shard_map(...)``
    — also matches ``partial(jax.jit, ...)`` decorator forms."""
    if not isinstance(node, ast.Call):
        return False
    if _tail(call_name(node)) in _JIT_DECOS:
        return True
    if _tail(call_name(node)) == "partial":
        return any(_tail(_dotted_of(a)) in _JIT_DECOS
                   for a in node.args)
    return False


def _dotted_of(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted_of(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _jit_names(tree: ast.Module) -> Set[str]:
    """Module-local names bound to jitted callables."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if _is_jit_call(deco) or _tail(_dotted_of(deco)) \
                        in _JIT_DECOS:
                    out.add(node.name)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_jit_call(node.value):
            out.add(node.targets[0].id)
    return out


def _mentions(expr: ast.AST, names: Set[str]) -> Optional[str]:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) and sub.id in names:
            return sub.id
    return None


def _has_call(expr: ast.AST, tails: Set[str]) -> bool:
    return any(isinstance(sub, ast.Call)
               and _tail(call_name(sub)) in tails
               for sub in ast.walk(expr))


def _is_length_source(expr: ast.AST) -> bool:
    """len(...) or .shape anywhere in the expression."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and _tail(call_name(sub)) == "len":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "shape":
            return True
    return False


def _list_accumulators(fn: ast.AST) -> Set[str]:
    """Locals assigned a list literal/ctor and later .append-ed to —
    their length is data-dependent by construction."""
    assigned: Set[str] = set()
    appended: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = node.value
            if isinstance(v, ast.List) or (
                    isinstance(v, ast.Call)
                    and _tail(call_name(v)) == "list"):
                assigned.add(node.targets[0].id)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "append" \
                and isinstance(node.func.value, ast.Name):
            appended.add(node.func.value.id)
    return assigned & appended


def _own_statements(fn: ast.AST) -> Iterable[ast.stmt]:
    """Source-order statements of fn's own body (nested defs are their
    own analysis units and are skipped)."""
    stack: List[ast.stmt] = list(reversed(getattr(fn, "body", [])))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        inner: List[ast.stmt] = []
        for field in ("body", "orelse", "finalbody"):
            inner.extend(getattr(node, field, []))
        for handler in getattr(node, "handlers", []):
            inner.extend(handler.body)
        stack.extend(reversed(inner))


def _qualnames(tree: ast.Module):
    """(fn node, qualname) for every function def in the module."""
    def visit(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield child, q
                yield from visit(child, q)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield from visit(child, q)
            else:
                yield from visit(child, prefix)
    yield from visit(tree, "")


@register
class RecompileRiskRule(Rule):
    id = "recompile-risk"
    help_anchor = "recompile-risk"
    description = (
        "jitted callsite in a covered kernel module whose operand "
        "shape derives from a data-dependent length (len()/.shape/"
        "list build) without flowing through a pad-to-bucket helper — "
        "every distinct length compiles a fresh executable (the "
        "static twin of DELTA_TPU_RECOMPILE_ALARM)")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        modules = _covered_modules()
        pads = _pad_helpers()
        exempt = _exempt_sites()
        out: List[Finding] = []
        for mod in mods:
            if mod.rel not in modules or mod.tree is None:
                continue
            jits = _jit_names(mod.tree)
            if not jits:
                continue
            for fn, qual in _qualnames(mod.tree):
                site = f"{mod.rel}::{qual}"
                if site in exempt:
                    continue
                out.extend(self._check_fn(mod.rel, fn, qual, jits,
                                          pads))
        return out

    def _check_fn(self, rel: str, fn: ast.AST, qual: str,
                  jits: Set[str], pads: Set[str]) -> List[Finding]:
        lists = _list_accumulators(fn)
        scalars: Set[str] = set()   # data-dependent lengths
        arrays: Set[str] = set()    # shape tracks a tainted length
        padded: Set[str] = set()    # flowed through a pad helper
        seen: Set[int] = set()      # callsites already judged
        out: List[Finding] = []

        def dd(expr: ast.AST) -> bool:
            """Data-dependent length expression (padded names are
            bucket-quantized, so a bare padded Name is NOT dd)."""
            return (_mentions(expr, scalars | lists) is not None
                    or _is_length_source(expr))

        def ctor_tainted(call: ast.AST) -> bool:
            if not isinstance(call, ast.Call):
                return False
            tail = _tail(call_name(call))
            if tail not in _ARRAY_CTORS or _has_call(call, pads):
                return False
            if tail in ("zeros", "ones", "empty", "full"):
                shape = call.args[0] if call.args else None
                for kw in call.keywords:
                    if kw.arg == "shape":
                        shape = kw.value
                return shape is not None and dd(shape)
            if tail == "arange":
                return any(dd(a) for a in call.args)
            if tail in ("asarray", "array"):
                arg = call.args[0] if call.args else None
                return arg is not None and _mentions(
                    arg, lists | arrays) is not None
            # concatenate/stack: output length sums the inputs
            for a in list(call.args) + [kw.value for kw in
                                        call.keywords]:
                if _mentions(a, lists | arrays):
                    return True
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Call) and sub is not call \
                            and ctor_tainted(sub):
                        return True
            return False

        for stmt in _own_statements(fn):
            if isinstance(stmt, ast.Assign) \
                    and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name, value = stmt.targets[0].id, stmt.value
                scalars.discard(name)
                arrays.discard(name)
                padded.discard(name)
                if _has_call(value, pads):
                    padded.add(name)
                elif isinstance(value, ast.Call) \
                        and _tail(call_name(value)) in _ARRAY_CTORS:
                    if ctor_tainted(value):
                        arrays.add(name)
                elif _mentions(value, arrays):
                    arrays.add(name)
                elif _mentions(value, padded) \
                        and not _is_length_source(value):
                    # bucket-complement arithmetic (pad = m - n)
                    padded.add(name)
                elif _is_length_source(value) \
                        or _mentions(value, scalars):
                    scalars.add(name)
            for node in ast.walk(stmt):
                if not (isinstance(node, ast.Call)
                        and _tail(call_name(node)) in jits) \
                        or id(node) in seen:
                    continue
                seen.add(id(node))
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    if _has_call(arg, pads):
                        continue
                    src = _mentions(arg, arrays)
                    if src is None and ctor_tainted(arg):
                        src = "<inline constructor>"
                    if src is None:
                        continue
                    out.append(Finding(
                        self.id, rel, node.lineno, node.col_offset,
                        f"operand {src!r} of jitted "
                        f"{_tail(call_name(node))}() in {qual}() takes "
                        f"its shape from a data-dependent length "
                        f"without a pad helper — every distinct length "
                        f"compiles a fresh executable; pad_bucket() "
                        f"the length or add a typed exemption for "
                        f"{rel}::{qual}"))
                    break  # one finding per callsite is enough
        return out

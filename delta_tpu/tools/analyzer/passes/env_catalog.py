"""Env-knob census: ``env-knob-uncataloged`` / ``env-knob-dead-entry``
/ ``env-knob-capture-stamp``.

The repo's runtime behavior is steered by ~70 ``DELTA_TPU_*`` /
``DELTA_LINT_*`` env knobs; docs drift and undocumented knobs were the
rule, not the exception. The single source of truth is
``delta_tpu/resources/env_knobs.json`` —
``{"knobs": {NAME: {"default", "modules", "doc", "help",
"capture"?}}}`` — and this pass cross-references read sites and
catalog in both directions, entirely statically (AST census, mirrors
the metric-name pass):

- ``env-knob-uncataloged`` — an ``os.environ.get`` / ``os.getenv`` /
  ``os.environ[...]`` read of a ``DELTA_TPU_*``/``DELTA_LINT_*`` name
  with no catalog entry, or from a module the entry doesn't list
  (drift);
- ``env-knob-dead-entry`` — a catalog entry no module reads, or whose
  ``modules`` list names a scanned module with no read site (docs
  would advertise a knob that does nothing there);
- ``env-knob-capture-stamp`` — an entry marked ``"capture": true``
  (routing-relevant: it changes a gate decision or what a bench
  measured) that is missing from the obs module's
  ``CAPTURE_ENV_KEYS`` stamp tuple — the PR 16 "forgot to stamp
  DELTA_TPU_DEVICE_DECODE" class of omission.

The census resolves two indirections interprocedurally: names held in
module-level string constants (``BASELINE_ENV = "DELTA_LINT_BASELINE"``
then ``os.environ.get(BASELINE_ENV)``) and module-local env-helper
functions (a function passing a parameter straight to
``os.environ.get`` — ``_env_num("DELTA_TPU_SERVE_WORKERS", 4)`` is a
read site). Dynamic names beyond that are out of scope by design; a
dynamic knob would surface as a dead catalog entry, which is the
point.

The catalog path defaults to the packaged resource and can be
overridden with ``DELTA_LINT_ENV_CATALOG`` (fixture tests); the obs
module holding ``CAPTURE_ENV_KEYS`` honors ``DELTA_LINT_OBS_MODULE``.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register
from delta_tpu.tools.analyzer.passes._astutil import call_name
from delta_tpu.tools.analyzer.passes.metrics_catalog import _catalog_key_line
from delta_tpu.tools.analyzer.passes.route_contract import (
    _module_str_constants,
    _obs_module,
    _str_const,
)

_KNOB_RE = re.compile(r"^DELTA_(TPU|LINT)_[A-Z0-9_]+$")

_ENV_GETTERS = ("os.environ.get", "environ.get", "os.getenv", "getenv")


def _catalog_path() -> Optional[str]:
    env = os.environ.get("DELTA_LINT_ENV_CATALOG")
    if env:
        return env
    try:
        import delta_tpu
    except ImportError:  # pragma: no cover - analyzer ships inside it
        return None
    path = os.path.join(os.path.dirname(delta_tpu.__file__),
                        "resources", "env_knobs.json")
    return path if os.path.exists(path) else None


def _load_catalog() -> Tuple[Optional[Dict], Optional[str]]:
    path = _catalog_path()
    if path is None:
        return None, None
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f), path
    except (OSError, ValueError):
        return None, None


def _env_helpers(tree: ast.Module) -> Set[str]:
    """Module-local functions that forward a parameter to
    os.environ.get / os.getenv — their literal-name call sites count
    as env reads."""
    out: Set[str] = set()
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = {a.arg for a in node.args.args}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and sub.args \
                    and call_name(sub) in _ENV_GETTERS \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id in params:
                out.add(node.name)
                break
    return out


class _EnvScan:
    """One project-wide census: {knob: [(rel, line), ...]}."""

    def __init__(self, mods: List[ModuleInfo]):
        self.sites: Dict[str, List[Tuple[str, int]]] = {}
        for mod in mods:
            if mod.tree is not None:
                self._scan(mod)

    def _add(self, name: Optional[str], rel: str, line: int) -> None:
        if name and _KNOB_RE.match(name):
            self.sites.setdefault(name, []).append((rel, line))

    def _scan(self, mod: ModuleInfo) -> None:
        consts = _module_str_constants(mod.tree)
        helpers = _env_helpers(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and node.args:
                cn = call_name(node)
                if cn is None:
                    continue
                arg = node.args[0]
                name = _str_const(arg)
                if name is None and isinstance(arg, ast.Name):
                    name = consts.get(arg.id)
                if cn in _ENV_GETTERS:
                    self._add(name, mod.rel, node.lineno)
                elif cn.rpartition(".")[2] in helpers:
                    # helper reads resolve only for literal/const names
                    self._add(_str_const(arg) or name, mod.rel,
                              node.lineno)
            elif isinstance(node, ast.Subscript):
                base = node.value
                is_environ = (isinstance(base, ast.Attribute)
                              and base.attr == "environ") or \
                             (isinstance(base, ast.Name)
                              and base.id == "environ")
                if is_environ:
                    name = _str_const(node.slice)
                    if name is None and isinstance(node.slice, ast.Name):
                        name = consts.get(node.slice.id)
                    self._add(name, mod.rel, node.lineno)


# identity-compared single-entry census cache (same idiom as the
# metric census: fresh ModuleInfos can never falsely hit a stale scan)
_CACHE: List[Tuple[List[ModuleInfo], _EnvScan]] = []


def _scan_for(mods: List[ModuleInfo]) -> _EnvScan:
    if _CACHE:
        cached_mods, cached = _CACHE[0]
        if len(cached_mods) == len(mods) \
                and all(a is b for a, b in zip(cached_mods, mods)):
            return cached
    scan = _EnvScan(mods)
    _CACHE[:] = [(list(mods), scan)]
    return scan


@register
class EnvKnobUncatalogedRule(Rule):
    id = "env-knob-uncataloged"
    help_anchor = "env-knob-census"
    description = (
        "os.environ read of a DELTA_TPU_*/DELTA_LINT_* name with no "
        "resources/env_knobs.json entry, or from a module the entry's "
        "'modules' list doesn't name (drifted catalog)")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        catalog, _path = _load_catalog()
        if catalog is None:
            return []
        knobs = catalog.get("knobs") or {}
        scan = _scan_for(mods)
        out: List[Finding] = []
        for name in sorted(scan.sites):
            entry = knobs.get(name)
            if entry is None:
                for rel, line in scan.sites[name]:
                    out.append(Finding(
                        self.id, rel, line, 0,
                        f"env knob {name!r} is not cataloged in "
                        f"env_knobs.json — add name, default, module, "
                        f"and doc anchor"))
                continue
            listed = set(entry.get("modules") or [])
            for rel, line in scan.sites[name]:
                if listed and rel not in listed:
                    out.append(Finding(
                        self.id, rel, line, 0,
                        f"env knob {name!r} is read in {rel} but the "
                        f"catalog lists {sorted(listed)} — update the "
                        f"entry's 'modules' (drifted catalog)"))
        return out


@register
class EnvKnobDeadEntryRule(Rule):
    id = "env-knob-dead-entry"
    help_anchor = "env-knob-census"
    description = (
        "env_knobs.json entry no module reads (dead knob — docs would "
        "advertise a switch wired to nothing), or whose 'modules' list "
        "names a scanned module with no read site")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        catalog, path = _load_catalog()
        if catalog is None:
            return []
        scan = _scan_for(mods)
        # only meaningful when the scanned set reads env at all (a
        # single-file fixture scan would mark everything dead)
        if not scan.sites:
            return []
        scanned_rels = {m.rel for m in mods}
        out: List[Finding] = []
        for name in sorted(catalog.get("knobs") or {}):
            entry = catalog["knobs"][name]
            sites = scan.sites.get(name)
            if not sites:
                out.append(Finding(
                    self.id, os.path.basename(path),
                    _catalog_key_line(path, name), 0,
                    f"catalog entry {name!r} is read by no scanned "
                    f"module (dead knob — remove the entry or wire "
                    f"the knob)"))
                continue
            read_rels = {rel for rel, _ in sites}
            for rel in sorted(set(entry.get("modules") or [])):
                if rel in scanned_rels and rel not in read_rels:
                    out.append(Finding(
                        self.id, os.path.basename(path),
                        _catalog_key_line(path, name), 0,
                        f"catalog entry {name!r} lists module {rel} "
                        f"but {rel} never reads it — the 'modules' "
                        f"list drifted"))
        return out


@register
class EnvKnobCaptureStampRule(Rule):
    id = "env-knob-capture-stamp"
    help_anchor = "env-knob-census"
    description = (
        "routing-relevant env knob (env_knobs.json \"capture\": true) "
        "missing from obs/device.py::CAPTURE_ENV_KEYS — bench "
        "captures taken with the knob set would be silently "
        "incomparable")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        catalog, _path = _load_catalog()
        if catalog is None:
            return []
        obs_mod = _obs_module(mods)
        if obs_mod is None or obs_mod.tree is None:
            return []
        keys: Optional[Set[str]] = None
        line = 1
        for node in obs_mod.tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None:
                target, value = node.target, node.value
            if isinstance(target, ast.Name) \
                    and target.id.lstrip("_") == "CAPTURE_ENV_KEYS" \
                    and isinstance(value, (ast.Tuple, ast.List)):
                keys = {v for v in (_str_const(e) for e in value.elts)
                        if v is not None}
                line = node.lineno
                break
        if keys is None:
            return []
        out: List[Finding] = []
        for name in sorted(catalog.get("knobs") or {}):
            entry = catalog["knobs"][name]
            if entry.get("capture") and name not in keys:
                out.append(Finding(
                    self.id, obs_mod.rel, line, 0,
                    f"routing-relevant env knob {name!r} is not in "
                    f"CAPTURE_ENV_KEYS — add it to the capture-"
                    f"conditions stamp (or drop \"capture\": true "
                    f"from its env_knobs.json entry)"))
        return out

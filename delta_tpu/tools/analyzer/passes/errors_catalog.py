"""Error-catalog conformance.

The reference implementation keeps every user-facing error in
``delta-error-classes.json`` and raises through typed factories; our
equivalent is ``delta_tpu/resources/error_classes.json`` plus
``error_class`` attributes on ``DeltaError`` subclasses. Three rules
cross-reference raise sites and catalog in both directions, entirely
statically (AST census — nothing is imported):

- ``error-uncataloged`` — an ``error_class`` string used in code
  (class default or explicit ``error_class=`` kwarg at a raise site)
  that has no catalog entry: a typo'd or forgotten class;
- ``error-dead-entry`` — a catalog entry no raise site can produce:
  not any raised type's default, not an ancestor default of a raised
  type, not an explicit kwarg anywhere, not a ``FAMILY.SUBCODE`` of a
  produced family, and not in the audited-unproduced allowlist;
- ``error-untyped-raise`` — a raise of an exception type that is
  neither a cataloged Delta error, an allowed builtin/protocol
  exception, a module-internal (``_``-prefixed) control-flow exception,
  nor a re-raised local.

The catalog path defaults to the installed package resource and can be
overridden with ``DELTA_LINT_CATALOG`` (fixture tests use this).
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register

# exceptions that are NOT user-facing Delta errors: builtins for
# internal invariants, storage-protocol exceptions with documented
# contracts, and parse-layer locals (kept in sync with
# tests/test_error_catalog.py, which exercises the same invariant
# dynamically)
_ALLOWED_NON_DELTA = {
    "ValueError", "TypeError", "KeyError", "IndexError", "RuntimeError",
    "IOError", "OSError", "FileNotFoundError", "FileExistsError",
    "NotImplementedError", "StopIteration", "TimeoutError",
    "AssertionError", "ConnectionError", "InterruptedError",
    "AttributeError", "EOFError", "SystemExit", "ImportError",
    "ModuleNotFoundError", "MemoryError", "OverflowError",
    "ZeroDivisionError", "StopAsyncIteration", "KeyboardInterrupt",
    "FileAlreadyExistsError", "PreconditionFailedError",
    "TableAlreadyExistsError", "TableNotInCatalogError",
    "ParseError", "CommitFailedException",
    "DecodeUnsupported", "DynamoDbError",
    # storage-protocol IOError subclasses: StorageRequestError carries
    # the HTTP status the resilience classifier keys on; ChaosError is
    # the chaos harness's injected (always-transient) fault, and the
    # Device* pair is its dispatch-funnel twin (classified transient
    # via the `retryable` attribute)
    "StorageRequestError", "ChaosError",
    "DeviceChaosError", "DeviceResourceExhaustedError",
}

# catalog entries with no statically-attributable raise site, each
# audited: UnsupportedTableFeatureError narrows to the WRITE class
# inside __init__; the merge clause-ordering trio is raised through a
# data-driven loop (error_class=ec) covered by test_merge_clause_validation
_AUDITED_UNPRODUCED = {
    "DELTA_UNSUPPORTED_FEATURES_FOR_WRITE",
    "DELTA_NON_LAST_MATCHED_CLAUSE_OMIT_CONDITION",
    "DELTA_NON_LAST_NOT_MATCHED_CLAUSE_OMIT_CONDITION",
    "DELTA_NON_LAST_NOT_MATCHED_BY_SOURCE_CLAUSE_OMIT_CONDITION",
    "DELTA_ERROR",  # the family root every DeltaError narrows from
}


def _catalog_path() -> Optional[str]:
    env = os.environ.get("DELTA_LINT_CATALOG")
    if env:
        return env
    try:
        import delta_tpu

        path = os.path.join(os.path.dirname(delta_tpu.__file__),
                            "resources", "error_classes.json")
        return path if os.path.exists(path) else None
    except ImportError:  # pragma: no cover - analyzer ships inside it
        return None


class _CatalogScan:
    """One project-wide census shared by the three rules."""

    def __init__(self, mods: List[ModuleInfo]):
        self.defaults: Dict[str, Tuple[str, str, int]] = {}  # cls -> (ec, rel, line)
        self.bases: Dict[str, List[str]] = {}
        self.raised: Dict[str, List[Tuple[str, int]]] = {}   # type -> sites
        self.kwarg_sites: List[Tuple[str, str, int]] = []    # (ec, rel, line)
        for mod in mods:
            self._scan(mod)

    def _scan(self, mod: ModuleInfo):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                self.bases.setdefault(node.name, [])
                for b in node.bases:
                    base = b.attr if isinstance(b, ast.Attribute) else (
                        b.id if isinstance(b, ast.Name) else None)
                    if base:
                        self.bases[node.name].append(base)
                for st in node.body:
                    targets = []
                    if isinstance(st, ast.Assign):
                        targets = st.targets
                    elif isinstance(st, ast.AnnAssign):  # error_class: str = ...
                        targets = [st.target]
                    for tg in targets:
                        if isinstance(tg, ast.Name) \
                                and tg.id == "error_class" \
                                and isinstance(st.value, ast.Constant) \
                                and isinstance(st.value.value, str):
                            self.defaults[node.name] = (
                                st.value.value, mod.rel, st.lineno)
            elif isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                if isinstance(exc, ast.Call):
                    for kw in exc.keywords:
                        if kw.arg == "error_class" \
                                and isinstance(kw.value, ast.Constant) \
                                and isinstance(kw.value.value, str):
                            self.kwarg_sites.append(
                                (kw.value.value, mod.rel, node.lineno))
                    exc = exc.func
                name = None
                if isinstance(exc, ast.Name):
                    name = exc.id
                elif isinstance(exc, ast.Attribute):
                    name = exc.attr
                if name:
                    self.raised.setdefault(name, []).append(
                        (mod.rel, node.lineno))

    def ancestors(self, cls: str) -> Set[str]:
        out: Set[str] = set()
        queue = list(self.bases.get(cls, ()))
        while queue:
            b = queue.pop()
            if b in out:
                continue
            out.add(b)
            queue.extend(self.bases.get(b, ()))
        return out

    def produced_classes(self) -> Set[str]:
        produced = {ec for ec, _rel, _line in self.kwarg_sites}
        for typ in self.raised:
            if typ in self.defaults:
                produced.add(self.defaults[typ][0])
            for anc in self.ancestors(typ):
                if anc in self.defaults:
                    produced.add(self.defaults[anc][0])
        return produced


# single-entry cache retaining the mods list: identity-compared, so a
# later run's fresh ModuleInfos can never falsely hit a stale census
# (see the matching comment in passes/locks.py)
_CACHE: List[Tuple[List[ModuleInfo], _CatalogScan]] = []


def _scan_for(mods: List[ModuleInfo]) -> _CatalogScan:
    if _CACHE:
        cached_mods, cached = _CACHE[0]
        if len(cached_mods) == len(mods) \
                and all(a is b for a, b in zip(cached_mods, mods)):
            return cached
    scan = _CatalogScan(mods)
    _CACHE[:] = [(list(mods), scan)]
    return scan


def _load_catalog() -> Tuple[Optional[Dict], Optional[str]]:
    path = _catalog_path()
    if path is None:
        return None, None
    with open(path, encoding="utf-8") as f:
        return json.load(f), path


def _catalog_key_line(path: str, key: str) -> int:
    """Locate a top-level key's line in the JSON text, for clickable
    dead-entry findings."""
    needle = f'"{key}"'
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            if line.lstrip().startswith(needle):
                return lineno
    return 1


@register
class ErrorUncatalogedRule(Rule):
    id = "error-uncataloged"
    description = ("error_class string (class default or error_class= "
                   "kwarg) with no entry in error_classes.json")

    def check_project(self, mods):
        catalog, _path = _load_catalog()
        if catalog is None:
            return ()
        scan = _scan_for(mods)
        findings = []
        for cls, (ec, rel, line) in sorted(scan.defaults.items()):
            if ec not in catalog:
                findings.append(Finding(
                    self.id, rel, line, 0,
                    f"class {cls} defaults to error_class {ec!r} which "
                    f"is not in error_classes.json"))
        for ec, rel, line in scan.kwarg_sites:
            if ec not in catalog:
                findings.append(Finding(
                    self.id, rel, line, 0,
                    f"raise site uses error_class={ec!r} which is not "
                    f"in error_classes.json"))
        return findings


@register
class ErrorDeadEntryRule(Rule):
    id = "error-dead-entry"
    description = ("catalog entry in error_classes.json that no raise "
                   "site can produce")

    def check_project(self, mods):
        catalog, path = _load_catalog()
        if catalog is None:
            return ()
        scan = _scan_for(mods)
        # only meaningful when the scanned set actually contains the
        # error taxonomy (a single-file scan would mark everything dead)
        if not scan.defaults:
            return ()
        produced = scan.produced_classes()
        findings = []
        for key in sorted(catalog):
            if key in produced or key in _AUDITED_UNPRODUCED:
                continue
            family = key.split(".", 1)[0]
            if family != key and (family in produced
                                  or family in _AUDITED_UNPRODUCED):
                continue  # subcode of a produced family
            findings.append(Finding(
                self.id, os.path.basename(path), _catalog_key_line(path, key),
                0, f"catalog entry {key!r} is produced by no raise site "
                   f"(dead entry — remove it or raise it)"))
        return findings


@register
class ErrorUntypedRaiseRule(Rule):
    id = "error-untyped-raise"
    description = ("raise of an exception type that is neither a "
                   "cataloged Delta error nor an allowed "
                   "builtin/protocol exception")

    def check_project(self, mods):
        scan = _scan_for(mods)
        findings = []
        for typ, sites in sorted(scan.raised.items()):
            if typ in scan.defaults or typ in _ALLOWED_NON_DELTA:
                continue
            if typ.startswith("_"):
                continue  # module-internal control-flow exception
            if not typ[0].isupper():
                continue  # re-raise of a caught local (e, err, exc, ...)
            if typ in scan.bases:
                # defined in the scanned set without error_class: only
                # allowed when some ancestor carries one
                if any(a in scan.defaults for a in scan.ancestors(typ)):
                    continue
            for rel, line in sites:
                findings.append(Finding(
                    self.id, rel, line, 0,
                    f"raise of {typ} which is neither a cataloged "
                    f"DeltaError nor an allowed builtin (add an "
                    f"error_class or extend the allowlist)"))
        return findings

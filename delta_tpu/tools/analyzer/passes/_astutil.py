"""Shared AST helpers for delta-lint passes."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c"; `name` -> "name"; anything else -> None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted(node.func)


def iter_functions(
        tree: ast.Module) -> Iterator[Tuple[str, Optional[str], ast.AST]]:
    """Yield (qualname, class_name, funcdef) for every function in the
    module: module-level functions, methods one class deep, and nothing
    nested inside other functions (those are handled by whoever walks
    the enclosing function's body)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, None, node
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{node.name}.{item.name}", node.name, item


def build_function_table(
        tree: ast.Module) -> Dict[str, ast.AST]:
    """qualname -> def node, for intra-module call resolution."""
    return {qn: fn for qn, _cls, fn in iter_functions(tree)}


def resolve_local_call(name: str, cls: Optional[str],
                       table: Dict[str, ast.AST]) -> Optional[str]:
    """Resolve a call's dotted name to a qualname in `table`:
    `helper()` -> "helper"; `self.m()` / `cls.m()` inside class C ->
    "C.m"; `C.m()` -> "C.m". Returns None for anything unresolvable
    (imported modules, attribute chains on objects)."""
    if name in table:
        return name
    head, _, rest = name.partition(".")
    if rest and "." not in rest:
        if head in ("self", "cls") and cls is not None:
            qn = f"{cls}.{rest}"
            return qn if qn in table else None
        qn = f"{head}.{rest}"
        return qn if qn in table else None
    return None

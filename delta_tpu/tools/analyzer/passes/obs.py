"""Tracing-hygiene rule: ``obs-span-leak``.

Two ways an instrumented module silently corrupts traces:

- **span() outside ``with``** — ``obs.span(...)`` returns a context
  manager; the span only starts/finishes (and restores the contextvar
  parent stack) through ``__enter__``/``__exit__``. A bare call —
  assigned to a variable, passed as an argument, or discarded — never
  records and, worse, reads as instrumentation that isn't there.
- **raw ``time.perf_counter_ns()``** — hand-rolled timing in a module
  that already imports ``delta_tpu.obs`` bypasses the span clock: the
  measured interval exists nowhere in the trace tree, so self-time math
  and Chrome export silently disagree with it. Use a span (or a
  registry histogram); audited exceptions carry a
  ``# delta-lint: disable=obs-span-leak`` pragma (e.g. ``metrics.py``,
  whose reports must work with tracing off).

The ``delta_tpu/obs`` package itself is the implementation of the span
clock and is exempt by path.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register
from delta_tpu.tools.analyzer.passes._astutil import call_name

_OBS_MODULES = ("delta_tpu.obs", "delta_tpu.obs.trace")


def _span_call_names(tree: ast.Module) -> Set[str]:
    """Dotted call names that resolve to ``delta_tpu.obs``'s ``span`` in
    this module: ``from delta_tpu.obs import span [as x]`` binds ``x``;
    ``from delta_tpu import obs [as o]`` / ``import delta_tpu.obs as o``
    bind ``o.span``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module in _OBS_MODULES:
                for a in node.names:
                    if a.name == "span":
                        names.add(a.asname or a.name)
            elif node.module == "delta_tpu":
                for a in node.names:
                    if a.name == "obs":
                        names.add(f"{a.asname or a.name}.span")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "delta_tpu.obs":
                    names.add(f"{a.asname or a.name}.span"
                              if a.asname else "delta_tpu.obs.span")
    return names


def _imports_obs(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.startswith("delta_tpu.obs"):
                return True
            if node.module == "delta_tpu" and any(
                    a.name == "obs" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any(a.name.startswith("delta_tpu.obs")
                   for a in node.names):
                return True
    return False


@register
class ObsSpanLeakRule(Rule):
    id = "obs-span-leak"
    description = ("span(...) used outside a `with` statement (the span "
                   "never records), or raw time.perf_counter_ns() timing "
                   "in a module that is already span-instrumented")

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        # the obs package IS the span clock; its internal cross-imports
        # (trace -> export) must not make it count as instrumented
        rel = mod.rel.replace("\\", "/")
        if "delta_tpu/obs/" in rel or rel.startswith("obs/"):
            return []
        span_names = _span_call_names(tree)
        instrumented = _imports_obs(tree)
        if not span_names and not instrumented:
            return []

        with_calls: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        with_calls.add(id(item.context_expr))

        out: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            if name in span_names and id(node) not in with_calls:
                out.append(Finding(
                    self.id, mod.rel, node.lineno, node.col_offset,
                    f"{name}(...) outside a `with` statement: the span "
                    f"is never entered, so it records nothing and the "
                    f"code looks instrumented when it isn't"))
            elif instrumented and name == "time.perf_counter_ns":
                out.append(Finding(
                    self.id, mod.rel, node.lineno, node.col_offset,
                    "raw time.perf_counter_ns() in a span-instrumented "
                    "module: the interval bypasses the trace tree — use "
                    "a span (or audit + suppress)"))
        return out

"""Undefined-name pass.

Absorbs (and extends to every scan target) the symtable check that
``tests/test_module_imports.py`` introduced after the r05
``_check_create_spec_matches`` gap: a name a function scope resolves as
GLOBAL must be bound at module level (imports, defs, assignments —
``symtable`` records bindings from every branch, so conditional imports
count) or be a builtin. This is exactly the class of bug where a helper
is called but never defined and only explodes when that code path runs.

Modules using ``from x import *`` are skipped (module-level bindings
are not statically enumerable there).
"""

from __future__ import annotations

import ast
import builtins
import symtable
from typing import List

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register

_BUILTINS = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__annotations__", "__class__",
    "__debug__", "__path__", "WindowsError",
}


@register
class UndefinedNameRule(Rule):
    id = "undefined-name"
    description = ("function references a module-level name that is "
                   "bound nowhere (missing import / undefined helper)")

    def check_module(self, mod: ModuleInfo):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) \
                    and any(a.name == "*" for a in node.names):
                return ()  # star-import: bindings not enumerable
        try:
            table = symtable.symtable(mod.source, mod.path, "exec")
        except SyntaxError:  # reported by the engine as parse-error
            return ()
        module_names = set(table.get_identifiers())
        findings: List[Finding] = []

        def line_of(name: str, scope_name: str) -> int:
            """Best-effort source line for the reference (symtable has
            no position info): first line mentioning the name inside
            the named function if findable, else the first mention."""
            for lineno, line in enumerate(mod.source.splitlines(),
                                          start=1):
                if name in line and not line.lstrip().startswith("#"):
                    return lineno
            return 1

        def walk(t):
            if t.get_type() == "function":
                for sym in t.get_symbols():
                    if (sym.is_referenced() and sym.is_global()
                            and not sym.is_assigned()
                            and sym.get_name() not in module_names
                            and sym.get_name() not in _BUILTINS):
                        findings.append(Finding(
                            self.id, mod.rel,
                            line_of(sym.get_name(), t.get_name()), 0,
                            f"{t.get_name()}() references undefined "
                            f"module-level name {sym.get_name()!r}"))
            for child in t.get_children():
                walk(child)

        walk(table)
        return findings

"""Retry discipline rule: ``retry-discipline``.

All transient-failure handling goes through ``delta_tpu/resilience``
(``RetryPolicy`` / ``io_call``): one classifier decides what is
retryable, one policy owns backoff/jitter/deadline, and the attempt and
sleep counters land in the shared metrics registry. A hand-rolled retry
loop anywhere else is a discipline leak three ways:

- **unbounded or uncoordinated waiting** — ad-hoc ``time.sleep`` inside
  an exception-handling loop invents its own backoff curve, invisible to
  the wall-clock deadline and the breaker state everything else honours;
- **wrong transient set** — local loops re-decide which errors are worth
  retrying and drift from the catalog-driven classifier;
- **invisible retries** — attempts outside the policy never increment
  ``storage.retry.attempts``, so chaos runs and production incidents
  under-report.

Three shapes are flagged:

1. a ``for``/``while`` loop that both handles exceptions and calls
   ``time.sleep`` — the classic grown-by-hand retry/backoff loop;
2. a ``for _ in range(<literal>)`` loop with a ``try`` directly in its
   body — a hard-coded attempt cap that belongs in ``RetryPolicy``
   (env-tunable), not in the call site;
3. a ``try`` whose body dispatches to the device (a ``device_dispatch``
   call, or a route thunk run through ``shed_retry``) with an exception
   handler that neither classifies the error (``classify`` /
   ``is_transient`` / ``route_failed`` / ``absorb_route_failure``) nor
   bumps a fallback counter (``.inc(...)``) nor re-raises — a silent
   device fallback that starves the route breaker and under-reports
   exactly the failures the chaos soak injects.

``delta_tpu/resilience/`` itself is exempt by path — the policy is the
one place allowed to own the loop, and the chaos harness's injected
latency is a sleep by design. Audited exceptions elsewhere (e.g. a
protocol-mandated ``Retry-After`` honoured from a server response) carry
a ``# delta-lint: disable=retry-discipline`` pragma.
"""

from __future__ import annotations

import ast
from typing import List, Set

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register
from delta_tpu.tools.analyzer.passes._astutil import call_name


def _sleep_call_names(tree: ast.Module) -> Set[str]:
    """Dotted call names that resolve to ``time.sleep`` in this module:
    ``import time [as t]`` binds ``t.sleep``; ``from time import sleep
    [as s]`` binds ``s``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "time":
                for a in node.names:
                    if a.name == "sleep":
                        names.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    names.add(f"{a.asname or a.name}.sleep")
    return names


def _has_handler(loop: ast.AST) -> bool:
    return any(isinstance(n, ast.ExceptHandler) for n in ast.walk(loop))


def _literal_range_loop(node: ast.For) -> bool:
    """``for _ in range(<number literal>)`` (one argument, constant)."""
    it = node.iter
    if not (isinstance(it, ast.Call) and call_name(it) == "range"):
        return False
    return (len(it.args) == 1
            and isinstance(it.args[0], ast.Constant)
            and isinstance(it.args[0].value, int))


# exception-handler calls that count as "the error was classified":
# the classifier itself, and the absorption helpers that route through
# it (resilience/device_faults.py, parallel/gate.py)
_CLASSIFIER_CALLS = {"classify", "is_transient", "route_failed",
                     "absorb_route_failure"}

# calls that mark the try body as a device-route dispatch site
_DISPATCH_CALLS = {"device_dispatch", "shed_retry"}


def _walk_same_scope(stmts):
    """Walk statements without descending into nested function/class/
    lambda scopes — a dispatch inside a nested def is its own call
    site, not this try's."""
    stack = list(stmts)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue  # also prunes defs that ARE the try's statements
        stack.extend(ast.iter_child_nodes(n))


def _dispatches_device(stmts) -> bool:
    """True when the statements contain a device-dispatch call."""
    return any(
        isinstance(n, ast.Call)
        and (call_name(n) or "").rpartition(".")[2] in _DISPATCH_CALLS
        for n in _walk_same_scope(stmts))


def _handler_disciplined(handler: ast.ExceptHandler) -> bool:
    """A disciplined device-dispatch handler classifies, counts, or
    re-raises (incl. `except X: raise`-style translation)."""
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Call):
            tail = (call_name(n) or "").rpartition(".")[2]
            if tail in _CLASSIFIER_CALLS or tail == "inc":
                return True
    return False


@register
class RetryDisciplineRule(Rule):
    id = "retry-discipline"
    description = ("hand-rolled retry loop (time.sleep inside an "
                   "exception-handling loop, or a literal attempt cap "
                   "around a try) outside delta_tpu/resilience — use "
                   "RetryPolicy/io_call so backoff, deadlines, and "
                   "retry metrics stay unified")

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        rel = mod.rel.replace("\\", "/")
        # the one package allowed to own retry loops and injected sleeps
        if "delta_tpu/resilience/" in rel or rel.startswith("resilience/"):
            return []
        sleep_names = _sleep_call_names(tree)
        out: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            if not _has_handler(node):
                continue
            sleeps = [
                n.lineno for n in ast.walk(node)
                if isinstance(n, ast.Call) and call_name(n) in sleep_names
            ] if sleep_names else []
            if sleeps:
                out.append(Finding(
                    self.id, mod.rel, node.lineno, node.col_offset,
                    f"loop handles exceptions and sleeps (line "
                    f"{sleeps[0]}): hand-rolled retry/backoff — route "
                    f"through resilience.RetryPolicy (or audit + "
                    f"suppress)"))
                continue  # one finding per loop
            if (isinstance(node, ast.For) and _literal_range_loop(node)
                    and any(isinstance(stmt, ast.Try)
                            for stmt in node.body)):
                out.append(Finding(
                    self.id, mod.rel, node.lineno, node.col_offset,
                    "literal attempt cap around a try block: move the "
                    "retry budget into resilience.RetryPolicy (env-"
                    "tunable) instead of hard-coding it (or audit + "
                    "suppress)"))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Try):
                continue
            if not node.handlers or not _dispatches_device(node.body):
                continue
            for handler in node.handlers:
                if not _handler_disciplined(handler):
                    out.append(Finding(
                        self.id, mod.rel, handler.lineno,
                        handler.col_offset,
                        "device_dispatch exception handler neither "
                        "classifies the error (resilience.classify / "
                        "absorb_route_failure), bumps a fallback "
                        "counter, nor re-raises: silent device "
                        "fallbacks starve the route breaker — follow "
                        "the resilience/device_faults.py contract (or "
                        "audit + suppress)"))
        return out

"""JAX purity lint.

Jitted replay/SQL kernels are retraced from cache keys that only cover
argument shapes/dtypes and static args — any host-side effect inside
the traced region either silently freezes at trace time (``time.time``
baked in as a constant, RNG drawn once) or breaks retracing. Two rules:

- ``jit-impure`` — walks every function reachable from a ``jax.jit`` /
  ``pl.pallas_call`` decoration or call site (including
  ``functools.partial(jax.jit, ...)`` decorator forms and module-level
  jit-wrapper aliases) and flags host impurities: wall-clock reads,
  non-JAX RNG (``random.*`` / ``np.random.*`` — ``jax.random`` is fine),
  file/process/network I/O, and mutation of closed-over or global state
  (``global`` / ``nonlocal`` rebinds, ``self.x = ...`` stores);
- ``jit-sync`` — host-synchronizing materialization in device code:
  ``.item()`` / ``.tolist()`` inside jit-reachable functions, and
  ``.block_until_ready()`` anywhere in library code (it belongs in
  benchmarks, not the serving path).

Call resolution is name-based within the module (an over-approximation:
all same-named functions are considered reachable), which is the right
trade-off for a lint — missing an alias would hide a real impurity.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register
from delta_tpu.tools.analyzer.passes._astutil import call_name, dotted

_JIT_NAMES = {"jax.jit", "jit", "pl.pallas_call", "pallas_call",
              "pltpu.pallas_call", "jax.pmap", "pmap",
              # sharded-kernel wrappers: a shard_map/pjit body is traced
              # exactly like a jit body and gets the same purity rules
              "shard_map", "jax.shard_map", "pjit", "jax.pjit"}

# cross-device collectives only appear inside traced (device) code, so
# any function calling one is a root even without a visible jit wrapper
# (e.g. a kernel-body factory returned into shard_map by the caller)
_COLLECTIVE_NAMES = {"lax.psum", "jax.lax.psum", "psum",
                     "lax.pmean", "jax.lax.pmean", "pmean",
                     "lax.all_gather", "jax.lax.all_gather",
                     "lax.ppermute", "jax.lax.ppermute"}

_IMPURE_EXACT = {
    "time.time", "time.time_ns", "time.perf_counter",
    "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
    "time.process_time", "time.sleep", "open", "input", "print",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "uuid.uuid4", "uuid.uuid1",
}
_IMPURE_PREFIXES = (
    "random.", "np.random.", "numpy.random.", "secrets.", "shutil.",
    "subprocess.", "socket.", "requests.", "urllib.", "os.",
)
_IMPURE_EXEMPT = {
    # pure helpers under impure prefixes
    "os.path.join", "os.path.dirname", "os.path.basename",
    "os.path.splitext", "os.fspath", "os.environ.get", "os.getenv",
}
_SYNC_METHODS = {"item", "tolist"}


def _contains_jit_name(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = dotted(sub)
        if name in _JIT_NAMES:
            return True
    return False


class _PurityScan:
    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.findings: List[Finding] = []
        tree = mod.tree

        # every function def in the module, by bare name (any nesting)
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)

        # module-level aliases wrapping jax.jit, e.g.
        # _block_kernel = functools.partial(jax.jit, static_argnames=...)
        aliases: Set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _contains_jit_name(node.value):
                aliases.add(node.targets[0].id)

        roots: List[ast.AST] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _contains_jit_name(dec) or dotted(dec) in aliases:
                        roots.append(node)
                        break
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if (name in _JIT_NAMES or name in aliases) and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name) and arg.id in defs:
                        roots.extend(defs[arg.id])
                    elif isinstance(arg, ast.Call):
                        # factory form: shard_map(make_kernel(...), ...)
                        # — the factory (and the nested body it returns)
                        # is traced code
                        inner = call_name(arg)
                        if inner in defs:
                            roots.extend(defs[inner])

        # any function using a collective is device code, jit'd or not
        for fns in defs.values():
            for fn in fns:
                if any(isinstance(sub, ast.Call)
                       and call_name(sub) in _COLLECTIVE_NAMES
                       for sub in ast.walk(fn)):
                    roots.append(fn)

        # reachability over name-based calls
        reachable: List[ast.AST] = []
        seen: Set[int] = set()
        queue = list(roots)
        while queue:
            fn = queue.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            reachable.append(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name is None:
                    continue
                tail = name.rpartition(".")[2]
                if name in defs:
                    queue.extend(defs[name])
                elif name.startswith(("self.", "cls.")) and tail in defs:
                    queue.extend(defs[tail])

        emitted: Set[tuple] = set()

        def emit(rule, node, msg):
            key = (rule, node.lineno, node.col_offset, msg)
            if key not in emitted:
                emitted.add(key)
                self.findings.append(Finding(
                    rule, mod.rel, node.lineno, node.col_offset, msg))

        for fn in reachable:
            ctx = f"jit-reachable function {fn.name}()"
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    if name and _is_impure(name):
                        emit("jit-impure", node,
                             f"host-impure call {name}() inside {ctx}")
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr in _SYNC_METHODS \
                            and not node.args:
                        emit("jit-sync", node,
                             f".{node.func.attr}() host sync inside {ctx}")
                elif isinstance(node, (ast.Global, ast.Nonlocal)):
                    emit("jit-impure", node,
                         f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                         f" rebinding of {', '.join(node.names)} inside "
                         f"{ctx} (traced code must not mutate host state)")
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            emit("jit-impure", node,
                                 f"self.{t.attr} store inside {ctx} "
                                 f"(traced code must not mutate host "
                                 f"state)")

        # block_until_ready: a benchmarking construct; flag anywhere
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                emit("jit-sync", node,
                     ".block_until_ready() in library code (host sync "
                     "belongs in benchmarks, not the serving path)")


def _is_impure(name: str) -> bool:
    if name in _IMPURE_EXACT:
        return True
    if name in _IMPURE_EXEMPT:
        return False
    return name.startswith(_IMPURE_PREFIXES)


class _PurityRuleBase(Rule):
    def check_module(self, mod: ModuleInfo):
        return [f for f in _PurityScan(mod).findings if f.rule == self.id]


@register
class JitImpureRule(_PurityRuleBase):
    id = "jit-impure"
    description = ("host impurity (clock, RNG, I/O, state mutation) in "
                   "a function reachable from jax.jit / pallas_call")


@register
class JitSyncRule(_PurityRuleBase):
    id = "jit-sync"
    description = (".item()/.tolist() in jit-reachable code or "
                   ".block_until_ready() in library code")

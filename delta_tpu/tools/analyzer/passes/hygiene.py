"""Exception hygiene.

- ``except-swallow`` — an ``except Exception:`` / bare ``except:`` /
  ``except BaseException:`` handler that swallows silently: it neither
  re-raises, nor uses the bound exception, nor logs/warns. Every such
  handler either gets narrowed to the exceptions the fallback is
  actually for, gains a ``logging`` breadcrumb, or carries an audited
  ``# delta-lint: disable=except-swallow`` pragma explaining why
  anything-goes is correct there (e.g. "never fail the commit for a
  post-commit accelerator").
- ``mutable-default`` — a mutable default argument (``def f(x=[])`` /
  ``={}`` / ``=set()``): the single most classic shared-state bug in
  long-running Python services; the default is evaluated once and
  shared by every call.
"""

from __future__ import annotations

import ast
from typing import List

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register
from delta_tpu.tools.analyzer.passes._astutil import call_name

_BROAD = {"Exception", "BaseException"}
_LOG_HEADS = ("logging", "logger", "log", "_log", "warnings", "traceback")
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log", "print_exc", "format_exc"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Attribute):
        return t.attr in _BROAD
    if isinstance(t, ast.Tuple):
        return any(_is_broad(ast.ExceptHandler(type=e, name=None, body=[]))
                   for e in t.elts)
    return False


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the body neither re-raises, uses the bound exception,
    nor logs."""
    bound = handler.name
    for node in ast.walk(ast.Module(body=handler.body,
                                    type_ignores=[])):
        if isinstance(node, ast.Raise):
            return False
        if bound and isinstance(node, ast.Name) and node.id == bound:
            return False
        if isinstance(node, ast.Call):
            name = call_name(node) or ""
            head = name.split(".", 1)[0]
            tail = name.rpartition(".")[2]
            if name == "print" or head in _LOG_HEADS \
                    or tail in _LOG_METHODS:
                return False
    return True


@register
class ExceptSwallowRule(Rule):
    id = "except-swallow"
    description = ("broad `except Exception`/bare `except` that "
                   "silently swallows: no re-raise, no use of the "
                   "exception, no logging")

    def check_module(self, mod: ModuleInfo):
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                    and _handler_swallows(node):
                what = ("bare except" if node.type is None else
                        "except Exception" if getattr(
                            node.type, "id", getattr(
                                node.type, "attr", "")) == "Exception"
                        else "except BaseException")
                findings.append(Finding(
                    self.id, mod.rel, node.lineno, node.col_offset,
                    f"{what} swallows silently — narrow it to the "
                    f"exceptions the fallback is for, log the error, or "
                    f"audit + suppress"))
        return findings


_MUTABLE_CALLS = {"list", "dict", "set", "OrderedDict", "defaultdict",
                  "deque", "Counter"}


@register
class MutableDefaultRule(Rule):
    id = "mutable-default"
    description = "mutable default argument (def f(x=[]) / ={} / =set())"

    def check_module(self, mod: ModuleInfo):
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and (call_name(d) or "").rpartition(".")[2]
                    in _MUTABLE_CALLS)
                if mutable:
                    findings.append(Finding(
                        self.id, mod.rel, d.lineno, d.col_offset,
                        f"mutable default argument in {node.name}() — "
                        f"evaluated once and shared across calls; use "
                        f"None + in-body default"))
        return findings

"""Device-transfer budget lint: ``transfer-budget`` /
``transfer-unbudgeted``.

The replay hot paths live and die by what crosses the host->device
link (PAPER.md's thesis; DEVICE_MERIT's link model): the r05 0.5x
single-chip kernel came from shipping one extra per-row lane plus a
widened payload dtype — a change that is *statically visible* in the
lane constructors. ``resources/transfer_budget.json`` commits each
budgeted path's lanes and per-unit byte cost; this pass re-derives the
cost from the AST and fails lint on any drift, so the diff review — not
a bench run — catches the regression.

Lane cost inference (per lane-named local in the site function):

- ``np.packbits(...)`` anywhere in the value -> a packed bitplane,
  0.125 B/unit (a later ``.view(np.uint32)`` reinterprets, it doesn't
  widen);
- otherwise the innermost dtype-bearing constructor wins:
  ``np.full(shape, fill, np.int32)``, ``np.zeros/ones/empty(shape,
  dt)``, ``np.asarray(x, dt)``, ``np.arange(..., dtype=dt)``,
  ``x.astype(dt)``, ``np.uint32(x)``.

``transfer-budget`` findings: a budgeted site or lane that no longer
exists (stale manifest), a lane whose kind/dtype drifted (with the
byte diff), a per-unit sum over budget, and — for entries with
``device_put_exhaustive`` — a ``device_put`` of a non-lane local
inside the site (the "extra lane" regression).

``transfer-unbudgeted``: inside the manifest's ``modules``, every
``jax.device_put`` call must sit in a budgeted site or a function
listed in ``audited_transfer_sites`` — new transfer code in the
disciplined modules must either get a budget entry or an audited
listing. The manifest path defaults to the packaged resource and can
be overridden with ``DELTA_LINT_TRANSFER_BUDGET`` (fixture tests).
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Tuple

from delta_tpu.tools.analyzer.core import (
    Finding,
    ModuleInfo,
    Rule,
    project_graph,
    register,
)
from delta_tpu.tools.analyzer.passes._astutil import call_name, dotted

_DTYPE_BYTES = {
    "bool": 1, "bool_": 1, "int8": 1, "uint8": 1,
    "int16": 2, "uint16": 2, "float16": 2, "bfloat16": 2,
    "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8,
}
_BITPLANE_BYTES = 0.125  # 1 bit/unit, packed

# constructors whose dtype argument sits at this positional index when
# not passed as dtype=...
_DTYPE_ARG_POS = {
    "full": 2, "zeros": 1, "ones": 1, "empty": 1, "asarray": 1,
    "array": 1, "astype": 0, "view": 0,
}


def _manifest_path() -> Optional[str]:
    env = os.environ.get("DELTA_LINT_TRANSFER_BUDGET")
    if env:
        return env
    try:
        import delta_tpu
    except ImportError:
        return None
    p = os.path.join(os.path.dirname(delta_tpu.__file__), "resources",
                     "transfer_budget.json")
    return p if os.path.exists(p) else None


def _load_manifest() -> Optional[dict]:
    p = _manifest_path()
    if p is None:
        return None
    try:
        with open(p, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _dtype_name(expr: ast.AST) -> Optional[str]:
    """``np.int32`` / ``jnp.uint32`` / ``"int32"`` -> "int32"."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value if expr.value in _DTYPE_BYTES else None
    name = dotted(expr)
    if name is None:
        return None
    tail = name.rpartition(".")[2]
    return tail if tail in _DTYPE_BYTES else None


def _infer_lane(value: ast.AST) -> Optional[Tuple[str, float, str]]:
    """Infer (kind, bytes_per_unit, dtype_name) for a lane value
    expression, or None when no dtype-bearing constructor is found."""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            n = call_name(node)
            if n and n.rpartition(".")[2] == "packbits":
                return ("bitplane", _BITPLANE_BYTES, "1-bit")
    best: Optional[Tuple[str, float, str]] = None
    for node in ast.walk(value):
        if not isinstance(node, ast.Call):
            continue
        n = call_name(node)
        if n is None:
            continue
        tail = n.rpartition(".")[2]
        dt: Optional[str] = None
        if tail in _DTYPE_BYTES:
            dt = tail                      # np.uint32(x) cast form
        elif tail in _DTYPE_ARG_POS:
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt = _dtype_name(kw.value)
            if dt is None:
                pos = _DTYPE_ARG_POS[tail]
                if len(node.args) > pos:
                    dt = _dtype_name(node.args[pos])
        elif tail == "arange":
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt = _dtype_name(kw.value)
        if dt is not None:
            cand = ("dtype", float(_DTYPE_BYTES[dt]), dt)
            # prefer the innermost constructor: later astype/view on the
            # same value reinterprets the same buffer, the first hit in
            # a preorder walk is the outermost -- keep the LAST hit
            best = cand
    return best


def _walk_own(fn: ast.AST):
    """Preorder walk of `fn`'s own body, skipping nested def/class
    subtrees (they are their own graph nodes and are checked
    separately)."""
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _lane_assignments(fn: ast.AST) -> Dict[str, ast.AST]:
    """lane-name -> value expr of the last whole-name assignment."""
    out: Dict[str, ast.AST] = {}
    for node in _walk_own(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            out[node.target.id] = node.value
    return out


def _device_put_calls(fn: ast.AST) -> List[ast.Call]:
    out = []
    for node in _walk_own(fn):
        if isinstance(node, ast.Call):
            n = call_name(node)
            if n and n.rpartition(".")[2] == "device_put":
                out.append(node)
    return out


@register
class TransferBudgetRule(Rule):
    id = "transfer-budget"
    help_anchor = "transfer-budget"
    description = (
        "statically-derived per-unit H2D bytes of a budgeted transfer "
        "path drifted from resources/transfer_budget.json (widened "
        "dtype, un-packed bitplane, extra device_put lane, or stale "
        "manifest)")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        manifest = _load_manifest()
        if not manifest:
            return []
        graph = project_graph(mods)
        out: List[Finding] = []
        for entry_name, entry in sorted(manifest.get("paths",
                                                     {}).items()):
            site = entry.get("site", "")
            rel = site.split("::", 1)[0]
            if rel not in graph.views:
                continue  # site's module not in this scan's target set
            fnode = graph.functions.get(site)
            if fnode is None:
                out.append(Finding(
                    self.id, rel, 1, 0,
                    f"transfer budget {entry_name!r}: site {site!r} not "
                    f"found — function renamed/removed; update "
                    f"resources/transfer_budget.json"))
                continue
            out.extend(self._check_site(entry_name, entry, fnode))
        return out

    def _check_site(self, entry_name: str, entry: dict,
                    fnode) -> List[Finding]:
        out: List[Finding] = []
        fn = fnode.node
        rel = fnode.mod_rel
        lanes = entry.get("lanes", [])
        assigns = _lane_assignments(fn)
        lane_names = {ln.get("name") for ln in lanes}
        per_unit_sum = 0.0
        lane_drift = False
        for ln in lanes:
            name, kind = ln.get("name"), ln.get("kind", "dtype")
            value = assigns.get(name)
            if value is None:
                lane_drift = True
                out.append(Finding(
                    self.id, rel, fn.lineno, fn.col_offset,
                    f"transfer budget {entry_name!r}: lane {name!r} "
                    f"not assigned in {fnode.qualname}() — renamed or "
                    f"removed; update the manifest"))
                continue
            inferred = _infer_lane(value)
            if inferred is None:
                lane_drift = True
                out.append(Finding(
                    self.id, rel, value.lineno, value.col_offset,
                    f"transfer budget {entry_name!r}: lane {name!r} has "
                    f"no statically-visible dtype — construct it with "
                    f"an explicit np dtype so the budget stays "
                    f"checkable"))
                continue
            ikind, ibytes, idt = inferred
            if kind == "bitplane":
                if ikind != "bitplane":
                    lane_drift = True
                    out.append(Finding(
                        self.id, rel, value.lineno, value.col_offset,
                        f"transfer budget {entry_name!r}: lane {name!r} "
                        f"is no longer a packed bitplane — now {idt} "
                        f"({ibytes:g} B/unit vs manifest "
                        f"{_BITPLANE_BYTES:g} B/unit)"))
                    per_unit_sum += ibytes
                else:
                    per_unit_sum += _BITPLANE_BYTES
                continue
            want_dt = ln.get("dtype", "")
            want_bytes = float(_DTYPE_BYTES.get(want_dt, 0))
            if ikind == "bitplane":
                ibytes = _BITPLANE_BYTES
            if ibytes != want_bytes or (
                    want_dt and idt != want_dt
                    and ibytes != want_bytes):
                lane_drift = True
                out.append(Finding(
                    self.id, rel, value.lineno, value.col_offset,
                    f"transfer budget {entry_name!r}: lane {name!r} "
                    f"widened — {idt} ({ibytes:g} B/unit) vs manifest "
                    f"{want_dt} ({want_bytes:g} B/unit)"))
            if kind != "scalar":
                per_unit_sum += ibytes
        budget = float(entry.get("budget_bytes_per_unit", 0))
        if not lane_drift and budget and per_unit_sum != budget:
            out.append(Finding(
                self.id, rel, fn.lineno, fn.col_offset,
                f"transfer budget {entry_name!r}: per-unit bytes "
                f"derived from {fnode.qualname}() = {per_unit_sum:g} B "
                f"!= manifest budget {budget:g} B per "
                f"{entry.get('unit', 'unit')}"))
        if entry.get("device_put_exhaustive"):
            for call in _device_put_calls(fn):
                arg = dotted(call.args[0]) if call.args else None
                if arg is None or arg not in lane_names:
                    out.append(Finding(
                        self.id, rel, call.lineno, call.col_offset,
                        f"transfer budget {entry_name!r}: device_put of "
                        f"{arg or '<expr>'} is not a budgeted lane — an "
                        f"extra per-unit lane changes the link cost; "
                        f"add it to resources/transfer_budget.json or "
                        f"drop the transfer"))
        return out


@register
class TransferUnbudgetedRule(Rule):
    id = "transfer-unbudgeted"
    help_anchor = "transfer-budget"
    description = (
        "jax.device_put in a transfer-disciplined module (manifest "
        "'modules') outside every budgeted site and audited transfer "
        "site — new H2D paths need a budget entry or an audit listing")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        manifest = _load_manifest()
        if not manifest:
            return []
        modules = set(manifest.get("modules", []))
        if not modules:
            return []
        allowed = {e.get("site") for e in manifest.get("paths",
                                                       {}).values()}
        allowed |= set(manifest.get("audited_transfer_sites", []))
        graph = project_graph(mods)
        out: List[Finding] = []
        for key, fnode in sorted(graph.functions.items()):
            if fnode.mod_rel not in modules or key in allowed:
                continue
            for call in _device_put_calls(fnode.node):
                out.append(Finding(
                    self.id, fnode.mod_rel, call.lineno,
                    call.col_offset,
                    f"device_put in {fnode.qualname}() is outside every "
                    f"budgeted transfer site — add a "
                    f"transfer_budget.json entry (or an "
                    f"audited_transfer_sites listing) so the H2D cost "
                    f"of this path stays pinned"))
        return out

"""Metric-name conformance.

``docs/observability.md`` and the Prometheus exposition
(`obs/expose.py`) both promise a stable metric surface; the single
source of truth is ``delta_tpu/resources/metric_names.json`` —
``{"counters": {name: help}, "histograms": {...}, "gauges": {...}}``.
Two rules cross-reference instrument sites and catalog in both
directions, entirely statically (AST census — nothing is imported),
mirroring the error-catalog pass:

- ``metric-uncataloged`` — a ``counter("...")`` / ``histogram("...")``
  / ``gauge("...")`` call whose literal name has no catalog entry
  *under that kind*: a typo'd, forgotten, or kind-mismatched metric;
- ``metric-dead-entry`` — a catalog entry no instrument site produces:
  documentation (and the zero-filled exposition) would advertise a
  series that can never move.

Only string-literal first arguments are censused; dynamic names are
out of scope by design (the repo has none — keeping it that way is
part of what this pass enforces, since a dynamic name would surface as
a dead catalog entry or an uncataloged runtime series).

The catalog path defaults to the installed package resource and can be
overridden with ``DELTA_LINT_METRIC_CATALOG`` (fixture tests and
`obs/expose.py` share the same override).
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Tuple

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register

_KIND_BY_FN = {"counter": "counters", "histogram": "histograms",
               "gauge": "gauges"}

# instrument sites inside the obs package itself are the machinery
# (registry definitions, exposition, tests' fixtures ride through env
# override), not product metrics — EXCEPT the device-execution
# profiler, whose instruments (device.*, gate.*) are product telemetry
# and must stay cataloged like any other module's — likewise the HBM
# resident ledger (hbm.*, plus the subsumed replay/scan gauges)
_EXEMPT_PREFIX = os.path.join("delta_tpu", "obs") + os.sep
_NON_EXEMPT_BASENAMES = {"device.py", "bench_trend.py", "hbm.py"}


def _catalog_path() -> Optional[str]:
    env = os.environ.get("DELTA_LINT_METRIC_CATALOG")
    if env:
        return env
    try:
        import delta_tpu

        path = os.path.join(os.path.dirname(delta_tpu.__file__),
                            "resources", "metric_names.json")
        return path if os.path.exists(path) else None
    except ImportError:  # pragma: no cover - analyzer ships inside it
        return None


def _load_catalog() -> Tuple[Optional[Dict], Optional[str]]:
    path = _catalog_path()
    if path is None:
        return None, None
    with open(path, encoding="utf-8") as f:
        return json.load(f), path


def _catalog_key_line(path: str, key: str) -> int:
    """Locate an entry's line in the JSON text, for clickable
    dead-entry findings (entries are one-per-line by convention)."""
    needle = f'"{key}"'
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            if line.lstrip().startswith(needle):
                return lineno
    return 1


class _MetricScan:
    """One project-wide census of literal instrument-creation sites:
    {kind: {name: [(rel, line), ...]}}."""

    def __init__(self, mods: List[ModuleInfo]):
        self.sites: Dict[str, Dict[str, List[Tuple[str, int]]]] = {
            kind: {} for kind in _KIND_BY_FN.values()}
        for mod in mods:
            if (mod.rel.startswith(_EXEMPT_PREFIX)
                    and os.path.basename(mod.rel)
                    not in _NON_EXEMPT_BASENAMES):
                continue
            self._scan(mod)

    def _scan(self, mod: ModuleInfo):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            fn_name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            kind = _KIND_BY_FN.get(fn_name or "")
            if kind is None:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            self.sites[kind].setdefault(arg.value, []).append(
                (mod.rel, node.lineno))


# identity-compared single-entry cache (same idiom as errors_catalog:
# a later run's fresh ModuleInfos can never falsely hit a stale census)
_CACHE: List[Tuple[List[ModuleInfo], _MetricScan]] = []


def _scan_for(mods: List[ModuleInfo]) -> _MetricScan:
    if _CACHE:
        cached_mods, cached = _CACHE[0]
        if len(cached_mods) == len(mods) \
                and all(a is b for a, b in zip(cached_mods, mods)):
            return cached
    scan = _MetricScan(mods)
    _CACHE[:] = [(list(mods), scan)]
    return scan


@register
class MetricUncatalogedRule(Rule):
    id = "metric-uncataloged"
    description = ("counter()/histogram()/gauge() literal name with no "
                   "entry of that kind in metric_names.json")

    def check_project(self, mods):
        catalog, _path = _load_catalog()
        if catalog is None:
            return ()
        scan = _scan_for(mods)
        findings = []
        for kind in sorted(scan.sites):
            cataloged = catalog.get(kind) or {}
            for name, sites in sorted(scan.sites[kind].items()):
                if name in cataloged:
                    continue
                other = [k for k in _KIND_BY_FN.values()
                         if k != kind and name in (catalog.get(k) or {})]
                hint = (f" (cataloged as a {other[0][:-1]}, not a "
                        f"{kind[:-1]})" if other
                        else " — add it to metric_names.json")
                for rel, line in sites:
                    findings.append(Finding(
                        self.id, rel, line, 0,
                        f"metric {name!r} ({kind[:-1]}) is not in "
                        f"metric_names.json{hint}"))
        return findings


@register
class MetricDeadEntryRule(Rule):
    id = "metric-dead-entry"
    description = ("metric_names.json entry that no instrument site "
                   "produces")

    def check_project(self, mods):
        catalog, path = _load_catalog()
        if catalog is None:
            return ()
        scan = _scan_for(mods)
        # only meaningful when the scanned set holds instrument sites
        # at all (a single-file scan would mark everything dead)
        if not any(scan.sites[k] for k in scan.sites):
            return ()
        findings = []
        for kind in sorted(_KIND_BY_FN.values()):
            produced = scan.sites.get(kind) or {}
            for name in sorted(catalog.get(kind) or {}):
                if name in produced:
                    continue
                findings.append(Finding(
                    self.id, os.path.basename(path),
                    _catalog_key_line(path, name), 0,
                    f"catalog entry {name!r} ({kind[:-1]}) is produced "
                    f"by no instrument site (dead entry — remove it or "
                    f"instrument it)"))
        return findings

"""Lock-discipline / race detector.

Three rules over one statically-built lock model:

- ``lock-order`` — builds a project-wide lock-acquisition graph (an
  edge A -> B means "some code path acquires B while holding A", either
  by lexical nesting or through a same-module call made inside the
  ``with A:`` block) and flags every edge that participates in a cycle
  (inconsistent acquisition order = deadlock potential), plus
  re-acquisition of a held non-reentrant ``threading.Lock``
  (self-deadlock), directly or through a call chain;
- ``lock-io`` — flags file/network I/O primitives invoked while any
  lock is held (long I/O under a hot lock serializes the whole
  optimistic-concurrency path; where mutual exclusion around the I/O
  *is* the point — put-if-absent emulation, once-only native compile —
  the site carries an audited ``# delta-lint: disable=lock-io``);
- ``global-mutation`` — in modules that declare themselves concurrent
  (they create at least one ``threading`` lock), flags mutation of
  module-level mutable state from function bodies outside any
  ``with <lock>:`` block.

Lock identity is ``<module-stem>.<Class>.<attr>`` for instance locks
(``self._lock = threading.Lock()`` in any method, dataclass
``field(default_factory=threading.Lock)``, and the
``self.__dict__.setdefault("x", threading.Lock())`` idiom),
``<module-stem>.<NAME>`` for module globals, and a function-scoped name
for locals bound to a fresh lock. Call resolution rides the shared
:class:`~delta_tpu.tools.analyzer.core.ProjectGraph` (cross-module
def/attr/method resolution), with the same-module heuristic as
fallback, so acquisitions and I/O propagate through project-wide call
chains. The analysis additionally records every instance-attr /
module-global mutation with its lexically-held locks
(:class:`Mutation`) — the fact base for the shared-state race detector
in ``passes/races.py``.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from delta_tpu.tools.analyzer.core import (
    Finding,
    ModuleInfo,
    Rule,
    project_graph,
    register,
)
from delta_tpu.tools.analyzer.passes._astutil import (
    build_function_table,
    call_name,
    dotted,
    iter_functions,
    resolve_local_call,
)

_LOCK_FACTORIES = {
    "threading.Lock": False,       # value: reentrant?
    "threading.RLock": True,
    "threading.Condition": True,
    "Lock": False,
    "RLock": True,
    "Condition": True,
}

_IO_PREFIXES = (
    "os.", "shutil.", "subprocess.", "socket.", "urllib.", "requests.",
    "http.client.",
)
_IO_EXEMPT = {
    # pure path/string/env helpers that happen to live under os.*
    "os.path.join", "os.path.dirname", "os.path.basename",
    "os.path.splitext", "os.path.abspath", "os.path.normpath",
    "os.path.relpath", "os.path.split", "os.path.exists", "os.fspath",
    "os.environ.get", "os.getenv", "os.getpid", "os.cpu_count",
}
_IO_CALLS = {"open", "time.sleep"}

_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "appendleft", "extendleft",
    "move_to_end",
}
_MUTABLE_FACTORIES = {
    "dict", "list", "set", "OrderedDict", "collections.OrderedDict",
    "defaultdict", "collections.defaultdict", "deque",
    "collections.deque", "Counter", "collections.Counter",
}


def _module_stem(rel: str) -> str:
    stem = rel[:-3] if rel.endswith(".py") else rel
    return stem.replace(os.sep, ".").replace("/", ".")


def _lock_factory(node: ast.AST) -> Optional[bool]:
    """If `node` constructs a lock, return its reentrancy, else None."""
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in _LOCK_FACTORIES:
            return _LOCK_FACTORIES[name]
    return None


@dataclass
class _ModuleLocks:
    mod: ModuleInfo
    stem: str
    locks: Dict[str, bool] = field(default_factory=dict)  # id -> reentrant
    by_attr: Dict[Tuple[Optional[str], str], str] = field(
        default_factory=dict)  # (Class|None, attr) -> lock id
    mutable_globals: Set[str] = field(default_factory=set)

    def define(self, cls: Optional[str], attr: str, reentrant: bool) -> str:
        lock_id = (f"{self.stem}.{cls}.{attr}" if cls
                   else f"{self.stem}.{attr}")
        self.locks.setdefault(lock_id, reentrant)
        self.by_attr.setdefault((cls, attr), lock_id)
        return lock_id


@dataclass
class _Edge:
    src: str
    dst: str
    path: str
    line: int
    via: str  # "" for lexical nesting, else the callee qualname


@dataclass(frozen=True)
class Mutation:
    """One state mutation observed in a function body, with the locks
    lexically held around it. Consumed by the shared-state race
    detector (passes/races.py)."""

    kind: str                 # rmw | item-store | mutate-call | del | store
    owner_cls: Optional[str]  # class of a `self.attr` target, None = global
    attr: str                 # attribute / global name mutated
    line: int
    col: int
    held: Tuple[str, ...]     # lock ids lexically held at the site
    detail: str = ""


@dataclass
class _FuncFacts:
    mod_rel: str
    qualname: str = ""
    cls: Optional[str] = None
    direct_acquires: Set[str] = field(default_factory=set)
    held_calls: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)  # (held locks, callee KEY, line)
    callees: Set[str] = field(default_factory=set)  # full function keys
    direct_io: Set[str] = field(default_factory=set)  # io call names
    mutations: List[Mutation] = field(default_factory=list)


def _collect_definitions(mod: ModuleInfo) -> _ModuleLocks:
    ml = _ModuleLocks(mod, _module_stem(mod.rel))
    tree = mod.tree
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            reentrant = _lock_factory(node.value)
            if reentrant is not None:
                ml.define(None, name, reentrant)
            elif isinstance(node.value, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(node.value, ast.Call)
                    and call_name(node.value) in _MUTABLE_FACTORIES):
                ml.mutable_globals.add(name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for st in node.body:
            if isinstance(st, ast.AnnAssign) \
                    and isinstance(st.target, ast.Name) \
                    and isinstance(st.value, ast.Call) \
                    and call_name(st.value) in ("field",
                                                "dataclasses.field"):
                for kw in st.value.keywords:
                    if kw.arg == "default_factory":
                        factory = dotted(kw.value)
                        if factory in _LOCK_FACTORIES:
                            ml.define(node.name, st.target.id,
                                      _LOCK_FACTORIES[factory])
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for st in ast.walk(item):
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Attribute) \
                        and isinstance(st.targets[0].value, ast.Name) \
                        and st.targets[0].value.id == "self":
                    reentrant = _lock_factory(st.value)
                    if reentrant is not None:
                        ml.define(node.name, st.targets[0].attr, reentrant)
    return ml


class _LockAnalysis:
    """Shared lock model; built once per module set and cached so the
    thin rules (lock-order / lock-io / global-mutation, plus the
    shared-state race detector in races.py) don't re-walk the project.

    Call resolution rides the shared :class:`ProjectGraph` — the graph
    records resolved callees per ``ast.Call`` node (same AST objects,
    joined by ``id()``), so held-lock propagation crosses modules. The
    same-module ``resolve_local_call`` remains as the fallback for call
    shapes the graph doesn't type."""

    def __init__(self, mods: List[ModuleInfo]):
        self.findings: List[Finding] = []
        self.edges: List[_Edge] = []
        self.facts: Dict[str, _FuncFacts] = {}
        self.graph = project_graph(mods)
        # id(ast.Call) -> lock ids lexically held around the call; the
        # race detector's propagate_meet edge gain
        self.held_at_call: Dict[int, Tuple[str, ...]] = {}
        self.per_mod = {m.rel: _collect_definitions(m) for m in mods}
        # lock id -> (module stem, owning class or None, attribute)
        self.lock_owners: Dict[str, Tuple[str, Optional[str], str]] = {}
        for ml in self.per_mod.values():
            for (cls, attr), lid in ml.by_attr.items():
                self.lock_owners[lid] = (ml.stem, cls, attr)
        for mod in mods:
            self._scan_module(self.per_mod[mod.rel])
        self._propagate(self.per_mod)
        self.findings.extend(self._cycle_findings())

    # -- per-module scan ---------------------------------------------------

    def _scan_module(self, ml: _ModuleLocks):
        mod = ml.mod
        table = build_function_table(mod.tree)
        for qualname, cls, fn in iter_functions(mod.tree):
            ff = _FuncFacts(mod.rel, qualname=qualname, cls=cls)
            self.facts[f"{mod.rel}::{qualname}"] = ff
            local_locks: Dict[str, Tuple[str, bool]] = {}
            declared_global: Set[str] = set()
            for st in ast.walk(fn):
                if isinstance(st, ast.Global):
                    declared_global.update(st.names)
            self._seed_locals(fn, ml, cls, qualname, local_locks)
            self._walk(fn.body, (), ml, cls, table, local_locks,
                       declared_global, ff)

    def _seed_locals(self, fn, ml, cls, qualname, local_locks):
        for st in ast.walk(fn):
            if not (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)):
                continue
            v = st.value
            reentrant = _lock_factory(v)
            if reentrant is not None:
                lock_id = f"{ml.stem}.{qualname}.{st.targets[0].id}"
                ml.locks.setdefault(lock_id, reentrant)
                local_locks[st.targets[0].id] = (lock_id, reentrant)
            elif isinstance(v, ast.Call) \
                    and (call_name(v) or "").endswith(
                        "__dict__.setdefault") \
                    and len(v.args) == 2 \
                    and isinstance(v.args[0], ast.Constant) \
                    and _lock_factory(v.args[1]) is not None:
                lock_id = ml.define(cls, str(v.args[0].value),
                                    bool(_lock_factory(v.args[1])))
                local_locks[st.targets[0].id] = (
                    lock_id, bool(_lock_factory(v.args[1])))

    def _resolve_lock(self, expr, ml: _ModuleLocks, cls, local_locks):
        name = dotted(expr)
        if name is None:
            return None
        if name in local_locks:
            return local_locks[name]
        head, _, rest = name.partition(".")
        if not rest and (None, name) in ml.by_attr:
            lock_id = ml.by_attr[(None, name)]
            return lock_id, ml.locks[lock_id]
        if head == "self" and rest and "." not in rest \
                and (cls, rest) in ml.by_attr:
            lock_id = ml.by_attr[(cls, rest)]
            return lock_id, ml.locks[lock_id]
        return None

    def _walk(self, stmts, held, ml, cls, table, local_locks,
              declared_global, ff: _FuncFacts):
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in st.items:
                    self._scan_expr(item.context_expr, held, ml, cls,
                                    table, ff)
                    resolved = self._resolve_lock(item.context_expr, ml,
                                                  cls, local_locks)
                    if resolved is None:
                        continue
                    lock_id, reentrant = resolved
                    ff.direct_acquires.add(lock_id)
                    if lock_id in held and not reentrant:
                        self.findings.append(Finding(
                            "lock-order", ml.mod.rel, st.lineno,
                            st.col_offset,
                            f"non-reentrant lock {lock_id} acquired "
                            f"while already held (self-deadlock)"))
                        continue
                    for h in held:
                        if h != lock_id:
                            self.edges.append(_Edge(h, lock_id,
                                                    ml.mod.rel,
                                                    st.lineno, ""))
                    acquired.append(lock_id)
                self._walk(st.body, held + tuple(acquired), ml, cls,
                           table, local_locks, declared_global, ff)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs run later, under no held lock
            else:
                for expr in _stmt_exprs(st):
                    self._scan_expr(expr, held, ml, cls, table, ff)
                if not held and ml.locks:
                    self._check_global_mutation(st, ml, declared_global)
                self._collect_mutations(st, held, ml, cls,
                                        declared_global, ff)
                for child_body in _sub_bodies(st):
                    self._walk(child_body, held, ml, cls, table,
                               local_locks, declared_global, ff)

    def _collect_mutations(self, st, held, ml: _ModuleLocks, cls,
                           declared_global, ff: _FuncFacts):
        """Record every instance-attribute / module-global mutation with
        the lock context, for the race detector. Taxonomy:

        - ``rmw``: aug-assign, or a plain assign whose value reads the
          same target (lost-update window even under the GIL);
        - ``item-store``: subscript store on a container attr/global;
        - ``mutate-call``: a mutator method on an attr/global container;
        - ``del``: deletion of an attr / global / item;
        - ``store``: plain attribute rebinding (GIL-atomic publication —
          collected but exempt in the race rule)."""
        held_t = tuple(held)

        def owner_of(t) -> Optional[Tuple[Optional[str], str]]:
            # self.attr -> (cls, attr); bare global name -> (None, name)
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self" and cls is not None:
                return (cls, t.attr)
            if isinstance(t, ast.Name) and (
                    t.id in declared_global
                    or t.id in ml.mutable_globals):
                return (None, t.id)
            return None

        def add(kind, owner, line, col, detail=""):
            ff.mutations.append(Mutation(kind, owner[0], owner[1],
                                         line, col, held_t, detail))

        if isinstance(st, ast.AugAssign):
            o = owner_of(st.target)
            if o is not None:
                add("rmw", o, st.lineno, st.col_offset)
            elif isinstance(st.target, ast.Subscript):
                o = owner_of(st.target.value)
                if o is not None:
                    add("rmw", o, st.lineno, st.col_offset)
        elif isinstance(st, (ast.Assign, ast.AnnAssign)):
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            for t in targets:
                o = owner_of(t)
                if o is not None:
                    kind = "store"
                    if st.value is not None and o[0] is not None:
                        for sub in ast.walk(st.value):
                            if isinstance(sub, ast.Attribute) \
                                    and sub.attr == o[1] \
                                    and isinstance(sub.value, ast.Name) \
                                    and sub.value.id == "self":
                                kind = "rmw"  # x = f(x): read-modify-write
                                break
                    add(kind, o, st.lineno, st.col_offset)
                elif isinstance(t, ast.Subscript):
                    o = owner_of(t.value)
                    if o is not None:
                        add("item-store", o, st.lineno, st.col_offset)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                o = owner_of(t)
                if o is None and isinstance(t, ast.Subscript):
                    o = owner_of(t.value)
                if o is not None:
                    add("del", o, st.lineno, st.col_offset)
        elif isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            name = call_name(st.value)
            if name and "." in name:
                recv, _, method = name.rpartition(".")
                if method in _MUTATORS:
                    o = None
                    parts = recv.split(".")
                    if len(parts) == 2 and parts[0] == "self" \
                            and cls is not None:
                        o = (cls, parts[1])
                    elif len(parts) == 1 and (
                            parts[0] in ml.mutable_globals):
                        o = (None, parts[0])
                    if o is not None:
                        add("mutate-call", o, st.lineno,
                            st.col_offset, detail=method)

    def _scan_expr(self, expr, held, ml, cls, table, ff: _FuncFacts):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            if held:
                self.held_at_call[id(node)] = tuple(held)
            name = call_name(node)
            if name is None:
                continue
            callee_keys = self.graph.call_sites.get(id(node))
            if not callee_keys:
                local = resolve_local_call(name, cls, table)
                callee_keys = ([f"{ml.mod.rel}::{local}"]
                               if local is not None else [])
            if callee_keys:
                ff.callees.update(callee_keys)
                if held:
                    for ck in callee_keys:
                        ff.held_calls.append((held, ck, node.lineno))
                continue
            if _is_io(name):
                ff.direct_io.add(name)
                if held:
                    self.findings.append(Finding(
                        "lock-io", ml.mod.rel, node.lineno,
                        node.col_offset,
                        f"I/O call {name}() while holding lock "
                        f"{held[-1]} (move the I/O outside the critical "
                        f"section, or audit + suppress)"))

    def _check_global_mutation(self, st, ml: _ModuleLocks,
                               declared_global):
        targets = []
        if isinstance(st, ast.Assign):
            targets = st.targets
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            targets = [st.target]
        elif isinstance(st, ast.Delete):
            targets = st.targets
        for t in targets:
            if isinstance(t, ast.Subscript) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id in ml.mutable_globals:
                self.findings.append(Finding(
                    "global-mutation", ml.mod.rel, st.lineno,
                    st.col_offset,
                    f"module-global {t.value.id!r} mutated outside any "
                    f"lock in a module that uses threading locks"))
            elif isinstance(t, ast.Name) and t.id in declared_global \
                    and t.id in ml.mutable_globals:
                self.findings.append(Finding(
                    "global-mutation", ml.mod.rel, st.lineno,
                    st.col_offset,
                    f"module-global {t.id!r} rebound outside any lock "
                    f"in a module that uses threading locks"))
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            name = call_name(st.value)
            if name and "." in name:
                head, _, method = name.rpartition(".")
                if head in ml.mutable_globals and method in _MUTATORS:
                    self.findings.append(Finding(
                        "global-mutation", ml.mod.rel, st.lineno,
                        st.col_offset,
                        f"module-global {head!r}.{method}() outside any "
                        f"lock in a module that uses threading locks"))

    # -- propagation + cycles ----------------------------------------------

    def _propagate(self, per_mod: Dict[str, _ModuleLocks]):
        trans: Dict[str, Set[str]] = {
            k: set(f.direct_acquires) for k, f in self.facts.items()}
        trans_io: Dict[str, Set[str]] = {
            k: set(f.direct_io) for k, f in self.facts.items()}
        changed = True
        while changed:
            changed = False
            for k, f in self.facts.items():
                for ck in f.callees:
                    if ck in trans and not trans[ck] <= trans[k]:
                        trans[k] |= trans[ck]
                        changed = True
                    if ck in trans_io and not trans_io[ck] <= trans_io[k]:
                        trans_io[k] |= trans_io[ck]
                        changed = True
        reentrant: Dict[str, bool] = {}
        for ml in per_mod.values():
            reentrant.update(ml.locks)
        for k, f in self.facts.items():
            for held, ck, line in f.held_calls:
                callee = ck.split("::", 1)[1]
                io_names = sorted(trans_io.get(ck, ()))
                if io_names:
                    self.findings.append(Finding(
                        "lock-io", f.mod_rel, line, 0,
                        f"call to {callee}() performs I/O "
                        f"({', '.join(io_names[:3])}) while holding "
                        f"lock {held[-1]}"))
                for acquired in sorted(trans.get(ck, ())):
                    if acquired in held:
                        if not reentrant.get(acquired, True):
                            self.findings.append(Finding(
                                "lock-order", f.mod_rel, line, 0,
                                f"call to {callee}() may re-acquire "
                                f"non-reentrant lock {acquired} already "
                                f"held here (self-deadlock)"))
                        continue
                    for h in held:
                        self.edges.append(_Edge(h, acquired, f.mod_rel,
                                                line, callee))

    def _cycle_findings(self) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for e in self.edges:
            graph.setdefault(e.src, set()).add(e.dst)
            graph.setdefault(e.dst, set())
        cyclic = [frozenset(s) for s in _tarjan(graph)
                  if len(s) > 1
                  or next(iter(s)) in graph.get(next(iter(s)), ())]
        findings, seen = [], set()
        for e in self.edges:
            for scc in cyclic:
                if e.src in scc and e.dst in scc \
                        and (e.src, e.dst) not in seen:
                    seen.add((e.src, e.dst))
                    via = f" (via {e.via}())" if e.via else ""
                    findings.append(Finding(
                        "lock-order", e.path, e.line, 0,
                        f"lock order cycle: acquires {e.dst} while "
                        f"holding {e.src}{via}; another path acquires "
                        f"them in the opposite order"))
        return findings


# single-entry cache: (mods list, analysis). The mods list is retained
# so the id()-tuple key stays sound — holding the objects alive means a
# later run's fresh ModuleInfos can never reuse their addresses and
# falsely hit a stale analysis.
_CACHE: List[Tuple[List[ModuleInfo], _LockAnalysis]] = []


def _analysis(mods: List[ModuleInfo]) -> _LockAnalysis:
    if _CACHE:
        cached_mods, cached = _CACHE[0]
        if len(cached_mods) == len(mods) \
                and all(a is b for a, b in zip(cached_mods, mods)):
            return cached
    analysis = _LockAnalysis(mods)
    _CACHE[:] = [(list(mods), analysis)]
    return analysis


class _LockRuleBase(Rule):
    def check_project(self, mods):
        return [f for f in _analysis(mods).findings if f.rule == self.id]


@register
class LockOrderRule(_LockRuleBase):
    id = "lock-order"
    description = ("inconsistent lock-acquisition order (cycle in the "
                   "static lock graph) or re-acquisition of a held "
                   "non-reentrant lock (self-deadlock)")


@register
class LockIoRule(_LockRuleBase):
    id = "lock-io"
    description = "file/network I/O performed while holding a lock"


@register
class GlobalMutationRule(_LockRuleBase):
    id = "global-mutation"
    description = ("module-level mutable state mutated outside any lock "
                   "in a module that uses threading locks")


def _is_io(name: str) -> bool:
    if name in _IO_CALLS:
        return True
    if name in _IO_EXEMPT:
        return False
    return name.startswith(_IO_PREFIXES)


def _sub_bodies(st) -> List[list]:
    out = []
    for attr in ("body", "orelse", "finalbody"):
        b = getattr(st, attr, None)
        if isinstance(b, list) and b and isinstance(b[0], ast.stmt):
            out.append(b)
    for h in getattr(st, "handlers", ()) or ():
        out.append(h.body)
    return out


def _stmt_exprs(st) -> List[ast.AST]:
    """Expressions evaluated by `st` itself (not nested statements)."""
    out = []
    for _name, value in ast.iter_fields(st):
        vals = value if isinstance(value, list) else [value]
        for v in vals:
            if isinstance(v, ast.expr):
                out.append(v)
    return out


def _tarjan(graph: Dict[str, Set[str]]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return out

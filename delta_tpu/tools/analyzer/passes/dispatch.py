"""Dispatch-funnel coverage lint: ``unprofiled-dispatch``.

PR 15's device-execution observability only works if every hot-path
kernel launch actually rides the `obs.device_dispatch` funnel — an
unfunneled ``jax.device_put`` is a transfer the runtime budget audit
never sees and a wall-time hole in the gate-calibration join. The
funnel sites were added by hand; this pass keeps them from rotting:
inside the covered device modules, every ``device_put`` call must sit
lexically inside a ``with ... device_dispatch(...)`` block (any number
of statements deep, including nested ``with`` items), or in an
explicitly allowlisted transfer helper whose caller holds the funnel
open around it.

Covered modules default to the instrumented hot-path set (the same
modules the transfer-budget manifest disciplines, minus the
checkpoint writers whose transfers happen inside their own pipelined
uploader). Overrides, mostly for fixture tests:

  DELTA_LINT_DISPATCH_MODULES  comma-separated rel paths replacing the
                               covered-module set
  DELTA_LINT_DISPATCH_ALLOW    comma-separated function names (bare or
                               ``rel.py::qualname``) replacing the
                               allowlist
"""

from __future__ import annotations

import ast
import os
from typing import List, Set

from delta_tpu.tools.analyzer.core import (
    Finding,
    ModuleInfo,
    Rule,
    register,
)
from delta_tpu.tools.analyzer.passes._astutil import call_name

# The instrumented device modules: every kernel launch in these files
# goes through the dispatch funnel (PR 15).
_DEFAULT_MODULES = (
    "delta_tpu/ops/json_parse.py",
    "delta_tpu/ops/page_decode.py",
    "delta_tpu/ops/skipping.py",
    "delta_tpu/ops/stats.py",
    "delta_tpu/ops/replay.py",
    "delta_tpu/ops/replay_blockwise.py",
    "delta_tpu/ops/zorder.py",
    "delta_tpu/parallel/resident.py",
    "delta_tpu/parallel/sharded_replay.py",
    "delta_tpu/parallel/sharded_blockwise.py",
    "delta_tpu/stats/device_index.py",
    "delta_tpu/ops/sqlops.py",
    "delta_tpu/ops/join.py",
    "delta_tpu/sqlengine/operands.py",
)

# Transfer helpers invoked from inside a caller's open funnel: the
# chunked uploader (replay), whose callers record the lane totals.
_DEFAULT_ALLOW = ("_put_chunked",)


def _covered_modules() -> Set[str]:
    env = os.environ.get("DELTA_LINT_DISPATCH_MODULES")
    if env is not None:
        return {p.strip() for p in env.split(",") if p.strip()}
    return set(_DEFAULT_MODULES)


def _allowed_functions() -> Set[str]:
    env = os.environ.get("DELTA_LINT_DISPATCH_ALLOW")
    if env is not None:
        return {p.strip() for p in env.split(",") if p.strip()}
    return set(_DEFAULT_ALLOW)


def _is_funnel_with(node: ast.With) -> bool:
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            name = call_name(expr)
            if name and name.rpartition(".")[2] == "device_dispatch":
                return True
    return False


def _collect_funneled(tree: ast.AST) -> Set[int]:
    """ids of every AST node lexically under a device_dispatch with."""
    covered: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.With) and _is_funnel_with(node):
            for sub in ast.walk(node):
                covered.add(id(sub))
    return covered


def _enclosing_functions(tree: ast.AST) -> dict:
    """node id -> name of the innermost enclosing function def."""
    owner: dict = {}

    def visit(node: ast.AST, current: str) -> None:
        for child in ast.iter_child_nodes(node):
            name = current
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                name = child.name
            owner[id(child)] = name
            visit(child, name)

    visit(tree, "<module>")
    return owner


@register
class UnprofiledDispatchRule(Rule):
    id = "unprofiled-dispatch"
    help_anchor = "unprofiled-dispatch"
    description = (
        "jax.device_put in a dispatch-instrumented device module "
        "outside every `with obs.device_dispatch(...)` block — the "
        "transfer bypasses the runtime budget audit and the gate-"
        "calibration wall-time join; open the funnel around the launch "
        "or allowlist the helper")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        modules = _covered_modules()
        allowed = _allowed_functions()
        out: List[Finding] = []
        for mod in mods:
            if mod.rel not in modules or mod.tree is None:
                continue
            covered = _collect_funneled(mod.tree)
            owner = _enclosing_functions(mod.tree)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if not name or name.rpartition(".")[2] != "device_put":
                    continue
                if id(node) in covered:
                    continue
                fn = owner.get(id(node), "<module>")
                if fn in allowed or f"{mod.rel}::{fn}" in allowed:
                    continue
                out.append(Finding(
                    self.id, mod.rel, node.lineno, node.col_offset,
                    f"device_put in {fn}() is outside every "
                    f"device_dispatch funnel — wrap the launch in "
                    f"`with obs.device_dispatch(...)` (budget-audited, "
                    f"gate-joined) or allowlist the transfer helper in "
                    f"the dispatch pass"))
        return out

"""Resident-ledger coverage lint: ``resident-ledger-discipline``.

PR 18's HBM accounting only balances if every persistent device lane
actually reaches the ledger — an artifact created without
`hbm.register()` is invisible to the budget rollups, and a handle whose
`release()` is unreachable turns every eviction into a phantom leak.
The instrumentation sites were added by hand; this pass keeps them
from rotting. Inside the covered resident-owner modules it enforces
three shapes:

- a `hbm.register(...)` result assigned to an attribute (or name) must
  have a matching ``.release()`` call on that attribute/name somewhere
  in the module (the owner's teardown path);
- a `hbm.register(...)` whose result is discarded is always wrong —
  the handle IS the only way to release or grow the entry;
- a class in a covered module that launches ``device_put`` transfers
  but never calls `hbm.register` anywhere is an unregistered resident
  lane.

Covered modules default to the resident-artifact owners (replay key
lanes, stats-index lanes, checkpoint handoff codes). Override, mostly
for fixture tests:

  DELTA_LINT_LEDGER_MODULES  comma-separated rel paths replacing the
                             covered-module set
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set, Tuple

from delta_tpu.tools.analyzer.core import (
    Finding,
    ModuleInfo,
    Rule,
    register,
)
from delta_tpu.tools.analyzer.passes._astutil import call_name

# The resident-artifact owner modules: every persistent device lane in
# these files registers with the HBM ledger (PR 18).
_DEFAULT_MODULES = (
    "delta_tpu/parallel/resident.py",
    "delta_tpu/stats/device_index.py",
    "delta_tpu/ops/page_decode.py",
)


def _covered_modules() -> Set[str]:
    env = os.environ.get("DELTA_LINT_LEDGER_MODULES")
    if env is not None:
        return {p.strip() for p in env.split(",") if p.strip()}
    return set(_DEFAULT_MODULES)


def _is_register_call(node: ast.Call) -> bool:
    name = call_name(node)
    return bool(name) and name.rpartition(".")[2] == "register" \
        and "hbm" in name.split(".")


def _handle_slot(target: ast.expr) -> Optional[Tuple[str, str]]:
    """("attr"|"name", slot) for an assignment target that can hold a
    ledger handle; None for targets the pass doesn't track (tuple
    unpacking, subscripts)."""
    if isinstance(target, ast.Attribute):
        return ("attr", target.attr)
    if isinstance(target, ast.Name):
        return ("name", target.id)
    return None


def _released_slots(tree: ast.AST) -> Set[Tuple[str, str]]:
    """Every ``<slot>.release()`` call in the module, keyed like
    `_handle_slot`: ``self._hbm.release()`` / ``p.hbm.release()`` yield
    ("attr", "_hbm") / ("attr", "hbm"); ``h.release()`` yields
    ("name", "h")."""
    out: Set[Tuple[str, str]] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "release"):
            continue
        recv = node.func.value
        slot = _handle_slot(recv)
        if slot is not None:
            out.add(slot)
    return out


@register
class ResidentLedgerRule(Rule):
    id = "resident-ledger-discipline"
    help_anchor = "resident-ledger-discipline"
    description = (
        "hbm ledger coverage in resident-owner modules: every "
        "`hbm.register()` handle needs a reachable `.release()`, a "
        "discarded register() handle can never be released, and a "
        "class launching device_put transfers without any register() "
        "call is an unregistered resident lane invisible to the HBM "
        "budget rollups")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        modules = _covered_modules()
        out: List[Finding] = []
        for mod in mods:
            if mod.rel not in modules or mod.tree is None:
                continue
            released = _released_slots(mod.tree)
            for node in ast.walk(mod.tree):
                # shape B: register() result discarded
                if isinstance(node, ast.Expr) \
                        and isinstance(node.value, ast.Call) \
                        and _is_register_call(node.value):
                    out.append(Finding(
                        self.id, mod.rel, node.lineno, node.col_offset,
                        "hbm.register() result discarded — the handle "
                        "is the only way to release (or grow) the "
                        "ledger entry; assign it to the owner"))
                    continue
                # shape A: register() assigned, no matching release()
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and _is_register_call(node.value):
                    for target in node.targets:
                        slot = _handle_slot(target)
                        if slot is not None and slot not in released:
                            kind, name = slot
                            out.append(Finding(
                                self.id, mod.rel, node.lineno,
                                node.col_offset,
                                f"hbm.register() handle stored in "
                                f"{'attribute' if kind == 'attr' else 'name'} "
                                f"{name!r} has no matching "
                                f"`.{name}.release()` in this module — "
                                f"every registered artifact needs a "
                                f"reachable teardown path"
                                if kind == "attr" else
                                f"hbm.register() handle bound to "
                                f"{name!r} has no matching "
                                f"`{name}.release()` in this module — "
                                f"every registered artifact needs a "
                                f"reachable teardown path"))
                # shape C: class with device lanes but no register()
                if isinstance(node, ast.ClassDef):
                    has_put = False
                    has_register = False
                    for sub in ast.walk(node):
                        if not isinstance(sub, ast.Call):
                            continue
                        name = call_name(sub)
                        if not name:
                            continue
                        if name.rpartition(".")[2] == "device_put":
                            has_put = True
                        if _is_register_call(sub):
                            has_register = True
                    if has_put and not has_register:
                        out.append(Finding(
                            self.id, mod.rel, node.lineno,
                            node.col_offset,
                            f"class {node.name} launches device_put "
                            f"transfers but never calls hbm.register() "
                            f"— a persistent device lane in a covered "
                            f"module must reach the resident ledger "
                            f"(or move the lane out of the covered "
                            f"set)"))
        return out

"""Route-contract conformance: ``route-contract``.

Every gated device route in this repo carries the same 7-point
contract (docs/architecture.md): a host twin, whole-unit fallback that
increments a cataloged ``*_fallbacks`` counter, an ``obs.device_dispatch``
funnel whose lanes are budgeted (or audited), a ``gate_observation``
calibration join on the host branch, an env override knob, a
capture-conditions stamp for that knob, and an architecture-doc
anchor. Until now the contract was enforced by convention and copied
tests; ROADMAP items 1/2/5/6 each mint new routes, so this pass makes
the contract machine-checked — a route is born conforming or lint
fails, the static twin of the PR 15 runtime transfer-budget audit.

The declarative half lives in ``parallel/gate.py::ROUTES`` (gate name
-> ``RouteSpec(env, fallback_counter, doc_anchor)``); the checker
parses it from the AST (nothing is imported) and cross-checks, per
gate:

1. a ``*_route`` function in the gate module reaches
   ``record_gate_decision`` (directly or through local helpers like
   ``_decide``) with that literal gate name — and every such function
   has a ``ROUTES`` entry (both directions);
2. the route function reads its declared env override knob;
3. the knob is stamped into the obs module's ``CAPTURE_ENV_KEYS``
   (consumed by ``capture_conditions()``);
4. at least one ``device_dispatch(..., gate="<g>")`` funnel exists
   project-wide, and each such site either carries a literal
   ``budget=`` naming a ``transfer_budget.json`` path or sits in a
   function listed under the manifest's budgeted sites /
   ``audited_transfer_sites``;
5. a ``gate_observation("<g>", ...)`` join exists (the host/fallback
   branch prices itself into gate calibration);
6. the declared fallback counter is cataloged in
   ``metric_names.json`` *and* some module creates it with
   ``counter("<name>")`` and calls ``.inc()`` on it;
7. ``docs/architecture.md`` has a heading matching the declared
   anchor slug.

Each finding names the missing contract element. Overrides (fixture
tests):

  DELTA_LINT_GATE_MODULE   rel path of the gate module (default:
                           any scanned ``*/parallel/gate.py``)
  DELTA_LINT_OBS_MODULE    rel path of the obs module holding
                           CAPTURE_ENV_KEYS (default ``*/obs/device.py``)
  DELTA_LINT_ARCH_DOC      path to the architecture doc (default:
                           ``docs/architecture.md`` found by walking up
                           from the gate module)

The budget manifest and metric catalog honor their existing overrides
(``DELTA_LINT_TRANSFER_BUDGET``, ``DELTA_LINT_METRIC_CATALOG``).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from delta_tpu.tools.analyzer.core import (
    Finding,
    ModuleInfo,
    Rule,
    register,
)
from delta_tpu.tools.analyzer.passes._astutil import call_name
from delta_tpu.tools.analyzer.passes.metrics_catalog import (
    _load_catalog as _load_metric_catalog,
)
from delta_tpu.tools.analyzer.passes.transfer_budget import _load_manifest


class _RouteSpec:
    def __init__(self, env: str = "", fallback_counter: str = "",
                 doc_anchor: str = ""):
        self.env = env
        self.fallback_counter = fallback_counter
        self.doc_anchor = doc_anchor


def _gate_module(mods: List[ModuleInfo]) -> Optional[ModuleInfo]:
    want = os.environ.get("DELTA_LINT_GATE_MODULE")
    for mod in mods:
        if want is not None:
            if mod.rel == want:
                return mod
        elif mod.rel.endswith(os.path.join("parallel", "gate.py")):
            return mod
    return None


def _obs_module(mods: List[ModuleInfo]) -> Optional[ModuleInfo]:
    want = os.environ.get("DELTA_LINT_OBS_MODULE")
    for mod in mods:
        if want is not None:
            if mod.rel == want:
                return mod
        elif mod.rel.endswith(os.path.join("obs", "device.py")):
            return mod
    return None


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _parse_routes(tree: ast.Module) -> Tuple[Dict[str, _RouteSpec], int]:
    """The literal ``ROUTES = {...}`` registry -> {gate: spec}, line."""
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        if not (isinstance(target, ast.Name) and target.id == "ROUTES"
                and isinstance(value, ast.Dict)):
            continue
        out: Dict[str, _RouteSpec] = {}
        for key, val in zip(value.keys, value.values):
            gate = _str_const(key) if key is not None else None
            if gate is None:
                continue
            spec = _RouteSpec()
            if isinstance(val, ast.Call):
                fields = ("env", "fallback_counter", "doc_anchor")
                for i, arg in enumerate(val.args[:3]):
                    setattr(spec, fields[i], _str_const(arg) or "")
                for kw in val.keywords:
                    if kw.arg in fields:
                        setattr(spec, kw.arg, _str_const(kw.value) or "")
            elif isinstance(val, (ast.Tuple, ast.List)):
                fields = ("env", "fallback_counter", "doc_anchor")
                for i, arg in enumerate(val.elts[:3]):
                    setattr(spec, fields[i], _str_const(arg) or "")
            out[gate] = spec
        return out, node.lineno
    return {}, 1


def _local_functions(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _reaching_record(local: Dict[str, ast.FunctionDef]) -> Set[str]:
    """Local function names that (transitively, within the gate
    module) call ``record_gate_decision``."""
    calls: Dict[str, Set[str]] = {}
    direct: Set[str] = set()
    for name, fn in local.items():
        callees: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn is None:
                    continue
                tail = cn.rpartition(".")[2]
                if tail == "record_gate_decision":
                    direct.add(name)
                elif tail in local:
                    callees.add(tail)
        calls[name] = callees
    reaching = set(direct)
    changed = True
    while changed:
        changed = False
        for name, callees in calls.items():
            if name not in reaching and callees & reaching:
                reaching.add(name)
                changed = True
    return reaching


def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            val = _str_const(node.value)
            if val is not None:
                out[node.targets[0].id] = val
    return out


def _env_name(arg: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    name = _str_const(arg)
    if name is None and isinstance(arg, ast.Name):
        name = consts.get(arg.id)
    return name


def _env_reads(fn: ast.AST, consts: Dict[str, str]) -> Set[str]:
    """Env-var names this function reads via os.environ.get /
    os.getenv / os.environ[...] (literal or module-constant names)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and node.args:
            cn = call_name(node)
            if cn in ("os.environ.get", "environ.get", "os.getenv",
                      "getenv"):
                name = _env_name(node.args[0], consts)
                if name:
                    out.add(name)
        elif isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Attribute) \
                    and node.value.attr == "environ":
                name = _env_name(node.slice, consts)
                if name:
                    out.add(name)
            elif isinstance(node.value, ast.Name) \
                    and node.value.id == "environ":
                name = _env_name(node.slice, consts)
                if name:
                    out.add(name)
    return out


def _capture_keys(tree: ast.Module) -> Optional[Set[str]]:
    """The literal CAPTURE_ENV_KEYS tuple, or None when absent."""
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        if not (isinstance(target, ast.Name)
                and target.id.lstrip("_") == "CAPTURE_ENV_KEYS"):
            continue
        if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            return {v for v in (_str_const(e) for e in value.elts)
                    if v is not None}
    return None


def _qualname_map(tree: ast.Module) -> Dict[int, str]:
    """id(node) -> qualname of the innermost enclosing function."""
    owner: Dict[int, str] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            q = prefix
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
            owner[id(child)] = q
            visit(child, q)

    visit(tree, "")
    return owner


def _arch_doc_path(gate_mod: ModuleInfo) -> Optional[str]:
    env = os.environ.get("DELTA_LINT_ARCH_DOC")
    if env is not None:
        return env if env and os.path.exists(env) else None
    d = os.path.dirname(os.path.abspath(gate_mod.path))
    for _ in range(6):
        cand = os.path.join(d, "docs", "architecture.md")
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


def _doc_slugs(path: str) -> Set[str]:
    slugs: Set[str] = set()
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                if not line.startswith("#"):
                    continue
                text = line.lstrip("#").strip().lower()
                slug = re.sub(r"[^a-z0-9_ -]", "", text)
                slugs.add(re.sub(r" ", "-", slug))
    except OSError:
        pass
    return slugs


@register
class RouteContractRule(Rule):
    id = "route-contract"
    help_anchor = "route-contract"
    description = (
        "gated device route violating the 7-point route contract "
        "(registry entry, env override read, capture-conditions stamp, "
        "budgeted/audited dispatch funnel, gate_observation join, "
        "cataloged+incremented fallback counter, architecture-doc "
        "anchor) declared in parallel/gate.py::ROUTES")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        gate_mod = _gate_module(mods)
        if gate_mod is None or gate_mod.tree is None:
            return []
        out: List[Finding] = []
        routes, routes_line = _parse_routes(gate_mod.tree)
        local = _local_functions(gate_mod.tree)
        reaching = _reaching_record(local)
        consts = _module_str_constants(gate_mod.tree)

        # 1. discovery <-> registry, both directions
        discovered: Dict[str, ast.FunctionDef] = {}
        for name, fn in sorted(local.items()):
            if not name.endswith("_route"):
                continue
            gates: Set[str] = set()
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                cn = call_name(node)
                tail = cn.rpartition(".")[2] if cn else ""
                if tail == "record_gate_decision" or tail in reaching:
                    g = _str_const(node.args[0])
                    if g:
                        gates.add(g)
            if not gates:
                out.append(Finding(
                    self.id, gate_mod.rel, fn.lineno, fn.col_offset,
                    f"route function {name}() never reaches "
                    f"record_gate_decision — every routing decision "
                    f"must emit a gate record for calibration"))
                continue
            for g in sorted(gates):
                discovered[g] = fn
                if g not in routes:
                    out.append(Finding(
                        self.id, gate_mod.rel, fn.lineno, fn.col_offset,
                        f"route function {name}() decides gate {g!r} "
                        f"but ROUTES has no {g!r} entry — register the "
                        f"route (env knob, fallback counter, doc "
                        f"anchor) in parallel/gate.py::ROUTES"))
        for g in sorted(set(routes) - set(discovered)):
            out.append(Finding(
                self.id, gate_mod.rel, routes_line, 0,
                f"ROUTES entry {g!r} has no *_route function reaching "
                f"record_gate_decision — stale registry entry"))

        obs_mod = _obs_module(mods)
        capture = (_capture_keys(obs_mod.tree)
                   if obs_mod is not None and obs_mod.tree is not None
                   else None)
        dispatch_gates, observations, counters = self._project_scan(mods)
        manifest = _load_manifest() or {}
        budget_paths = set(manifest.get("paths", {}))
        audited = set(manifest.get("audited_transfer_sites", []))
        audited |= {e.get("site") for e in
                    manifest.get("paths", {}).values()}
        metric_catalog, _ = _load_metric_catalog()
        cataloged_counters = set((metric_catalog or {}).get("counters",
                                                            {}))
        doc = _arch_doc_path(gate_mod)
        slugs = _doc_slugs(doc) if doc else set()

        for g in sorted(routes):
            spec = routes[g]
            fn = discovered.get(g)
            line = fn.lineno if fn is not None else routes_line

            # 2. env override read
            if spec.env and fn is not None \
                    and spec.env not in _env_reads(fn, consts):
                out.append(Finding(
                    self.id, gate_mod.rel, line, 0,
                    f"route {g!r}: declared env override {spec.env!r} "
                    f"is never read in {fn.name}() — the knob must "
                    f"outrank the economics (tests, bench lanes)"))

            # 3. capture-conditions stamp
            if spec.env and capture is not None \
                    and spec.env not in capture:
                out.append(Finding(
                    self.id, gate_mod.rel, line, 0,
                    f"route {g!r}: env override {spec.env!r} is not in "
                    f"CAPTURE_ENV_KEYS — bench captures with the knob "
                    f"set would be silently incomparable; stamp it "
                    f"into obs/device.py::CAPTURE_ENV_KEYS"))

            # 4. dispatch funnel + budget/audit coverage
            sites = dispatch_gates.get(g, [])
            if not sites:
                out.append(Finding(
                    self.id, gate_mod.rel, line, 0,
                    f"route {g!r}: no device_dispatch funnel anywhere "
                    f"carries gate={g!r} — the device branch runs "
                    f"outside the dispatch profiler and the "
                    f"calibration join"))
            for rel, lineno, qual, budget in sites:
                if budget is not None:
                    if budget_paths and budget not in budget_paths:
                        out.append(Finding(
                            self.id, rel, lineno, 0,
                            f"route {g!r}: dispatch lane budget "
                            f"{budget!r} has no transfer_budget.json "
                            f"path entry"))
                elif audited and f"{rel}::{qual}" not in audited:
                    out.append(Finding(
                        self.id, rel, lineno, 0,
                        f"route {g!r}: gate-tagged dispatch in "
                        f"{qual}() carries no budget= and "
                        f"{rel}::{qual} is not an audited transfer "
                        f"site — budget the lanes or audit the site "
                        f"in transfer_budget.json"))

            # 5. gate_observation calibration join
            if g not in observations:
                out.append(Finding(
                    self.id, gate_mod.rel, line, 0,
                    f"route {g!r}: no gate_observation({g!r}, ...) "
                    f"join anywhere — the host/fallback branch never "
                    f"prices itself into gate calibration"))

            # 6. fallback counter: cataloged and incremented
            c = spec.fallback_counter
            if c:
                if metric_catalog is not None \
                        and c not in cataloged_counters:
                    out.append(Finding(
                        self.id, gate_mod.rel, line, 0,
                        f"route {g!r}: fallback counter {c!r} is not "
                        f"cataloged in metric_names.json"))
                if c not in counters:
                    out.append(Finding(
                        self.id, gate_mod.rel, line, 0,
                        f"route {g!r}: fallback counter {c!r} is "
                        f"never created-and-incremented — the "
                        f"fallback path must bump a counter("
                        f"{c!r}).inc() so operators see route "
                        f"regressions"))

            # 7. architecture-doc anchor
            if spec.doc_anchor and slugs \
                    and not any(spec.doc_anchor in s for s in slugs):
                out.append(Finding(
                    self.id, gate_mod.rel, line, 0,
                    f"route {g!r}: no docs/architecture.md heading "
                    f"matches anchor {spec.doc_anchor!r} — document "
                    f"the route or fix the ROUTES anchor"))
        return out

    @staticmethod
    def _project_scan(mods: List[ModuleInfo]):
        """One walk over every module: gate-tagged dispatch sites,
        gate_observation joins, created-and-incremented counters."""
        dispatch: Dict[str, List[Tuple[str, int, str, Optional[str]]]] = {}
        observations: Set[str] = set()
        counters: Set[str] = set()
        for mod in mods:
            if mod.tree is None:
                continue
            owner = _qualname_map(mod.tree)
            created: Dict[str, str] = {}   # var -> counter name
            incremented: Set[str] = set()  # vars with .inc() calls
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call) \
                        and node.value.args:
                    cn = call_name(node.value)
                    if cn and cn.rpartition(".")[2] == "counter":
                        name = _str_const(node.value.args[0])
                        if name:
                            created[node.targets[0].id] = name
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                if cn is None:
                    continue
                tail = cn.rpartition(".")[2]
                if tail == "inc" and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name):
                    incremented.add(node.func.value.id)
                elif tail == "gate_observation" and node.args:
                    g = _str_const(node.args[0])
                    if g:
                        observations.add(g)
                elif tail == "device_dispatch":
                    gate = budget = None
                    for kw in node.keywords:
                        if kw.arg == "gate":
                            gate = _str_const(kw.value)
                        elif kw.arg == "budget":
                            budget = _str_const(kw.value)
                    if gate:
                        qual = owner.get(id(node), "") or "<module>"
                        dispatch.setdefault(gate, []).append(
                            (mod.rel, node.lineno, qual, budget))
            counters.update(name for var, name in created.items()
                            if var in incremented)
        return dispatch, observations, counters

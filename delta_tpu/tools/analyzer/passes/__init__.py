"""Bundled delta-lint passes. Importing this package registers every
rule; add new rule modules to the import list below."""

from delta_tpu.tools.analyzer.passes import (  # noqa: F401
    dispatch,
    env_catalog,
    errors_catalog,
    handler_discipline,
    hygiene,
    imports,
    locks,
    metrics_catalog,
    obs,
    purity,
    races,
    recompile,
    resident_ledger,
    retry_discipline,
    route_contract,
    threads,
    transfer_budget,
)
